"""Fleet-wide search plane (search/columnar.py, docs/SEARCH.md).

The acceptance properties this suite pins:

- PARITY: the vectorized columnar query plane returns BYTE-IDENTICAL
  result sets (same objects, same cached-from-cluster annotations, same
  deterministic order) as the dict-based ResourceCache for randomized
  fleets and label/field/name queries.
- RV CONSISTENCY: a query pinned at rv R never observes a row folded
  after R, under concurrent ingest churn; pins that predate the
  snapshot ring fail loudly (SnapshotExpired -> 410).
- FOLLOWER PARITY: follower-served GET /search answers byte-match the
  leader's at the same min_rv barrier — the replicated summary feed
  builds the identical index off the leader's original rvs.
"""
from __future__ import annotations

import json
import random
import threading
import time
from urllib.error import HTTPError
from urllib.parse import urlencode
from urllib.request import urlopen

import pytest

from karmada_tpu.api.meta import ObjectMeta
from karmada_tpu.api.search import (
    ClusterObjectSummary,
    KIND_CLUSTER_OBJECT_SUMMARY,
    ObjectSummaryRow,
    ResourceRegistry,
    ResourceRegistrySpec,
    SearchResourceSelector,
    summary_name,
)
from karmada_tpu.api.cluster import Cluster
from karmada_tpu.api.policy import ClusterAffinity
from karmada_tpu.api.unstructured import Unstructured
from karmada_tpu.members.member import InMemoryMember, MemberConfig
from karmada_tpu.search import (
    ColumnarIndex,
    QueryError,
    SearchIngestor,
    SnapshotExpired,
    compile_query,
    execute,
    field_pairs_of,
    parse_label_selector,
    run_query,
)
from karmada_tpu.search.search import CLUSTER_ANNOTATION, ResourceCache
from karmada_tpu.store.store import Store

GVK = "apps/v1/Deployment"


def upsert(ix, cluster, name, labels=None, ns="default", rv=1, gvk=GVK,
           fields=None, doc=None):
    av, _, kind = gvk.rpartition("/")
    manifest = {
        "apiVersion": av, "kind": kind,
        "metadata": {"name": name, "namespace": ns,
                     "labels": dict(labels or {})},
    }
    d = doc if doc is not None else Unstructured(manifest)
    return ix.upsert(cluster, gvk, ns, name, labels=labels or {},
                     fields=fields or field_pairs_of(manifest),
                     rv=rv, doc=d)


def names_of(items):
    return [o.name for o in items]


# ---------------------------------------------------------------------------
# columnar index + selector execution
# ---------------------------------------------------------------------------


class TestColumnarIndex:
    def test_label_eq_and_neq(self):
        ix = ColumnarIndex()
        upsert(ix, "c1", "web", {"app": "web"})
        upsert(ix, "c1", "db", {"app": "db"})
        upsert(ix, "c2", "bare", {})  # no labels at all
        snap = ix.publish()
        assert names_of(execute(snap, compile_query(
            {"labelSelector": "app=web"}))) == ["web"]
        # k8s semantics: != matches objects MISSING the key too
        assert names_of(execute(snap, compile_query(
            {"labelSelector": "app!=web"}))) == ["db", "bare"]

    def test_set_ops_and_exists(self):
        ix = ColumnarIndex()
        upsert(ix, "c1", "a", {"tier": "web"})
        upsert(ix, "c1", "b", {"tier": "db"})
        upsert(ix, "c1", "c", {"other": "x"})
        snap = ix.publish()
        q = compile_query({"labelSelector": "tier in (web, cache)"})
        assert names_of(execute(snap, q)) == ["a"]
        q = compile_query({"labelSelector": "tier notin (web)"})
        assert names_of(execute(snap, q)) == ["b", "c"]
        assert names_of(execute(snap, compile_query(
            {"labelSelector": "tier"}))) == ["a", "b"]
        assert names_of(execute(snap, compile_query(
            {"labelSelector": "!tier"}))) == ["c"]

    def test_unknown_value_never_grows_vocabulary(self):
        ix = ColumnarIndex()
        upsert(ix, "c1", "web", {"app": "web"})
        snap = ix.publish()
        before = len(snap.lpairs)
        assert execute(snap, compile_query(
            {"labelSelector": "app=never-seen"})) == []
        assert len(snap.lpairs) == before

    def test_field_selector_and_name_contains(self):
        ix = ColumnarIndex()
        upsert(ix, "c1", "web-1", fields={"metadata.name": "web-1",
                                          "spec.replicas": "3"})
        upsert(ix, "c1", "api-1", fields={"metadata.name": "api-1",
                                          "spec.replicas": "5"})
        snap = ix.publish()
        assert names_of(execute(snap, compile_query(
            {"fieldSelector": "spec.replicas=3"}))) == ["web-1"]
        assert names_of(execute(snap, compile_query(
            {"fieldSelector": "spec.replicas!=3"}))) == ["api-1"]
        assert names_of(execute(snap, compile_query(
            {"nameContains": "web"}))) == ["web-1"]

    def test_kind_only_query_scans_gvk_dictionary(self):
        ix = ColumnarIndex()
        upsert(ix, "c1", "web", gvk="apps/v1/Deployment")
        upsert(ix, "c1", "svc", gvk="v1/Service")
        snap = ix.publish()
        assert names_of(execute(snap, compile_query(
            {"kind": "Deployment"}))) == ["web"]
        assert names_of(execute(snap, compile_query(
            {"kind": "Deployment", "apiVersion": "apps/v1"}))) == ["web"]
        assert execute(snap, compile_query(
            {"kind": "Deployment", "apiVersion": "v1"})) == []

    def test_cluster_filter_namespace_and_limit(self):
        ix = ColumnarIndex()
        for c in ("c1", "c2", "c3"):
            upsert(ix, c, "web", ns="prod")
            upsert(ix, c, "web", ns="dev")
        snap = ix.publish()
        q = compile_query({"clusters": "c1,c3", "namespace": "prod"})
        hits = execute(snap, q)
        assert [(h.namespace, h.name) for h in hits] == [
            ("prod", "web"), ("prod", "web")]
        assert len(execute(snap, compile_query({"limit": "4"}))) == 4

    def test_remove_and_drop_cluster(self):
        ix = ColumnarIndex()
        upsert(ix, "c1", "web")
        upsert(ix, "c2", "web")
        upsert(ix, "c2", "db")
        assert ix.remove("c2", GVK, "default", "web", rv=5)
        assert not ix.remove("c2", GVK, "default", "missing", rv=5)
        snap = ix.publish()
        assert [(s.cluster_ids[i], s.name_ids[i]) for s, i in []] == []
        assert len(execute(snap, compile_query({}))) == 2
        assert ix.drop_cluster("c2", rv=6) == 1
        assert names_of(execute(ix.publish(), compile_query({}))) == ["web"]

    def test_change_suppression_skips_rebuild(self):
        ix = ColumnarIndex()
        doc = Unstructured({"apiVersion": "apps/v1", "kind": "Deployment",
                            "metadata": {"name": "web", "namespace": "default",
                                         "resourceVersion": 7}})
        assert upsert(ix, "c1", "web", {"a": "b"}, rv=1, doc=doc)
        s1 = ix.publish()
        # identical re-report: not dirty, publish shares the tip arrays
        assert not upsert(ix, "c1", "web", {"a": "b"}, rv=2, doc=doc)
        s2 = ix.publish(rv=9)
        assert s2.name_ids is s1.name_ids
        assert s2.rv == 9  # but the freshness stamp still advances
        # a changed selector surface is a real write again
        assert upsert(ix, "c1", "web", {"a": "c"}, rv=3, doc=doc)
        assert ix.publish().name_ids is not s1.name_ids

    def test_bad_selector_syntax_raises_query_error(self):
        with pytest.raises(QueryError):
            parse_label_selector("a==b==c")
        with pytest.raises(QueryError):
            compile_query({"labelSelector": "tier in web"})  # missing parens
        with pytest.raises(QueryError):
            compile_query({"fieldSelector": "spec.x in (a)"})  # sets invalid
        with pytest.raises(QueryError):
            compile_query({"limit": "nope"})


class TestSnapshotRing:
    def test_at_rv_pin_resolves_older_snapshot(self):
        ix = ColumnarIndex()
        upsert(ix, "c1", "v1-only", rv=10)
        s10 = ix.publish()
        upsert(ix, "c1", "v2-extra", rv=20)
        ix.publish()
        pinned = ix.snapshot(at_rv=15)
        assert pinned.rv == s10.rv
        assert names_of(execute(pinned, compile_query({}))) == ["v1-only"]

    def test_pin_before_ring_raises_snapshot_expired(self):
        ix = ColumnarIndex(ring=4)
        for i in range(8):
            upsert(ix, "c1", f"o{i}", rv=(i + 1) * 10)
            ix.publish()
        with pytest.raises(SnapshotExpired):
            ix.snapshot(at_rv=15)

    def test_ring_rvs_monotone(self):
        ix = ColumnarIndex()
        upsert(ix, "c1", "a", rv=50)
        ix.publish()
        upsert(ix, "c1", "b", rv=20)  # stale stamp folds in...
        s = ix.publish()
        assert s.rv >= 50  # ...but the ring never goes backwards


# ---------------------------------------------------------------------------
# parity: columnar plane vs the dict-based ResourceCache
# ---------------------------------------------------------------------------


def _match_labels(terms, labels):
    for t in terms:
        have = t.key in labels
        if t.op == "eq" and not (have and labels[t.key] == t.values[0]):
            return False
        if t.op == "neq" and (have and labels[t.key] == t.values[0]):
            return False
        if t.op == "exists" and not have:
            return False
        if t.op == "nexists" and have:
            return False
        if t.op == "in" and not (have and labels[t.key] in t.values):
            return False
        if t.op == "notin" and (have and labels[t.key] in t.values):
            return False
    return True


class TestParityWithDictCache:
    """Randomized fleets: the columnar plane must return byte-identical
    result sets — same `to_dict()` bytes (including the
    resource.karmada.io/cached-from-cluster annotation), same
    deterministic order — as filtering the dict cache's sorted items."""

    def _fleet(self, seed):
        rng = random.Random(seed)
        store = Store()
        members = {}
        apps = ["web", "api", "db", "cache"]
        for c in range(3):
            cfg = MemberConfig(name=f"m{c}", allocatable={"cpu": 10.0})
            m = InMemoryMember(cfg)
            members[m.name] = m
            store.apply(Cluster(metadata=ObjectMeta(name=m.name)))
            for i in range(rng.randint(3, 9)):
                labels = {"app": rng.choice(apps)}
                if rng.random() < 0.5:
                    labels["tier"] = rng.choice(["fe", "be"])
                m.apply_manifest({
                    "apiVersion": "apps/v1", "kind": "Deployment",
                    "metadata": {
                        "name": f"{labels['app']}-{i}",
                        "namespace": rng.choice(["default", "prod"]),
                        "labels": labels,
                    },
                    "spec": {"replicas": rng.randint(1, 5)},
                })
        store.apply(ResourceRegistry(
            metadata=ObjectMeta(name="reg"),
            spec=ResourceRegistrySpec(
                target_cluster=ClusterAffinity(),
                resource_selectors=[SearchResourceSelector(
                    api_version="apps/v1", kind="Deployment")])))
        index = ColumnarIndex()
        cache = ResourceCache(store, members, index=index)
        cache.sweep()
        return cache, index, rng

    def _reference(self, cache, query):
        out = []
        for key, obj in sorted(cache._cache.items()):
            if query.namespace and obj.namespace != query.namespace:
                continue
            if query.name_contains and query.name_contains not in obj.name:
                continue
            if query.clusters and key[0] not in query.clusters:
                continue
            if not _match_labels(query.labels, dict(obj.metadata.labels)):
                continue
            fields = field_pairs_of(obj.to_dict())
            if not _match_labels(query.fields, fields):
                continue
            out.append(obj)
        return out

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_randomized_queries_byte_identical(self, seed):
        cache, index, rng = self._fleet(seed)
        snap = index.snapshot()
        assert snap.count == len(cache._cache)
        queries = (
            [{"labelSelector": f"app={a}"} for a in
             ("web", "api", "db", "cache", "ghost")] +
            [{"labelSelector": "tier in (fe, be)"},
             {"labelSelector": "tier notin (fe)"},
             {"labelSelector": "!tier"},
             {"labelSelector": "app=web,tier=fe"},
             {"namespace": "prod"},
             {"nameContains": "api"},
             {"clusters": "m0,m2", "labelSelector": "app"},
             {"fieldSelector": "spec.replicas=3"},
             {"fieldSelector": "metadata.namespace=default",
              "labelSelector": "app=db"}]
        )
        for params in queries:
            q = compile_query(params)
            got = execute(snap, q)
            want = self._reference(cache, q)
            got_b = [json.dumps(o.to_dict(), sort_keys=True) for o in got]
            want_b = [json.dumps(o.to_dict(), sort_keys=True) for o in want]
            assert got_b == want_b, params
            for o in got:
                assert o.metadata.annotations[CLUSTER_ANNOTATION] in (
                    "m0", "m1", "m2")

    def test_sweep_prunes_removed_objects_from_index(self):
        cache, index, _ = self._fleet(seed=7)
        victim = sorted(cache._cache)[0]
        cname, _, ns, name = victim
        cache.members[cname].delete_manifest("apps/v1", "Deployment", ns, name)
        cache.sweep()
        snap = index.snapshot()
        assert snap.count == len(cache._cache)
        assert victim not in cache._cache

    def test_detach_member_drops_cluster_rows(self):
        cache, index, _ = self._fleet(seed=8)
        before = index.snapshot().count
        dropped = sum(1 for k in cache._cache if k[0] == "m1")
        cache.detach_member("m1")
        assert index.publish().count == before - dropped


# ---------------------------------------------------------------------------
# ingest: summary feed, freshness, rv consistency under churn
# ---------------------------------------------------------------------------


def make_summary(cluster, rows_spec, av="apps/v1", kind="Deployment"):
    rows = [
        ObjectSummaryRow(
            namespace=ns, name=name, labels=dict(labels),
            manifest={"apiVersion": av, "kind": kind,
                      "metadata": {"name": name, "namespace": ns,
                                   "labels": dict(labels)}})
        for ns, name, labels in rows_spec
    ]
    return ClusterObjectSummary(
        metadata=ObjectMeta(name=summary_name(cluster, av, kind)),
        cluster=cluster, api_version=av, object_kind=kind, rows=rows)


class TestSearchIngestor:
    def test_summary_folds_and_slice_replacement(self):
        store = Store()
        index = ColumnarIndex()
        ing = SearchIngestor(store, index)
        try:
            store.apply(make_summary("c1", [
                ("default", "web", {"app": "web"}),
                ("default", "db", {"app": "db"}),
            ]))
            assert ing.flush()
            snap = index.snapshot()
            assert names_of(execute(snap, compile_query({}))) == ["db", "web"]
            hit = execute(snap, compile_query({"labelSelector": "app=web"}))[0]
            assert hit.metadata.annotations[CLUSTER_ANNOTATION] == "c1"
            # a replacement summary retracts vanished rows (level-triggered)
            store.apply(make_summary("c1", [
                ("default", "web", {"app": "web"}),
            ]))
            assert ing.flush()
            assert names_of(execute(index.snapshot(),
                                    compile_query({}))) == ["web"]
            # empty rows retracts the whole slice
            store.apply(make_summary("c1", []))
            assert ing.flush()
            assert index.snapshot().count == 0
        finally:
            ing.close()

    def test_prime_attaches_revision_consistent(self):
        store = Store()
        store.apply(make_summary("c1", [("default", "pre", {})]))
        index = ColumnarIndex()
        ing = SearchIngestor(store, index)  # attaches AFTER the write
        try:
            assert ing.flush()
            assert names_of(execute(index.snapshot(),
                                    compile_query({}))) == ["pre"]
        finally:
            ing.close()

    def test_snapshot_rv_tracks_store_and_lag_drains(self):
        store = Store()
        index = ColumnarIndex()
        ing = SearchIngestor(store, index)
        try:
            for w in range(20):
                store.apply(make_summary(f"c{w % 4}", [
                    ("default", f"o{w}", {"wave": str(w)})]))
            assert ing.flush()
            assert index.snapshot().rv == store.current_rv
        finally:
            ing.close()

    def test_pinned_query_never_sees_future_rows_under_churn(self):
        """RV CONSISTENCY: pin at rv R while a writer churns — every
        snapshot served for the pin holds only rows folded at <= R."""
        store = Store()
        index = ColumnarIndex()
        ing = SearchIngestor(store, index)
        stop = threading.Event()

        def churn():
            i = 0
            while not stop.is_set():
                store.apply(make_summary("hot", [
                    ("default", f"obj-{i % 5}", {"i": str(i)})]))
                i += 1
                time.sleep(0.001)
        t = threading.Thread(target=churn, daemon=True)
        try:
            store.apply(make_summary("cold", [("default", "pinned", {})]))
            assert ing.flush()
            t.start()
            checks = 0
            deadline = time.monotonic() + 1.0
            while time.monotonic() < deadline:
                pin = index.snapshot().rv
                try:
                    snap = index.snapshot(at_rv=pin)
                except SnapshotExpired:
                    continue  # churn rolled the ring past the pin: re-pin
                assert snap.rv <= pin
                assert (snap.rvs <= pin).all()
                checks += 1
            assert checks > 0
        finally:
            stop.set()
            t.join(timeout=5.0)
            ing.close()

    def test_overflow_sets_resync_and_recovers(self):
        store = Store()
        index = ColumnarIndex()
        ing = SearchIngestor(store, index, start=False)  # worker held
        try:
            for i in range(SearchIngestor.QUEUE_MAX + 50):
                ing._sink(KIND_CLUSTER_OBJECT_SUMMARY, "MODIFIED",
                          make_summary("c1", [("default", f"o{i}", {})]))
            assert ing._resync
            # the real store state re-lists on recovery
            store.apply(make_summary("c1", [("default", "real", {})]))
            ing._thread.start()
            assert ing.flush(timeout=30.0)
            assert names_of(execute(index.snapshot(),
                                    compile_query({}))) == ["real"]
        finally:
            ing.close()

    def test_fold_does_not_mutate_committed_summary(self):
        """The sink hands the ingestor the store's committed object by
        reference; annotating the manifest in place would corrupt the
        store (and race its deepcopies)."""
        store = Store()
        index = ColumnarIndex()
        ing = SearchIngestor(store, index)
        try:
            store.apply(make_summary("c1", [("default", "web", {})]))
            assert ing.flush()
            stored = store.get(KIND_CLUSTER_OBJECT_SUMMARY,
                               summary_name("c1", "apps/v1", "Deployment"))
            assert CLUSTER_ANNOTATION not in json.dumps(
                stored.rows[0].manifest)
        finally:
            ing.close()


# ---------------------------------------------------------------------------
# agent summary feed (the coalesced status path)
# ---------------------------------------------------------------------------


class TestAgentSearchReports:
    def _plane(self, flush_delay=0.0):
        from karmada_tpu.agent.agent import KarmadaAgent
        from karmada_tpu.interpreter.interpreter import ResourceInterpreter
        from karmada_tpu.runtime.controller import Runtime

        store = Store()
        cfg = MemberConfig(name="edge-1", sync_mode="Pull",
                           allocatable={"cpu": 4.0})
        member = InMemoryMember(cfg)
        store.apply(Cluster(metadata=ObjectMeta(name="edge-1")))
        store.apply(ResourceRegistry(
            metadata=ObjectMeta(name="reg"),
            spec=ResourceRegistrySpec(
                target_cluster=ClusterAffinity(),
                resource_selectors=[SearchResourceSelector(
                    api_version="apps/v1", kind="Deployment")])))
        agent = KarmadaAgent(store, member, ResourceInterpreter(), Runtime(),
                             status_flush_delay=flush_delay,
                             search_reports=True)
        return store, member, agent

    def test_heartbeat_publishes_selected_summaries(self):
        store, member, agent = self._plane()
        member.apply_manifest({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "web", "namespace": "default",
                         "labels": {"app": "web"}},
            "spec": {"replicas": 2}})
        member.apply_manifest({  # NOT registry-selected
            "apiVersion": "v1", "kind": "Service",
            "metadata": {"name": "svc", "namespace": "default"}})
        agent.heartbeat()
        s = store.get(KIND_CLUSTER_OBJECT_SUMMARY,
                      summary_name("edge-1", "apps/v1", "Deployment"))
        assert [r.name for r in s.rows] == ["web"]
        assert s.rows[0].labels == {"app": "web"}
        assert s.rows[0].fields["spec.replicas"] == "2"
        assert store.try_get(KIND_CLUSTER_OBJECT_SUMMARY,
                             summary_name("edge-1", "v1", "Service")) is None

    def test_quiet_heartbeat_is_change_suppressed(self):
        store, member, agent = self._plane()
        member.apply_manifest({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "web", "namespace": "default"}})
        agent.heartbeat()
        sname = summary_name("edge-1", "apps/v1", "Deployment")
        rv = store.get(KIND_CLUSTER_OBJECT_SUMMARY,
                       sname).metadata.resource_version
        agent.heartbeat()  # nothing changed member-side: no summary write
        assert store.get(KIND_CLUSTER_OBJECT_SUMMARY,
                         sname).metadata.resource_version == rv
        member.apply_manifest({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "web2", "namespace": "default"}})
        agent.heartbeat()
        assert store.get(KIND_CLUSTER_OBJECT_SUMMARY,
                         sname).metadata.resource_version > rv

    def test_summaries_ride_the_coalesced_status_path(self):
        store, member, agent = self._plane(flush_delay=5.0)
        member.apply_manifest({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "web", "namespace": "default"}})
        rv = store.current_rv
        agent.heartbeat()  # Lease writes through; the summary buffers
        assert store.try_get(
            KIND_CLUSTER_OBJECT_SUMMARY,
            summary_name("edge-1", "apps/v1", "Deployment")) is None
        assert agent.flush_status() >= 1
        assert store.current_rv > rv
        assert store.get(KIND_CLUSTER_OBJECT_SUMMARY,
                         summary_name("edge-1", "apps/v1", "Deployment"))
        agent.close()

    def test_end_to_end_agent_to_query(self):
        store, member, agent = self._plane()
        member.apply_manifest({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "web", "namespace": "default",
                         "labels": {"app": "web"}}})
        index = ColumnarIndex()
        ing = SearchIngestor(store, index)
        try:
            agent.heartbeat()
            assert ing.flush()
            res = run_query(index, compile_query(
                {"labelSelector": "app=web"}))
            assert names_of(res.items) == ["web"]
            assert res.items[0].metadata.annotations[
                CLUSTER_ANNOTATION] == "edge-1"
            assert res.rv == store.current_rv
        finally:
            ing.close()


# ---------------------------------------------------------------------------
# GET /search endpoint + follower/leader parity
# ---------------------------------------------------------------------------


def _get(url, params=None):
    q = f"?{urlencode(params)}" if params else ""
    with urlopen(f"{url}/search{q}") as r:
        return r.status, json.loads(r.read())


class TestSearchEndpoint:
    @pytest.fixture
    def plane(self):
        from karmada_tpu.server.apiserver import ControlPlaneServer
        from karmada_tpu.store.replication import ReplicaControlPlane

        cp = ReplicaControlPlane(search=True)
        srv = ControlPlaneServer(cp)
        srv.start()
        yield cp, srv
        srv.stop()
        cp.close()

    def test_search_serves_and_filters(self, plane):
        cp, srv = plane
        cp.store.apply(make_summary("c1", [
            ("default", "web", {"app": "web"}),
            ("default", "db", {"app": "db"})]))
        assert cp.search_ingestor.flush()
        status, body = _get(srv.url, {"labelSelector": "app=web"})
        assert status == 200
        assert body["count"] == 1
        assert body["resourceVersion"] == cp.store.current_rv
        names = [o["manifest"]["metadata"]["name"] for o in body["items"]]
        assert names == ["web"]

    def test_bad_selector_is_400_expired_pin_410(self, plane):
        cp, srv = plane
        cp.store.apply(make_summary("c1", [("default", "web", {})]))
        assert cp.search_ingestor.flush()
        with pytest.raises(HTTPError) as e:
            _get(srv.url, {"labelSelector": "a==b==c"})
        assert e.value.code == 400
        # roll the ring past rv 1 (ring=32), then pin before it
        for i in range(40):
            cp.store.apply(make_summary("c1", [
                ("default", "web", {"i": str(i)})]))
            assert cp.search_ingestor.flush()
            cp.search_index.publish()
        with pytest.raises(HTTPError) as e:
            _get(srv.url, {"at_rv": "1"})
        assert e.value.code == 410

    def test_at_rv_pin_serves_old_state(self, plane):
        cp, srv = plane
        cp.store.apply(make_summary("c1", [("default", "old", {})]))
        assert cp.search_ingestor.flush()
        pin = cp.store.current_rv
        cp.store.apply(make_summary("c1", [
            ("default", "old", {}), ("default", "new", {})]))
        assert cp.search_ingestor.flush()
        status, body = _get(srv.url, {"at_rv": str(pin)})
        assert status == 200
        assert [o["manifest"]["metadata"]["name"]
                for o in body["items"]] == ["old"]
        status, body = _get(srv.url)
        assert body["count"] == 2

    def test_plane_without_search_is_404(self):
        from karmada_tpu.server.apiserver import ControlPlaneServer
        from karmada_tpu.store.replication import ReplicaControlPlane

        cp = ReplicaControlPlane()  # search not enabled
        srv = ControlPlaneServer(cp)
        srv.start()
        try:
            with pytest.raises(HTTPError) as e:
                _get(srv.url)
            assert e.value.code == 404
        finally:
            srv.stop()


class TestFollowerLeaderParity:
    def test_follower_answers_match_leader_at_min_rv(self):
        """FOLLOWER PARITY: replicated summaries build a byte-identical
        index on the follower; GET /search at the same min_rv barrier
        returns the same items in the same order from either replica."""
        from karmada_tpu.coordination.lease import LeaseCoordinator  # noqa: F401
        from karmada_tpu.server.apiserver import ControlPlaneServer
        from karmada_tpu.store.replication import (
            REPLICATION_LEASE,
            ReplicaControlPlane,
            ReplicationManager,
        )

        follower_cp = ReplicaControlPlane(search=True)
        follower = ControlPlaneServer(follower_cp)
        follower.start()
        leader_cp = ReplicaControlPlane(search=True)
        lease, ok = leader_cp.coordinator.acquire(
            REPLICATION_LEASE, "leader-0", 10.0)
        assert ok
        manager = ReplicationManager(
            leader_cp.store, [follower.url], mode="quorum", quorum=1,
            token=lease.spec.fencing_token, identity="leader-0")
        leader = ControlPlaneServer(leader_cp, replication=manager)
        leader.start()
        try:
            for c in ("c1", "c2"):
                leader_cp.store.apply(make_summary(c, [
                    ("default", "web", {"app": "web"}),
                    ("prod", "db", {"app": "db"})]))
            rv = leader_cp.store.current_rv
            deadline = time.monotonic() + 10.0
            while (min((p.acked_rv for p in manager.peers), default=0) < rv
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert manager.fleet_acked_rv() >= rv
            assert leader_cp.search_ingestor.flush()
            assert follower_cp.search_ingestor.flush()
            for params in ({"labelSelector": "app=web"},
                           {"namespace": "prod"},
                           {"nameContains": "b"}):
                q = dict(params, min_rv=str(rv), at_rv=str(rv))
                _, lbody = _get(leader.url, q)
                _, fbody = _get(follower.url, q)
                assert lbody["items"] == fbody["items"], params
                assert lbody["resourceVersion"] == fbody["resourceVersion"]
            # the leader reports the replication floor
            _, lbody = _get(leader.url, {"min_rv": str(rv)})
            assert lbody["replicated_rv"] >= rv
        finally:
            leader.stop()
            follower.stop()
            leader_cp.close()
            follower_cp.close()


# ---------------------------------------------------------------------------
# karmadactl search
# ---------------------------------------------------------------------------


class TestKarmadactlSearch:
    def _cp(self):
        class _CP:
            def __init__(self):
                self.search_index = ColumnarIndex()

            def search(self, params, *, at_rv=None, trace_id=""):
                return run_query(self.search_index, compile_query(params),
                                 at_rv=at_rv, trace_id=trace_id)
        cp = _CP()
        doc = Unstructured({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "web", "namespace": "default",
                         "labels": {"app": "web"},
                         "annotations": {CLUSTER_ANNOTATION: "m1"}}})
        upsert(cp.search_index, "m1", "web", {"app": "web"}, rv=3, doc=doc)
        cp.search_index.publish()
        return cp

    def test_table_output(self):
        from karmada_tpu.cli.karmadactl import run

        out = run(self._cp(), ["search", "apps/v1/Deployment",
                               "-l", "app=web"])
        assert "rv: 3 (1 item)" in out
        lines = out.splitlines()
        assert lines[1].split() == ["CLUSTER", "NAMESPACE", "NAME", "KIND"]
        assert lines[2].split() == ["m1", "default", "web",
                                    "apps/v1/Deployment"]

    def test_json_output_and_empty(self):
        from karmada_tpu.cli.karmadactl import run

        got = json.loads(run(self._cp(), ["search", "-o", "json"]))
        assert got["resourceVersion"] == 3
        assert got["items"][0]["metadata"]["name"] == "web"
        assert run(self._cp(), ["search", "-l", "app=ghost"]) == "rv: 3 (0 items)"

    def test_bad_selector_is_cli_error(self):
        from karmada_tpu.cli.karmadactl import CLIError, run

        with pytest.raises(CLIError):
            run(self._cp(), ["search", "-l", "a==b==c"])

    def test_plane_without_search_plane(self):
        from karmada_tpu.cli.karmadactl import CLIError, run

        with pytest.raises(CLIError):
            run(object(), ["search"])

    def test_remote_plane_maps_wire_errors(self):
        """The wire surface keeps the in-process exception contract:
        HTTP 400 -> QueryError, 410 -> SnapshotExpired, so karmadactl
        handles both planes with one except clause."""
        from karmada_tpu.cli.karmadactl import CLIError, run
        from karmada_tpu.server.apiserver import ControlPlaneServer
        from karmada_tpu.server.remote import RemoteControlPlane
        from karmada_tpu.store.replication import ReplicaControlPlane

        cp = ReplicaControlPlane(search=True)
        srv = ControlPlaneServer(cp)
        srv.start()
        try:
            cp.store.apply(make_summary("c1", [("default", "web", {})]))
            assert cp.search_ingestor.flush()
            rc = RemoteControlPlane(srv.url)
            assert "web" in run(rc, ["search"])
            with pytest.raises(CLIError):
                run(rc, ["search", "-l", "a==b==c"])  # 400 over the wire
            for i in range(40):  # roll the ring past rv 1
                cp.store.apply(make_summary("c1", [
                    ("default", "web", {"i": str(i)})]))
                assert cp.search_ingestor.flush()
                cp.search_index.publish()
            with pytest.raises(CLIError):
                run(rc, ["search", "--at-rv", "1"])  # 410 over the wire
        finally:
            srv.stop()
            cp.close()


# ---------------------------------------------------------------------------
# OpenSearch backend flush threshold
# ---------------------------------------------------------------------------


class TestOpenSearchFlushThreshold:
    def _obj(self, name):
        return Unstructured({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": name, "namespace": "default",
                         "uid": f"uid-{name}"}})

    def test_threshold_ships_bulk_mid_sweep(self):
        from karmada_tpu.search.search import (
            BufferingTransport,
            OpenSearchBackend,
        )

        t = BufferingTransport()
        be = OpenSearchBackend(["http://os:9200"], transport=t,
                               flush_threshold=3)
        for i in range(7):
            be.index("m1", self._obj(f"web-{i}"))
        bulks = [r for r in t.requests if r.path == "/_bulk"]
        assert len(bulks) == 2  # at op 3 and op 6
        assert len(be._bulk) == 1  # the remainder awaits the sweep flush
        be.flush()
        assert be._bulk == []
        assert len([r for r in t.requests if r.path == "/_bulk"]) == 3

    def test_zero_threshold_keeps_one_bulk_per_sweep(self):
        from karmada_tpu.search.search import (
            BufferingTransport,
            OpenSearchBackend,
        )

        t = BufferingTransport()
        be = OpenSearchBackend(["http://os:9200"], transport=t)
        for i in range(10):
            be.index("m1", self._obj(f"web-{i}"))
        assert [r for r in t.requests if r.path == "/_bulk"] == []
        be.flush()
        assert len([r for r in t.requests if r.path == "/_bulk"]) == 1

    def test_threshold_flush_failure_keeps_queue(self):
        from karmada_tpu.search.search import (
            BufferingTransport,
            HttpRequest,
            OpenSearchBackend,
        )

        class Flaky(BufferingTransport):
            def perform(self, request: HttpRequest):
                if request.path == "/_bulk":
                    raise OSError("down")
                return super().perform(request)

        be = OpenSearchBackend(["http://os:9200"], transport=Flaky(),
                               flush_threshold=2)
        for i in range(5):
            be.index("m1", self._obj(f"web-{i}"))
        assert len(be._bulk) == 5  # nothing lost while the transport is down


# ---------------------------------------------------------------------------
# slow path: the bench acceptance line, end to end
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestSearchSmokeScript:
    def test_search_smoke(self):
        """scripts/search_smoke.sh: the `search` bench config — columnar
        query p99 >= 5x the per-cluster fan-out baseline at 1k clusters
        with per-query result parity, churn freshness lag bounded and
        draining to 0 — asserted from the emitted JSON line."""
        import os
        import subprocess

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            ["bash", "scripts/search_smoke.sh"],
            capture_output=True, text=True, timeout=900, cwd=repo,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "SEARCH OK" in r.stdout

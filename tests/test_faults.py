"""Fault-tolerance plane unit tests (karmada_tpu/faults/, docs/ROBUSTNESS.md):

- FaultPlan determinism: same seed + same plan ⇒ byte-identical schedule;
- CircuitBreaker state machine under a fake clock (open / half-open probe
  timing, probe admission limits);
- RetryPolicy full-jitter envelope + deadline budget; Backoff streaks;
- staleness penalty + tracker semantics;
- typed per-manifest apply results (retryable vs terminal) and the
  execution controller's bounded re-dispatch;
- degraded estimator sweeps: open breaker ⇒ stale penalized column, fresh
  sweep ⇒ cache refresh, and the estimator error metric by status code.
"""
from __future__ import annotations

import numpy as np
import pytest

from karmada_tpu import faults
from karmada_tpu.faults import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    Backoff,
    BreakerRegistry,
    CircuitBreaker,
    FaultPlan,
    FaultRule,
    InjectedFault,
    RetryPolicy,
    StalenessTracker,
    apply_staleness_penalty,
)


@pytest.fixture(autouse=True)
def _no_ambient_plan():
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# deterministic fault plans
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def plan(self) -> FaultPlan:
        return FaultPlan(seed=42, rules=[
            FaultRule(boundary="grpc", target="m1", kind="flap", period=3),
            FaultRule(boundary="grpc", target="m2", kind="partition",
                      after=2, heal_after=6),
            FaultRule(boundary="http", kind="error", rate=0.5,
                      heal_after=50),
            FaultRule(boundary="apply", target="m3", kind="latency",
                      latency=0.001, rate=0.25),
        ])

    def test_same_seed_same_plan_byte_identical_schedule(self):
        p1 = self.plan()
        p2 = FaultPlan.from_json(p1.to_json())  # round-trips the plan
        for boundary, target in (("grpc", "m1"), ("grpc", "m2"),
                                 ("http", "x:1"), ("apply", "m3")):
            assert (p1.schedule(boundary, target, 64)
                    == p2.schedule(boundary, target, 64))

    def test_different_seed_changes_probabilistic_schedule(self):
        p1, p2 = self.plan(), self.plan()
        p2.seed = 43
        assert (p1.schedule("http", "x:1", 256)
                != p2.schedule("http", "x:1", 256))

    def test_flap_alternates_in_period_windows(self):
        p = self.plan()
        states = [p.decide("grpc", "m1", n).error for n in range(9)]
        assert states == [None] * 3 + ["UNAVAILABLE"] * 3 + [None] * 3

    def test_partition_window_and_heal(self):
        p = self.plan()
        states = [p.decide("grpc", "m2", n).error for n in range(8)]
        assert states == [None, None] + ["UNAVAILABLE"] * 4 + [None, None]

    def test_unmatched_site_is_clean(self):
        p = self.plan()
        for n in range(16):
            a = p.decide("apply", "m-not-listed", n)
            assert a.error is None and a.latency == 0.0

    def test_injector_counts_per_site_and_traces(self):
        inj = faults.install(self.plan())
        hits = 0
        for _ in range(6):
            try:
                inj.check("grpc", "m1")
            except InjectedFault as e:
                assert e.code == "UNAVAILABLE"
                hits += 1
        assert hits == 3  # ops 3,4,5 of the flap
        t1 = inj.trace_bytes()
        # replaying the same driver against a fresh injector reproduces the
        # trace byte-for-byte
        inj2 = faults.FaultInjector(self.plan())
        for _ in range(6):
            try:
                inj2.check("grpc", "m1")
            except InjectedFault:
                pass
        assert t1 == inj2.trace_bytes()

    def test_env_gate_installs_and_malformed_plan_raises(self, monkeypatch,
                                                         tmp_path):
        monkeypatch.setenv(faults.ENV_FAULT_PLAN, self.plan().to_json())
        faults.reset()
        assert faults.active() is not None
        faults.reset()
        f = tmp_path / "plan.json"
        f.write_text(self.plan().to_json())
        monkeypatch.setenv(faults.ENV_FAULT_PLAN, str(f))
        assert faults.active() is not None
        faults.reset()
        monkeypatch.setenv(faults.ENV_FAULT_PLAN, '{"rules": [{"boundary": "grpc", "kind": "nope"}]}')
        with pytest.raises(ValueError, match="unknown fault kind"):
            faults.install_from_env()

    def test_check_is_noop_without_plan(self, monkeypatch):
        monkeypatch.delenv(faults.ENV_FAULT_PLAN, raising=False)
        faults.reset()
        faults.check("grpc", "m1")  # must not raise

    def test_typoed_boundary_rejected_at_install(self):
        with pytest.raises(ValueError, match="unknown fault boundary"):
            faults.install(FaultPlan(seed=1, rules=[
                FaultRule(boundary="gprc", target="m1", kind="partition"),
            ]))

    def test_malformed_env_plan_raises_persistently(self, monkeypatch):
        """A broken chaos plan must never quietly become a clean run: the
        lazy env install fails on EVERY boundary hit, not just the first
        (which a broad except at some call site could swallow)."""
        monkeypatch.setenv(faults.ENV_FAULT_PLAN, '{"rules": "nope"}')
        faults.reset()
        with pytest.raises(Exception):
            faults.active()
        with pytest.raises(Exception):
            faults.active()  # still raising — not latched into silence
        with pytest.raises(Exception):
            faults.check("grpc", "m1")

    def test_env_gate_mints_exactly_one_injector(self, monkeypatch):
        """Repeated active() calls must return the SAME injector — a second
        install would reset per-site op counters and break replay."""
        monkeypatch.setenv(faults.ENV_FAULT_PLAN, self.plan().to_json())
        faults.reset()
        a = faults.active()
        b = faults.active()
        assert a is not None and a is b
        assert faults.active() is a


# ---------------------------------------------------------------------------
# circuit breaker (fake clock)
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def setup_method(self):
        self.t = [0.0]
        self.br = CircuitBreaker(
            "m1", failure_threshold=3, open_seconds=5.0,
            clock=lambda: self.t[0],
        )

    def test_closed_until_consecutive_threshold(self):
        for _ in range(2):
            self.br.record_failure()
        assert self.br.state == CLOSED and self.br.allow()
        self.br.record_success()  # success resets the streak
        for _ in range(2):
            self.br.record_failure()
        assert self.br.state == CLOSED
        self.br.record_failure()
        assert self.br.state == OPEN
        assert not self.br.allow()

    def _trip(self):
        for _ in range(3):
            self.br.record_failure()

    def test_half_open_probe_timing(self):
        self._trip()
        self.t[0] = 4.9
        assert not self.br.allow(), "open window not elapsed"
        self.t[0] = 5.0
        assert self.br.state == HALF_OPEN
        assert self.br.allow()  # the single probe
        assert not self.br.allow(), "only one probe admitted"

    def test_probe_failure_reopens_and_restarts_window(self):
        self._trip()
        self.t[0] = 5.0
        assert self.br.allow()
        self.br.record_failure()
        assert self.br.state == OPEN
        self.t[0] = 9.9  # window restarted at t=5.0
        assert not self.br.allow()
        self.t[0] = 10.0
        assert self.br.allow()

    def test_probe_success_closes(self):
        self._trip()
        self.t[0] = 5.0
        assert self.br.allow()
        self.br.record_success()
        assert self.br.state == CLOSED
        assert self.br.allow()

    def test_transition_metrics(self):
        from karmada_tpu.metrics import breaker_state, breaker_transitions

        before = breaker_transitions.value(member="m1", to=OPEN)
        self._trip()
        assert breaker_transitions.value(member="m1", to=OPEN) == before + 1
        assert breaker_state.value(member="m1") == 2.0
        self.t[0] = 5.0
        self.br.allow()
        self.br.record_success()
        assert breaker_state.value(member="m1") == 0.0

    def test_registry_open_members(self):
        t = [0.0]
        reg = BreakerRegistry(failure_threshold=1, open_seconds=5.0,
                              clock=lambda: t[0])
        reg.for_member("a").record_failure()
        reg.for_member("b").record_success()
        assert reg.open_members() == {"a"}
        assert reg.any_open()
        t[0] = 5.0  # half-open probes: no longer dark
        assert reg.open_members() == set()


# ---------------------------------------------------------------------------
# retry policy + backoff
# ---------------------------------------------------------------------------


class TestRetryPolicy:
    def test_full_jitter_delay_envelope(self):
        p = RetryPolicy(base_delay=1.0, max_delay=8.0, multiplier=2.0)
        assert p.delay(0, u=1.0) == 1.0
        assert p.delay(2, u=1.0) == 4.0
        assert p.delay(5, u=1.0) == 8.0  # capped
        assert p.delay(5, u=0.0) == 0.0  # full jitter reaches zero

    def test_run_retries_then_succeeds(self):
        calls = {"n": 0}
        sleeps: list[float] = []

        def fn():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionError("transient")
            return "ok"

        out = RetryPolicy(max_attempts=5).run(
            fn, retryable=lambda e: isinstance(e, ConnectionError),
            sleep=sleeps.append, rng=lambda: 1.0,
        )
        assert out == "ok" and calls["n"] == 3 and len(sleeps) == 2

    def test_run_gives_up_on_terminal_and_attempt_budget(self):
        with pytest.raises(ValueError):
            RetryPolicy().run(
                lambda: (_ for _ in ()).throw(ValueError("terminal")),
                retryable=lambda e: False, sleep=lambda s: None,
            )
        calls = {"n": 0}

        def always_fail():
            calls["n"] += 1
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            RetryPolicy(max_attempts=3).run(
                always_fail, retryable=lambda e: True,
                sleep=lambda s: None, rng=lambda: 0.5,
            )
        assert calls["n"] == 3

    def test_run_respects_deadline_budget(self):
        t = [0.0]
        calls = {"n": 0}

        def fail():
            calls["n"] += 1
            t[0] += 10.0  # each attempt burns 10s of the 15s budget
            raise ConnectionError("slow failure")

        with pytest.raises(ConnectionError):
            RetryPolicy(max_attempts=10, deadline=15.0,
                        base_delay=6.0, multiplier=1.0).run(
                fail, retryable=lambda e: True,
                sleep=lambda s: None, clock=lambda: t[0], rng=lambda: 1.0,
            )
        assert calls["n"] == 2  # attempt 3 would overrun the deadline

    def test_backoff_streak_and_reset(self):
        bo = Backoff(base=0.5, cap=2.0, rng=lambda: 1.0)
        assert [bo.next(), bo.next(), bo.next(), bo.next()] == \
            [0.5, 1.0, 2.0, 2.0]
        bo.reset()
        assert bo.next() == 0.5


# ---------------------------------------------------------------------------
# staleness penalty
# ---------------------------------------------------------------------------


class TestStaleness:
    def test_penalty_halves_per_epoch_and_keeps_sentinel(self):
        v = np.array([64, 1, 0, -1], np.int32)
        assert list(apply_staleness_penalty(v, 0)) == [64, 1, 0, -1]
        assert list(apply_staleness_penalty(v, 1)) == [32, 0, 0, -1]
        assert list(apply_staleness_penalty(v, 3)) == [8, 0, 0, -1]
        # age caps: stable past MAX_STALENESS_AGE (replay can re-engage)
        a = apply_staleness_penalty(v, faults.MAX_STALENESS_AGE)
        b = apply_staleness_penalty(v, faults.MAX_STALENESS_AGE + 5)
        assert list(a) == list(b)

    def test_tracker_round_trip(self):
        st = StalenessTracker()
        st.record_fresh("m1", ["a", "b", None], np.array([8, -1, 5]))
        col = st.fill_stale("m1", ["a", "b", "new"])
        assert list(col) == [4, -1, -1]  # age 1: halved; unknown → sentinel
        col = st.fill_stale("m1", ["a"])
        assert list(col) == [2]  # age 2
        st.record_fresh("m1", ["a"], np.array([100]))
        assert st.age("m1") == 0
        assert st.fill_stale("never-seen", ["a"]) is None


# ---------------------------------------------------------------------------
# typed per-manifest apply results + bounded re-dispatch
# ---------------------------------------------------------------------------


def _work_for(cluster: str, name: str, manifests: list[dict]):
    from karmada_tpu.api.meta import ObjectMeta, new_uid
    from karmada_tpu.api.work import (
        Work,
        WorkSpec,
        work_namespace_for_cluster,
    )

    return Work(
        metadata=ObjectMeta(
            namespace=work_namespace_for_cluster(cluster), name=name,
            uid=new_uid("work"),
        ),
        spec=WorkSpec(workload_manifests=manifests),
    )


def _manifest(name: str, replicas: int = 1) -> dict:
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"namespace": "default", "name": name},
        "spec": {"replicas": replicas},
    }


class TestManifestResults:
    def test_apply_returns_typed_results_with_same_message_strings(self):
        from karmada_tpu.controllers.execution import apply_work_manifests
        from karmada_tpu.interpreter.interpreter import ResourceInterpreter
        from karmada_tpu.members.member import InMemoryMember, MemberConfig

        member = InMemoryMember(MemberConfig(name="m1"))
        work = _work_for("m1", "w", [_manifest("app")])
        results = apply_work_manifests(work, member,
                                       ResourceInterpreter())
        assert len(results) == 1 and results[0].ok
        assert member.get("apps/v1", "Deployment", "app", "default") is not None

    def test_injected_apply_fault_is_retryable_and_message_format_stable(self):
        from karmada_tpu.controllers.execution import apply_work_manifests
        from karmada_tpu.interpreter.interpreter import ResourceInterpreter
        from karmada_tpu.members.member import InMemoryMember, MemberConfig

        faults.install(FaultPlan(seed=1, rules=[
            FaultRule(boundary="apply", target="m1", kind="partition"),
        ]))
        member = InMemoryMember(MemberConfig(name="m1"))
        work = _work_for("m1", "w", [_manifest("app")])
        results = apply_work_manifests(work, member, ResourceInterpreter())
        assert not results[0].ok and results[0].retryable
        # the Work-condition string format the controllers always wrote
        assert results[0].message.startswith("Deployment/app: ")

    def test_classification(self):
        from karmada_tpu.controllers.execution import classify_apply_error
        from karmada_tpu.store.store import ConflictError

        assert classify_apply_error(ConflictError("rv"))
        assert classify_apply_error(InjectedFault("apply", "m1"))
        assert classify_apply_error(ConnectionError("reset"))
        assert classify_apply_error(TimeoutError("deadline"))
        assert not classify_apply_error(ValueError("bad manifest"))
        assert not classify_apply_error(KeyError("missing"))

    def test_execution_controller_requeues_only_retryable(self):
        """A transient apply fault heals after 2 ops: the controller's
        bounded re-dispatch lands the manifest without operator action,
        and the Work condition carries the unchanged message strings
        while failing."""
        from karmada_tpu.api.meta import get_condition
        from karmada_tpu.api.work import WORK_CONDITION_APPLIED
        from karmada_tpu.controllers.execution import ExecutionController
        from karmada_tpu.interpreter.interpreter import ResourceInterpreter
        from karmada_tpu.members.member import InMemoryMember, MemberConfig
        from karmada_tpu.runtime.controller import Runtime
        from karmada_tpu.store.store import Store

        faults.install(FaultPlan(seed=1, rules=[
            FaultRule(boundary="apply", target="m1", kind="partition",
                      heal_after=2),
        ]))
        store = Store()
        runtime = Runtime()
        members = {"m1": InMemoryMember(MemberConfig(name="m1"))}
        ExecutionController(store, members, ResourceInterpreter(), runtime)
        store.create(_work_for("m1", "w", [_manifest("app")]))
        runtime.settle()
        work = store.get("Work", "w", "karmada-es-m1")
        cond = get_condition(work.status.conditions, WORK_CONDITION_APPLIED)
        assert cond is not None and cond.status == "True"
        assert members["m1"].get("apps/v1", "Deployment", "app",
                                 "default") is not None

    def test_terminal_failure_does_not_requeue(self):
        """A manifest the member rejects terminally parks on the condition:
        the queue must not spin on it (retry budget untouched)."""
        from karmada_tpu.api.meta import get_condition
        from karmada_tpu.api.work import WORK_CONDITION_APPLIED
        from karmada_tpu.controllers.execution import ExecutionController
        from karmada_tpu.interpreter.interpreter import ResourceInterpreter
        from karmada_tpu.members.member import InMemoryMember, MemberConfig
        from karmada_tpu.runtime.controller import Runtime
        from karmada_tpu.store.store import Store

        store = Store()
        runtime = Runtime()
        member = InMemoryMember(MemberConfig(name="m1"))
        applies = {"n": 0}
        orig = member.apply_manifest

        def failing_apply(manifest):
            applies["n"] += 1
            raise ValueError("field is immutable")

        member.apply_manifest = failing_apply
        ExecutionController(store, {"m1": member}, ResourceInterpreter(),
                            runtime)
        store.create(_work_for("m1", "w", [_manifest("app")]))
        runtime.settle()
        # event-driven reconciles (finalizer + condition updates) may apply
        # a couple of times, but the RETRY path must not engage: a
        # requeueing terminal failure would burn the whole 16-deep budget
        n0 = applies["n"]
        assert n0 <= 3, f"terminal failure re-dispatched {n0} times"
        runtime.settle()
        assert applies["n"] == n0, "terminal failure must reach a fixpoint"
        work = store.get("Work", "w", "karmada-es-m1")
        cond = get_condition(work.status.conditions, WORK_CONDITION_APPLIED)
        assert cond.status == "False"
        assert cond.message == "Deployment/app: field is immutable"
        member.apply_manifest = orig


# ---------------------------------------------------------------------------
# degraded estimator sweeps (breaker-open column → stale penalized rows)
# ---------------------------------------------------------------------------


class _FlakyRows:
    """Deterministic row estimator whose per-cluster legs raise while that
    cluster is 'dark' — the in-process stand-in for a member estimator
    daemon (answers per (binding, cluster) = 100 + 10·b + c)."""

    def __init__(self, breakers):
        self.breakers = breakers
        self.dark: set[str] = set()

    def max_available_replicas(self, clusters, requirements, replicas):
        out = []
        for c, cluster in enumerate(clusters):
            br = self.breakers.for_member(cluster)
            if not br.allow():
                out.append(-1)
                continue
            if cluster in self.dark:
                br.record_failure()
                from karmada_tpu.metrics import estimator_rpc_errors

                estimator_rpc_errors.inc(cluster=cluster, code="UNAVAILABLE")
                out.append(-1)
                continue
            br.record_success()
            out.append(100 + c)
        return out


def _dyn_binding(i: int, replicas: int = 4):
    from karmada_tpu.api.meta import CPU, ObjectMeta, new_uid
    from karmada_tpu.api import policy as pol
    from karmada_tpu.api.work import (
        BindingSpec,
        ObjectReference,
        ReplicaRequirements,
        ResourceBinding,
    )

    return ResourceBinding(
        metadata=ObjectMeta(namespace="default", name=f"app-{i}",
                            uid=f"rb-{i}"),
        spec=BindingSpec(
            resource=ObjectReference(api_version="apps/v1",
                                     kind="Deployment",
                                     namespace="default", name=f"app-{i}"),
            replicas=replicas,
            replica_requirements=ReplicaRequirements(
                resource_request={CPU: 0.1}),
            placement=pol.Placement(
                cluster_affinity=pol.ClusterAffinity(cluster_names=[]),
                replica_scheduling=pol.ReplicaSchedulingStrategy(
                    replica_scheduling_type=pol.REPLICA_SCHEDULING_DIVIDED,
                    replica_division_preference=(
                        pol.DIVISION_PREFERENCE_AGGREGATED),
                ),
            ),
        ),
    )


class TestDegradedSweep:
    def test_open_breaker_serves_penalized_stale_column(self):
        from karmada_tpu.estimator.client import EstimatorRegistry

        t = [0.0]
        breakers = BreakerRegistry(failure_threshold=2, open_seconds=60.0,
                                   clock=lambda: t[0])
        registry = EstimatorRegistry(breakers=breakers)
        est = _FlakyRows(breakers)
        registry.register_replica_estimator("flaky", est)
        bindings = [_dyn_binding(i) for i in range(3)]
        clusters = ["m1", "m2", "m3"]

        fresh = registry.batch_estimates(bindings, clusters)
        assert registry.last_sweep_open == []
        assert (fresh[:, 1] == 101).all()

        # the sweep runs one estimator leg per binding, so the 3 failed
        # legs of this sweep cross failure_threshold=2 and OPEN the breaker
        # mid-sweep — the overlay then serves the stale column immediately
        est.dark = {"m2"}
        out = registry.batch_estimates(bindings, clusters)
        assert registry.last_sweep_open == ["m2"]
        assert registry.last_sweep_stale == ["m2"]
        # the stale column is the last FRESH answer decayed by age 1
        assert (out[:, 1] == 101 >> 1).all()
        # healthy columns unaffected
        assert (out[:, 0] == 100).all() and (out[:, 2] == 102).all()

        # next degraded sweep decays further (age 2) — no estimator call
        # reaches the dark member (breaker fast-fails)
        out = registry.batch_estimates(bindings, clusters)
        assert (out[:, 1] == 101 >> 2).all()

        # heal: probe window elapses, the probe succeeds, fresh answers
        # return and the staleness epoch resets
        est.dark = set()
        t[0] = 60.0
        out = registry.batch_estimates(bindings, clusters)
        assert registry.last_sweep_open == []
        assert (out[:, 1] == 101).all()
        assert registry.staleness.age("m2") == 0

    def test_stale_column_min_merges_with_live_estimators(self):
        """Another registered estimator may still answer live for a
        breaker-open member (e.g. the model-based one): the stale decayed
        snapshot may only TIGHTEN or fill its column, never loosen it."""
        from karmada_tpu.estimator.client import EstimatorRegistry

        t = [0.0]
        breakers = BreakerRegistry(failure_threshold=1, open_seconds=60.0,
                                   clock=lambda: t[0])
        registry = EstimatorRegistry(breakers=breakers)
        flaky = _FlakyRows(breakers)
        registry.register_replica_estimator("flaky", flaky)

        class Model:
            """Live for every cluster regardless of member health."""

            answer = 200

            def max_available_replicas(self, clusters, requirements,
                                       replicas):
                return [self.answer] * len(clusters)

        model = Model()
        registry.register_replica_estimator("model", model)
        bindings = [_dyn_binding(i) for i in range(2)]

        fresh = registry.batch_estimates(bindings, ["m9"])
        assert (fresh[:, 0] == 100).all()  # min(member 100, model 200)

        flaky.dark = {"m9"}
        model.answer = 8  # the live model bound DROPS while m9 is dark
        out = registry.batch_estimates(bindings, ["m9"])
        assert registry.last_sweep_open == ["m9"]
        # stale decayed member answer is 100>>1 = 50, but the live model
        # says 8 — the merged column must keep the tighter live bound
        assert (out[:, 0] == 8).all()

    def test_http_only_plan_keeps_fused_fleet_kernel(self):
        from karmada_tpu.estimator.client import MemberEstimators

        faults.install(FaultPlan(seed=5, rules=[
            FaultRule(boundary="http", kind="error", rate=0.5),
        ]))
        me = MemberEstimators({}, breakers=BreakerRegistry())
        assert not me._guards_engaged(["m1"]), (
            "an http-only plan must not reroute the estimator sweep"
        )
        faults.install(FaultPlan(seed=5, rules=[
            FaultRule(boundary="grpc", target="m1", kind="flap"),
        ]))
        assert me._guards_engaged(["m1"])

    def test_error_metric_by_code(self):
        from karmada_tpu.metrics import estimator_rpc_errors

        t = [0.0]
        breakers = BreakerRegistry(failure_threshold=2, open_seconds=60.0,
                                   clock=lambda: t[0])
        registry = EstimatorRegistry = None  # noqa: F841 - clarity below
        from karmada_tpu.estimator.client import EstimatorRegistry

        registry = EstimatorRegistry(breakers=breakers)
        est = _FlakyRows(breakers)
        est.dark = {"m9"}
        registry.register_replica_estimator("flaky", est)
        before = estimator_rpc_errors.value(cluster="m9", code="UNAVAILABLE")
        registry.batch_estimates([_dyn_binding(0)], ["m9"])
        assert estimator_rpc_errors.value(
            cluster="m9", code="UNAVAILABLE") == before + 1


class TestGrpcClientBreakerOrdering:
    def test_addressless_leg_does_not_leak_half_open_probe(self):
        """_fanout resolves the call BEFORE breaker admission: a half-open
        probe slot consumed by a leg that never issues an RPC would never
        settle, sticking the breaker in HALF_OPEN and fast-failing the
        member forever."""
        from karmada_tpu.estimator.service import GrpcSchedulerEstimator

        t = [0.0]
        breakers = BreakerRegistry(failure_threshold=1, open_seconds=5.0,
                                   clock=lambda: t[0])
        client = GrpcSchedulerEstimator(lambda c: None, breakers=breakers)
        br = breakers.for_member("m1")
        br.record_failure()
        assert br.state == OPEN
        t[0] = 5.0
        assert br.state == HALF_OPEN
        out = client.max_available_replicas(["m1"], None, 1)
        assert out == [-1]
        assert br.state == HALF_OPEN
        assert br.allow(), (
            "the addressless leg must not have consumed the probe slot"
        )

    def test_addressless_batch_shard_does_not_leak_probe(self):
        from karmada_tpu.estimator.service import GrpcSchedulerEstimator

        t = [0.0]
        breakers = BreakerRegistry(failure_threshold=1, open_seconds=5.0,
                                   clock=lambda: t[0])
        client = GrpcSchedulerEstimator(lambda c: None, breakers=breakers)
        br = breakers.for_member("m1")
        br.record_failure()
        t[0] = 5.0
        out = client.batch_max_available_replicas(["m1"], [None])
        assert out.tolist() == [[-1]]
        assert br.allow(), "batch shard leaked the half-open probe slot"


class TestMemberEstimatorsGuard:
    def test_injected_grpc_fault_feeds_breaker_and_sentinel(self):
        from karmada_tpu.api.meta import CPU, MEMORY
        from karmada_tpu.estimator.client import (
            MemberEstimators,
            UNAUTHENTIC_REPLICA,
        )
        from karmada_tpu.members.member import InMemoryMember, MemberConfig
        from karmada_tpu.models.nodes import NodeSpec

        GiB = 1024.0 ** 3
        faults.install(FaultPlan(seed=3, rules=[
            FaultRule(boundary="grpc", target="m1", kind="partition"),
        ]))
        breakers = BreakerRegistry(failure_threshold=2, open_seconds=60.0)
        members = {
            name: InMemoryMember(MemberConfig(
                name=name,
                nodes=[NodeSpec(name="n1",
                                allocatable={CPU: 10.0, MEMORY: 40 * GiB})],
            ))
            for name in ("m1", "m2")
        }
        me = MemberEstimators(members, breakers=breakers)
        from karmada_tpu.api.work import ReplicaRequirements

        req = ReplicaRequirements(resource_request={CPU: 1.0})
        out = me.max_available_replicas(["m1", "m2"], req, 4)
        assert out[0] == UNAUTHENTIC_REPLICA  # injected
        assert out[1] > 0  # healthy member answers
        me.max_available_replicas(["m1", "m2"], req, 4)
        assert breakers.for_member("m1").state == OPEN
        assert breakers.for_member("m2").state == CLOSED


# -- process-level fault vocabulary (soak harness; docs/ROBUSTNESS.md) ------


class TestProcessFaultRules:
    def test_schedule_is_deterministic_bytes(self):
        from karmada_tpu.faults import ProcessFaultRule

        rules = [
            ProcessFaultRule(kind="leader_kill", wave=2),
            ProcessFaultRule(kind="shard_kill", rate=0.5),
            ProcessFaultRule(kind="partition", target="follower-1",
                             rate=0.3),
            ProcessFaultRule(kind="estimator_blackout", wave=0),
        ]
        a = FaultPlan(seed=11, process_rules=rules)
        b = FaultPlan(seed=11, process_rules=list(rules))
        assert a.process_schedule(16) == b.process_schedule(16)
        # seed moves the probabilistic firings
        c = FaultPlan(seed=12, process_rules=rules)
        assert a.process_schedule(64) != c.process_schedule(64)

    def test_pinned_wave_fires_exactly_once(self):
        from karmada_tpu.faults import ProcessFaultRule

        plan = FaultPlan(seed=7, process_rules=[
            ProcessFaultRule(kind="leader_kill", wave=3)])
        fired = [(w, e.kind) for w in range(8)
                 for e in plan.process_events(w)]
        assert fired == [(3, "leader_kill")]

    def test_rate_one_fires_every_wave(self):
        from karmada_tpu.faults import ProcessFaultRule

        plan = FaultPlan(seed=7, process_rules=[
            ProcessFaultRule(kind="shard_kill", rate=1.0)])
        assert all(plan.process_events(w) for w in range(6))

    def test_serialization_round_trip(self):
        from karmada_tpu.faults import ProcessFaultRule

        plan = FaultPlan(
            seed=5,
            rules=[FaultRule(boundary="http", kind="error", rate=0.1)],
            process_rules=[
                ProcessFaultRule(kind="partition", target="follower-0",
                                 wave=1, rate=0.25),
            ],
        )
        back = FaultPlan.from_dict(__import__("json").loads(plan.to_json()))
        assert back.process_schedule(32) == plan.process_schedule(32)
        assert back.process_rules == plan.process_rules

    def test_empty_process_rules_not_serialized(self):
        plan = FaultPlan(seed=5, rules=[
            FaultRule(boundary="http", kind="error", rate=0.1)])
        assert "process_rules" not in plan.to_json()

    def test_validate_rejects_bad_rules(self):
        from karmada_tpu.faults import ProcessFaultRule

        with pytest.raises(ValueError):
            FaultPlan(seed=1, process_rules=[
                ProcessFaultRule(kind="meteor_strike")]).validate()
        with pytest.raises(ValueError):
            FaultPlan(seed=1, process_rules=[
                ProcessFaultRule(kind="leader_kill", rate=1.5)]).validate()
        with pytest.raises(ValueError):
            FaultPlan(seed=1, process_rules=[
                ProcessFaultRule(kind="leader_kill", wave=-2)]).validate()


# -- retry/backoff audit pins (satellite: every boundary site jittered) -----


class TestRemoteStoreRetryPolicies:
    """remote.py used bare `0.2 * (attempt + 1)` sleeps on its write
    paths — linear, uncapped, and synchronized across clients (thundering
    herd on leader failover). Pinned here: both sites now ride RetryPolicy
    full-jitter with a hard cap."""

    def test_write_retry_full_jitter_envelope(self):
        from karmada_tpu.server.remote import BATCH_RETRY, WRITE_RETRY

        for policy, base in ((WRITE_RETRY, 0.2), (BATCH_RETRY, 0.1)):
            assert policy.max_delay <= 2.0
            for attempt in range(8):
                ceiling = min(policy.max_delay,
                              base * policy.multiplier ** attempt)
                draws = {policy.delay(attempt) for _ in range(64)}
                assert all(0.0 <= d <= ceiling for d in draws)
                # full jitter, not a constant: draws actually spread
                assert len(draws) > 1

    def test_write_call_sleeps_through_policy(self, monkeypatch):
        """The stale-redirect fallback in RemoteStore._write_call must
        take its sleeps from WRITE_RETRY (jittered + capped), not the old
        bare `0.2 * (attempt + 1)` formula."""
        import threading

        from karmada_tpu.server import remote as remote_mod
        from karmada_tpu.server.remote import (
            LeaderRedirect,
            RemoteError,
            RemoteStore,
        )

        rs = RemoteStore.__new__(RemoteStore)
        rs.timeout = 0.01
        rs.read_preference = "leader"
        rs._replicas = []
        rs._trace_tl = threading.local()
        rs._set_base("http://origin:1")

        # scripted transport: redirect, then the redirect target is dead
        # (the stale-failover window) — twice — then the origin dies too
        script = [
            LeaderRedirect("moved", "http://stale:2"),
            RemoteError("redirect target unreachable"),
            LeaderRedirect("moved", "http://stale:2"),
            RemoteError("redirect target unreachable"),
            RemoteError("origin unreachable"),
        ]
        monkeypatch.setattr(
            RemoteStore, "_call",
            lambda self, m, p, b=None: (_ for _ in ()).throw(
                script.pop(0)))

        slept = []
        monkeypatch.setattr(remote_mod.time, "sleep", slept.append)
        sentinel = {1: 0.123, 3: 0.456}

        class StubPolicy:
            def delay(self, attempt, u=None):
                return sentinel[attempt]

        monkeypatch.setattr(remote_mod, "WRITE_RETRY", StubPolicy())
        with pytest.raises(RemoteError):
            rs._write_call("POST", "/create", {"x": 1})
        # both post-redirect fallbacks slept through the policy
        assert slept == [0.123, 0.456]


class TestShardResizeListRetry:
    """Regression pinned from the soak (wave `shard_kill` under http
    chaos): ShardedDaemon.set_total / relist listed bindings over the
    wire UNGUARDED — one injected 503 during the map-resize sweep killed
    the resize and left the handoff fence stuck. Both now ride a bounded
    transient-only RetryPolicy."""

    def _daemon(self, store):
        from karmada_tpu.sched.shards.daemon import ShardedDaemon

        d = ShardedDaemon.__new__(ShardedDaemon)
        d.store = store
        return d

    def test_transient_remote_errors_are_retried(self, monkeypatch):
        from karmada_tpu.server.remote import RemoteError

        calls = {"n": 0}

        class FlakyStore:
            def list(self, kind):
                calls["n"] += 1
                if calls["n"] < 3:
                    raise RemoteError("injected fault [http] UNAVAILABLE")
                return ["rb-sentinel"]

        monkeypatch.setattr("time.sleep", lambda s: None)
        out = self._daemon(FlakyStore())._list_bindings_retried()
        assert out == ["rb-sentinel"]
        assert calls["n"] == 3

    def test_terminal_errors_escape_immediately(self):
        from karmada_tpu.store.store import ConflictError

        calls = {"n": 0}

        class ConflictStore:
            def list(self, kind):
                calls["n"] += 1
                raise ConflictError("not transient")

        with pytest.raises(ConflictError):
            self._daemon(ConflictStore())._list_bindings_retried()
        assert calls["n"] == 1

    def test_set_total_resets_handoff_state_on_failure(self, monkeypatch):
        """Even when the retried list exhausts its budget, the resize
        must drop the handoff fence — a permanently-stuck 'resizing'
        state was the failure mode the soak exposed."""
        from karmada_tpu.sched.shards import ShardMap
        from karmada_tpu.sched.shards.daemon import ShardedDaemon
        from karmada_tpu.server.remote import RemoteError

        d = ShardedDaemon.__new__(ShardedDaemon)
        d.shards = ShardMap(0, 2)
        d._handoff_state = ""
        monkeypatch.setattr(
            ShardedDaemon, "_list_bindings_retried",
            lambda self: (_ for _ in ()).throw(RemoteError("exhausted")))
        with pytest.raises(RemoteError):
            d.set_total(1)
        assert d._handoff_state == ""
        assert d.shards.total == 1  # the map swap itself is committed

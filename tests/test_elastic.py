"""Closed-loop elasticity plane (karmada_tpu/elastic — docs/ELASTICITY.md).

Runs without the cryptography stack: the topologies here are bare Store +
InMemoryMember fleets (like tests/test_watchcache.py's stub plane), with
Duplicated member semantics simulated by the `_Plane` helper and the real
streaming scheduler attached where re-admission is the claim under test.
"""
from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from karmada_tpu.api.autoscaling import (
    CronFederatedHPA,
    CronFederatedHPARule,
    CronFederatedHPASpec,
    FederatedHPA,
    FederatedHPASpec,
    HPABehavior,
    KIND_WORKLOAD_METRICS_REPORT,
    ResourceMetricSource,
    ScaleTargetRef,
)
from karmada_tpu.api.meta import CPU, ObjectMeta, new_uid
from karmada_tpu.controllers.autoscaling import (
    HPA_TOLERANCE,
    _template_kinds,
    hpa_desired_replicas,
)
from karmada_tpu.elastic import (
    ElasticityDaemon,
    build_metrics_report,
    publish_report,
    solve_step,
    workload_key,
)
from karmada_tpu.elastic.solver import empty_inputs
from karmada_tpu.interpreter.interpreter import ResourceInterpreter
from karmada_tpu.members.member import (
    InMemoryMember,
    MemberConfig,
    cluster_object_for,
)
from karmada_tpu.runtime.controller import Clock
from karmada_tpu.store.store import Store
from karmada_tpu.testing.fixtures import new_deployment


def fhpa(name="hpa", target="web", ns="default", min_r=1, max_r=10,
         target_util=50, scale_to_zero=False, up_s=0.0, down_s=0.0):
    return FederatedHPA(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=FederatedHPASpec(
            scale_target_ref=ScaleTargetRef(kind="Deployment", name=target),
            min_replicas=min_r, max_replicas=max_r,
            metrics=[ResourceMetricSource(
                name="cpu", target_average_utilization=target_util)],
            behavior=HPABehavior(
                scale_up_stabilization_seconds=up_s,
                scale_down_stabilization_seconds=down_s,
            ),
            scale_to_zero=scale_to_zero,
        ),
    )


def _divided_placement():
    from karmada_tpu.api.policy import (
        DIVISION_PREFERENCE_WEIGHTED,
        DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
        ClusterAffinity,
        ClusterPreferences,
        Placement,
        REPLICA_SCHEDULING_DIVIDED,
        ReplicaSchedulingStrategy,
    )

    return Placement(
        cluster_affinity=ClusterAffinity(cluster_names=[]),
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=DIVISION_PREFERENCE_WEIGHTED,
            weight_preference=ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
        ),
    )


class _Plane:
    """Crypto-free mini control plane: bare store, in-memory members with
    Duplicated semantics (every member runs each template's replica count),
    a closed-loop demand model (per-pod usage = total demand / total ready,
    so scaling actually RELIEVES utilization), and the elasticity daemon."""

    def __init__(self, n_members=2, hysteresis=True, preflight=False,
                 ns="default", **daemon_kw):
        self.ns = ns
        self.clock = Clock(fixed=1_700_000_000.0)
        self.store = Store()
        self.members: dict[str, InMemoryMember] = {}
        for i in range(n_members):
            cfg = MemberConfig(name=f"m{i + 1}",
                               allocatable={"cpu": 100.0, "pods": 500.0})
            m = InMemoryMember(cfg)
            self.members[cfg.name] = m
            self.store.create(cluster_object_for(cfg))
        self.daemon = ElasticityDaemon(
            self.store, self.clock, interpreter=ResourceInterpreter(),
            hysteresis=hysteresis, preflight=preflight, **daemon_kw,
        )
        self.demand: dict[str, float] = {}  # template name -> total demand

    def add_workload(self, name="web", replicas=2, cpu=1.0, ns=None):
        dep = new_deployment(ns or self.ns, name, replicas=replicas, cpu=cpu)
        self.store.create(dep)
        return dep

    def set_usage(self, name, cpu, ns=None):
        """Open-loop per-pod usage (mirrors member.set_workload_usage)."""
        for m in self.members.values():
            m.set_workload_usage("Deployment", ns or self.ns, name,
                                 {"cpu": cpu})

    def ready_total(self, name, ns=None) -> int:
        total = 0
        for m in self.members.values():
            ready, _ = m.pod_metrics("Deployment", ns or self.ns, name)
            total += ready
        return total

    def _sync_members(self):
        for dep in self.store.list("apps/v1/Deployment"):
            man = dep.to_dict()
            man.pop("status", None)
            for m in self.members.values():
                m.apply_manifest(man)

    def collect(self):
        for m in self.members.values():
            publish_report(self.store,
                           build_metrics_report(m, self.clock.now()))

    def tick(self, seconds=1.0):
        """Advance time, converge members, apply the demand model, publish
        reports, run ONE daemon step."""
        if seconds:
            self.clock.advance(seconds)
        self._sync_members()
        for name, demand in self.demand.items():
            ready = self.ready_total(name)
            self.set_usage(name, demand / max(ready, 1))
        self.collect()
        return self.daemon.step()

    def replicas(self, name="web", ns=None) -> int:
        dep = self.store.get("apps/v1/Deployment", name, ns or self.ns)
        return int(dep.get("spec", "replicas"))


# -- satellite: template-kind index ----------------------------------------


class TestTemplateKindIndex:
    def test_lookup_cached_until_kind_registration(self):
        store = Store()
        store.create(new_deployment("default", "a"))
        calls = {"n": 0}
        orig = store.kinds

        def counting_kinds():
            calls["n"] += 1
            return orig()

        store.kinds = counting_kinds
        assert _template_kinds(store, "Deployment") == ["apps/v1/Deployment"]
        warm = calls["n"]
        for _ in range(50):
            assert _template_kinds(store, "Deployment") == [
                "apps/v1/Deployment"
            ]
        # HPA reconciles stop being O(kinds) store scans: repeated lookups
        # answer from the index, not a rescan
        assert calls["n"] == warm

        # kind registration invalidates: a new gvk bucket must surface
        from karmada_tpu.api.unstructured import Unstructured

        store.create(Unstructured({
            "apiVersion": "batch/v1", "kind": "Deployment",
            "metadata": {"namespace": "default", "name": "other"},
            "spec": {},
        }))
        got = _template_kinds(store, "Deployment")
        assert sorted(got) == ["apps/v1/Deployment", "batch/v1/Deployment"]
        assert calls["n"] > warm

    def test_index_is_per_store(self):
        s1, s2 = Store(), Store()
        s1.create(new_deployment("d", "a"))
        assert _template_kinds(s1, "Deployment") == ["apps/v1/Deployment"]
        assert _template_kinds(s2, "Deployment") == []


# -- satellite: vectorized/scalar bit parity -------------------------------


def _scalar_reference(current, ready, rows, lo, hi):
    """The per-object FederatedHPAController answer: the factored scalar
    algorithm + the reconcile clamp (ready==0 / current<=0 hold first)."""
    if current <= 0 or ready <= 0:
        desired = current
    else:
        desired, _ = hpa_desired_replicas(current, ready, rows)
    return max(lo, min(desired, hi))


class TestVectorizedParity:
    def test_randomized_sweep_matches_per_hpa_algorithm(self):
        """W x C randomized sweep: the vectorized step's desired replicas
        are IDENTICAL to the existing per-HPA algorithm for every workload
        — tolerance band, min/max clamp, and ceil edge cases included."""
        rng = np.random.default_rng(7)
        for trial in range(4):
            w = 257
            m = 3
            current = rng.integers(0, 40, size=w)
            ready = rng.integers(0, 120, size=w)
            lo = rng.integers(1, 5, size=w)
            hi = lo + rng.integers(0, 60, size=w)
            inp = empty_inputs(w, m)
            inp.current[:] = current
            inp.ready[:] = ready
            inp.min_r[:] = lo
            inp.max_r[:] = hi
            scalar_rows: list[list[tuple]] = [[] for _ in range(w)]
            for wi in range(w):
                n_metrics = int(rng.integers(0, m + 1))
                for mi in range(n_metrics):
                    req = float(rng.choice([0.25, 0.5, 1.0, 2.0]))
                    target = float(rng.choice([50, 60, 80, 100]))
                    kind = rng.integers(0, 5)
                    if kind == 0:   # exactly on-target (inside tolerance)
                        avg = req * target / 100.0
                    elif kind == 1:  # exactly AT the tolerance edge
                        avg = req * target / 100.0 * (1.0 + HPA_TOLERANCE)
                    elif kind == 2:  # ceil edge: ready*ratio lands integer
                        avg = req * target / 100.0 * 2.0
                    elif kind == 3:  # zero usage
                        avg = 0.0
                    else:
                        avg = float(rng.uniform(0.0, 3.0)) * req
                    inp.avg_usage[wi, mi] = avg
                    inp.request[wi, mi] = req
                    inp.target[wi, mi] = target
                    inp.valid[wi, mi] = True
                    scalar_rows[wi].append((avg, req, target))
            got = solve_step(inp, None, [f"w{i}" for i in range(w)],
                             now=0.0).desired
            want = np.array([
                _scalar_reference(int(current[wi]), int(ready[wi]),
                                  scalar_rows[wi], int(lo[wi]), int(hi[wi]))
                for wi in range(w)
            ])
            assert (got == want).all(), (
                f"trial {trial}: mismatch rows "
                f"{np.nonzero(got != want)[0][:5]}"
            )

    def test_closed_loop_matches_controller_numbers(self):
        """End to end through reports + matrix: the exact numbers the
        per-object controller suite pins (4 ready at 90% vs target 50 ->
        8; within-tolerance holds; min clamp)."""
        p = _Plane()
        p.add_workload("web", replicas=2, cpu=1.0)
        p.store.create(fhpa(target_util=50))
        p._sync_members()
        p.set_usage("web", 0.9)
        p.collect()
        p.daemon.step()
        assert p.replicas("web") == 8  # ready 4, ratio 1.8 -> ceil(4*1.8)
        hpa = p.store.get("FederatedHPA", "hpa", "default")
        assert hpa.status.desired_replicas == 8
        assert hpa.status.current_average_utilization == 90

    def test_within_tolerance_holds(self):
        p = _Plane()
        p.add_workload("web", replicas=2, cpu=1.0)
        p.store.create(fhpa(target_util=50))
        p._sync_members()
        p.set_usage("web", 0.52)  # 4% over target < 10% tolerance
        p.collect()
        p.daemon.step()
        assert p.replicas("web") == 2
        assert p.daemon.stats["scale_ups"] == 0

    def test_min_clamp(self):
        p = _Plane()
        p.add_workload("web", replicas=4, cpu=1.0)
        p.store.create(fhpa(min_r=2, target_util=80))
        p._sync_members()
        p.set_usage("web", 0.05)
        p.collect()
        p.daemon.step()
        assert p.replicas("web") == 2

    def test_one_vectorized_launch_for_all_workloads(self):
        """W workloads cost ONE solve launch per tick — never a per-HPA
        loop."""
        import karmada_tpu.elastic.daemon as daemon_mod

        p = _Plane()
        w = 17
        for i in range(w):
            p.add_workload(f"app-{i}", replicas=2, cpu=1.0)
            p.store.create(fhpa(name=f"hpa-{i}", target=f"app-{i}"))
            p.demand[f"app-{i}"] = 3.0
        calls = {"n": 0}
        orig = daemon_mod.solve_step

        def counting(*a, **kw):
            calls["n"] += 1
            return orig(*a, **kw)

        daemon_mod.solve_step = counting
        try:
            for _ in range(3):
                p.tick()
        finally:
            daemon_mod.solve_step = orig
        assert calls["n"] == 3  # one launch per tick, 17 workloads each
        assert p.daemon.stats["solves"] == p.daemon.stats["ticks"] == 3
        assert p.daemon.last_step_stats["workloads"] == w


# -- satellite: fake-clock hysteresis --------------------------------------


class TestHysteresis:
    def test_flap_inside_window_zero_scale_events(self):
        """A metric flapping inside BOTH stabilization windows produces
        ZERO scale events."""
        p = _Plane()
        p.add_workload("web", replicas=4, cpu=1.0)
        p.store.create(fhpa(min_r=1, max_r=20, target_util=50,
                            up_s=30.0, down_s=300.0))
        # seed the ring with steady history at the current level
        p.demand["web"] = 4.0  # per-pod 0.5 -> exactly on target
        for _ in range(3):
            p.tick()
        assert p.replicas("web") == 4
        # flap demand hi/lo every tick, well inside the 30 s up window
        for i in range(10):
            p.demand["web"] = 14.0 if i % 2 == 0 else 0.5
            p.tick()
        assert p.replicas("web") == 4
        assert p.daemon.stats["scale_ups"] == 0
        assert p.daemon.stats["scale_downs"] == 0

    def test_sustained_spike_scales_exactly_once(self):
        p = _Plane()
        p.add_workload("web", replicas=4, cpu=1.0)
        p.store.create(fhpa(min_r=1, max_r=20, target_util=50,
                            up_s=3.0, down_s=300.0))
        p.demand["web"] = 4.0
        for _ in range(3):
            p.tick()
        assert p.daemon.stats["scale_ups"] == 0
        # sustained spike: desired ceil(14/(1.0*0.5)) = 28 -> clamp 20;
        # held while pre-spike recommendations sit in the up window, then
        # ONE scale event, then steady (closed loop: utilization relieved)
        p.demand["web"] = 14.0
        for _ in range(8):
            p.tick()
        assert p.replicas("web") == 20
        assert p.daemon.stats["scale_ups"] == 1
        assert p.daemon.stats["scale_downs"] == 0

    def test_no_hysteresis_leg_flaps(self):
        """The same flapping trace WITHOUT hysteresis scales on every
        transition — the counterfactual the bench quantifies at >=5x."""
        p = _Plane(hysteresis=False)
        p.add_workload("web", replicas=4, cpu=1.0)
        p.store.create(fhpa(min_r=1, max_r=20, target_util=50))
        p.demand["web"] = 4.0
        for _ in range(3):
            p.tick()
        for i in range(10):
            p.demand["web"] = 14.0 if i % 2 == 0 else 0.5
            p.tick()
        events = p.daemon.stats["scale_ups"] + p.daemon.stats["scale_downs"]
        assert events >= 5

    def test_scale_to_zero_and_resurrection(self):
        p = _Plane()
        p.add_workload("web", replicas=2, cpu=1.0)
        p.store.create(fhpa(min_r=0, max_r=10, target_util=50,
                            scale_to_zero=True, up_s=0.0, down_s=2.0))
        p.demand["web"] = 2.0
        for _ in range(3):
            p.tick()
        assert p.replicas("web") == 2  # per-pod 0.5 = exactly on target
        # demand vanishes: utilization 0 -> recommendation 0, applied once
        # the down window drains
        p.demand["web"] = 0.0
        for _ in range(5):
            p.tick()
        assert p.replicas("web") == 0
        hpa = p.store.get("FederatedHPA", "hpa", "default")
        assert hpa.status.desired_replicas == 0
        # cold resurrection: demand returns while ZERO pods are ready —
        # the zero-ready demand rows wake the workload at one replica,
        # then the loop right-sizes it
        p.demand["web"] = 3.0
        p.tick()
        assert p.replicas("web") == 1
        assert p.daemon.stats["resurrected"] == 1
        for _ in range(3):
            p.tick()
        assert p.replicas("web") == 6  # ceil(3/0.5)


# -- cron fold -------------------------------------------------------------


class TestCronFold:
    def test_cron_updates_hpa_bounds_as_matrix_rows(self):
        p = _Plane()
        p.add_workload("web", replicas=2, cpu=1.0)
        p.store.create(fhpa(min_r=1, max_r=10))
        p.store.create(CronFederatedHPA(
            metadata=ObjectMeta(name="peak", namespace="default"),
            spec=CronFederatedHPASpec(
                scale_target_ref=ScaleTargetRef(kind="FederatedHPA",
                                                name="hpa"),
                rules=[CronFederatedHPARule(
                    name="peak", schedule="* * * * *",
                    target_min_replicas=4, target_max_replicas=20)],
            ),
        ))
        p.tick(seconds=90)  # rule fires; the new MIN bound row forces 2->4
        hpa = p.store.get("FederatedHPA", "hpa", "default")
        assert hpa.spec.min_replicas == 4
        assert hpa.spec.max_replicas == 20
        assert p.replicas("web") == 4
        assert p.daemon.stats["cron_fired"] == 1

    def test_cron_pins_workload_without_hpa(self):
        p = _Plane()
        p.add_workload("web", replicas=2, cpu=1.0)
        p.store.create(CronFederatedHPA(
            metadata=ObjectMeta(name="night", namespace="default"),
            spec=CronFederatedHPASpec(
                scale_target_ref=ScaleTargetRef(kind="Deployment",
                                                name="web"),
                rules=[CronFederatedHPARule(name="night",
                                            schedule="* * * * *",
                                            target_replicas=6)],
            ),
        ))
        p.tick(seconds=120)
        assert p.replicas("web") == 6
        cron = p.store.get("CronFederatedHPA", "night", "default")
        assert cron.status.execution_histories[0].last_result == "Succeed"

    def test_bad_schedule_records_failure(self):
        p = _Plane()
        p.add_workload("web", replicas=2, cpu=1.0)
        cron = CronFederatedHPA(
            metadata=ObjectMeta(name="bad", namespace="default"),
            spec=CronFederatedHPASpec(
                scale_target_ref=ScaleTargetRef(kind="Deployment",
                                                name="web"),
                rules=[CronFederatedHPARule(name="bad", schedule="nope",
                                            target_replicas=1)],
            ),
        )
        # bypass admission (bare store has no webhook chain): the daemon
        # must still record the parse failure instead of crashing the tick
        p.store.create(cron)
        p.tick(seconds=60)
        cron = p.store.get("CronFederatedHPA", "bad", "default")
        assert cron.status.execution_histories[0].last_result == "Failed"
        assert p.replicas("web") == 2


# -- aggregation / reports -------------------------------------------------


class TestReports:
    def test_report_rows_and_demand_signal(self):
        cfg = MemberConfig(name="m1", allocatable={"cpu": 100.0})
        m = InMemoryMember(cfg)
        dep = new_deployment("default", "web", replicas=3, cpu=1.0)
        man = dep.to_dict()
        m.apply_manifest(man)
        m.set_workload_usage("Deployment", "default", "web", {"cpu": 0.7})
        report = build_metrics_report(m, now=123.0)
        assert report.cluster == "m1" and report.reported_at == 123.0
        (row,) = report.rows
        assert (row.ready_pods, row.usage) == (3, {"cpu": 0.7})
        assert row.demand == {}
        # scale the workload to zero: the usage entry becomes the DEMAND
        # row (no ready pods -> no pod metrics, but traffic still knocks)
        man["spec"]["replicas"] = 0
        m.apply_manifest(man)
        report = build_metrics_report(m, now=124.0)
        (row,) = report.rows
        assert row.ready_pods == 0
        assert row.usage == {} and row.demand == {"cpu": 0.7}

    def test_publish_is_change_suppressed(self):
        store = Store()
        cfg = MemberConfig(name="m1", allocatable={"cpu": 100.0})
        m = InMemoryMember(cfg)
        m.apply_manifest(new_deployment("d", "w", replicas=1,
                                        cpu=0.5).to_dict())
        m.set_workload_usage("Deployment", "d", "w", {"cpu": 0.1})
        assert publish_report(store, build_metrics_report(m, 1.0))
        rv = store.current_rv
        # identical rows, fresher timestamp: NO write (freshness is the
        # resourceVersion's job, not reported_at's)
        assert not publish_report(store, build_metrics_report(m, 2.0))
        assert store.current_rv == rv
        m.set_workload_usage("Deployment", "d", "w", {"cpu": 0.2})
        assert publish_report(store, build_metrics_report(m, 3.0))
        assert store.current_rv > rv

    def test_not_ready_cluster_stops_feeding_the_matrix(self):
        """A crashed/partitioned member's last retained report must not
        keep phantom ready pods in the solve: flipping its Cluster Ready
        condition excludes it from the fold."""
        from karmada_tpu.api.cluster import CLUSTER_CONDITION_READY
        from karmada_tpu.api.meta import Condition, set_condition

        p = _Plane(n_members=2)
        p.add_workload("web", replicas=2, cpu=1.0)
        p.store.create(fhpa(target_util=50))
        p._sync_members()
        p.set_usage("web", 0.9)
        p.collect()
        p.daemon.step()
        assert p.replicas("web") == 8  # both members' pods count (4 ready)
        # m2 "crashes": its report is retained but its cluster goes NotReady
        c = p.store.get("Cluster", "m2")
        set_condition(c.status.conditions, Condition(
            type=CLUSTER_CONDITION_READY, status="False",
            reason="ClusterLeaseExpired"))
        p.store.update(c)
        p.daemon.step()
        hpa = p.store.get("FederatedHPA", "hpa", "default")
        # only m1's 8 pods remain in the matrix now (the solve re-derives
        # from half the ready pool instead of the dead member's ghost rows)
        assert p.daemon.last_step_stats["workloads"] == 1
        assert hpa.status.current_replicas == 8

    def test_deleted_report_drops_cluster_rows(self):
        from karmada_tpu.api.autoscaling import KIND_WORKLOAD_METRICS_REPORT
        from karmada_tpu.elastic import UtilizationAggregator

        store = Store()
        cfg = MemberConfig(name="m1", allocatable={"cpu": 100.0})
        m = InMemoryMember(cfg)
        m.apply_manifest(new_deployment("d", "w", replicas=2,
                                        cpu=0.5).to_dict())
        m.set_workload_usage("Deployment", "d", "w", {"cpu": 0.4})
        agg = UtilizationAggregator(store)
        publish_report(store, build_metrics_report(m, 1.0))
        key = workload_key("Deployment", "d", "w")
        assert agg.snapshot([key], ["cpu"]).ready_total()[0] == 2
        store.delete(KIND_WORKLOAD_METRICS_REPORT, "m1")
        assert agg.snapshot([key], ["cpu"]).ready_total()[0] == 0

    def test_agent_heartbeat_publishes_report(self):
        """The pull path: KarmadaAgent.heartbeat() publishes the member's
        report when metrics_reports is on (the coalesced status seam)."""
        from karmada_tpu.agent import KarmadaAgent
        from karmada_tpu.runtime.controller import Runtime

        store = Store()
        cfg = MemberConfig(name="m1", allocatable={"cpu": 100.0},
                           sync_mode="Pull")
        m = InMemoryMember(cfg)
        store.create(cluster_object_for(cfg))
        runtime = Runtime(clock=Clock(fixed=1_700_000_000.0))
        agent = KarmadaAgent(store, m, ResourceInterpreter(), runtime,
                             metrics_reports=True)
        m.apply_manifest(new_deployment("d", "w", replicas=2,
                                        cpu=0.5).to_dict())
        m.set_workload_usage("Deployment", "d", "w", {"cpu": 0.4})
        agent.heartbeat()
        report = store.get(KIND_WORKLOAD_METRICS_REPORT, "m1")
        assert report.rows[0].ready_pods == 2


# -- quota preflight veto --------------------------------------------------


class TestPreflightVeto:
    def test_scale_up_stranding_replicas_is_vetoed(self):
        from karmada_tpu.api.search import (
            FederatedResourceQuota,
            FederatedResourceQuotaSpec,
            StaticClusterAssignment,
        )
        from karmada_tpu.api.work import (
            BindingSpec,
            ObjectReference,
            ReplicaRequirements,
            ResourceBinding,
            TargetCluster,
        )

        p = _Plane(n_members=2, preflight=True)
        p.add_workload("web", replicas=2, cpu=30.0)
        p.store.create(fhpa(min_r=1, max_r=10, target_util=50))
        # the binding the preflight re-solves (30 cpu/replica)
        p.store.create(ResourceBinding(
            metadata=ObjectMeta(namespace="default", name="web-deployment",
                                uid=new_uid("rb")),
            spec=BindingSpec(
                resource=ObjectReference(api_version="apps/v1",
                                         kind="Deployment",
                                         namespace="default", name="web"),
                replicas=2, placement=_divided_placement(),
                replica_requirements=ReplicaRequirements(
                    resource_request={CPU: 30.0}),
                clusters=[TargetCluster(name="m1", replicas=1),
                          TargetCluster(name="m2", replicas=1)],
            ),
        ))
        p.store.create(FederatedResourceQuota(
            metadata=ObjectMeta(namespace="default", name="caps"),
            spec=FederatedResourceQuotaSpec(
                overall={CPU: 120.0},
                static_assignments=[
                    StaticClusterAssignment(cluster_name="m1",
                                            hard={CPU: 60.0}),
                    StaticClusterAssignment(cluster_name="m2",
                                            hard={CPU: 60.0}),
                ],
            ),
        ))
        p._sync_members()
        p.set_usage("web", 0.9 * 30.0)  # 90% of request -> desired 8
        p.collect()
        p.daemon.step()
        # 8 replicas x 30 cpu = 240 > the 120 the caps leave: VETOED —
        # the template stays put and the veto is counted
        assert p.replicas("web") == 2
        assert p.daemon.stats["vetoed"] == 1
        assert p.daemon.stats["scale_ups"] == 0

    def test_quota_less_namespace_is_never_vetoed(self):
        """The preflight is scoped per namespace: a scale-up in a
        namespace with NO FederatedResourceQuota must not compete with
        (or be vetoed by) another namespace's caps."""
        from karmada_tpu.api.search import (
            FederatedResourceQuota,
            FederatedResourceQuotaSpec,
            StaticClusterAssignment,
        )
        from karmada_tpu.api.work import (
            BindingSpec,
            ObjectReference,
            ReplicaRequirements,
            ResourceBinding,
            TargetCluster,
        )

        p = _Plane(n_members=2, preflight=True)
        # ns "default": the scaled workload, NO quota
        p.add_workload("web", replicas=2, cpu=30.0)
        p.store.create(fhpa(min_r=1, max_r=10, target_util=50))
        p.store.create(ResourceBinding(
            metadata=ObjectMeta(namespace="default", name="web-deployment",
                                uid=new_uid("rb")),
            spec=BindingSpec(
                resource=ObjectReference(api_version="apps/v1",
                                         kind="Deployment",
                                         namespace="default", name="web"),
                replicas=2, placement=_divided_placement(),
                replica_requirements=ReplicaRequirements(
                    resource_request={CPU: 30.0}),
                clusters=[TargetCluster(name="m1", replicas=1),
                          TargetCluster(name="m2", replicas=1)],
            ),
        ))
        # a DIFFERENT namespace carries a tight quota
        p.store.create(FederatedResourceQuota(
            metadata=ObjectMeta(namespace="other", name="caps"),
            spec=FederatedResourceQuotaSpec(
                overall={CPU: 2.0},
                static_assignments=[
                    StaticClusterAssignment(cluster_name="m1",
                                            hard={CPU: 1.0}),
                    StaticClusterAssignment(cluster_name="m2",
                                            hard={CPU: 1.0}),
                ],
            ),
        ))
        p._sync_members()
        p.set_usage("web", 0.9 * 30.0)
        p.collect()
        p.daemon.step()
        assert p.replicas("web") == 8  # other/caps is not ours: emitted
        assert p.daemon.stats["vetoed"] == 0

    def test_scale_up_within_quota_passes(self):
        from karmada_tpu.api.search import (
            FederatedResourceQuota,
            FederatedResourceQuotaSpec,
            StaticClusterAssignment,
        )
        from karmada_tpu.api.work import (
            BindingSpec,
            ObjectReference,
            ReplicaRequirements,
            ResourceBinding,
            TargetCluster,
        )

        p = _Plane(n_members=2, preflight=True)
        p.add_workload("web", replicas=2, cpu=1.0)
        p.store.create(fhpa(min_r=1, max_r=10, target_util=50))
        p.store.create(ResourceBinding(
            metadata=ObjectMeta(namespace="default", name="web-deployment",
                                uid=new_uid("rb")),
            spec=BindingSpec(
                resource=ObjectReference(api_version="apps/v1",
                                         kind="Deployment",
                                         namespace="default", name="web"),
                replicas=2, placement=_divided_placement(),
                replica_requirements=ReplicaRequirements(
                    resource_request={CPU: 1.0}),
                clusters=[TargetCluster(name="m1", replicas=1),
                          TargetCluster(name="m2", replicas=1)],
            ),
        ))
        p.store.create(FederatedResourceQuota(
            metadata=ObjectMeta(namespace="default", name="caps"),
            spec=FederatedResourceQuotaSpec(
                overall={CPU: 120.0},
                static_assignments=[
                    StaticClusterAssignment(cluster_name="m1",
                                            hard={CPU: 60.0}),
                    StaticClusterAssignment(cluster_name="m2",
                                            hard={CPU: 60.0}),
                ],
            ),
        ))
        p._sync_members()
        p.set_usage("web", 0.9)
        p.collect()
        p.daemon.step()
        assert p.replicas("web") == 8  # fits under the caps: emitted
        assert p.daemon.stats["vetoed"] == 0


# -- streaming re-admission ------------------------------------------------


class TestStreamingReadmission:
    @staticmethod
    def _placed(store, name, ns="bench"):
        rb = store.try_get("ResourceBinding", name, ns)
        if rb is None or rb.status.scheduler_observed_generation != rb.metadata.generation:
            return None
        return sum(t.replicas for t in (rb.spec.clusters or []))

    def _wait(self, fn, want, deadline_s=30.0):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            if fn() == want:
                return True
            time.sleep(0.02)
        return False

    def test_resurrection_readmits_through_streaming_scheduler(self):
        """Scale-to-zero then cold resurrection: the replica-delta emission
        is an ordinary store write the STREAMING scheduler absorbs as an
        admission — zero special-casing in the placement plane."""
        from karmada_tpu.api.work import (
            BindingSpec,
            ObjectReference,
            ReplicaRequirements,
            ResourceBinding,
        )
        from karmada_tpu.runtime.controller import Runtime
        from karmada_tpu.sched.scheduler import SchedulerDaemon

        ns = "bench"
        p = _Plane(n_members=2, ns=ns)
        p.add_workload("web", replicas=2, cpu=1.0)
        p.store.create(fhpa(ns=ns, min_r=0, max_r=10, target_util=50,
                            scale_to_zero=True))
        placement = _divided_placement()
        rb = ResourceBinding(
            metadata=ObjectMeta(namespace=ns, name="web", uid="rb-elastic-0"),
            spec=BindingSpec(
                resource=ObjectReference(api_version="apps/v1",
                                         kind="Deployment",
                                         namespace=ns, name="web"),
                replicas=2, placement=placement,
                replica_requirements=ReplicaRequirements(
                    resource_request={CPU: 1.0}),
            ),
        )
        p.store.create(rb)

        # detector-lite: template spec.replicas -> binding spec.replicas
        def on_template(event, dep):
            if event == "DELETED" or dep.name != "web":
                return
            fresh = p.store.try_get("ResourceBinding", "web", ns)
            want = int(dep.get("spec", "replicas", default=0) or 0)
            if fresh is not None and fresh.spec.replicas != want:
                fresh.spec.replicas = want
                p.store.update(fresh)

        p.store.watch("apps/v1/Deployment", on_template, replay=False)

        daemon = SchedulerDaemon(p.store, Runtime())
        svc = daemon.streaming(batch_delay=0.001, interval=0.02,
                               max_batch=64)
        stop = threading.Event()
        t = threading.Thread(
            target=lambda: svc.serve(should_stop=stop.is_set), daemon=True)
        t.start()
        try:
            assert self._wait(lambda: self._placed(p.store, "web"), 2)
            # scale to zero
            p.demand["web"] = 2.0
            p.tick()
            p.demand["web"] = 0.0
            for _ in range(3):
                p.tick()
            dep = p.store.get("apps/v1/Deployment", "web", ns)
            assert int(dep.get("spec", "replicas")) == 0
            rb2 = p.store.get("ResourceBinding", "web", ns)
            assert rb2.spec.replicas == 0
            # resurrection: demand returns at zero ready -> one replica,
            # re-placed by the streaming scheduler like any admission
            p.demand["web"] = 3.0
            p.tick()
            dep = p.store.get("apps/v1/Deployment", "web", ns)
            assert int(dep.get("spec", "replicas")) == 1
            assert self._wait(lambda: self._placed(p.store, "web"), 1)
        finally:
            stop.set()
            svc.stop()
            t.join(timeout=30.0)


# -- printers + metrics ----------------------------------------------------


class _StubCP:
    def __init__(self, store):
        self.store = store
        self.members = {}


class TestPrinterAndMetrics:
    def test_get_federatedhpas_table(self):
        from karmada_tpu.cli.karmadactl import cmd_get

        store = Store()
        h = fhpa(min_r=2, max_r=12, target_util=50)
        h.status.current_replicas = 4
        h.status.current_average_utilization = 90
        h.status.last_scale_time = time.time() - 30.0
        store.create(h)
        out = cmd_get(_StubCP(store), "federatedhpas")
        for col in ("TARGETS", "MINPODS", "MAXPODS", "REPLICAS",
                    "LASTSCALE"):
            assert col in out
        assert "cpu: 90%/50%" in out
        assert " 2 " in out and " 12 " in out and " 4 " in out
        wide = cmd_get(_StubCP(store), "fhpa", output="wide")
        assert "Deployment/web" in wide and "DESIRED" in wide

    def test_targets_attributes_utilization_to_resolved_metric(self):
        """Multi-metric HPA: the one stored percent renders against the
        metric it belongs to (status.current_metric), never fabricated
        onto the others."""
        from karmada_tpu.cli.karmadactl import cmd_get

        store = Store()
        h = fhpa(target_util=80)
        h.spec.metrics.insert(0, ResourceMetricSource(
            name="memory", target_average_utilization=60))
        h.status.current_average_utilization = 57
        h.status.current_metric = "cpu"
        store.create(h)
        out = cmd_get(_StubCP(store), "federatedhpas")
        assert "memory: <unknown>/60%" in out
        assert "cpu: 57%/80%" in out

    def test_metrics_exported(self):
        from karmada_tpu.metrics import (
            elastic_loop_seconds,
            elastic_solves,
            hpa_desired_replicas,
            hpa_scale_events,
            registry,
        )

        loops0 = elastic_loop_seconds.count()
        solves0 = elastic_solves.total()
        ups0 = hpa_scale_events.value(direction="up")
        p = _Plane()
        p.add_workload("web", replicas=2, cpu=1.0)
        p.store.create(fhpa(target_util=50))
        p.demand["web"] = 4.0  # desired ceil(4/0.5) = 8
        p.tick()
        key = workload_key("Deployment", "default", "web")
        assert hpa_desired_replicas.value(workload=key) == 8.0
        assert hpa_scale_events.value(direction="up") == ups0 + 1
        assert elastic_loop_seconds.count() == loops0 + 1
        assert elastic_solves.total() == solves0 + 1
        text = registry.render()
        for name in ("karmada_hpa_desired_replicas",
                     "karmada_hpa_scale_events_total",
                     "karmada_elastic_loop_seconds"):
            assert name in text

    def test_scale_events_recorded(self):
        from karmada_tpu.events import EventRecorder

        p = _Plane()
        p.daemon.event_recorder = EventRecorder(p.store, clock=p.clock)
        p.add_workload("web", replicas=2, cpu=1.0)
        p.store.create(fhpa(target_util=50))
        p.demand["web"] = 4.0
        p.tick()
        hpa = p.store.get("FederatedHPA", "hpa", "default")
        events = p.daemon.event_recorder.events_for(hpa)
        assert any(e.reason == "SuccessfulRescale" for e in events)

    def test_gauge_rows_removed_with_hpa(self):
        from karmada_tpu.metrics import hpa_desired_replicas

        p = _Plane()
        p.add_workload("web", replicas=2, cpu=1.0)
        p.store.create(fhpa(target_util=50))
        p.demand["web"] = 1.0
        p.tick()
        key = workload_key("Deployment", "default", "web")
        assert hpa_desired_replicas.value(workload=key) > 0
        p.store.delete("FederatedHPA", "hpa", "default")
        p.tick()
        assert hpa_desired_replicas.value(workload=key) == 0.0


class TestElasticStatusRoute:
    def test_get_elastic_status(self):
        """GET /elastic/status: 404 on a plane without the elasticity
        plane, daemon counters when enabled."""
        import json as json_mod
        import urllib.error
        import urllib.request

        from karmada_tpu.server.apiserver import ControlPlaneServer

        cp = _StubCP(Store())
        srv = ControlPlaneServer(cp)
        srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(f"{srv.url}/elastic/status")
            assert exc.value.code == 404
            cp.elasticity = ElasticityDaemon(cp.store)
            cp.elasticity.step()
            with urllib.request.urlopen(f"{srv.url}/elastic/status") as r:
                body = json_mod.loads(r.read())
            assert body["leader"] is True
            assert body["ticks"] == 1 and body["solves"] == 1
        finally:
            srv.stop()


# -- leadership ------------------------------------------------------------


class TestLeadership:
    def test_non_leader_tick_is_noop(self):
        """With a coordinator, the daemon elects on karmada-elastic; a
        second daemon against the same coordinator stays standby and its
        ticks are no-ops."""
        from karmada_tpu.coordination.lease import LeaseCoordinator
        from karmada_tpu.elastic.daemon import LEASE_ELASTIC

        clock = Clock(fixed=1_700_000_000.0)
        store = Store()
        coordinator = LeaseCoordinator(store, clock)
        a = ElasticityDaemon(store, clock, coordinator=coordinator,
                             identity="a")
        b = ElasticityDaemon(store, clock, coordinator=coordinator,
                             identity="b")
        sa = a.step()
        sb = b.step()
        assert sa["leader"] is True
        assert sb == {"leader": False}
        lease = store.get("LeaderLease", LEASE_ELASTIC, "karmada-system")
        assert lease.spec.holder_identity == "a"
        # the leader's lease expires -> the standby takes over
        clock.advance(60.0)
        assert b.step()["leader"] is True


# -- the smoke wrapper (slow path) -----------------------------------------


@pytest.mark.slow
class TestElasticSmokeScript:
    def test_elastic_smoke(self):
        """scripts/elastic_smoke.sh: the diurnal-replay bench against the
        live daemon topology — spike->placed p99 under the SLO, the
        hysteresis leg >=5x fewer scale events than the no-hysteresis leg
        on the same seeded trace, one vectorized launch per tick —
        asserted from the emitted JSON line."""
        import os
        import subprocess

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            ["bash", "scripts/elastic_smoke.sh"],
            capture_output=True, text=True, timeout=900, cwd=repo,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "ELASTIC OK" in r.stdout

"""Control-plane write path (docs/PERF.md "Write path at fleet scale"):
transactional batch writes — one lock hold, contiguous resourceVersions,
one WAL fsync, all-or-nothing with typed per-object results — plus the
lock-scope shrink (watcher dispatch/encode/copies out of the hold), the
serving-seam batch route with replay-safe retry, and the coalesced
writers (scheduler patch, binding Work fan-out, agent status)."""
from __future__ import annotations

import itertools
import json
import threading

import pytest

from karmada_tpu.api.unstructured import Unstructured
from karmada_tpu.server import codec
from karmada_tpu.store.store import (
    ADDED,
    MODIFIED,
    BatchError,
    ConflictError,
    Store,
)


def cm(i, t="", ns="d"):
    return Unstructured({
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": f"o-{i:04d}", "namespace": ns},
        "data": {"t": t},
    })


KIND = "v1/ConfigMap"


# -- transactional semantics ------------------------------------------------


class TestBatchWrites:
    def test_create_batch_contiguous_rvs(self):
        s = Store()
        outs = s.create_batch([cm(i) for i in range(20)])
        rvs = [o.metadata.resource_version for o in outs]
        assert rvs == list(range(rvs[0], rvs[0] + 20))
        assert all(o.metadata.uid for o in outs)

    def test_all_or_nothing_with_typed_results(self):
        s = Store()
        s.create(cm(0))
        with pytest.raises(BatchError) as ei:
            s.create_batch([cm(1), cm(0), cm(2)])
        results = ei.value.results
        assert [r.reason for r in results] == ["aborted", "conflict",
                                              "aborted"]
        # a conflict keeps its neighbors retryable — the batch's
        # retryable/terminal distinction survives one bad object
        assert all(r.retryable for r in results)
        # NOTHING committed: neither the earlier nor the later neighbor
        assert s.try_get(KIND, "o-0001", "d") is None
        assert s.try_get(KIND, "o-0002", "d") is None

    def test_admission_denial_is_terminal_and_commits_nothing(self):
        from karmada_tpu.webhook.admission import AdmissionDenied

        s = Store()

        def admit(op, kind, obj, old):
            if obj.metadata.name == "o-0001":
                raise AdmissionDenied(kind, "nope")
            return obj

        s.set_admission(admit)
        with pytest.raises(BatchError) as ei:
            s.apply_batch([cm(0), cm(1)])
        r0, r1 = ei.value.results
        assert r0.reason == "aborted" and r0.retryable
        assert r1.reason == "admission" and not r1.retryable
        assert s.try_get(KIND, "o-0000", "d") is None

    def test_update_batch_check_rv_conflict_torches_batch(self):
        s = Store()
        outs = s.create_batch([cm(0), cm(1)])
        stale = outs[1]
        s.update(cm(1, t="newer"))  # bump rv behind the stale copy's back
        fresh0 = s.get(KIND, "o-0000", "d")
        with pytest.raises(BatchError) as ei:
            s.update_batch([fresh0, stale], check_rv=True)
        assert [r.reason for r in ei.value.results] == ["aborted", "conflict"]
        # the valid neighbor did NOT land
        assert s.get(KIND, "o-0000", "d").metadata.resource_version \
            == fresh0.metadata.resource_version

    def test_update_batch_skip_missing(self):
        s = Store()
        s.create(cm(0))
        outs = s.update_batch([cm(0, t="x"), cm(7)], skip_missing=True)
        assert outs[0].get("data", "t") == "x"
        assert outs[1] is None

    def test_in_batch_create_then_update_behaves_sequentially(self):
        s = Store()
        outs = s.apply_batch([cm(0, t="a"), cm(0, t="b")])
        assert outs[0].metadata.resource_version + 1 \
            == outs[1].metadata.resource_version
        final = s.get(KIND, "o-0000", "d")
        assert final.get("data", "t") == "b"
        # spec changed between the two in-batch writes: generation bumped
        assert final.metadata.generation == 2

    def test_get_batch(self):
        s = Store()
        s.create_batch([cm(0), cm(1)])
        got = s.get_batch(KIND, [("o-0001", "d"), ("o-9999", "d")])
        assert got[0].metadata.name == "o-0001"
        assert got[1] is None

    def test_batch_input_isolation(self):
        """Caller mutation after the call must not reach the store (same
        contract as the single-object paths)."""
        s = Store()
        obj = cm(0, t="v1")
        s.apply_batch([obj])
        obj.set("data", "t", "HACKED")
        assert s.get(KIND, "o-0000", "d").get("data", "t") == "v1"


# -- batch-vs-sequential bit parity ----------------------------------------


def run_ops(batched: bool, ops, chunk=7):
    """Apply `ops` to a fresh store; returns (event stream, final bytes)
    with wall-clock stamps pinned so any difference is real."""
    import karmada_tpu.store.store as store_mod

    counter = itertools.count(1)
    old_now, old_uid = store_mod.now, store_mod.new_uid
    store_mod.now = lambda: 1000.0
    store_mod.new_uid = lambda prefix="uid": f"{prefix}-{next(counter)}"
    try:
        s = Store()
        events = []
        s.watch_all(
            lambda k, ev, o: events.append(
                (k, ev, o.metadata.resource_version,
                 json.dumps(codec.encode(o), sort_keys=True))
            ),
            replay=False,
        )
        if batched:
            for i in range(0, len(ops), chunk):
                s.apply_batch(ops[i:i + chunk])
        else:
            for o in ops:
                s.apply(o)
        final = sorted(
            json.dumps(codec.encode(o), sort_keys=True)
            for kind in s.kinds() for o in s.list(kind)
        )
        return events, final
    finally:
        store_mod.now, store_mod.new_uid = old_now, old_uid


class TestBitParity:
    def test_apply_batch_bit_identical_to_sequential(self):
        ops = [cm(i, t="v1") for i in range(25)]
        ops += [cm(i, t="v2") for i in range(0, 25, 2)]  # spec changes
        ops += [cm(i, t="v2") for i in range(0, 25, 4)]  # no-spec-change
        seq_events, seq_final = run_ops(False, ops)
        bat_events, bat_final = run_ops(True, ops)
        assert seq_final == bat_final
        assert seq_events == bat_events


# -- lock scope (satellite: dispatch outside the hold) ----------------------


class TestLockScope:
    def test_watch_handlers_run_outside_lock_even_under_apply(self):
        s = Store()
        held = []
        s.watch(KIND, lambda ev, o: held.append(s._lock._is_owned()))
        s.apply(cm(0))
        s.apply(cm(0, t="x"))
        s.create(cm(1))
        s.update(cm(1, t="y"))
        s.delete(KIND, "o-0001", "d")
        s.apply_batch([cm(2), cm(3)])
        assert held and not any(held)

    def test_subscriber_lock_no_longer_inverts_with_store_lock(self):
        """The ABBA regression: a watch handler that takes its own lock L,
        racing a thread that holds L and calls back into Store.apply. With
        notify under the store lock this deadlocked (store→L vs L→store);
        with dispatch outside the hold both sides complete."""
        s = Store()
        sub_lock = threading.Lock()
        entered = threading.Event()
        release = threading.Event()

        def handler(ev, obj):
            if obj.metadata.name != "o-0000":
                return  # only the first apply's event takes part
            entered.set()
            release.wait(timeout=10.0)
            with sub_lock:
                pass

        s.watch(KIND, handler)

        def mutator():
            s.apply(cm(0))  # dispatches to handler outside the lock

        def locked_applier():
            entered.wait(timeout=10.0)
            with sub_lock:
                release.set()
                s.apply(cm(1))  # would block forever under old ordering

        t1 = threading.Thread(target=mutator, daemon=True)
        t2 = threading.Thread(target=locked_applier, daemon=True)
        t1.start()
        t2.start()
        t1.join(timeout=20.0)
        t2.join(timeout=20.0)
        assert not t1.is_alive() and not t2.is_alive(), \
            "lock-order inversion: store.apply deadlocked against a " \
            "subscriber holding its own lock"
        assert s.try_get(KIND, "o-0001", "d") is not None


# -- WAL: one group-commit unit per batch -----------------------------------


class TestWalBatch:
    def test_batch_commits_one_fsync(self, tmp_path, monkeypatch):
        import os as os_mod

        from karmada_tpu.store.persistence import StorePersistence

        s = Store()
        p = StorePersistence(s, str(tmp_path))
        p.attach()
        count = [0]
        real = os_mod.fsync
        monkeypatch.setattr(os_mod, "fsync",
                            lambda fd: (count.__setitem__(0, count[0] + 1),
                                        real(fd))[1])
        s.create_batch([cm(i) for i in range(100)])
        assert count[0] == 1, "a 100-object batch must be ONE fsync"
        p.close()

    def test_batch_is_durable_and_replayable(self, tmp_path):
        from karmada_tpu.store.persistence import StorePersistence

        s = Store()
        p = StorePersistence(s, str(tmp_path))
        p.attach()
        s.create_batch([cm(i) for i in range(10)])
        s.update_batch([cm(i, t="x") for i in range(10)])
        p.close()
        s2 = Store()
        p2 = StorePersistence(s2, str(tmp_path))
        assert p2.load() == 10
        assert all(
            s2.get(KIND, f"o-{i:04d}", "d").get("data", "t") == "x"
            for i in range(10)
        )


# -- rv contiguity + strict watch-cache order under racing batch writers ----


class TestRacingBatchWriters:
    def test_rv_contiguity_and_cache_order(self):
        from karmada_tpu.store.watchcache import WatchCache

        s = Store()
        cache = WatchCache(s, capacity=65536)
        cache.attach()
        n_writers, per_batch, rounds = 6, 16, 10
        batches: list[list[int]] = []
        lock = threading.Lock()

        def writer(w):
            for r in range(rounds):
                objs = [cm(w * 1000 + r * per_batch + k)
                        for k in range(per_batch)]
                outs = s.create_batch(objs)
                with lock:
                    batches.append(
                        [o.metadata.resource_version for o in outs])

        threads = [threading.Thread(target=writer, args=(w,), daemon=True)
                   for w in range(n_writers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        # every batch's rvs are contiguous (one lock hold each) and no rv
        # appears twice across the race
        all_rvs = [rv for b in batches for rv in b]
        assert len(set(all_rvs)) == len(all_rvs)
        for b in batches:
            assert b == list(range(b[0], b[0] + per_batch))
        # the cache ring observed the interleaved log in strict rv order
        events, _, ok = cache.events_since(0)
        assert ok
        rvs = [e.rv for e in events]
        assert rvs == sorted(rvs) and len(set(rvs)) == len(rvs)
        assert len(rvs) == n_writers * per_batch * rounds
        cache.detach()


# -- the serving seam: POST /objects/batch + RemoteStore --------------------


class _MiniCP:
    """Minimal cp surface for ControlPlaneServer (no PKI/cryptography)."""

    def __init__(self, store):
        self.store = store
        self.members = {}

    def settle(self, max_steps=0):
        return 0

    def tick(self, seconds=0.0):
        return 0


@pytest.fixture()
def served_store():
    from karmada_tpu.server.apiserver import ControlPlaneServer

    s = Store()
    srv = ControlPlaneServer(_MiniCP(s))
    srv.start()
    yield s, srv
    srv.stop()


class TestRemoteBatch:
    def test_apply_batch_roundtrip_and_get_batch(self, served_store):
        from karmada_tpu.server.remote import RemoteStore

        s, srv = served_store
        remote = RemoteStore(srv.url)
        outs = remote.apply_batch([cm(i) for i in range(30)], chunk=8)
        assert len(outs) == 30
        rvs = [o.metadata.resource_version for o in outs]
        assert len(set(rvs)) == 30
        got = remote.get_batch(KIND, [("o-0003", "d"), ("o-bogus", "d")])
        assert got[0].metadata.name == "o-0003" and got[1] is None
        assert s.get(KIND, "o-0003", "d") is not None

    def test_conflict_carries_typed_results_over_the_wire(self, served_store):
        from karmada_tpu.server.remote import RemoteStore

        s, srv = served_store
        s.create(cm(1))
        remote = RemoteStore(srv.url)
        with pytest.raises(BatchError) as ei:
            remote.create_batch([cm(0), cm(1)])
        assert [r.reason for r in ei.value.results] == ["aborted",
                                                        "conflict"]
        assert s.try_get(KIND, "o-0000", "d") is None  # all-or-nothing

    def test_replayed_chunk_after_timeout_does_not_double_create(
            self, served_store, monkeypatch):
        """The partial-retry idempotency contract: the server commits the
        chunk but the response is lost (timeout). The client's replay sees
        409 conflicts for the objects that landed, treats them as
        satisfied-by-replay, and re-sends nothing twice."""
        from karmada_tpu.server.remote import RemoteError, RemoteStore

        s, srv = served_store
        remote = RemoteStore(srv.url)
        real = RemoteStore._call_batch
        dropped = [0]

        def lossy(self, body):
            out = real(self, body)
            if body.get("op") == "create" and not dropped[0]:
                dropped[0] = 1
                raise RemoteError("simulated timeout: response lost")
            return out

        monkeypatch.setattr(RemoteStore, "_call_batch", lossy)
        outs = remote.create_batch([cm(i) for i in range(12)], chunk=12)
        assert dropped[0] == 1
        assert len(outs) == 12
        assert all(o is not None for o in outs)
        # exactly one copy of each landed
        assert len(s.list(KIND)) == 12

    def test_pre_batch_server_falls_back_per_object(self, served_store,
                                                    monkeypatch):
        from karmada_tpu.server import remote as remote_mod
        from karmada_tpu.server.remote import RemoteStore

        s, srv = served_store
        remote = RemoteStore(srv.url)

        def no_route(self, body):
            raise remote_mod._NoBatchRoute("404")

        monkeypatch.setattr(RemoteStore, "_call_batch", no_route)
        outs = remote.apply_batch([cm(0), cm(1)])
        assert len(outs) == 2 and len(s.list(KIND)) == 2

    def test_fencing_applies_to_batch_route(self, served_store):
        """A deposed leader's batch writes must bounce exactly like its
        single writes (the fencing check runs before the store op)."""
        from karmada_tpu.server.remote import RemoteStore

        s, srv = served_store
        remote = RemoteStore(srv.url)
        remote._fence = "ns/lease:42"  # no coordinator on _MiniCP: ignored
        outs = remote.apply_batch([cm(0)])
        assert len(outs) == 1


# -- coalesced writers ------------------------------------------------------


class TestWriteCoalescer:
    def test_same_key_writes_coalesce_last_write_wins(self):
        from karmada_tpu.store.batching import WriteCoalescer

        s = Store()
        wc = WriteCoalescer(s, flush_delay=30.0, path="t")  # manual flush
        wc.apply(cm(0, t="v1"))
        wc.apply(cm(0, t="v2"))
        wc.apply(cm(1, t="v1"))
        assert wc.pending() == 2
        assert wc.flush() == 2
        assert s.get(KIND, "o-0000", "d").get("data", "t") == "v2"
        wc.close()

    def test_zero_delay_writes_through(self):
        from karmada_tpu.store.batching import WriteCoalescer

        s = Store()
        wc = WriteCoalescer(s, flush_delay=0.0)
        out = wc.apply(cm(0))
        assert out is not None and s.try_get(KIND, "o-0000", "d") is not None

    def test_background_flush_within_delay(self):
        import time

        from karmada_tpu.store.batching import WriteCoalescer

        s = Store()
        wc = WriteCoalescer(s, flush_delay=0.01, path="t")
        wc.apply(cm(0))
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if s.try_get(KIND, "o-0000", "d") is not None:
                break
            time.sleep(0.005)
        assert s.try_get(KIND, "o-0000", "d") is not None
        wc.close()

    def test_apply_all_degrades_per_object_on_batch_error(self):
        from karmada_tpu.store.batching import apply_all
        from karmada_tpu.webhook.admission import AdmissionDenied

        s = Store()

        def admit(op, kind, obj, old):
            if obj.metadata.name == "o-0001":
                raise AdmissionDenied(kind, "nope")
            return obj

        s.set_admission(admit)
        with pytest.raises(AdmissionDenied):
            apply_all(s, [cm(0), cm(1), cm(2)])
        # pre-batch loop semantics: the object BEFORE the bad one landed
        assert s.try_get(KIND, "o-0000", "d") is not None


class TestSchedulerPatchCoalescing:
    def _topology(self):
        from karmada_tpu.runtime.controller import Runtime
        from karmada_tpu.sched.scheduler import SchedulerDaemon
        from karmada_tpu.testing.fixtures import synthetic_fleet

        class CountingStore(Store):
            def __init__(self):
                super().__init__()
                self.n_update = 0
                self.n_update_batch = 0

            def update(self, obj, **kw):
                self.n_update += 1
                return super().update(obj, **kw)

            def update_batch(self, objs, **kw):
                self.n_update_batch += 1
                return super().update_batch(objs, **kw)

        store = CountingStore()
        runtime = Runtime()
        for c in synthetic_fleet(5, seed=3):
            store.create(c)
        daemon = SchedulerDaemon(store, runtime)
        return store, runtime, daemon

    def test_streaming_microbatch_patches_in_one_batch_call(self):
        from tests.test_parallel import dyn_placement, make_binding

        store, runtime, daemon = self._topology()
        bindings = [make_binding(f"app-{i}", 2 + i % 3, dyn_placement(),
                                 cpu=0.1) for i in range(16)]
        for rb in bindings:
            store.create(rb)
        svc = daemon.streaming(batch_delay=0.0)
        store.n_update = store.n_update_batch = 0
        svc.serve(quiescent=True)
        placed = [rb for rb in store.list("ResourceBinding")
                  if rb.spec.clusters]
        assert len(placed) == 16
        # the patch path must be BATCH calls, not B per-binding updates
        assert store.n_update_batch >= 1
        assert store.n_update == 0, (
            f"per-object updates leaked into the micro-batch patch path "
            f"({store.n_update} update() calls)"
        )

    def test_batch_round_patches_in_batch_calls(self):
        from tests.test_parallel import dyn_placement, make_binding

        store, runtime, daemon = self._topology()
        for i in range(12):
            store.create(make_binding(f"app-{i}", 2, dyn_placement(),
                                      cpu=0.1))
        store.n_update = store.n_update_batch = 0
        runtime.settle()
        placed = [rb for rb in store.list("ResourceBinding")
                  if rb.spec.clusters]
        assert len(placed) == 12
        assert store.n_update_batch >= 1
        assert store.n_update == 0


class TestBindingWorksCoalesced:
    def test_work_fanout_rides_batch_writes(self):
        from karmada_tpu.metrics import writes_coalesced

        before = writes_coalesced.value(path="binding_works")
        from karmada_tpu.controlplane import ControlPlane
        try:
            cp = ControlPlane()
        except ModuleNotFoundError:
            pytest.skip("optional crypto stack missing")
        from karmada_tpu.members.member import MemberConfig

        for name in ("m1", "m2", "m3"):
            cp.join_member(MemberConfig(name=name, sync_mode="Push",
                                        allocatable={"cpu": 8.0}))
        deployment = Unstructured({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {"replicas": 3},
        })
        from karmada_tpu.api.policy import (
            ClusterAffinity,
            Placement,
            PropagationPolicy,
            PropagationSpec,
            ResourceSelector,
        )
        from karmada_tpu.api.meta import ObjectMeta

        cp.store.create(deployment)
        cp.store.create(PropagationPolicy(
            metadata=ObjectMeta(name="pp", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[ResourceSelector(
                    api_version="apps/v1", kind="Deployment", name="web",
                    namespace="default")],
                placement=Placement(cluster_affinity=ClusterAffinity(
                    cluster_names=["m1", "m2", "m3"])),
            ),
        ))
        cp.settle()
        works = cp.store.list("Work")
        assert len(works) >= 3
        assert writes_coalesced.value(path="binding_works") > before


class TestAgentStatusCoalescing:
    def test_agent_buffers_status_and_flushes(self):
        from karmada_tpu.agent.agent import KarmadaAgent
        from karmada_tpu.api.meta import ObjectMeta
        from karmada_tpu.api.work import Work, WorkSpec
        from karmada_tpu.interpreter.interpreter import ResourceInterpreter
        from karmada_tpu.members.member import InMemoryMember, MemberConfig
        from karmada_tpu.api.work import work_namespace_for_cluster
        from karmada_tpu.runtime.controller import Runtime

        store = Store()
        runtime = Runtime()
        member = InMemoryMember(MemberConfig(name="m1", sync_mode="Pull",
                                             allocatable={"cpu": 4.0}))
        agent = KarmadaAgent(store, member, ResourceInterpreter(), runtime,
                             status_flush_delay=30.0)  # manual flush only
        ns = work_namespace_for_cluster("m1")
        for i in range(4):
            store.create(Work(
                metadata=ObjectMeta(name=f"w-{i}", namespace=ns),
                spec=WorkSpec(workload_manifests=[{
                    "apiVersion": "v1", "kind": "ConfigMap",
                    "metadata": {"name": f"c-{i}", "namespace": "default"},
                }]),
            ))
        runtime.settle()
        # conditions are buffered, not yet visible
        pending = agent.flush_status()
        assert pending == 4
        for i in range(4):
            w = store.get("Work", f"w-{i}", ns)
            assert any(c.type == "Applied" and c.status == "True"
                       for c in w.status.conditions)
        agent.close()


# -- metrics ---------------------------------------------------------------


class TestWritePathMetrics:
    def test_lock_and_txn_metrics_flow(self):
        from karmada_tpu.metrics import (
            registry,
            store_lock_hold,
            store_lock_wait,
            txn_batch_size,
        )

        s = Store()
        w0 = store_lock_wait.count()
        h0 = store_lock_hold.count()
        t0 = txn_batch_size.count()
        s.create(cm(0))
        s.apply_batch([cm(1), cm(2), cm(3)])
        assert store_lock_wait.count() > w0
        assert store_lock_hold.count() > h0
        assert txn_batch_size.count() == t0 + 1
        text = registry.render()
        assert "karmada_store_lock_wait_seconds" in text
        assert "karmada_txn_batch_size" in text
        assert "karmada_writes_coalesced_total" in text


# -- the smoke wrapper (slow path) -----------------------------------------


@pytest.mark.slow
class TestWriteloadSmokeScript:
    def test_writeload_smoke(self):
        """scripts/writeload_smoke.sh: the W=32 point of the writeload
        bench — batched vs per-object write path over a live apiserver,
        the acceptance booleans asserted from the emitted JSON line."""
        import os
        import subprocess

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            ["bash", "scripts/writeload_smoke.sh"],
            capture_output=True, text=True, timeout=600, cwd=repo,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "WRITELOAD OK" in r.stdout

"""Store persistence: snapshot + WAL survive restarts (the etcd role of
the reference's L1; SURVEY §5 — everything else is a rebuildable cache)."""
from __future__ import annotations

import json
import os
import signal
import time

import pytest

from karmada_tpu.api.meta import CPU, MEMORY
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.members.member import MemberConfig
from karmada_tpu.store.persistence import StorePersistence
from karmada_tpu.testing.fixtures import (
    duplicated_placement,
    new_deployment,
    new_policy,
    selector_for,
)

GiB = 1024.0**3


def start_daemon(data_dir: str):
    from karmada_tpu.testing.daemon import spawn_daemon

    return spawn_daemon("--members", "1", "--tick-interval", "0.5",
                        "--data-dir", data_dir)


def plane_with_members(n=2):
    cp = ControlPlane()
    for i in range(1, n + 1):
        cp.join_member(MemberConfig(
            name=f"member{i}", region=f"r{i}",
            allocatable={CPU: 100.0, MEMORY: 400 * GiB, "pods": 1000.0},
        ))
    return cp


class TestPersistenceRoundTrip:
    def test_restart_restores_state_and_controllers_converge(self, tmp_path):
        cp1 = plane_with_members()
        p1 = StorePersistence(cp1.store, str(tmp_path))
        p1.attach()
        dep = new_deployment("default", "web", replicas=3, cpu=0.25)
        cp1.store.create(dep)
        cp1.store.create(new_policy(
            "default", "pp", [selector_for(dep)], duplicated_placement([])))
        cp1.settle()
        rb1 = cp1.store.get("ResourceBinding", "web-deployment", "default")
        works1 = {w.metadata.key() for w in cp1.store.list("Work")}
        assert works1
        p1.close()

        # a NEW plane restores the store; join_member re-attaches the member
        # sims behind the restored Cluster objects without conflicting
        cp2 = ControlPlane()
        p2 = StorePersistence(cp2.store, str(tmp_path))
        n = p2.load()
        assert n > 0
        cp2.join_member(MemberConfig(
            name="member1", region="r1",
            allocatable={CPU: 100.0, MEMORY: 400 * GiB, "pods": 1000.0}))
        cp2.join_member(MemberConfig(
            name="member2", region="r2",
            allocatable={CPU: 100.0, MEMORY: 400 * GiB, "pods": 1000.0}))
        cp2.settle()

        rb2 = cp2.store.get("ResourceBinding", "web-deployment", "default")
        # identity survived: uid and placement unchanged (the scheduler saw
        # nothing to reschedule)
        assert rb2.metadata.uid == rb1.metadata.uid
        assert {t.name for t in rb2.spec.clusters} == \
            {t.name for t in rb1.spec.clusters}
        assert {w.metadata.key() for w in cp2.store.list("Work")} == works1
        # and the pipeline is live: members received the workload again
        for m in cp2.members.values():
            assert m.get("apps/v1", "Deployment", "web", "default") is not None

    def test_delete_is_persisted(self, tmp_path):
        cp1 = plane_with_members(1)
        p1 = StorePersistence(cp1.store, str(tmp_path))
        p1.attach()
        dep = new_deployment("default", "gone", replicas=1, cpu=0.1)
        cp1.store.create(dep)
        cp1.store.delete("apps/v1/Deployment", "gone", "default")
        cp1.settle()
        p1.close()

        cp2 = ControlPlane()
        StorePersistence(cp2.store, str(tmp_path)).load()
        assert cp2.store.try_get("apps/v1/Deployment", "gone", "default") is None

    def test_snapshot_rotation_and_reload(self, tmp_path):
        cp1 = plane_with_members(1)
        p1 = StorePersistence(cp1.store, str(tmp_path), snapshot_every=10**9)
        p1.attach()
        for i in range(5):
            cp1.store.create(new_deployment("default", f"app-{i}", replicas=1))
        p1.snapshot()  # WAL rotated + dropped, snapshot holds the 5
        cp1.store.create(new_deployment("default", "after-snap", replicas=1))
        p1.close()
        assert os.path.exists(tmp_path / "snapshot.jsonl")
        assert not os.path.exists(tmp_path / "wal.1.jsonl")

        cp2 = ControlPlane()
        StorePersistence(cp2.store, str(tmp_path)).load()
        names = {o.name for o in cp2.store.list("apps/v1/Deployment", "default")}
        assert names == {f"app-{i}" for i in range(5)} | {"after-snap"}

    def test_torn_wal_tail_is_ignored(self, tmp_path):
        cp1 = plane_with_members(1)
        p1 = StorePersistence(cp1.store, str(tmp_path))
        p1.attach()
        cp1.store.create(new_deployment("default", "ok", replicas=1))
        p1.close()
        with open(tmp_path / "wal.jsonl", "a") as f:
            f.write('{"kind": "apps/v1/Deployment", "event": "ADDED", "obj"')

        cp2 = ControlPlane()
        StorePersistence(cp2.store, str(tmp_path)).load()
        assert cp2.store.try_get("apps/v1/Deployment", "ok", "default") is not None


class TestTornTailHardening:
    """Crash-mid-append WALs at EVERY truncation point (docs/HA.md:
    replication replay makes partial tails routine): load() must keep
    every intact record, truncate the live WAL back to the last whole
    record, and never fail the boot."""

    def _seed_wal(self, tmp_path, n=4):
        from karmada_tpu.store.store import Store

        store = Store()
        p = StorePersistence(store, str(tmp_path))
        p.attach()
        from karmada_tpu.api.unstructured import Unstructured

        for i in range(n):
            store.create(Unstructured({
                "apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": f"t-{i}", "namespace": "d"},
                "data": {"k": "v" * 20},
            }))
        p.close()
        return (tmp_path / "wal.jsonl").read_bytes()

    def test_truncate_at_every_byte_offset(self, tmp_path):
        from karmada_tpu.store.store import Store

        wal = self._seed_wal(tmp_path)
        lines = wal.splitlines(keepends=True)
        assert len(lines) == 4
        # offsets of record boundaries (end of each whole line)
        bounds = []
        acc = 0
        for ln in lines:
            acc += len(ln)
            bounds.append(acc)
        wal_path = tmp_path / "wal.jsonl"
        for cut in range(bounds[0], len(wal) + 1):
            wal_path.write_bytes(wal[:cut])
            store = Store()
            p = StorePersistence(store, str(tmp_path))
            n = p.load()
            # every record wholly before the cut survives; records the
            # cut tore are dropped. A cut exactly at a boundary keeps
            # that record (incl. the no-trailing-newline case cut-1 of
            # a boundary, where the line is complete JSON)
            whole = sum(1 for b in bounds if cut >= b)
            if cut + 1 in bounds:  # complete JSON, newline itself torn off
                whole += 1
            assert n == whole, (cut, n, whole)
            # the live WAL was truncated to a record boundary: appending
            # afterwards must produce a clean, fully-replayable log
            p.attach()
            from karmada_tpu.api.unstructured import Unstructured

            store.create(Unstructured({
                "apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": "after-tear", "namespace": "d"},
                "data": {},
            }))
            p.close()
            store2 = Store()
            n2 = StorePersistence(store2, str(tmp_path)).load()
            assert n2 == whole + 1, (cut, n2, whole)
            assert store2.try_get("v1/ConfigMap", "after-tear", "d") \
                is not None
            wal_path.unlink()  # reseed cleanly for the next offset

    def test_corrupt_mid_file_record_is_skipped_not_fatal(self, tmp_path):
        from karmada_tpu.store.store import Store

        wal = self._seed_wal(tmp_path)
        lines = wal.splitlines(keepends=True)
        lines[1] = b'{"torn": \n'  # corrupt a MIDDLE record
        (tmp_path / "wal.jsonl").write_bytes(b"".join(lines))
        store = Store()
        n = StorePersistence(store, str(tmp_path)).load()
        # the records AFTER the corrupt one still replay (the old loader
        # broke out of the file at the first bad line)
        assert n == 3
        assert store.try_get("v1/ConfigMap", "t-3", "d") is not None

    def test_non_object_json_record_is_corrupt_not_fatal(self, tmp_path):
        """`123` parses as valid JSON but is not a record — it must take
        the corrupt-line path, not crash the replay with AttributeError."""
        from karmada_tpu.store.store import Store

        wal = self._seed_wal(tmp_path)
        (tmp_path / "wal.jsonl").write_bytes(b"123\n" + wal + b'"x"')
        store = Store()
        n = StorePersistence(store, str(tmp_path)).load()
        assert n == 4  # all real records; int skipped, str tail truncated
        data = (tmp_path / "wal.jsonl").read_bytes()
        assert not data.endswith(b'"x"')  # tail repaired


class TestDaemonPersistence:
    def test_daemon_restart_preserves_objects(self, tmp_path):
        """Kill -INT a real daemon and restart it on the same --data-dir:
        objects created through the socket must come back."""
        from karmada_tpu.server.remote import RemoteControlPlane

        data = str(tmp_path / "state")

        proc, url = start_daemon(data)
        try:
            rcp = RemoteControlPlane(url)
            rcp.store.create(new_deployment("default", "durable", replicas=2))
            rcp.settle()
        finally:
            proc.send_signal(signal.SIGINT)
            proc.wait(timeout=30)

        proc, url = start_daemon(data)
        try:
            rcp = RemoteControlPlane(url)
            got = rcp.store.get("apps/v1/Deployment", "durable", "default")
            assert got.get("spec", "replicas") == 2
        finally:
            proc.send_signal(signal.SIGINT)
            proc.wait(timeout=30)

    def test_daemon_sigkill_recovers_from_wal(self, tmp_path):
        """SIGKILL the daemon (no shutdown snapshot runs) and restart: the
        per-event WAL flush alone must bring every committed write back."""
        from karmada_tpu.server.remote import RemoteControlPlane

        data = str(tmp_path / "state")

        proc, url = start_daemon(data)
        try:
            rcp = RemoteControlPlane(url)
            for i in range(5):
                rcp.store.create(
                    new_deployment("default", f"crash-{i}", replicas=i + 1)
                )
        finally:
            proc.kill()  # SIGKILL: no snapshot, no WAL close
            proc.wait(timeout=30)

        proc, url = start_daemon(data)
        try:
            rcp = RemoteControlPlane(url)
            for i in range(5):
                got = rcp.store.get("apps/v1/Deployment", f"crash-{i}", "default")
                assert got.get("spec", "replicas") == i + 1
        finally:
            proc.send_signal(signal.SIGINT)
            proc.wait(timeout=30)

"""Cluster-API auto-discovery + CoreDNS resolution detector
(ref pkg/clusterdiscovery/clusterapi, pkg/servicenameresolutiondetector)."""
from karmada_tpu.api.unstructured import Unstructured
from karmada_tpu.clusterdiscovery import SERVICE_DNS_CONDITION
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.members.member import MemberConfig
from karmada_tpu.runtime.controller import Clock
from karmada_tpu.testing.fixtures import (
    duplicated_placement, new_deployment, new_policy, selector_for,
)


def capi_cluster(name, phase="Provisioned"):
    return Unstructured({
        "apiVersion": "cluster.x-k8s.io/v1beta1",
        "kind": "Cluster",
        "metadata": {"name": name, "namespace": ""},
        "spec": {"allocatable": {"cpu": 50.0, "memory": 200.0, "pods": 500.0}},
        "status": {"phase": phase},
    })


class TestClusterAPIDiscovery:
    def test_provisioned_cluster_auto_joins(self):
        cp = ControlPlane(clock=Clock(fixed=0.0))
        cp.store.create(capi_cluster("capi-1"))
        cp.settle()
        assert cp.store.try_get("Cluster", "capi-1") is not None
        assert "capi-1" in cp.members
        # it schedules like any member
        dep = new_deployment("default", "web", replicas=2, cpu=0.1)
        cp.store.create(dep)
        cp.store.create(new_policy("default", "pp", [selector_for(dep)],
                                   duplicated_placement([])))
        cp.settle()
        rb = cp.store.get("ResourceBinding", "web-deployment", "default")
        assert [t.name for t in rb.spec.clusters] == ["capi-1"]

    def test_pending_cluster_waits_for_provisioned(self):
        cp = ControlPlane(clock=Clock(fixed=0.0))
        obj = capi_cluster("capi-2", phase="Pending")
        cp.store.create(obj)
        cp.settle()
        assert cp.store.try_get("Cluster", "capi-2") is None
        fresh = cp.store.get("cluster.x-k8s.io/v1beta1/Cluster", "capi-2")
        fresh.set("status", "phase", "Provisioned")
        cp.store.update(fresh)
        cp.settle()
        assert cp.store.try_get("Cluster", "capi-2") is not None

    def test_deletion_unjoins(self):
        cp = ControlPlane(clock=Clock(fixed=0.0))
        cp.store.create(capi_cluster("capi-3"))
        cp.settle()
        assert "capi-3" in cp.members
        cp.store.delete("cluster.x-k8s.io/v1beta1/Cluster", "capi-3")
        cp.settle()
        assert cp.store.try_get("Cluster", "capi-3") is None
        assert "capi-3" not in cp.members


class TestCorednsDetector:
    def test_dns_condition_with_flap_suppression(self):
        cp = ControlPlane(clock=Clock(fixed=0.0))
        cp.join_member(MemberConfig(name="m1", allocatable={"cpu": 10.0}))
        cp.tick()
        cond = {c.type: c.status for c in
                cp.store.get("Cluster", "m1").status.conditions}
        assert cond[SERVICE_DNS_CONDITION] == "True"

        # flap inside the threshold: condition retained
        cp.members["m1"].dns_healthy = False
        cp.tick(seconds=5)
        cond = {c.type: c.status for c in
                cp.store.get("Cluster", "m1").status.conditions}
        assert cond[SERVICE_DNS_CONDITION] == "True"

        # sustained failure past the threshold flips it
        cp.tick(seconds=31)
        cond = {c.type: c.status for c in
                cp.store.get("Cluster", "m1").status.conditions}
        assert cond[SERVICE_DNS_CONDITION] == "False"

    def test_pull_mode_deletion_cleans_agent_and_lease(self):
        """Orphaned agents/leases after auto-unjoin crashed the next tick
        (lease detector firing for a Cluster that no longer exists)."""
        cp = ControlPlane(clock=Clock(fixed=0.0))
        obj = capi_cluster("capi-pull")
        obj.set("spec", "syncMode", "Pull")
        cp.store.create(obj)
        cp.settle()
        assert "capi-pull" in cp.agents
        lease_ns = "karmada-es-capi-pull"
        assert cp.store.try_get("Lease", "capi-pull", lease_ns) is not None

        cp.members["capi-pull"].set_healthy(False)  # outage precedes removal
        cp.store.delete("cluster.x-k8s.io/v1beta1/Cluster", "capi-pull")
        cp.settle()
        assert "capi-pull" not in cp.agents
        assert cp.store.try_get("Lease", "capi-pull", lease_ns) is None
        cp.tick(seconds=100)  # must not raise on the vanished cluster

"""Workload-class scheduling (sched/preemption.py, docs/SCHEDULING.md):

- segmented-tier parity: a mixed-priority micro-batch's decisions are
  bit-identical to solving the tiers as separate sequential rounds
  (single-chip and mesh legs), the tiered solve stays ONE launch, and
  steady-state jit_compiles == 0 holds with tiers active;
- gang atomicity: a K-binding gang commits all K placements in one batch
  cohort or none (mid-cohort stale-epoch veto re-admits the whole gang;
  store state asserted never-partial);
- preemption end-to-end: a full fleet + arriving high-priority binding
  evicts the minimal victim set, victims re-enter the queue and re-place
  where capacity remains, and the simulate preview answers the identical
  victim set without mutating anything;
- priority aging x streaming drain: a sustained high-priority flood must
  not starve a priority-0 gang — the aged gang eventually co-admits as
  one cohort (fake clock).
"""
from __future__ import annotations

import copy

import numpy as np
import pytest

import karmada_tpu.sched.preemption as preemption
from karmada_tpu.api.policy import PREEMPT_LOWER_PRIORITY
from karmada_tpu.api.work import (
    CONDITION_SCHEDULED,
    POLICY_PLACEMENT_ANNOTATION,
    REASON_GANG_TIMEOUT,
    REASON_GANG_UNSCHEDULABLE,
    TargetCluster,
)
from karmada_tpu.features import FeatureGates, PRIORITY_BASED_SCHEDULING
from karmada_tpu.metrics import gang_admissions, preemptions_total
from karmada_tpu.runtime.controller import Clock, Runtime
from karmada_tpu.sched.core import ArrayScheduler
from karmada_tpu.sched.scheduler import SchedulerDaemon, placement_json
from karmada_tpu.store.store import Store
from karmada_tpu.testing.fixtures import (
    duplicated_placement,
    new_cluster_with_resource,
    synthetic_fleet,
)
from tests.test_parallel import dyn_placement, make_binding


def tight_fleet(free=(4.0, 4.0, 4.0), alloc=8.0):
    """Clusters m0..mN with `alloc` cpu allocatable and free[i] cpu free
    (the rest pre-allocated) — whole cores, so the tier-residual integer
    units convert exactly on both parity legs."""
    out = []
    for i, f in enumerate(free):
        out.append(new_cluster_with_resource(
            f"m{i}",
            allocatable={"cpu": alloc, "memory": 64.0, "pods": 200.0},
            allocated={"cpu": alloc - f},
        ))
    return out


def mixed_priority_bindings(n=12, cpu=1.0):
    out = []
    for i in range(n):
        p = dyn_placement(aggregated=i % 3 == 0)
        rb = make_binding(f"b-{i}", 2 + i % 3, p, cpu=cpu)
        rb.spec.schedule_priority = (i % 3) * 5  # three tiers: 0 / 5 / 10
        out.append(rb)
    return out


def targets_of(dec):
    return tuple(sorted((t.name, t.replicas) for t in (dec.targets or [])))


def placements(store):
    return {
        rb.metadata.name: tuple(
            sorted((t.name, t.replicas) for t in (rb.spec.clusters or []))
        )
        for rb in store.list("ResourceBinding")
    }


def topology(clock=None, gates=None, **daemon_kwargs):
    store = Store()
    runtime = Runtime(clock=clock)
    daemon = SchedulerDaemon(store, runtime, gates=gates, **daemon_kwargs)
    return store, runtime, daemon


def mark_placed(rb, placement_targets):
    """Stamp a binding as already scheduled (applied-placement annotation +
    targets) so the daemon's trigger decision leaves it alone."""
    rb.spec.clusters = [
        TargetCluster(name=n, replicas=r) for n, r in placement_targets
    ]
    rb.metadata.annotations[POLICY_PLACEMENT_ANNOTATION] = placement_json(
        rb.spec.placement
    )
    return rb


def scheduled_condition(rb):
    return next(
        (c for c in rb.status.conditions if c.type == CONDITION_SCHEDULED),
        None,
    )


# --------------------------------------------------------------------------
# segmented tiers
# --------------------------------------------------------------------------


class TestTieredSolve:
    def test_parity_single_chip_contended(self):
        """Mixed-priority batch over a CONTENDED fleet: higher tiers claim
        availability first, lower tiers see the residual — bit-identical
        to sequential per-tier rounds against capacity-decremented fleets,
        in ONE launch."""
        clusters = tight_fleet(free=(5.0, 4.0, 3.0))
        arr = ArrayScheduler(clusters)
        bindings = mixed_priority_bindings(n=9)
        assert preemption.wants_tiers(arr, bindings)
        n0 = preemption.LAUNCHES.tiered
        pend = preemption.launch_tiered(arr, bindings)
        decisions = arr.materialize_chunk(pend)
        assert preemption.LAUNCHES.tiered - n0 == 1  # ONE launch, 3 tiers
        ref = preemption.solve_tiers_sequential(clusters, bindings)
        for d, r in zip(decisions, ref):
            assert (d.ok, targets_of(d)) == (r.ok, targets_of(r)), d.key
        # the residual must actually bite: at least one lower-tier row is
        # short or placed differently than a tier-blind solve would place
        blind = arr.schedule(bindings)
        assert any(
            (d.ok, targets_of(d)) != (b.ok, targets_of(b))
            for d, b in zip(decisions, blind)
        ), "fleet not contended enough to exercise tier residuals"

    def test_parity_with_unschedulable_middle_tier(self):
        """An unschedulable row's partial dispenser output must not be
        charged against the residual — the sequential reference subtracts
        nothing for failed rows, and a lower tier must still see the
        capacity the failed tier could not actually claim."""
        clusters = tight_fleet(free=(3.0, 3.0))
        arr = ArrayScheduler(clusters)
        hi = make_binding("hi", 4, dyn_placement(), cpu=1.0)
        hi.spec.schedule_priority = 20
        mid = make_binding("mid", 40, dyn_placement(), cpu=1.0)
        mid.spec.schedule_priority = 10  # cannot fit anywhere
        lo = make_binding("lo", 2, dyn_placement(), cpu=1.0)
        lo.spec.schedule_priority = 0  # fits in hi's residual
        bindings = [hi, mid, lo]
        decisions = arr.materialize_chunk(
            preemption.launch_tiered(arr, bindings)
        )
        ref = preemption.solve_tiers_sequential(clusters, bindings)
        for d, r in zip(decisions, ref):
            assert (d.ok, targets_of(d)) == (r.ok, targets_of(r)), d.key
        assert not decisions[1].ok and decisions[2].ok

    def test_parity_mesh(self):
        """Same contract on the mesh leg (sharded fleet tensors; GSPMD
        partitions the tiered kernel like every other round kernel)."""
        import jax

        from karmada_tpu.parallel import make_mesh

        clusters = tight_fleet(free=(5.0, 4.0, 3.0, 4.0))
        arr = ArrayScheduler(clusters, mesh=make_mesh(jax.devices()))
        bindings = mixed_priority_bindings(n=8)
        pend = preemption.launch_tiered(arr, bindings)
        decisions = arr.materialize_chunk(pend)
        ref = preemption.solve_tiers_sequential(clusters, bindings)
        for d, r in zip(decisions, ref):
            assert (d.ok, targets_of(d)) == (r.ok, targets_of(r)), d.key

    def test_uniform_priority_not_routed(self):
        clusters = tight_fleet()
        arr = ArrayScheduler(clusters)
        bindings = [make_binding(f"u-{i}", 2, dyn_placement(), cpu=0.5)
                    for i in range(4)]
        assert not preemption.wants_tiers(arr, bindings)

    def test_steady_state_zero_compiles(self):
        """Second tiered batch at the same bucketed shapes compiles
        nothing — the tiers/gangs-active steady-state invariant."""
        from karmada_tpu.sched.compilecache import (
            compile_counts, compile_delta,
        )

        clusters = tight_fleet()
        arr = ArrayScheduler(clusters)
        warm = mixed_priority_bindings(n=10)
        arr.materialize_chunk(preemption.launch_tiered(arr, warm))
        snap = compile_counts()
        again = mixed_priority_bindings(n=11)  # same row bucket (16)
        arr.materialize_chunk(preemption.launch_tiered(arr, again))
        assert compile_delta(snap)["jit_compiles"] == 0

    def test_streaming_micro_batch_tiers(self):
        """A mixed-priority backlog admits as ONE micro-batch that solves
        tiered (one launch) and lands the sequential-reference
        placements in the store."""
        clusters = tight_fleet(free=(5.0, 4.0, 3.0))
        store, _, daemon = topology()
        for c in clusters:
            store.create(copy.deepcopy(c))
        svc = daemon.streaming(batch_delay=0.0)
        # 8 bindings = exactly one drain-quota lattice bucket, so the whole
        # backlog admits as ONE micro-batch (9 would floor to 8 + 1)
        bindings = mixed_priority_bindings(n=8)
        for rb in bindings:
            store.create(copy.deepcopy(rb))
        n0 = preemption.LAUNCHES.tiered
        # ONE micro-batch: the whole backlog drains into a single tiered
        # launch. (A quiescent serve would then legitimately re-solve the
        # unschedulable losers alone — level-triggered retry against this
        # test's static capacity — so the parity snapshot is taken after
        # exactly the first batch.)
        svc.serve(max_batches=1)
        assert preemption.LAUNCHES.tiered - n0 == 1
        ref = preemption.solve_tiers_sequential(clusters, bindings)
        got = placements(store)
        for rb, r in zip(bindings, ref):
            want = targets_of(r) if r.ok else ()
            assert got[rb.metadata.name] == want, rb.metadata.name


# --------------------------------------------------------------------------
# gangs
# --------------------------------------------------------------------------


def gang_bindings(n=3, name="team", size=None, cpu=1.0, replicas=2,
                  priority=0):
    out = []
    for i in range(n):
        rb = make_binding(f"{name}-{i}", replicas, dyn_placement(), cpu=cpu)
        rb.spec.gang_name = name
        rb.spec.gang_size = size if size is not None else n
        rb.spec.schedule_priority = priority
        out.append(rb)
    return out


class TestGangScheduling:
    def test_gang_commits_all_in_one_cohort(self):
        clusters = tight_fleet(free=(8.0, 8.0))
        store, _, daemon = topology()
        for c in clusters:
            store.create(copy.deepcopy(c))
        svc = daemon.streaming(batch_delay=0.0)
        placed0 = gang_admissions.value(outcome="placed")
        for rb in gang_bindings(n=3):
            store.create(copy.deepcopy(rb))
        svc.serve(quiescent=True)
        got = placements(store)
        rvs = sorted(
            rb.metadata.resource_version
            for rb in store.list("ResourceBinding")
        )
        for i in range(3):
            assert sum(r for _, r in got[f"team-{i}"]) == 2
        # ONE update_batch cohort: the three commits mint contiguous rvs
        assert rvs[2] - rvs[0] == 2
        assert gang_admissions.value(outcome="placed") - placed0 == 1

    def test_gang_infeasible_commits_nothing(self):
        """One member cannot place → the joint feasibility check fails the
        WHOLE cohort: zero placements reach the store and every member
        carries the GangUnschedulable condition."""
        clusters = tight_fleet(free=(4.0, 4.0))
        store, _, daemon = topology()
        for c in clusters:
            store.create(copy.deepcopy(c))
        svc = daemon.streaming(batch_delay=0.0)
        rejected0 = gang_admissions.value(outcome="rejected")
        gang = gang_bindings(n=3, cpu=1.0, replicas=2)
        gang[2].spec.replicas = 50  # cannot fit anywhere
        for rb in gang:
            store.create(copy.deepcopy(rb))
        svc.serve(quiescent=True)
        got = placements(store)
        for i in range(3):
            assert got[f"team-{i}"] == (), "partial gang placement leaked"
            rb = store.get("ResourceBinding", f"team-{i}", "default")
            cond = scheduled_condition(rb)
            assert cond is not None and cond.status == "False"
            assert cond.reason == REASON_GANG_UNSCHEDULABLE
        assert gang_admissions.value(outcome="rejected") - rejected0 >= 1

    def test_partial_gang_holds_then_times_out(self):
        clock = Clock(fixed=50.0)
        clusters = tight_fleet(free=(8.0, 8.0))
        store, _, daemon = topology(clock=clock, gang_wait_seconds=30.0)
        for c in clusters:
            store.create(copy.deepcopy(c))
        svc = daemon.streaming(batch_delay=0.0)
        timeout0 = gang_admissions.value(outcome="timeout")
        gang = gang_bindings(n=3)
        for rb in gang[:2]:  # third member never arrives
            store.create(copy.deepcopy(rb))
        svc.serve(quiescent=True)
        assert placements(store)["team-0"] == ()  # held, not solved
        assert daemon.gangs.held_count() == 2
        clock.advance(31.0)
        assert daemon.gang_tick() == 1
        assert daemon.gangs.held_count() == 0
        for i in range(2):
            rb = store.get("ResourceBinding", f"team-{i}", "default")
            cond = scheduled_condition(rb)
            assert cond is not None and cond.reason == REASON_GANG_TIMEOUT
        assert gang_admissions.value(outcome="timeout") - timeout0 == 1
        # the late member completes a FRESH cohort: all three place
        store.create(copy.deepcopy(gang[2]))
        store.update(store.get("ResourceBinding", "team-0", "default"))
        store.update(store.get("ResourceBinding", "team-1", "default"))
        svc.serve(quiescent=True)
        got = placements(store)
        assert all(sum(r for _, r in got[f"team-{i}"]) == 2
                   for i in range(3))

    def test_midcohort_stale_epoch_readmits_whole_gang(self):
        """A member that dirties between the epoch snapshot and the patch
        vetoes the WHOLE gang — nothing commits (store never-partial) and
        the full cohort re-admits and places against the fresh spec."""
        from karmada_tpu.sched.pipeline import StageTimer

        clusters = tight_fleet(free=(8.0, 8.0))
        store, _, daemon = topology()
        for c in clusters:
            store.create(copy.deepcopy(c))
        svc = daemon.streaming(batch_delay=0.0)
        for rb in gang_bindings(n=3):
            store.create(copy.deepcopy(rb))
        array = daemon._ensure_fleet()
        svc._array = array
        svc._timer = StageTimer()
        mb = svc._form_batch(array)
        assert mb is not None and len(mb.keys) == 3  # gang released whole
        # dirty ONE member mid-flight (replicas 2→3)
        fresh = store.get("ResourceBinding", "team-1", "default")
        fresh.spec.replicas = 3
        store.update(fresh)
        with array.pipeline_context(svc._timer, overlap=True):
            stream = svc._open_stream(array, svc._timer)
            assert svc._submit(stream, array, mb)
            stream.drain()
            stream.close(raise_failure=True)
        svc._array = svc._timer = None
        got = placements(store)
        assert all(got[f"team-{i}"] == () for i in range(3)), (
            "stale-epoch veto leaked a partial gang commit"
        )
        assert svc._ready() >= 3  # whole gang re-admitted
        svc.serve(quiescent=True)
        got = placements(store)
        assert sum(r for _, r in got["team-1"]) == 3  # fresh spec won
        assert sum(r for _, r in got["team-0"]) == 2
        assert sum(r for _, r in got["team-2"]) == 2


# --------------------------------------------------------------------------
# preemption
# --------------------------------------------------------------------------


class TestPreemption:
    def _fleet(self):
        # m0: 4 cpu, fully held by the victim; m1: 8 cpu with 4 free
        return [
            new_cluster_with_resource(
                "m0", allocatable={"cpu": 4.0, "memory": 64.0,
                                   "pods": 200.0},
                allocated={"cpu": 4.0},
            ),
            new_cluster_with_resource(
                "m1", allocatable={"cpu": 8.0, "memory": 64.0,
                                   "pods": 200.0},
                allocated={"cpu": 4.0},
            ),
        ]

    def _victim(self):
        rb = make_binding("victim", 4, dyn_placement(), cpu=1.0)
        rb.spec.schedule_priority = 0
        rb.status.last_scheduled_time = 10.0
        return mark_placed(rb, [("m0", 4)])

    def _preemptor(self):
        rb = make_binding("urgent", 6, dyn_placement(), cpu=1.0)
        rb.spec.schedule_priority = 5
        rb.spec.preemption_policy = PREEMPT_LOWER_PRIORITY
        return rb

    def test_preemption_end_to_end_with_identical_preview(self):
        clusters = self._fleet()
        victim, urgent = self._victim(), self._preemptor()

        # preview FIRST — plain objects in, plan out, nothing mutated
        plan = preemption.preview_preemption(
            clusters, [victim, urgent], urgent,
        )
        assert plan.feasible
        preview_victims = sorted(
            (v.key, v.cluster, v.replicas) for v in plan.victims
        )
        assert preview_victims, "preview found no victims"
        assert victim.spec.clusters[0].replicas == 4  # untouched

        committed0 = preemptions_total.value(outcome="committed")
        store, runtime, daemon = topology()
        for c in clusters:
            store.create(copy.deepcopy(c))
        store.create(victim)
        runtime.settle()
        assert placements(store)["victim"] == (("m0", 4),)

        store.create(urgent)
        runtime.settle()
        got = placements(store)
        # the preemptor placed fully (6 replicas over m0-reclaimed + m1)
        assert sum(r for _, r in got["urgent"]) == 6
        assert preemptions_total.value(outcome="committed") - committed0 == 1
        # the victim's cut flowed through a graceful-eviction task and the
        # LIVE victim set matches the preview exactly
        v = store.get("ResourceBinding", "victim", "default")
        assert v.spec.graceful_eviction_tasks, "no eviction task on victim"
        live_victims = sorted(
            ("default/victim", t.from_cluster, t.replicas)
            for t in v.spec.graceful_eviction_tasks
        )
        assert live_victims == preview_victims
        # minimal disruption: only as many replicas as the deficit needed
        urgent_on_m0 = dict(got["urgent"]).get("m0", 0)
        assert sum(t.replicas for t in v.spec.graceful_eviction_tasks) \
            == urgent_on_m0
        # victims re-entered the queue and re-placed where capacity
        # remains (m1 has free cpu; m0 is excluded while evicting)
        assert sum(r for _, r in got["victim"]) == 4
        assert dict(got["victim"]).get("m0", 0) + urgent_on_m0 <= 4

    def test_preemption_infeasible_without_lower_priority(self):
        clusters = self._fleet()
        # the "victim" now outranks the arrival: nothing is reclaimable
        victim = self._victim()
        victim.spec.schedule_priority = 50
        infeasible0 = preemptions_total.value(outcome="infeasible")
        store, runtime, _ = topology()
        for c in clusters:
            store.create(copy.deepcopy(c))
        store.create(victim)
        runtime.settle()
        urgent = self._preemptor()
        store.create(urgent)
        runtime.settle()
        got = placements(store)
        assert got["urgent"] == ()  # stays pending
        rb = store.get("ResourceBinding", "urgent", "default")
        cond = scheduled_condition(rb)
        assert cond is not None and cond.status == "False"
        v = store.get("ResourceBinding", "victim", "default")
        assert not v.spec.graceful_eviction_tasks
        assert preemptions_total.value(outcome="infeasible") \
            - infeasible0 >= 1

    def test_two_preemptors_share_a_ledger_no_overcommit(self):
        """Two short-placed preemptors at DIFFERENT priorities in one
        micro-batch plan against one ledger: the second group must claim
        the victim replicas the first left, not re-count the same ones —
        the combined cut equals the combined placement (review-pinned;
        without the ledger each plan reclaimed the full victim and the
        max-merged commit overcommitted the cluster)."""
        clusters = [new_cluster_with_resource(
            "solo", allocatable={"cpu": 8.0, "memory": 64.0, "pods": 200.0},
            allocated={"cpu": 8.0},
        )]
        victim = make_binding("victim", 8, dyn_placement(), cpu=1.0)
        victim.spec.schedule_priority = 0
        mark_placed(victim, [("solo", 8)])
        store, _, daemon = topology()
        for c in clusters:
            store.create(copy.deepcopy(c))
        store.create(victim)
        svc = daemon.streaming(batch_delay=0.0)
        svc.serve(quiescent=True)
        for i, prio in enumerate((20, 10)):
            rb = make_binding(f"urgent-{i}", 4, dyn_placement(), cpu=1.0)
            rb.spec.schedule_priority = prio
            rb.spec.preemption_policy = PREEMPT_LOWER_PRIORITY
            store.create(rb)
        svc.serve(max_batches=1)  # ONE mixed-priority batch plans both
        got = placements(store)
        placed_total = sum(
            r for i in range(2) for _, r in got[f"urgent-{i}"]
        )
        v = store.get("ResourceBinding", "victim", "default")
        cut_total = sum(t.replicas for t in v.spec.graceful_eviction_tasks)
        # every placed preemptor replica is backed by exactly one cut
        # victim replica — never more placed than freed
        assert placed_total == cut_total == 8, (placed_total, cut_total)
        assert sum(t.replicas for t in v.spec.clusters) == 0

    def test_engine_rejects_preempt_scenarios(self):
        from karmada_tpu.api.simulation import SCENARIO_PREEMPT, Scenario
        from karmada_tpu.simulation.engine import SimulationError, Simulator

        sim = Simulator(self._fleet())
        with pytest.raises(SimulationError):
            sim.simulate([], [Scenario(kind=SCENARIO_PREEMPT,
                                       binding="default/urgent")])

    def test_controlplane_simulate_preview(self):
        """POST /simulate's backend: a Preemption scenario renders the
        planner's victim set in the report, store bindings untouched."""
        pytest.importorskip("cryptography")
        from karmada_tpu.api.simulation import (
            SCENARIO_PREEMPT, Scenario, SimulationRequest,
            SimulationRequestSpec,
        )
        from karmada_tpu.controlplane import ControlPlane

        cp = ControlPlane(controllers=["-scheduler"])
        for c in self._fleet():
            cp.store.create(c)
        cp.store.create(self._victim())
        cp.store.create(self._preemptor())
        report = cp.simulate(SimulationRequest(spec=SimulationRequestSpec(
            scenarios=[Scenario(kind=SCENARIO_PREEMPT,
                                binding="default/urgent")],
        )))
        assert len(report.scenarios) == 1
        sc = report.scenarios[0]
        assert sc.victims and sc.displaced == 1
        assert {v.binding for v in sc.victims} == {"default/victim"}
        v = cp.store.get("ResourceBinding", "victim", "default")
        assert v.spec.clusters[0].replicas == 4  # store untouched
        assert not v.spec.graceful_eviction_tasks


# --------------------------------------------------------------------------
# priority aging x streaming drain (anti-starvation)
# --------------------------------------------------------------------------


class TestAgingGangFlood:
    def test_flood_does_not_starve_aged_gang(self):
        """Sustained priority-5 flood against a priority-0 gang of 3 on a
        fake clock: while the flood outranks the gang its members never
        drain (quota smaller than the flood), but aging (+1/60 s) lifts
        them past the flood and the coordinator co-admits the gang as ONE
        cohort that commits atomically."""
        clock = Clock(fixed=1000.0)
        gates = FeatureGates({PRIORITY_BASED_SCHEDULING: True})
        clusters = tight_fleet(free=(8.0, 8.0, 8.0), alloc=16.0)
        store, _, daemon = topology(clock=clock, gates=gates)
        for c in clusters:
            store.create(copy.deepcopy(c))
        svc = daemon.streaming(batch_delay=0.0, max_batch=8)
        gang = gang_bindings(n=3, cpu=0.25, priority=0)
        for rb in gang:
            store.create(copy.deepcopy(rb))
        flood_n = 0

        def flood(n):
            nonlocal flood_n
            for _ in range(n):
                rb = make_binding(f"hot-{flood_n}", 1, dyn_placement(),
                                  cpu=0.1)
                rb.spec.schedule_priority = 5
                store.create(copy.deepcopy(rb))
                flood_n += 1

        # flood-dominated phase: 3 rounds of 16 fresh hi-prio arrivals, one
        # 8-key micro-batch admitted per round — the gang never out-ranks
        # the flood (age < 5 aging steps), so it stays queued/held
        for _ in range(3):
            flood(16)
            svc.serve(max_batches=1)
            clock.advance(60.0)  # +1 effective priority per round
        got = placements(store)
        assert all(got[f"team-{i}"] == () for i in range(3)), (
            "gang placed before aging could lift it — flood too weak"
        )
        # age past the flood priority (5 steps total), keep flooding: the
        # gang must now win the drain and co-admit as one cohort
        clock.advance(60.0 * 4)
        placed0 = gang_admissions.value(outcome="placed")
        for _ in range(4):
            flood(8)
            svc.serve(max_batches=2)
            clock.advance(60.0)
            if gang_admissions.value(outcome="placed") > placed0:
                break
        got = placements(store)
        assert all(sum(r for _, r in got[f"team-{i}"]) == 2
                   for i in range(3)), "aged gang still starved"
        assert gang_admissions.value(outcome="placed") - placed0 == 1
        # one cohort: contiguous rvs across the three members
        rvs = sorted(
            store.get("ResourceBinding", f"team-{i}",
                      "default").metadata.resource_version
            for i in range(3)
        )
        assert rvs[2] - rvs[0] == 2


# --------------------------------------------------------------------------
# webhook validation + detector plumbing
# --------------------------------------------------------------------------


class TestWorkloadClassValidation:
    def _policy(self, **spec_kwargs):
        from karmada_tpu.api.meta import ObjectMeta
        from karmada_tpu.api.policy import (
            Placement, PropagationPolicy, PropagationSpec, ResourceSelector,
        )

        return PropagationPolicy(
            metadata=ObjectMeta(name="pp"),
            spec=PropagationSpec(
                resource_selectors=[ResourceSelector(
                    api_version="apps/v1", kind="Deployment", name="web",
                )],
                placement=Placement(),
                **spec_kwargs,
            ),
        )

    def _validate(self, policy):
        from karmada_tpu.webhook.admission import AdmissionRequest
        from karmada_tpu.webhook.handlers import (
            _validate_propagation_policy,
        )

        _validate_propagation_policy(AdmissionRequest(
            operation="CREATE", kind="PropagationPolicy", obj=policy,
        ))

    def test_policy_accepts_valid_fields(self):
        self._validate(self._policy(
            scheduler_priority=100, scheduler_preemption="PreemptLowerPriority",
            gang_name="team", gang_size=4,
        ))

    def test_policy_rejects_out_of_range_priority(self):
        from karmada_tpu.webhook.admission import AdmissionDenied

        with pytest.raises(AdmissionDenied):
            self._validate(self._policy(scheduler_priority=10**10))

    def test_policy_rejects_bad_preemption_enum(self):
        from karmada_tpu.webhook.admission import AdmissionDenied

        with pytest.raises(AdmissionDenied):
            self._validate(self._policy(scheduler_preemption="Sometimes"))

    def test_policy_rejects_incoherent_gang(self):
        from karmada_tpu.webhook.admission import AdmissionDenied

        with pytest.raises(AdmissionDenied):
            self._validate(self._policy(gang_name="team", gang_size=0))
        with pytest.raises(AdmissionDenied):
            self._validate(self._policy(gang_size=3))

    def test_binding_webhook_validates_plumbed_fields(self):
        from karmada_tpu.webhook.admission import (
            AdmissionDenied, AdmissionRequest,
        )
        from karmada_tpu.webhook.handlers import _validate_binding

        rb = make_binding("b", 1, dyn_placement(), cpu=0.1)
        rb.spec.schedule_priority = 2 * 10**9  # past the bound
        with pytest.raises(AdmissionDenied):
            _validate_binding(AdmissionRequest(
                operation="CREATE", kind="ResourceBinding", obj=rb,
            ))

    def test_detector_plumbs_gang_and_priority(self):
        """Policy fields flow into the binding; template labels override
        them (several templates under one policy forming one gang)."""
        from karmada_tpu.api.meta import ObjectMeta
        from karmada_tpu.api.policy import (
            ClusterAffinity, Placement, PropagationPolicy, PropagationSpec,
            ResourceSelector,
        )
        from karmada_tpu.api.work import (
            GANG_NAME_LABEL, GANG_SIZE_LABEL, SCHEDULE_PRIORITY_LABEL,
        )
        from karmada_tpu.detector.detector import ResourceDetector
        from karmada_tpu.interpreter.interpreter import ResourceInterpreter
        from karmada_tpu.testing.fixtures import new_deployment

        store = Store()
        runtime = Runtime()
        ResourceDetector(store, ResourceInterpreter(), runtime)
        pol = PropagationPolicy(
            metadata=ObjectMeta(namespace="default", name="pp"),
            spec=PropagationSpec(
                resource_selectors=[ResourceSelector(
                    api_version="apps/v1", kind="Deployment",
                )],
                placement=Placement(
                    cluster_affinity=ClusterAffinity(cluster_names=["m0"]),
                ),
                scheduler_priority=7,
                scheduler_preemption="PreemptLowerPriority",
                gang_name="squad", gang_size=2,
            ),
        )
        store.create(pol)
        dep = new_deployment("default", "web", replicas=2)
        store.create(dep)
        labeled = new_deployment("default", "api", replicas=2)
        labeled.metadata.labels[GANG_NAME_LABEL] = "other"
        labeled.metadata.labels[GANG_SIZE_LABEL] = "5"
        labeled.metadata.labels[SCHEDULE_PRIORITY_LABEL] = "42"
        store.create(labeled)
        runtime.settle()
        rb = store.get("ResourceBinding", "web-deployment", "default")
        assert (rb.spec.schedule_priority, rb.spec.preemption_policy,
                rb.spec.gang_name, rb.spec.gang_size) == (
            7, "PreemptLowerPriority", "squad", 2)
        rb2 = store.get("ResourceBinding", "api-deployment", "default")
        assert (rb2.spec.schedule_priority, rb2.spec.gang_name,
                rb2.spec.gang_size) == (42, "other", 5)


# --------------------------------------------------------------------------
# rebalancer re-pack mode + printer
# --------------------------------------------------------------------------


class TestRebalancerRepack:
    def test_repack_triggers_only_improving_moves(self):
        from types import SimpleNamespace

        from karmada_tpu.api.apps import (
            REASON_NO_IMPROVING_MOVE, REASON_REPACK_TRIGGERED,
            RebalancerObjectReference, WorkloadRebalancer,
            WorkloadRebalancerSpec,
        )
        from karmada_tpu.api.meta import ObjectMeta
        from karmada_tpu.controllers.rebalancer import (
            WorkloadRebalancerController,
        )
        from karmada_tpu.utils.names import binding_name

        clock = Clock(fixed=500.0)
        store = Store()
        runtime = Runtime(clock=clock)
        ctl = WorkloadRebalancerController(store, runtime)
        for c in tight_fleet(free=(8.0, 8.0)):
            store.create(c)
        # "short": placed 1 of 4 replicas — a fresh solve lands all 4
        short = make_binding("short", 4, dyn_placement(), cpu=1.0)
        short.metadata.name = binding_name("Deployment", "short")
        mark_placed(short, [("m0", 1)])
        store.create(short)
        # "full": placed all its replicas — re-pack must not churn it
        full = make_binding("full", 2, dyn_placement(), cpu=1.0)
        full.metadata.name = binding_name("Deployment", "full")
        mark_placed(full, [("m1", 2)])
        store.create(full)
        store.create(WorkloadRebalancer(
            metadata=ObjectMeta(name="repacker"),
            spec=WorkloadRebalancerSpec(
                workloads=[
                    RebalancerObjectReference(
                        api_version="apps/v1", kind="Deployment",
                        namespace="default", name="short"),
                    RebalancerObjectReference(
                        api_version="apps/v1", kind="Deployment",
                        namespace="default", name="full"),
                ],
                repack_every_seconds=120,
            ),
        ))
        runtime.settle()
        assert ctl.tick() == 1  # exactly the improving move fired
        srb = store.get("ResourceBinding", binding_name("Deployment",
                                                        "short"), "default")
        frb = store.get("ResourceBinding", binding_name("Deployment",
                                                        "full"), "default")
        assert srb.spec.reschedule_triggered_at == 500.0
        assert frb.spec.reschedule_triggered_at is None
        reb = store.get("WorkloadRebalancer", "repacker")
        reasons = {w.workload.name: w.reason
                   for w in reb.status.observed_workloads}
        assert reasons == {"short": REASON_REPACK_TRIGGERED,
                           "full": REASON_NO_IMPROVING_MOVE}
        assert reb.status.finish_time is None  # periodic: never finishes
        assert reb.status.last_repack_time == 500.0
        # inside the interval: no second pass
        clock.advance(60.0)
        assert ctl.tick() == 0
        clock.advance(61.0)
        ctl.tick()  # due again (whether it fires depends on state)

        # printer: NAME/WORKLOADS/SUCCESSFUL/FAILED/FINISHED + wide TTL
        from karmada_tpu.cli.karmadactl import cmd_get

        cp = SimpleNamespace(store=store, members={})
        out = cmd_get(cp, "workloadrebalancers")
        assert out.splitlines()[0].split() == [
            "NAME", "WORKLOADS", "SUCCESSFUL", "FAILED", "FINISHED"]
        assert "repacker" in out and "<periodic>" in out
        wide = cmd_get(cp, "wr", output="wide")
        assert "TTL" in wide.splitlines()[0]
        assert "120s" in wide


# --------------------------------------------------------------------------
# the smoke wrapper (slow path)
# --------------------------------------------------------------------------


@pytest.mark.slow
class TestPreemptSmokeScript:
    def test_preempt_smoke(self):
        """scripts/preempt_smoke.sh: the `preempt` bench config against the
        live streaming topology — preemption-decision p99 within 2x of
        non-preempting admissions on the same SLO histogram, victims
        re-placed, solves O(1) in gang count — asserted from the emitted
        JSON line."""
        import os
        import subprocess

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            ["bash", "scripts/preempt_smoke.sh"],
            capture_output=True, text=True, timeout=900, cwd=repo,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "PREEMPT OK" in r.stdout

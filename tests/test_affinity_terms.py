"""Ordered cluster-affinity terms (scheduler.go:562-625 failover loop)."""
from __future__ import annotations

from karmada_tpu.api.meta import ObjectMeta, new_uid
from karmada_tpu.api.policy import (
    ClusterAffinity,
    ClusterAffinityTerm,
    Placement,
)
from karmada_tpu.api.work import BindingSpec, ObjectReference, ResourceBinding
from karmada_tpu.sched.core import ArrayScheduler
from karmada_tpu.testing.fixtures import new_cluster_with_resource


def fleet():
    return [
        new_cluster_with_resource(f"m{i}", {"cpu": 10.0}) for i in range(1, 4)
    ]


def binding(terms, observed=""):
    rb = ResourceBinding(
        metadata=ObjectMeta(namespace="default", name="web", uid=new_uid("rb")),
        spec=BindingSpec(
            resource=ObjectReference(api_version="apps/v1", kind="Deployment",
                                     namespace="default", name="web"),
            replicas=2,
            placement=Placement(cluster_affinities=[
                ClusterAffinityTerm(affinity_name=name,
                                    affinity=ClusterAffinity(cluster_names=names))
                for name, names in terms
            ]),
        ),
    )
    rb.status.scheduler_observed_affinity_name = observed
    return rb


class TestOrderedAffinityTerms:
    def test_first_term_wins_when_feasible(self):
        sched = ArrayScheduler(fleet())
        (d,) = sched.schedule([binding([("primary", ["m1"]), ("backup", ["m2"])])])
        assert d.ok
        assert d.affinity_name == "primary"
        assert [t.name for t in d.targets] == ["m1"]

    def test_falls_through_to_next_term(self):
        sched = ArrayScheduler(fleet())
        # first term matches nothing in the fleet
        (d,) = sched.schedule([binding([("primary", ["gone"]), ("backup", ["m2"])])])
        assert d.ok
        assert d.affinity_name == "backup"
        assert [t.name for t in d.targets] == ["m2"]

    def test_all_terms_fail(self):
        sched = ArrayScheduler(fleet())
        (d,) = sched.schedule([binding([("a", ["gone1"]), ("b", ["gone2"])])])
        assert not d.ok

    def test_resumes_from_observed_term(self):
        sched = ArrayScheduler(fleet())
        # observed=backup → starts at backup even though primary is feasible
        (d,) = sched.schedule(
            [binding([("primary", ["m1"]), ("backup", ["m2"])], observed="backup")]
        )
        assert d.affinity_name == "backup"
        assert [t.name for t in d.targets] == ["m2"]

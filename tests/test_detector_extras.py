"""Detector claim stability, policy preemption (gate), lazy activation,
schedule priority propagation, Job completions split."""
from __future__ import annotations

import pytest

from karmada_tpu.api.unstructured import Unstructured
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.features import FeatureGates, POLICY_PREEMPTION
from karmada_tpu.members.member import MemberConfig
from karmada_tpu.testing.fixtures import (
    duplicated_placement,
    new_deployment,
    new_policy,
    selector_for,
    static_weight_placement,
)


def plane(gates=None):
    cp = ControlPlane(gates=gates)
    cp.join_member(MemberConfig(name="m1", allocatable={"cpu": 100.0}))
    cp.join_member(MemberConfig(name="m2", allocatable={"cpu": 100.0}))
    return cp


class TestClaimStability:
    def test_claimed_template_keeps_policy_without_gate(self):
        cp = plane()
        dep = new_deployment("default", "web", replicas=1)
        cp.store.create(dep)
        cp.store.create(new_policy("default", "pp-a", [selector_for(dep)],
                                   duplicated_placement(["m1"])))
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert [t.name for t in rb.spec.clusters] == ["m1"]
        # a higher-priority policy appears but the gate is off → no preemption
        high = new_policy("default", "pp-b", [selector_for(dep)],
                          duplicated_placement(["m2"]))
        high.spec.priority = 10
        high.spec.preemption = "Always"
        cp.store.create(high)
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert [t.name for t in rb.spec.clusters] == ["m1"]

    def test_preemption_with_gate(self):
        cp = plane(gates=FeatureGates({POLICY_PREEMPTION: True}))
        dep = new_deployment("default", "web", replicas=1)
        cp.store.create(dep)
        cp.store.create(new_policy("default", "pp-a", [selector_for(dep)],
                                   duplicated_placement(["m1"])))
        cp.settle()
        high = new_policy("default", "pp-b", [selector_for(dep)],
                          duplicated_placement(["m2"]))
        high.spec.priority = 10
        high.spec.preemption = "Always"
        cp.store.create(high)
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert [t.name for t in rb.spec.clusters] == ["m2"]
        template = cp.store.get("apps/v1/Deployment", "web", "default")
        assert template.metadata.annotations["policy.karmada.io/name"] == "pp-b"

    def test_no_preemption_without_always(self):
        cp = plane(gates=FeatureGates({POLICY_PREEMPTION: True}))
        dep = new_deployment("default", "web", replicas=1)
        cp.store.create(dep)
        cp.store.create(new_policy("default", "pp-a", [selector_for(dep)],
                                   duplicated_placement(["m1"])))
        cp.settle()
        high = new_policy("default", "pp-b", [selector_for(dep)],
                          duplicated_placement(["m2"]))
        high.spec.priority = 10  # preemption stays default "Never"
        cp.store.create(high)
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert [t.name for t in rb.spec.clusters] == ["m1"]

    def test_claim_released_when_policy_stops_matching(self):
        cp = plane()
        dep = new_deployment("default", "web", replicas=1)
        cp.store.create(dep)
        cp.store.create(new_policy("default", "pp-a", [selector_for(dep)],
                                   duplicated_placement(["m1"])))
        cp.store.create(new_policy("default", "pp-b", [selector_for(dep)],
                                   duplicated_placement(["m2"])))
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert [t.name for t in rb.spec.clusters] == ["m1"]  # name asc wins
        cp.store.delete("PropagationPolicy", "pp-a", "default")
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert [t.name for t in rb.spec.clusters] == ["m2"]


class TestLazyActivation:
    def test_policy_update_deferred_until_template_change(self):
        cp = plane()
        dep = new_deployment("default", "web", replicas=1)
        cp.store.create(dep)
        pol = new_policy("default", "pp", [selector_for(dep)],
                         duplicated_placement(["m1"]))
        pol.spec.activation_preference = "Lazy"
        cp.store.create(pol)
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert [t.name for t in rb.spec.clusters] == ["m1"]
        # policy changes target — binding must NOT move yet
        pol = cp.store.get("PropagationPolicy", "pp", "default")
        pol.spec.placement = duplicated_placement(["m2"])
        cp.store.update(pol)
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert [t.name for t in rb.spec.clusters] == ["m1"]
        # template change activates the pending policy
        dep2 = cp.store.get("apps/v1/Deployment", "web", "default")
        dep2.set("spec", "replicas", 2)
        cp.store.update(dep2)
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert [t.name for t in rb.spec.clusters] == ["m2"]


class TestSchedulePriorityPropagation:
    def test_priority_copied_to_binding(self):
        cp = plane()
        dep = new_deployment("default", "web", replicas=1)
        cp.store.create(dep)
        pol = new_policy("default", "pp", [selector_for(dep)], duplicated_placement())
        pol.spec.scheduler_priority = 7
        cp.store.create(pol)
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert rb.spec.schedule_priority == 7


class TestJobCompletionsSplit:
    def test_divided_job_splits_completions(self):
        cp = plane()
        job = Unstructured({
            "apiVersion": "batch/v1",
            "kind": "Job",
            "metadata": {"namespace": "default", "name": "batch"},
            "spec": {
                "parallelism": 9,
                "completions": 9,
                "template": {"spec": {"containers": [{"name": "c", "image": "busybox"}]}},
            },
        })
        cp.store.create(job)
        cp.store.create(new_policy(
            "default", "pp", [selector_for(job)],
            static_weight_placement({"m1": 1, "m2": 2}),
        ))
        cp.settle()
        j1 = cp.members["m1"].get("batch/v1", "Job", "batch", "default")
        j2 = cp.members["m2"].get("batch/v1", "Job", "batch", "default")
        assert j1 is not None and j2 is not None
        assert int(j1.get("spec", "completions")) + int(j2.get("spec", "completions")) == 9
        assert int(j2.get("spec", "completions")) == 6  # 2/3 share

"""Default native interpreter matrix (I2): per-kind per-operation behavior
mirroring pkg/resourceinterpreter/default/native/*.go, plus the
federated-generation protocol end to end."""
from __future__ import annotations

import pytest

from karmada_tpu.api.unstructured import Unstructured
from karmada_tpu.api.work import AggregatedStatusItem
from karmada_tpu.interpreter.interpreter import (
    HEALTHY,
    ResourceInterpreter,
    UNHEALTHY,
)


def interp() -> ResourceInterpreter:
    return ResourceInterpreter()


def obj(api_version, kind, *, spec=None, status=None, generation=1,
        ns="default", name="x", labels=None, annotations=None, data=None,
        typ=None, secrets=None):
    m = {
        "apiVersion": api_version, "kind": kind,
        "metadata": {"name": name, "namespace": ns, "generation": generation,
                     "labels": dict(labels or {}),
                     "annotations": dict(annotations or {})},
    }
    if spec is not None:
        m["spec"] = spec
    if status is not None:
        m["status"] = status
    if data is not None:
        m["data"] = data
    if typ is not None:
        m["type"] = typ
    if secrets is not None:
        m["secrets"] = secrets
    return Unstructured(m)


def item(cluster, status):
    return AggregatedStatusItem(cluster_name=cluster, status=status)


class TestDeployment:
    def test_aggregate_observed_generation_protocol(self):
        tmpl = obj("apps/v1", "Deployment", generation=3,
                   spec={"replicas": 4}, status={"observedGeneration": 2})
        caught_up = [
            item("m1", {"replicas": 2, "readyReplicas": 2,
                        "generation": 7, "observedGeneration": 7,
                        "resourceTemplateGeneration": 3}),
            item("m2", {"replicas": 2, "readyReplicas": 2,
                        "generation": 5, "observedGeneration": 5,
                        "resourceTemplateGeneration": 3}),
        ]
        st = interp().aggregate_status(tmpl, caught_up).get("status")
        assert st["replicas"] == 4 and st["readyReplicas"] == 4
        assert st["observedGeneration"] == 3  # every member caught up
        # one member on a stale template revision → holds the previous value
        stale = [caught_up[0],
                 item("m2", {"replicas": 2, "generation": 5,
                             "observedGeneration": 5,
                             "resourceTemplateGeneration": 2})]
        tmpl2 = obj("apps/v1", "Deployment", generation=3,
                    spec={"replicas": 4}, status={"observedGeneration": 2})
        st2 = interp().aggregate_status(tmpl2, stale).get("status")
        assert st2["observedGeneration"] == 2

    def test_reflect_lifts_generation_annotation(self):
        o = obj("apps/v1", "Deployment", generation=6,
                annotations={"resourcetemplate.karmada.io/generation": "4"},
                status={"replicas": 2, "readyReplicas": 2,
                        "observedGeneration": 6})
        st = interp().reflect_status(o)
        assert st["generation"] == 6
        assert st["resourceTemplateGeneration"] == 4
        assert st["readyReplicas"] == 2

    def test_retain_replicas_label(self):
        ri = interp()
        desired = obj("apps/v1", "Deployment", spec={"replicas": 3},
                      labels={"resourcetemplate.karmada.io/retain-replicas":
                              "true"})
        observed = obj("apps/v1", "Deployment", spec={"replicas": 9})
        assert ri.retain(desired, observed).get("spec", "replicas") == 9
        plain = obj("apps/v1", "Deployment", spec={"replicas": 3})
        assert ri.retain(plain, observed).get("spec", "replicas") == 3


class TestReplicaSetAndDaemonSet:
    def test_replicaset_health(self):
        ri = interp()
        ok = obj("apps/v1", "ReplicaSet", generation=1,
                 spec={"replicas": 2},
                 status={"observedGeneration": 1, "availableReplicas": 2})
        assert ri.interpret_health(ok) == HEALTHY
        low = obj("apps/v1", "ReplicaSet", generation=1,
                  spec={"replicas": 3},
                  status={"observedGeneration": 1, "availableReplicas": 2})
        assert ri.interpret_health(low) == UNHEALTHY

    def test_daemonset_aggregate_and_health(self):
        ri = interp()
        tmpl = obj("apps/v1", "DaemonSet", generation=1, status={})
        items = [
            item("m1", {"desiredNumberScheduled": 3, "numberReady": 3,
                        "updatedNumberScheduled": 3, "numberAvailable": 3,
                        "generation": 1, "observedGeneration": 1,
                        "resourceTemplateGeneration": 1}),
        ]
        st = ri.aggregate_status(tmpl, items).get("status")
        assert st["desiredNumberScheduled"] == 3
        assert st["observedGeneration"] == 1
        healthy = obj("apps/v1", "DaemonSet", generation=1,
                      status={"observedGeneration": 1,
                              "desiredNumberScheduled": 2,
                              "updatedNumberScheduled": 2,
                              "numberAvailable": 2})
        assert ri.interpret_health(healthy) == HEALTHY


class TestJob:
    def test_aggregate_conditions_and_times(self):
        tmpl = obj("batch/v1", "Job", spec={"parallelism": 2}, status={})
        items = [
            item("m1", {"succeeded": 1, "startTime": "2024-01-01T00:00:00Z",
                        "completionTime": "2024-01-01T01:00:00Z",
                        "conditions": [{"type": "Complete",
                                        "status": "True"}]}),
            item("m2", {"succeeded": 1, "startTime": "2024-01-01T00:30:00Z",
                        "completionTime": "2024-01-01T02:00:00Z",
                        "conditions": [{"type": "Complete",
                                        "status": "True"}]}),
        ]
        st = interp().aggregate_status(tmpl, items).get("status")
        assert st["succeeded"] == 2
        assert [c["type"] for c in st["conditions"]] == ["Complete"]
        assert st["startTime"] == "2024-01-01T00:00:00Z"  # earliest
        assert st["completionTime"] == "2024-01-01T02:00:00Z"  # latest

    def test_aggregate_failed_lists_clusters(self):
        tmpl = obj("batch/v1", "Job", status={})
        items = [item("m1", {"failed": 1, "conditions": [
            {"type": "Failed", "status": "True"}]})]
        st = interp().aggregate_status(tmpl, items).get("status")
        cond = st["conditions"][0]
        assert cond["type"] == "Failed" and "m1" in cond["message"]

    def test_finished_job_never_updates(self):
        tmpl = obj("batch/v1", "Job", status={
            "succeeded": 5,
            "conditions": [{"type": "Complete", "status": "True"}]})
        st = interp().aggregate_status(
            tmpl, [item("m1", {"succeeded": 1})]
        ).get("status")
        assert st["succeeded"] == 5  # untouched

    def test_retain_selector(self):
        desired = obj("batch/v1", "Job", spec={"template": {"metadata": {}}})
        observed = obj("batch/v1", "Job", spec={
            "selector": {"matchLabels": {"controller-uid": "u1"}},
            "template": {"metadata": {"labels": {"controller-uid": "u1"}}},
        })
        out = interp().retain(desired, observed)
        assert out.get("spec", "selector", "matchLabels") == {
            "controller-uid": "u1"}
        assert out.get("spec", "template", "metadata", "labels") == {
            "controller-uid": "u1"}


class TestCronJob:
    def test_aggregate_latest_times(self):
        tmpl = obj("batch/v1", "CronJob", status={})
        items = [
            item("m1", {"active": [{"name": "j1"}],
                        "lastScheduleTime": "2024-01-01T00:00:00Z"}),
            item("m2", {"active": [{"name": "j2"}],
                        "lastScheduleTime": "2024-01-02T00:00:00Z"}),
        ]
        st = interp().aggregate_status(tmpl, items).get("status")
        assert len(st["active"]) == 2
        assert st["lastScheduleTime"] == "2024-01-02T00:00:00Z"

    def test_dependencies_from_job_template(self):
        o = obj("batch/v1", "CronJob", spec={"jobTemplate": {"spec": {
            "template": {"spec": {"volumes": [
                {"name": "v", "configMap": {"name": "cm"}}]}}}}})
        assert {d["name"] for d in interp().get_dependencies(o)} == {"cm"}


class TestPod:
    def test_replicas_is_one_with_own_spec(self):
        o = obj("v1", "Pod", spec={"containers": [
            {"resources": {"requests": {"cpu": "2"}}}]})
        n, req = interp().get_replicas(o)
        assert n == 1 and req.resource_request["cpu"] == 2.0

    def test_aggregate_phase_precedence(self):
        ri = interp()
        tmpl = obj("v1", "Pod", status={})
        st = ri.aggregate_status(tmpl, [
            item("m1", {"phase": "Running"}),
            item("m2", {"phase": "Failed"}),
        ]).get("status")
        assert st["phase"] == "Failed"
        tmpl2 = obj("v1", "Pod", status={})
        st2 = ri.aggregate_status(tmpl2, [
            item("m1", {"phase": "Running"}),
            AggregatedStatusItem(cluster_name="m2", status=None),  # pending
        ]).get("status")
        assert st2["phase"] == "Pending"

    def test_health(self):
        ri = interp()
        ok = obj("v1", "Pod", status={"phase": "Running", "conditions": [
            {"type": "Ready", "status": "True"}]})
        assert ri.interpret_health(ok) == HEALTHY
        assert ri.interpret_health(
            obj("v1", "Pod", status={"phase": "Succeeded"})) == HEALTHY
        assert ri.interpret_health(
            obj("v1", "Pod", status={"phase": "Running"})) == UNHEALTHY

    def test_retain_member_fields(self):
        desired = obj("v1", "Pod", spec={"containers": [{"name": "c"}]})
        observed = obj("v1", "Pod", spec={
            "nodeName": "node-7", "serviceAccountName": "sa",
            "volumes": [{"name": "tok"}],
            "containers": [{"name": "c", "volumeMounts": [{"name": "tok"}]}],
        })
        out = interp().retain(desired, observed)
        assert out.get("spec", "nodeName") == "node-7"
        assert out.get("spec", "containers")[0]["volumeMounts"] == [
            {"name": "tok"}]


class TestServiceAndIngress:
    def test_service_lb_aggregate_dedupes_and_sorts(self):
        tmpl = obj("v1", "Service", spec={"type": "LoadBalancer"}, status={})
        items = [
            item("m1", {"loadBalancer": {"ingress": [{"ip": "10.0.0.2"}]}}),
            item("m2", {"loadBalancer": {"ingress": [{"ip": "10.0.0.1"},
                                                     {"ip": "10.0.0.2"}]}}),
        ]
        st = interp().aggregate_status(tmpl, items).get("status")
        assert st["loadBalancer"]["ingress"] == [
            {"ip": "10.0.0.1"}, {"ip": "10.0.0.2"}]

    def test_clusterip_service_aggregate_noop(self):
        tmpl = obj("v1", "Service", spec={"type": "ClusterIP"},
                   status={"x": 1})
        st = interp().aggregate_status(tmpl, [item("m1", {})]).get("status")
        assert st == {"x": 1}

    def test_service_retain(self):
        desired = obj("v1", "Service", spec={"type": "LoadBalancer"})
        observed = obj("v1", "Service", spec={
            "clusterIP": "10.96.0.5", "healthCheckNodePort": 30101})
        out = interp().retain(desired, observed)
        assert out.get("spec", "clusterIP") == "10.96.0.5"
        assert out.get("spec", "healthCheckNodePort") == 30101

    def test_ingress_health_and_deps(self):
        ri = interp()
        ok = obj("networking.k8s.io/v1", "Ingress",
                 status={"loadBalancer": {"ingress": [{"ip": "1.2.3.4"}]}})
        assert ri.interpret_health(ok) == HEALTHY
        o = obj("networking.k8s.io/v1", "Ingress",
                spec={"tls": [{"secretName": "tls-cert"}]})
        assert [d["name"] for d in ri.get_dependencies(o)] == ["tls-cert"]


class TestVolumesAndPolicy:
    def test_pv_phase_precedence(self):
        tmpl = obj("v1", "PersistentVolume", status={})
        st = interp().aggregate_status(tmpl, [
            item("m1", {"phase": "Bound"}),
            item("m2", {"phase": "Available"}),
        ]).get("status")
        assert st["phase"] == "Available"

    def test_pvc_lost_short_circuits(self):
        tmpl = obj("v1", "PersistentVolumeClaim", status={})
        st = interp().aggregate_status(tmpl, [
            item("m1", {"phase": "Lost"}),
            item("m2", {"phase": "Bound"}),
        ]).get("status")
        assert st["phase"] == "Lost"

    def test_pvc_retain_volume_name(self):
        desired = obj("v1", "PersistentVolumeClaim", spec={})
        observed = obj("v1", "PersistentVolumeClaim",
                       spec={"volumeName": "pv-123"})
        assert interp().retain(desired, observed).get(
            "spec", "volumeName") == "pv-123"

    def test_pv_retain_claim_ref(self):
        desired = obj("v1", "PersistentVolume", spec={})
        observed = obj("v1", "PersistentVolume",
                       spec={"claimRef": {"name": "pvc-a"}})
        assert interp().retain(desired, observed).get(
            "spec", "claimRef") == {"name": "pvc-a"}

    def test_pdb_aggregate_prefixes_disrupted_pods(self):
        tmpl = obj("policy/v1", "PodDisruptionBudget", status={})
        st = interp().aggregate_status(tmpl, [
            item("m1", {"currentHealthy": 2, "desiredHealthy": 2,
                        "disruptedPods": {"p1": "t1"}}),
            item("m2", {"currentHealthy": 1, "desiredHealthy": 1}),
        ]).get("status")
        assert st["currentHealthy"] == 3
        assert st["disruptedPods"] == {"m1/p1": "t1"}

    def test_hpa_aggregate(self):
        tmpl = obj("autoscaling/v2", "HorizontalPodAutoscaler", status={})
        st = interp().aggregate_status(tmpl, [
            item("m1", {"currentReplicas": 2, "desiredReplicas": 3}),
            item("m2", {"currentReplicas": 1, "desiredReplicas": 1}),
        ]).get("status")
        assert st["currentReplicas"] == 3 and st["desiredReplicas"] == 4


class TestSecretsAndServiceAccounts:
    def test_sa_token_secret_retained(self):
        ri = interp()
        desired = obj("v1", "Secret", typ="kubernetes.io/service-account-token",
                      data={})
        observed = obj("v1", "Secret",
                       typ="kubernetes.io/service-account-token",
                       data={"token": "abc"})
        assert ri.retain(desired, observed).get("data") == {"token": "abc"}
        plain_desired = obj("v1", "Secret", typ="Opaque", data={"k": "v"})
        plain_observed = obj("v1", "Secret", typ="Opaque", data={"k": "w"})
        assert ri.retain(plain_desired, plain_observed).get("data") == {"k": "v"}

    def test_service_account_secret_merge(self):
        desired = obj("v1", "ServiceAccount", secrets=[{"name": "a"}])
        observed = obj("v1", "ServiceAccount",
                       secrets=[{"name": "a"}, {"name": "token-xyz"}])
        out = interp().retain(desired, observed)
        assert out.get("secrets") == [{"name": "a"}, {"name": "token-xyz"}]


class TestStatefulSetDeps:
    def test_volume_claim_template_pvcs_excluded(self):
        o = obj("apps/v1", "StatefulSet", spec={
            "volumeClaimTemplates": [{"metadata": {"name": "data"}}],
            "template": {"spec": {"volumes": [
                {"name": "d", "persistentVolumeClaim": {"claimName": "data"}},
                {"name": "x", "persistentVolumeClaim": {"claimName": "extern"}},
            ]}},
        })
        deps = interp().get_dependencies(o)
        names = {d["name"] for d in deps if d["kind"] == "PersistentVolumeClaim"}
        assert names == {"extern"}


class TestServiceImport:
    def test_derived_service_and_endpointslice(self):
        o = obj("multicluster.x-k8s.io/v1alpha1", "ServiceImport", name="web")
        deps = interp().get_dependencies(o)
        assert deps[0] == {"apiVersion": "v1", "kind": "Service",
                           "namespace": "default", "name": "derived-web"}
        assert deps[1]["kind"] == "EndpointSlice"
        assert deps[1]["labelSelector"]["matchLabels"][
            "kubernetes.io/service-name"] == "derived-web"


class TestGenerationProtocolEndToEnd:
    def test_binding_stamps_annotation_and_aggregate_converges(self):
        """The federated-generation protocol through the REAL pipeline:
        ensureWork stamps resourcetemplate.karmada.io/generation on member
        manifests; status reflection lifts it; the aggregation's caught-up
        count advances the template's observedGeneration."""
        from karmada_tpu.controlplane import ControlPlane
        from karmada_tpu.members.member import MemberConfig
        from karmada_tpu.testing.fixtures import (
            duplicated_placement,
            new_deployment,
            new_policy,
            selector_for,
        )

        cp = ControlPlane()
        cp.join_member(MemberConfig(name="m1", allocatable={"cpu": 100.0}))
        cp.join_member(MemberConfig(name="m2", allocatable={"cpu": 100.0}))
        dep = new_deployment("default", "web", replicas=2)
        cp.store.create(dep)
        cp.store.create(new_policy("default", "pp", [selector_for(dep)],
                                   duplicated_placement(["m1", "m2"])))
        cp.settle()

        for m in ("m1", "m2"):
            got = cp.members[m].get("apps/v1", "Deployment", "web", "default")
            assert got.metadata.annotations[
                "resourcetemplate.karmada.io/generation"
            ] == str(cp.store.get("apps/v1/Deployment", "web",
                                  "default").metadata.generation)

        tmpl = cp.store.get("apps/v1/Deployment", "web", "default")
        st = tmpl.get("status") or {}
        assert st.get("replicas") == 4  # 2 members x 2 duplicated replicas
        # every member runs the latest template revision + its own status
        # is current → aggregated observedGeneration == template generation
        assert st.get("observedGeneration") == tmpl.metadata.generation

"""Coordination plane: lease CAS, leader election, write fencing, hot
standby failover, and the data-dir flock (docs/HA.md).

The split-brain scenarios the subsystem exists for:
- two electors racing acquire -> exactly one leader;
- a leader paused past its TTL resumes -> renew rejected AND its fenced
  in-flight write bounces with 409;
- leader dies mid-round -> the standby is promoted within one lease TTL
  and the two-daemon run's placements are bit-identical to a
  single-daemon run;
- a second server on one --data-dir exits non-zero, fast.
"""
from __future__ import annotations

import subprocess
import sys
import threading
import time

import pytest

from karmada_tpu.api.coordination import (
    LEADER_LEASE_NAMESPACE,
    LeaderLease,
)
from karmada_tpu.api.meta import CPU, ObjectMeta, new_uid
from karmada_tpu.api.work import (
    BindingSpec,
    ObjectReference,
    ReplicaRequirements,
    ResourceBinding,
)
from karmada_tpu.coordination import (
    DataDirLockedError,
    Elector,
    FencingError,
    LeaseCoordinator,
    LocalLeaseClient,
    StaleLeaseError,
    lock_data_dir,
)
from karmada_tpu.runtime.controller import Clock, Runtime
from karmada_tpu.server.apiserver import ControlPlaneServer
from karmada_tpu.server.remote import RemoteStore
from karmada_tpu.store.store import ConflictError, Store


def wait_until(pred, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


class MiniPlane:
    """Store + coordinator + clock: everything the serving/coordination
    seam needs, without the full ControlPlane (which requires the
    cryptography package for its PKI)."""

    def __init__(self):
        self.store = Store()
        self.clock = Clock(fixed=10_000.0)
        self.coordinator = LeaseCoordinator(self.store, self.clock)
        self.members: dict = {}

    def settle(self, max_steps: int = 0) -> int:
        return 0

    def tick(self, seconds: float = 0.0) -> int:
        if seconds:
            self.clock.advance(seconds)
        return 0


# ---------------------------------------------------------------------------
# LeaseCoordinator CAS semantics
# ---------------------------------------------------------------------------


class TestLeaseCoordinator:
    def setup_method(self):
        self.clock = Clock(fixed=1000.0)
        self.store = Store()
        self.c = LeaseCoordinator(self.store, self.clock)

    def test_first_acquire_mints_token_one(self):
        lease, ok = self.c.acquire("karmada-scheduler", "a", 10.0)
        assert ok
        assert lease.spec.fencing_token == 1
        assert lease.spec.holder_identity == "a"
        assert lease.metadata.namespace == LEADER_LEASE_NAMESPACE

    def test_live_lease_is_not_stolen(self):
        self.c.acquire("karmada-scheduler", "a", 10.0)
        lease, ok = self.c.acquire("karmada-scheduler", "b", 10.0)
        assert not ok
        assert lease.spec.holder_identity == "a"

    def test_holder_reacquire_is_renewal_token_stable(self):
        l1, _ = self.c.acquire("karmada-scheduler", "a", 10.0)
        self.clock.advance(5.0)
        l2, ok = self.c.acquire("karmada-scheduler", "a", 10.0)
        assert ok
        assert l2.spec.fencing_token == l1.spec.fencing_token == 1
        assert l2.spec.renew_time > l1.spec.renew_time

    def test_expired_takeover_bumps_token_and_transitions(self):
        self.c.acquire("karmada-scheduler", "a", 10.0)
        self.clock.advance(10.1)
        lease, ok = self.c.acquire("karmada-scheduler", "b", 10.0)
        assert ok
        assert lease.spec.holder_identity == "b"
        assert lease.spec.fencing_token == 2
        assert lease.spec.lease_transitions == 1

    def test_same_identity_reacquiring_expired_lease_mints_fresh_token(self):
        """A leader that slept past its own TTL must not resume on its old
        token even when nobody else took over."""
        self.c.acquire("karmada-scheduler", "a", 10.0)
        self.clock.advance(10.1)
        lease, ok = self.c.acquire("karmada-scheduler", "a", 10.0)
        assert ok
        assert lease.spec.fencing_token == 2
        assert lease.spec.lease_transitions == 0  # holder never changed

    def test_renew_by_deposed_holder_rejected(self):
        self.c.acquire("karmada-scheduler", "a", 10.0)
        self.clock.advance(10.1)
        self.c.acquire("karmada-scheduler", "b", 10.0)
        with pytest.raises(StaleLeaseError):
            self.c.renew("karmada-scheduler", "a", 1)

    def test_renew_past_ttl_rejected_even_unclaimed(self):
        self.c.acquire("karmada-scheduler", "a", 10.0)
        self.clock.advance(10.1)
        with pytest.raises(StaleLeaseError):
            self.c.renew("karmada-scheduler", "a", 1)

    def test_release_keeps_token_monotonic(self):
        self.c.acquire("karmada-scheduler", "a", 10.0)
        self.c.release("karmada-scheduler", "a", 1)
        lease = self.store.get("LeaderLease", "karmada-scheduler",
                               LEADER_LEASE_NAMESPACE)
        assert lease.spec.holder_identity == ""
        lease, ok = self.c.acquire("karmada-scheduler", "b", 10.0)
        assert ok
        assert lease.spec.fencing_token == 2  # never goes back to 1

    def test_release_by_deposed_holder_is_noop(self):
        self.c.acquire("karmada-scheduler", "a", 10.0)
        self.clock.advance(10.1)
        self.c.acquire("karmada-scheduler", "b", 10.0)
        self.c.release("karmada-scheduler", "a", 1)  # stale: must not land
        lease = self.store.get("LeaderLease", "karmada-scheduler",
                               LEADER_LEASE_NAMESPACE)
        assert lease.spec.holder_identity == "b"

    def test_check_fence(self):
        self.c.acquire("karmada-scheduler", "a", 10.0)
        self.c.check_fence("karmada-scheduler", 1)  # current: passes
        with pytest.raises(FencingError):
            self.c.check_fence("karmada-scheduler", 0)
        with pytest.raises(FencingError):
            self.c.check_fence("unknown-lease", 1)
        self.clock.advance(10.1)
        self.c.acquire("karmada-scheduler", "b", 10.0)
        with pytest.raises(FencingError):
            self.c.check_fence("karmada-scheduler", 1)  # deposed
        self.c.check_fence("karmada-scheduler", 2)

    def test_racing_acquires_single_winner(self):
        """Split-brain scenario 1: N electors race a fresh lease; the CAS
        admits exactly one."""
        results: list[tuple[str, bool]] = []
        lock = threading.Lock()
        barrier = threading.Barrier(8)

        def contend(identity: str) -> None:
            barrier.wait()
            lease, ok = self.c.acquire("karmada-scheduler", identity, 30.0)
            with lock:
                results.append((identity, ok))

        threads = [
            threading.Thread(target=contend, args=(f"cand-{i}",))
            for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        winners = [i for i, ok in results if ok]
        assert len(results) == 8
        assert len(winners) == 1, winners
        lease = self.store.get("LeaderLease", "karmada-scheduler",
                               LEADER_LEASE_NAMESPACE)
        assert lease.spec.holder_identity == winners[0]
        assert lease.spec.fencing_token == 1


# ---------------------------------------------------------------------------
# Elector state machine (deterministic, injected clock)
# ---------------------------------------------------------------------------


class TestElector:
    def setup_method(self):
        self.clock = Clock(fixed=1000.0)
        self.store = Store()
        self.client = LocalLeaseClient(LeaseCoordinator(self.store, self.clock))
        self.events: list[tuple] = []

    def elector(self, identity: str, **kw) -> Elector:
        return Elector(
            self.client, "karmada-scheduler", identity, lease_duration=10.0,
            on_started_leading=lambda t: self.events.append(("start", identity, t)),
            on_stopped_leading=lambda r: self.events.append(("stop", identity)),
            **kw,
        )

    def test_one_leader_standby_promoted_within_ttl(self):
        a, b = self.elector("a"), self.elector("b")
        assert a.step() is True
        assert b.step() is False
        assert a.token == 1 and b.token == 0
        # leader dies (no more renews); TTL elapses; next standby step wins
        self.clock.advance(10.1)
        assert b.step() is True
        assert b.token == 2
        # the dead leader resuming observes its deposition
        assert a.step() is False
        assert self.events == [("start", "a", 1), ("start", "b", 2),
                               ("stop", "a")]

    def test_leader_renews_and_keeps_token(self):
        a = self.elector("a")
        a.step()
        for _ in range(5):
            self.clock.advance(3.0)
            assert a.step() is True
        assert a.token == 1

    def test_voluntary_stop_releases_for_instant_takeover(self):
        a, b = self.elector("a"), self.elector("b")
        a.step()
        a.stop(release=True)
        # NO clock advance: the release means b wins without waiting out TTL
        assert b.step() is True
        assert b.token == 2

    def test_transport_failure_demotes_only_after_ttl(self):
        class FlakyClient:
            def __init__(self, inner):
                self.inner = inner
                self.down = False

            def acquire_lease(self, *a, **k):
                if self.down:
                    raise OSError("plane unreachable")
                return self.inner.acquire_lease(*a, **k)

            def renew_lease(self, *a, **k):
                if self.down:
                    raise OSError("plane unreachable")
                return self.inner.renew_lease(*a, **k)

            def release_lease(self, *a, **k):
                self.inner.release_lease(*a, **k)

        flaky = FlakyClient(self.client)
        fake_mono = [0.0]
        a = Elector(flaky, "karmada-scheduler", "a", lease_duration=10.0,
                    on_stopped_leading=lambda r: self.events.append(("stop", "a")),
                    monotonic=lambda: fake_mono[0])
        assert a.step() is True
        flaky.down = True
        fake_mono[0] = 5.0
        assert a.step() is True  # a blip is tolerated inside the TTL
        fake_mono[0] = 10.5  # can no longer prove the lease is held
        assert a.step() is False
        assert ("stop", "a") in self.events


# ---------------------------------------------------------------------------
# Fencing end-to-end over the serving wire
# ---------------------------------------------------------------------------


@pytest.fixture()
def wire():
    cp = MiniPlane()
    srv = ControlPlaneServer(cp, token="tok")
    srv.start()
    stores: list[RemoteStore] = []

    def client() -> RemoteStore:
        s = RemoteStore(srv.url, token="tok")
        stores.append(s)
        return s

    yield cp, srv, client
    for s in stores:
        s.close()
    srv.stop()


def make_rb(name: str, replicas: int = 1, placement=None) -> ResourceBinding:
    return ResourceBinding(
        metadata=ObjectMeta(namespace="default", name=name, uid=new_uid("rb")),
        spec=BindingSpec(
            resource=ObjectReference(api_version="apps/v1", kind="Deployment",
                                     namespace="default", name=name),
            replicas=replicas,
            replica_requirements=ReplicaRequirements(
                resource_request={CPU: 0.1}),
            placement=placement,
        ),
    )


class TestFencedWrites:
    def test_paused_leader_resumes_renew_409_and_write_409(self, wire):
        """Split-brain scenario 2: the leader pauses past its TTL (GC stop,
        SIGSTOP, network partition), a standby takes over, and the old
        leader's in-flight mutation + renew both come back 409."""
        cp, srv, client = wire
        old = client()
        lease, ok = old.acquire_lease("karmada-scheduler", "old", 10.0)
        assert ok
        old.set_fence("karmada-scheduler", lease.spec.fencing_token)
        old.create(make_rb("web"))  # fenced write lands while current

        cp.clock.advance(10.5)  # the pause
        new = client()
        l2, ok2 = new.acquire_lease("karmada-scheduler", "new", 10.0)
        assert ok2 and l2.spec.fencing_token == 2

        # the paused leader resumes: its in-flight patch must NOT land
        rb = old.try_get("ResourceBinding", "web", "default")
        rb.spec.replicas = 99
        with pytest.raises(ConflictError, match="stale token"):
            old.update(rb)
        with pytest.raises(ConflictError):
            old.renew_lease("karmada-scheduler", "old", 1)
        # and the store still holds the pre-pause state
        assert new.get("ResourceBinding", "web", "default").spec.replicas == 1

    def test_deposed_client_reenters_election_despite_stale_fence(self, wire):
        cp, srv, client = wire
        old = client()
        lease, _ = old.acquire_lease("karmada-scheduler", "old", 10.0)
        old.set_fence("karmada-scheduler", lease.spec.fencing_token)
        cp.clock.advance(10.5)
        new = client()
        new.acquire_lease("karmada-scheduler", "new", 10.0)
        # lease routes are fencing-exempt: the old leader can campaign again
        l3, ok3 = old.acquire_lease("karmada-scheduler", "old", 10.0)
        assert not ok3  # new holder is live
        new.release_lease("karmada-scheduler", "new", 2)
        l4, ok4 = old.acquire_lease("karmada-scheduler", "old", 10.0)
        assert ok4 and l4.spec.fencing_token == 3

    def test_malformed_fence_header_is_400(self, wire):
        import json
        from urllib.error import HTTPError
        from urllib.request import Request, urlopen

        cp, srv, client = wire
        req = Request(
            srv.url + "/objects",
            data=json.dumps({"obj": None}).encode(), method="POST",
            headers={"Authorization": "Bearer tok",
                     "Content-Type": "application/json",
                     "X-Karmada-Fencing": "not-a-fence"},
        )
        with pytest.raises(HTTPError) as ei:
            urlopen(req)
        assert ei.value.code == 400

    def test_elections_visible_over_wire_and_cli(self, wire):
        cp, srv, client = wire
        s = client()
        s.acquire_lease("karmada-scheduler", "sched-host_1", 10.0)
        s.acquire_lease("karmada-descheduler", "desched-host_1", 15.0)
        els = s.elections()
        assert {l.metadata.name for l in els} == {
            "karmada-scheduler", "karmada-descheduler"}
        from karmada_tpu.cli.karmadactl import cmd_elections, run

        out = cmd_elections(cp)
        assert "karmada-scheduler" in out and "sched-host_1" in out
        assert "FENCING" in out
        out = run(cp, ["elections", "-o", "wide"])
        assert LEADER_LEASE_NAMESPACE in out
        out = run(cp, ["get", "leaderleases"])
        assert "karmada-descheduler" in out


# ---------------------------------------------------------------------------
# Two scheduler daemons, one control plane: parity + failover
# ---------------------------------------------------------------------------


class SchedHarness:
    """Everything `python -m karmada_tpu.sched` wires (RemoteStore watches,
    SchedulerDaemon, elector with fencing callbacks), in-process so the
    clock is injectable and 'kill -9' is 'stop stepping'."""

    def __init__(self, url: str, identity: str, coordinator=None,
                 registry=None):
        self.identity = identity
        self.store = RemoteStore(url, token="tok")
        self.runtime = Runtime()
        from karmada_tpu.sched.scheduler import SchedulerDaemon

        self.daemon = SchedulerDaemon(self.store, self.runtime,
                                      estimator_registry=registry)
        self.elector = Elector(
            self.store, "karmada-scheduler", identity, lease_duration=10.0,
            on_started_leading=lambda t: self.store.set_fence(
                "karmada-scheduler", t),
            on_stopped_leading=lambda r: self.store.clear_fence(),
        )

    def drive(self) -> bool:
        """One daemon loop turn: elect, then drain if leading (standby
        stays warm instead)."""
        if self.elector.step():
            self.runtime.settle()
            return True
        self.daemon.prewarm()
        return False

    def close(self) -> None:
        self.store.close()


def _mk_cluster(name: str):
    from karmada_tpu.api.meta import MEMORY
    from karmada_tpu.testing.fixtures import new_cluster_with_resource

    GiB = 1024.0**3
    return new_cluster_with_resource(
        name, {CPU: 100.0, MEMORY: 400 * GiB, "pods": 1000.0}
    )


def _placements(store) -> dict[str, tuple]:
    out = {}
    for rb in store.list("ResourceBinding", "default"):
        out[rb.metadata.name] = tuple(
            sorted((t.name, t.replicas) for t in rb.spec.clusters)
        )
    return out


def _churn(user, round_no: int) -> None:
    """One deterministic churn round: new bindings + a capacity wobble."""
    from karmada_tpu.testing.fixtures import duplicated_placement

    for i in range(3):
        user.create(make_rb(f"app-r{round_no}-{i}", replicas=1 + i,
                            placement=duplicated_placement([])))


class TestSchedulerFailoverParity:
    def _run_epoch(self, harnesses, user, rounds, on_round=None):
        """Apply churn rounds; after each, drive every live harness until
        all bindings are placed."""
        for r in rounds:
            _churn(user, r)
            if on_round is not None:
                on_round(r)

            def all_placed() -> bool:
                for h in harnesses:
                    h.drive()
                return all(
                    rb.spec.clusters
                    for rb in user.list("ResourceBinding", "default")
                )

            assert wait_until(all_placed, timeout=60.0), (
                f"round {r} never fully placed"
            )

    def _fleet(self, user) -> None:
        for name in ("m1", "m2", "m3"):
            user.create(_mk_cluster(name))

    def test_two_daemons_bit_identical_to_one_with_midrun_kill(self):
        """Acceptance: two scheduler daemons against one control plane
        under churn produce placements bit-identical to the single-daemon
        run; the leader dies mid-run and the standby takes over within one
        lease TTL; the dead leader's late write is fenced."""
        # --- single-daemon baseline ---------------------------------------
        cp1 = MiniPlane()
        srv1 = ControlPlaneServer(cp1, token="tok")
        srv1.start()
        user1 = RemoteStore(srv1.url, token="tok")
        solo = SchedHarness(srv1.url, "solo_1")
        try:
            self._fleet(user1)
            self._run_epoch([solo], user1, rounds=(1, 2, 3))
            baseline = _placements(user1)
        finally:
            solo.close()
            user1.close()
            srv1.stop()
        assert baseline and all(v for v in baseline.values())

        # --- HA pair with a mid-run kill ----------------------------------
        cp2 = MiniPlane()
        srv2 = ControlPlaneServer(cp2, token="tok")
        srv2.start()
        user2 = RemoteStore(srv2.url, token="tok")
        a = SchedHarness(srv2.url, "a_1")
        b = SchedHarness(srv2.url, "b_2")
        try:
            self._fleet(user2)
            # round 1: both compete; exactly one leads
            self._run_epoch([a, b], user2, rounds=(1,))
            leaders = [h for h in (a, b) if h.elector.is_leader]
            assert len(leaders) == 1
            leader = leaders[0]
            standby = b if leader is a else a
            old_token = leader.elector.token

            # kill -9 the leader: it stops stepping/renewing entirely.
            # TTL elapses on the plane clock; the standby's next step wins.
            cp2.clock.advance(10.5)
            assert standby.elector.step() is True, (
                "standby not promoted within one lease TTL"
            )
            assert standby.elector.token == old_token + 1

            # rounds 2-3 under the new leader only
            self._run_epoch([standby], user2, rounds=(2, 3))

            # the dead leader's in-flight patch arrives late: fenced out
            rb = leader.store.try_get("ResourceBinding", "app-r1-0",
                                      "default")
            rb.spec.replicas = 77
            with pytest.raises(ConflictError):
                leader.store.update(rb)

            assert _placements(user2) == baseline, (
                "HA pair placements diverged from the single-daemon run"
            )
        finally:
            a.close()
            b.close()
            user2.close()
            srv2.stop()

    def test_standby_is_warm_before_promotion(self):
        """The standby builds encoders + primes the solve while NOT leading
        (the hot-standby half of the tentpole)."""
        cp = MiniPlane()
        srv = ControlPlaneServer(cp, token="tok")
        srv.start()
        user = RemoteStore(srv.url, token="tok")
        a = SchedHarness(srv.url, "a_1")
        b = SchedHarness(srv.url, "b_2")
        try:
            self._fleet(user)
            assert a.drive() is True

            def standby_warm() -> bool:
                b.drive()
                arr = b.daemon._array
                return arr is not None and arr.n_real_clusters == 3
            assert wait_until(standby_warm, timeout=30.0), (
                "standby never built its fleet encoders"
            )
            assert b.elector.is_leader is False
        finally:
            a.close()
            b.close()
            user.close()
            srv.stop()


# ---------------------------------------------------------------------------
# Chaos overlap: SIGKILL failover WHILE the fault injector flaps one
# member's estimator (faults/ plane × coordination plane)
# ---------------------------------------------------------------------------


class TestChaosOverlapFailover:
    """The two robustness planes interfering: the estimator of one member
    flaps (fault injector + per-member breaker) while the scheduler leader
    dies mid-run. Fencing must still 409 the deposed leader's late write,
    and the final placements must be bit-identical to the fault-free
    single-daemon baseline — estimator-side chaos must never leak into
    placement results when its answers don't bind (answers above the
    GeneralEstimator bound) nor corrupt the election."""

    # answers far above the GeneralEstimator capacity bound: the min-merge
    # always resolves to the general bound, so flap (-1), stale (decayed)
    # and fresh answers all land identical placements — chaos is pure
    # interference here, which is exactly what the parity assertion needs
    ANSWERS = {"m1": 10 ** 6, "m2": 10 ** 6, "m3": 10 ** 6}

    def _registry(self):
        from karmada_tpu.estimator.client import EstimatorRegistry
        from karmada_tpu.faults import BreakerRegistry
        from tests.test_chaos import GuardedRows

        breakers = BreakerRegistry(failure_threshold=2, open_seconds=0.2)
        registry = EstimatorRegistry(breakers=breakers)
        registry.register_replica_estimator(
            "members", GuardedRows(breakers, answers=self.ANSWERS)
        )
        return registry, breakers

    def _churn(self, user, round_no: int) -> None:
        from tests.test_chaos import dyn_placement

        _churn(user, round_no)  # the duplicated set
        # dynamic rows so the estimator fan-out (the flapping boundary)
        # actually runs every round
        user.create(make_rb(f"dyn-r{round_no}", replicas=2 + round_no,
                            placement=dyn_placement()))

    def _run_epoch(self, harnesses, user, rounds):
        for r in rounds:
            self._churn(user, r)

            def all_placed() -> bool:
                for h in harnesses:
                    h.drive()
                return all(
                    rb.spec.clusters
                    for rb in user.list("ResourceBinding", "default")
                )

            assert wait_until(all_placed, timeout=60.0), (
                f"round {r} never fully placed"
            )

    def test_failover_during_estimator_flap(self):
        from karmada_tpu import faults
        from karmada_tpu.metrics import estimator_rpc_errors

        # --- fault-free single-daemon baseline ----------------------------
        faults.reset()
        cp1 = MiniPlane()
        srv1 = ControlPlaneServer(cp1, token="tok")
        srv1.start()
        user1 = RemoteStore(srv1.url, token="tok")
        solo = SchedHarness(srv1.url, "solo_1",
                            registry=self._registry()[0])
        try:
            for name in ("m1", "m2", "m3"):
                user1.create(_mk_cluster(name))
            self._run_epoch([solo], user1, rounds=(1, 2, 3))
            baseline = _placements(user1)
        finally:
            solo.close()
            user1.close()
            srv1.stop()
        assert baseline

        # --- HA pair, estimator of m2 flapping, SIGKILL mid-run -----------
        faults.install(faults.FaultPlan(seed=99, rules=[
            faults.FaultRule(boundary="grpc", target="m2", kind="flap",
                             period=2),
        ]))
        errs0 = estimator_rpc_errors.value(cluster="m2", code="UNAVAILABLE")
        cp2 = MiniPlane()
        srv2 = ControlPlaneServer(cp2, token="tok")
        srv2.start()
        user2 = RemoteStore(srv2.url, token="tok")
        a = SchedHarness(srv2.url, "a_1", registry=self._registry()[0])
        b = SchedHarness(srv2.url, "b_2", registry=self._registry()[0])
        try:
            for name in ("m1", "m2", "m3"):
                user2.create(_mk_cluster(name))
            self._run_epoch([a, b], user2, rounds=(1,))
            leaders = [h for h in (a, b) if h.elector.is_leader]
            assert len(leaders) == 1
            leader = leaders[0]
            standby = b if leader is a else a

            # SIGKILL the leader (stop stepping); TTL elapses; the standby
            # wins — all while the injector keeps flapping m2's estimator
            cp2.clock.advance(10.5)
            assert standby.elector.step() is True, (
                "standby not promoted within one lease TTL during the flap"
            )
            self._run_epoch([standby], user2, rounds=(2, 3))

            # the flap genuinely fired against m2's estimator
            assert estimator_rpc_errors.value(
                cluster="m2", code="UNAVAILABLE") > errs0
            inj = faults.active()
            assert inj is not None and inj.trace, "no faults recorded"

            # fencing holds across the breaker flaps: the dead leader's
            # late write bounces with 409
            rb = leader.store.try_get("ResourceBinding", "app-r1-0",
                                      "default")
            rb.spec.replicas = 77
            with pytest.raises(ConflictError):
                leader.store.update(rb)

            assert _placements(user2) == baseline, (
                "chaos-overlap placements diverged from the fault-free "
                "single-daemon run"
            )
        finally:
            faults.reset()
            a.close()
            b.close()
            user2.close()
            srv2.stop()


# ---------------------------------------------------------------------------
# Data-dir flock
# ---------------------------------------------------------------------------


class TestDataDirFlock:
    def test_second_lock_in_process_fails_fast(self, tmp_path):
        d = str(tmp_path / "data")
        first = lock_data_dir(d)
        assert first is not None
        with pytest.raises(DataDirLockedError, match="locked by another"):
            lock_data_dir(d)
        first.close()  # dropping the handle releases the lock
        again = lock_data_dir(d)
        assert again is not None
        again.close()

    def test_lock_survives_for_subprocess_holder(self, tmp_path):
        """A lock held by another PROCESS blocks us; its death frees it
        (flock semantics — no stale pidfile)."""
        d = str(tmp_path / "data")
        holder = subprocess.Popen(
            [sys.executable, "-c",
             "import sys, time; sys.path.insert(0, %r); "
             "from karmada_tpu.coordination.flock import lock_data_dir; "
             "h = lock_data_dir(%r); print('held', flush=True); "
             "time.sleep(60)" % ("/root/repo", d)],
            stdout=subprocess.PIPE, text=True,
        )
        try:
            assert holder.stdout.readline().strip() == "held"
            with pytest.raises(DataDirLockedError):
                lock_data_dir(d)
        finally:
            holder.kill()
            holder.wait(timeout=15)
        # SIGKILL'd holder leaves no stale lock
        assert wait_until(
            lambda: _try_lock(d), timeout=15.0
        ), "lock not released after holder SIGKILL"

    def test_second_server_process_exits_nonzero(self, tmp_path):
        """Split-brain scenario 4, end to end: the second server daemon on
        one --data-dir must exit non-zero with a clear message."""
        pytest.importorskip("cryptography")
        from karmada_tpu.testing.daemon import reaping, spawn_daemon

        d = str(tmp_path / "data")
        proc, url = spawn_daemon("--data-dir", d, "--tick-interval", "0")
        with reaping(proc):
            second = subprocess.run(
                [sys.executable, "-m", "karmada_tpu.server",
                 "--platform", "cpu", "--data-dir", d],
                capture_output=True, text=True, timeout=120,
            )
            assert second.returncode != 0
            assert "locked by another running server" in second.stderr


def _try_lock(d: str) -> bool:
    try:
        h = lock_data_dir(d)
    except DataDirLockedError:
        return False
    if h is not None:
        h.close()
    return True


# ---------------------------------------------------------------------------
# /metrics surfaces
# ---------------------------------------------------------------------------


class TestMetricsSurfaces:
    def test_apiserver_metrics_route_same_auth_as_wire(self):
        from urllib.error import HTTPError
        from urllib.request import Request, urlopen

        cp = MiniPlane()
        srv = ControlPlaneServer(cp, token="tok")
        srv.start()
        try:
            with pytest.raises(HTTPError) as ei:
                urlopen(Request(srv.url + "/metrics"))
            assert ei.value.code == 401
            resp = urlopen(Request(
                srv.url + "/metrics",
                headers={"Authorization": "Bearer tok"},
            ))
            body = resp.read().decode()
            assert resp.headers["Content-Type"].startswith("text/plain")
            assert "karmada_scheduler_schedule_attempts_total" in body
            assert "karmada_leader_election_is_leader" in body
        finally:
            srv.stop()

    def test_daemon_metrics_server(self):
        from urllib.error import HTTPError
        from urllib.request import Request, urlopen

        from karmada_tpu.server.metricsserver import MetricsServer

        srv = MetricsServer(token="tok")
        port = srv.start()
        base = f"http://127.0.0.1:{port}"
        try:
            # healthz open (liveness probes), metrics behind the wire token
            import json

            ok = json.loads(urlopen(base + "/healthz").read())
            assert ok == {"ok": True}
            with pytest.raises(HTTPError) as ei:
                urlopen(base + "/metrics")
            assert ei.value.code == 401
            body = urlopen(Request(
                base + "/metrics", headers={"Authorization": "Bearer tok"},
            )).read().decode()
            assert "karmada_leader_election_transitions_total" in body
            with pytest.raises(HTTPError) as ei:
                urlopen(Request(
                    base + "/nope", headers={"Authorization": "Bearer tok"},
                ))
            assert ei.value.code == 404
        finally:
            srv.stop()

    def test_leader_gauge_flips_on_transition(self):
        from karmada_tpu.metrics import leader_election_is_leader

        clock = Clock(fixed=1000.0)
        client = LocalLeaseClient(LeaseCoordinator(Store(), clock))
        a = Elector(client, "gauge-lease", "a", lease_duration=10.0)
        b = Elector(client, "gauge-lease", "b", lease_duration=10.0)
        a.step()
        assert leader_election_is_leader.value(
            lease="gauge-lease", identity="a") == 1.0
        clock.advance(10.5)
        b.step()
        a.step()
        assert leader_election_is_leader.value(
            lease="gauge-lease", identity="a") == 0.0
        assert leader_election_is_leader.value(
            lease="gauge-lease", identity="b") == 1.0


# ---------------------------------------------------------------------------
# Process-level: kill -9 the leader daemon, standby promoted within TTL
# ---------------------------------------------------------------------------


class TestProcessFailover:
    def test_kill9_leader_standby_promoted_within_ttl(self):
        """Split-brain scenario 3 with real OS processes: two
        `python -m karmada_tpu.sched` daemons, SIGKILL the lease holder,
        the other one holds the lease within ~TTL."""
        pytest.importorskip("cryptography")
        from karmada_tpu.server.remote import RemoteControlPlane
        from karmada_tpu.testing.daemon import (
            reaping,
            spawn_daemon,
            spawn_process,
        )

        cp_proc, url = spawn_daemon(
            "--members", "2", "--tick-interval", "0.5",
            "--controllers", "*,-scheduler",
        )
        with reaping(cp_proc) as reap:
            def sched(identity: str):
                proc, _ = spawn_process(
                    [sys.executable, "-m", "karmada_tpu.sched",
                     "--server", url, "--platform", "cpu",
                     "--identity", identity, "--lease-duration", "3",
                     "--metrics-port", "-1"],
                    r"attached", label=f"sched-{identity}", timeout=120,
                )
                reap(proc)
                return proc

            pa, pb = sched("sched-a"), sched("sched-b")
            rcp = RemoteControlPlane(url)

            def holder():
                lease = rcp.store.try_get(
                    "LeaderLease", "karmada-scheduler",
                    LEADER_LEASE_NAMESPACE)
                return lease.spec.holder_identity if lease else ""

            assert wait_until(lambda: holder() in ("sched-a", "sched-b"),
                              timeout=60.0), "no daemon took the lease"
            first = holder()
            victim = pa if first == "sched-a" else pb
            survivor = "sched-b" if first == "sched-a" else "sched-a"
            victim.kill()  # SIGKILL: no release; standby must wait out TTL
            assert wait_until(lambda: holder() == survivor, timeout=30.0), (
                f"standby {survivor} not promoted after SIGKILL "
                f"(holder={holder()!r})"
            )
            # and the promoted daemon actually schedules
            from karmada_tpu.testing.fixtures import (
                duplicated_placement,
                new_deployment,
                new_policy,
                selector_for,
            )

            dep = new_deployment("default", "web", replicas=2, cpu=0.1)
            rcp.store.create(dep)
            rcp.store.create(new_policy(
                "default", "pp", [selector_for(dep)],
                duplicated_placement([]),
            ))
            rcp.settle()
            assert wait_until(lambda: any(
                rb.spec.clusters
                for rb in rcp.store.list("ResourceBinding", "default")
            ), timeout=60.0), "promoted scheduler never placed the binding"


@pytest.mark.slow
class TestHASmokeScript:
    def test_ha_smoke(self):
        """scripts/ha_smoke.sh: server + two schedulers, kill the leader,
        takeover asserted via /metrics (the soak-path wiring)."""
        pytest.importorskip("cryptography")
        r = subprocess.run(
            ["bash", "scripts/ha_smoke.sh"],
            capture_output=True, text=True, timeout=300, cwd="/root/repo",
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "TAKEOVER OK" in r.stdout

"""Cluster resource modeling (EST6): grade histogram + model-based estimates."""
from __future__ import annotations

import numpy as np
import pytest

from karmada_tpu.api.cluster import ResourceModel, ResourceModelRange
from karmada_tpu.api.work import ReplicaRequirements
from karmada_tpu.modeling import (
    GradeHistogram,
    ModelBasedEstimator,
    default_resource_models,
    max_replicas_from_models,
    model_estimates_batch,
)


def small_models():
    """3 grades: cpu [0,1) [1,2) [2,inf); memory [0,4) [4,16) [16,inf)."""
    return [
        ResourceModel(grade=0, ranges=[
            ResourceModelRange(name="cpu", min=0, max=1),
            ResourceModelRange(name="memory", min=0, max=4),
        ]),
        ResourceModel(grade=1, ranges=[
            ResourceModelRange(name="cpu", min=1, max=2),
            ResourceModelRange(name="memory", min=4, max=16),
        ]),
        ResourceModel(grade=2, ranges=[
            ResourceModelRange(name="cpu", min=2, max=float("inf")),
            ResourceModelRange(name="memory", min=16, max=float("inf")),
        ]),
    ]


class TestGradeHistogram:
    def test_classify_min_over_resources(self):
        h = GradeHistogram(small_models())
        # cpu 4 → grade 2, memory 5 → grade 1 ⇒ node grade = min = 1
        assert h.classify({"cpu": 4.0, "memory": 5.0}) == 1
        assert h.classify({"cpu": 0.5, "memory": 100.0}) == 0
        assert h.classify({"cpu": 8.0, "memory": 64.0}) == 2

    def test_add_nodes_histogram(self):
        h = GradeHistogram(small_models())
        h.add_nodes([
            {"cpu": 0.5, "memory": 2.0},   # grade 0
            {"cpu": 1.5, "memory": 8.0},   # grade 1
            {"cpu": 4.0, "memory": 32.0},  # grade 2
            {"cpu": 4.0, "memory": 32.0},  # grade 2
        ])
        assert h.counts.tolist() == [1, 1, 2]
        ams = h.to_allocatable_modelings()
        assert [(a.grade, a.count) for a in ams] == [(0, 1), (1, 1), (2, 2)]

    def test_default_models_shape(self):
        models = default_resource_models()
        assert len(models) == 9
        assert models[0].ranges[0].min == 0.0
        assert models[8].ranges[0].min == 128.0
        assert models[8].ranges[0].max == float("inf")


class TestModelEstimate:
    def test_scalar_math(self):
        models = small_models()
        counts = [5, 3, 2]  # 5 tiny, 3 medium, 2 large nodes
        # request cpu=1: min compliant grade = 1 (grade1 min cpu=1 >= 1)
        # grade1: floor(min(1/1, 4/0→inf)) = 1 → 3*1; grade2: min(2/1, ...) = 2 → 2*2
        assert max_replicas_from_models(models, counts, {"cpu": 1.0}) == 3 * 1 + 2 * 2
        # request cpu=1, memory=8: compliant grade = max(1, 2) = 2
        # grade2 per node: min(2//1, 16//8) = 2 → 2*2 = 4
        assert max_replicas_from_models(models, counts, {"cpu": 1.0, "memory": 8.0}) == 4
        # request bigger than every grade min → 0
        assert max_replicas_from_models(models, counts, {"cpu": 1000.0}) == 0
        # unknown resource → -1 (model inapplicable)
        assert max_replicas_from_models(models, counts, {"gpu": 1.0}) == -1

    def test_first_suitable_grade_counts_one_pod(self):
        models = small_models()
        # request cpu=2: compliant grade 2, per-node floor(2/2)=1 → count*1
        assert max_replicas_from_models(models, [0, 0, 4], {"cpu": 2.0}) == 4
        # request cpu=1.5: compliant grade 2 (grade1 min 1 < 1.5), floor(2/1.5)=1
        assert max_replicas_from_models(models, [9, 9, 4], {"cpu": 1.5}) == 4

    def test_batch_matches_scalar(self):
        models = small_models()
        counts = np.array([[5, 3, 2], [0, 1, 7], [2, 0, 0]])
        reqs = [
            {"cpu": 1.0},
            {"cpu": 1.0, "memory": 8.0},
            {"cpu": 0.25, "memory": 1.0},
            {"memory": 64.0},
        ]
        names = ["cpu", "memory"]
        R = np.zeros((len(reqs), 2))
        for b, r in enumerate(reqs):
            for i, n in enumerate(names):
                R[b, i] = r.get(n, 0.0)
        got = model_estimates_batch(models, counts, R, names)
        for b, r in enumerate(reqs):
            for c in range(counts.shape[0]):
                assert got[b, c] == max_replicas_from_models(models, counts[c].tolist(), r), (b, c)


class TestModelBasedEstimatorIntegration:
    def test_fleet_modelings_populated_and_estimator_answers(self):
        from karmada_tpu.controlplane import ControlPlane
        from karmada_tpu.members.member import MemberConfig
        from karmada_tpu.models.nodes import NodeSpec

        cp = ControlPlane()
        nodes = [NodeSpec(name=f"n{i}", allocatable={"cpu": 4.0, "memory": 32.0}) for i in range(3)]
        cp.join_member(MemberConfig(name="m1", nodes=nodes))
        cp.join_member(MemberConfig(name="m2", allocatable={"cpu": 10.0}))  # no nodes → no models
        cluster = cp.store.get("Cluster", "m1")
        assert cluster.spec.resource_models
        ams = cluster.status.resource_summary.allocatable_modelings
        assert sum(a.count for a in ams) == 3
        # cpu 4, mem 32GB → default grade: cpu grade 3 ([4,8)), mem grade 3 ([32,64)) → 3
        assert [a.count for a in ams if a.grade == 3] == [3]

        est = ModelBasedEstimator(cp.store)
        rows = est.max_available_replicas_rows(
            ["m1", "m2"], [ReplicaRequirements(resource_request={"cpu": 1.0})]
        )
        # grade 3 min cpu = 4 → 4 replicas/node × 3 nodes; m2 unauthenticated
        assert rows[0][0] == 12
        assert rows[0][1] == -1

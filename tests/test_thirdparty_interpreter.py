"""Thirdparty interpreter customization library (I3): per-operation behavior
mirroring the reference's shipped customization sets
(default/thirdparty/resourcecustomizations/*/*/customizations.yaml)."""
from __future__ import annotations

import pytest

from karmada_tpu.api.unstructured import Unstructured
from karmada_tpu.api.work import AggregatedStatusItem
from karmada_tpu.interpreter.interpreter import (
    HEALTHY,
    ResourceInterpreter,
    UNHEALTHY,
)
from karmada_tpu.interpreter.thirdparty import (
    THIRDPARTY_CUSTOMIZATIONS,
    load_thirdparty_tier,
)

REFERENCE_SET = [
    "apps.kruise.io/v1alpha1/AdvancedCronJob",
    "apps.kruise.io/v1alpha1/BroadcastJob",
    "apps.kruise.io/v1alpha1/CloneSet",
    "apps.kruise.io/v1alpha1/DaemonSet",
    "apps.kruise.io/v1beta1/StatefulSet",
    "argoproj.io/v1alpha1/Workflow",
    "flink.apache.org/v1beta1/FlinkDeployment",
    "helm.toolkit.fluxcd.io/v2beta1/HelmRelease",
    "kustomize.toolkit.fluxcd.io/v1/Kustomization",
    "kyverno.io/v1/ClusterPolicy",
    "kyverno.io/v1/Policy",
    "source.toolkit.fluxcd.io/v1/GitRepository",
    "source.toolkit.fluxcd.io/v1beta2/Bucket",
    "source.toolkit.fluxcd.io/v1beta2/HelmChart",
    "source.toolkit.fluxcd.io/v1beta2/HelmRepository",
    "source.toolkit.fluxcd.io/v1beta2/OCIRepository",
]


def interp() -> ResourceInterpreter:
    ri = ResourceInterpreter()
    ri.load_thirdparty()
    return ri


def obj(gvk: str, *, spec=None, status=None, generation=1, ns="default",
        annotations=None, name="x"):
    api_version, kind = gvk.rsplit("/", 1)
    return Unstructured({
        "apiVersion": api_version,
        "kind": kind,
        "metadata": {
            "name": name, "namespace": ns, "generation": generation,
            "annotations": dict(annotations or {}),
        },
        **({"spec": spec} if spec is not None else {}),
        **({"status": status} if status is not None else {}),
    })


def item(cluster: str, status) -> AggregatedStatusItem:
    return AggregatedStatusItem(cluster_name=cluster, status=status)


POD_TEMPLATE = {
    "spec": {
        "containers": [
            {"name": "c", "resources": {"requests": {"cpu": "500m",
                                                     "memory": "1Gi"}}},
        ],
        "volumes": [
            {"name": "cfg", "configMap": {"name": "app-config"}},
            {"name": "creds", "secret": {"secretName": "app-secret"}},
        ],
    },
}


class TestLibraryCompleteness:
    def test_all_reference_gvks_present(self):
        for gvk in REFERENCE_SET:
            assert gvk in THIRDPARTY_CUSTOMIZATIONS, gvk

    def test_tier_builds(self):
        tier = load_thirdparty_tier()
        assert len(tier) >= 16
        for gvk in REFERENCE_SET:
            assert tier[gvk] is not None


class TestCloneSet:
    GVK = "apps.kruise.io/v1alpha1/CloneSet"

    def test_get_replicas_and_requirements(self):
        o = obj(self.GVK, spec={"replicas": 3, "template": POD_TEMPLATE})
        n, req = interp().get_replicas(o)
        assert n == 3
        assert req.resource_request["cpu"] == 0.5
        assert req.resource_request["memory"] == 1024.0**3

    def test_revise_replica(self):
        o = obj(self.GVK, spec={"replicas": 3})
        out = interp().revise_replica(o, 7)
        assert out.get("spec", "replicas") == 7

    def test_aggregate_sums_and_revisions(self):
        tmpl = obj(self.GVK, spec={"replicas": 4}, generation=2,
                   status={"observedGeneration": 1})
        items = [
            item("m1", {"replicas": 2, "readyReplicas": 2,
                        "updatedReplicas": 2, "availableReplicas": 2,
                        "updateRevision": "rev-a",
                        "resourceTemplateGeneration": 2,
                        "generation": 5, "observedGeneration": 5}),
            item("m2", {"replicas": 2, "readyReplicas": 1,
                        "updatedReplicas": 1, "availableReplicas": 1,
                        "updateRevision": "rev-b",
                        "resourceTemplateGeneration": 2,
                        "generation": 3, "observedGeneration": 3}),
        ]
        out = interp().aggregate_status(tmpl, items)
        st = out.get("status")
        assert st["replicas"] == 4 and st["readyReplicas"] == 3
        assert st["updateRevision"] == "rev-b"  # last non-empty wins
        # every member caught up → observedGeneration advances
        assert st["observedGeneration"] == 2

    def test_aggregate_holds_generation_when_member_behind(self):
        tmpl = obj(self.GVK, spec={"replicas": 4}, generation=2,
                   status={"observedGeneration": 1})
        items = [
            item("m1", {"resourceTemplateGeneration": 1,  # stale template
                        "generation": 5, "observedGeneration": 5}),
        ]
        st = interp().aggregate_status(tmpl, items).get("status")
        assert st["observedGeneration"] == 1

    def test_aggregate_empty_items_resets(self):
        tmpl = obj(self.GVK, spec={"replicas": 4}, generation=3,
                   status={"replicas": 9})
        st = interp().aggregate_status(tmpl, []).get("status")
        assert st["observedGeneration"] == 3
        assert st["replicas"] == 0 and st["readyReplicas"] == 0

    def test_reflect_lifts_template_generation_annotation(self):
        o = obj(self.GVK, generation=4,
                annotations={"resourcetemplate.karmada.io/generation": "2"},
                status={"replicas": 2, "readyReplicas": 2})
        st = interp().reflect_status(o)
        assert st["replicas"] == 2
        assert st["generation"] == 4
        assert st["resourceTemplateGeneration"] == 2

    def test_health(self):
        ri = interp()
        healthy = obj(self.GVK, generation=2, spec={"replicas": 2},
                      status={"observedGeneration": 2, "updatedReplicas": 2,
                              "availableReplicas": 2})
        assert ri.interpret_health(healthy) == HEALTHY
        behind = obj(self.GVK, generation=3, spec={"replicas": 2},
                     status={"observedGeneration": 2, "updatedReplicas": 2,
                             "availableReplicas": 2})
        assert ri.interpret_health(behind) == UNHEALTHY
        not_updated = obj(self.GVK, generation=2, spec={"replicas": 2},
                          status={"observedGeneration": 2,
                                  "updatedReplicas": 1,
                                  "availableReplicas": 1})
        assert ri.interpret_health(not_updated) == UNHEALTHY

    def test_dependencies(self):
        o = obj(self.GVK, spec={"replicas": 1, "template": POD_TEMPLATE})
        deps = interp().get_dependencies(o)
        kinds = {(d["kind"], d["name"]) for d in deps}
        assert ("ConfigMap", "app-config") in kinds
        assert ("Secret", "app-secret") in kinds


class TestKruiseStatefulSet:
    GVK = "apps.kruise.io/v1beta1/StatefulSet"

    def test_aggregate_current_replicas(self):
        tmpl = obj(self.GVK, spec={"replicas": 2}, generation=1, status={})
        items = [
            item("m1", {"replicas": 1, "currentReplicas": 1,
                        "currentRevision": "c1",
                        "resourceTemplateGeneration": 1,
                        "generation": 2, "observedGeneration": 2}),
            item("m2", {"replicas": 1, "currentReplicas": 1,
                        "resourceTemplateGeneration": 1,
                        "generation": 2, "observedGeneration": 2}),
        ]
        st = interp().aggregate_status(tmpl, items).get("status")
        assert st["currentReplicas"] == 2
        assert st["currentRevision"] == "c1"
        assert st["observedGeneration"] == 1

    def test_empty_init_has_revision_strings(self):
        tmpl = obj(self.GVK, spec={"replicas": 2}, generation=1, status={})
        st = interp().aggregate_status(tmpl, []).get("status")
        assert st["updateRevision"] == "" and st["currentRevision"] == ""


class TestKruiseDaemonSet:
    GVK = "apps.kruise.io/v1alpha1/DaemonSet"

    def test_no_replica_hooks(self):
        o = obj(self.GVK, spec={"template": POD_TEMPLATE})
        n, req = interp().get_replicas(o)
        assert n == 0 and req is None  # non-workload for scheduling purposes

    def test_aggregate_and_health(self):
        ri = interp()
        tmpl = obj(self.GVK, generation=1, status={})
        items = [
            item("m1", {"desiredNumberScheduled": 2, "numberReady": 2,
                        "updatedNumberScheduled": 2, "numberAvailable": 2,
                        "daemonSetHash": "h1",
                        "resourceTemplateGeneration": 1,
                        "generation": 1, "observedGeneration": 1}),
        ]
        st = ri.aggregate_status(tmpl, items).get("status")
        assert st["desiredNumberScheduled"] == 2
        assert st["daemonSetHash"] == "h1"
        healthy = obj(self.GVK, generation=1,
                      status={"observedGeneration": 1,
                              "desiredNumberScheduled": 2,
                              "updatedNumberScheduled": 2,
                              "numberAvailable": 2})
        assert ri.interpret_health(healthy) == HEALTHY
        lagging = obj(self.GVK, generation=1,
                      status={"observedGeneration": 1,
                              "desiredNumberScheduled": 3,
                              "updatedNumberScheduled": 2,
                              "numberAvailable": 2})
        assert ri.interpret_health(lagging) == UNHEALTHY


class TestAdvancedCronJob:
    GVK = "apps.kruise.io/v1alpha1/AdvancedCronJob"

    def test_aggregate_concatenates_active(self):
        tmpl = obj(self.GVK, status={})
        items = [
            item("m1", {"active": [{"name": "j1"}], "type": "Job",
                        "lastScheduleTime": "t1"}),
            item("m2", {"active": [{"name": "j2"}, {"name": "j3"}],
                        "lastScheduleTime": "t2"}),
        ]
        st = interp().aggregate_status(tmpl, items).get("status")
        assert [a["name"] for a in st["active"]] == ["j1", "j2", "j3"]
        assert st["type"] == "Job"
        assert st["lastScheduleTime"] == "t2"

    def test_dependencies_from_either_template(self):
        ri = interp()
        o = obj(self.GVK, spec={"template": {"jobTemplate": {
            "spec": {"template": POD_TEMPLATE}}}})
        kinds = {d["kind"] for d in ri.get_dependencies(o)}
        assert kinds == {"ConfigMap", "Secret"}
        o2 = obj(self.GVK, spec={"template": {"broadcastJobTemplate": {
            "spec": {"template": POD_TEMPLATE}}}})
        assert {d["kind"] for d in ri.get_dependencies(o2)} == {
            "ConfigMap", "Secret"
        }


class TestBroadcastJob:
    GVK = "apps.kruise.io/v1alpha1/BroadcastJob"

    def test_replicas_from_parallelism(self):
        ri = interp()
        o = obj(self.GVK, spec={"parallelism": 5, "template": POD_TEMPLATE})
        n, req = ri.get_replicas(o)
        assert n == 5 and req.resource_request["cpu"] == 0.5
        out = ri.revise_replica(o, 9)
        assert out.get("spec", "parallelism") == 9

    def test_health(self):
        ri = interp()
        ok = obj(self.GVK, status={"desired": 3, "failed": 0, "active": 1,
                                   "succeeded": 0})
        assert ri.interpret_health(ok) == HEALTHY
        failed = obj(self.GVK, status={"desired": 3, "failed": 1, "active": 1,
                                       "succeeded": 0})
        assert ri.interpret_health(failed) == UNHEALTHY
        idle = obj(self.GVK, status={"desired": 3, "failed": 0, "active": 0,
                                     "succeeded": 0})
        assert ri.interpret_health(idle) == UNHEALTHY

    def test_aggregate_builds_conditions(self):
        tmpl = obj(self.GVK, status={})
        items = [
            item("m1", {"desired": 1, "succeeded": 1, "conditions": [
                {"type": "Complete", "status": "True"}]}),
            item("m2", {"desired": 1, "failed": 1, "conditions": [
                {"type": "Failed", "status": "True"}]}),
        ]
        st = interp().aggregate_status(tmpl, items).get("status")
        assert st["desired"] == 2 and st["succeeded"] == 1 and st["failed"] == 1
        types = {c["type"] for c in st["conditions"]}
        assert "Failed" in types and "Completed" not in types
        failed_cond = next(c for c in st["conditions"] if c["type"] == "Failed")
        assert "m2" in failed_cond["message"]

    def test_aggregate_all_complete(self):
        tmpl = obj(self.GVK, status={})
        items = [
            item("m1", {"desired": 1, "succeeded": 1, "conditions": [
                {"type": "Complete", "status": "True"}]}),
            item("m2", {"desired": 1, "succeeded": 1, "conditions": [
                {"type": "Complete", "status": "True"}]}),
        ]
        st = interp().aggregate_status(tmpl, items).get("status")
        assert [c["type"] for c in st["conditions"]] == ["Completed"]

    def test_retain_pod_template_labels(self):
        desired = obj(self.GVK, spec={"template": {"metadata": {"labels": {}}}})
        observed = obj(self.GVK, spec={"template": {"metadata": {
            "labels": {"injected": "yes"}}}})
        out = interp().retain(desired, observed)
        assert out.get("spec", "template", "metadata", "labels") == {
            "injected": "yes"
        }


class TestArgoWorkflow:
    GVK = "argoproj.io/v1alpha1/Workflow"

    def test_replicas_from_parallelism_with_node_claim(self):
        o = obj(self.GVK, spec={
            "parallelism": 4,
            "nodeSelector": {"zone": "a"},
            "tolerations": [{"key": "gpu", "operator": "Exists"}],
        })
        n, req = interp().get_replicas(o)
        assert n == 4
        assert req.node_claim.node_selector == {"zone": "a"}
        assert req.node_claim.tolerations[0]["key"] == "gpu"

    def test_health_phases(self):
        ri = interp()
        assert ri.interpret_health(
            obj(self.GVK, status={"phase": "Running"})) == HEALTHY
        assert ri.interpret_health(
            obj(self.GVK, status={"phase": "Failed"})) == UNHEALTHY
        assert ri.interpret_health(
            obj(self.GVK, status={"phase": "Error"})) == UNHEALTHY
        assert ri.interpret_health(
            obj(self.GVK, status={"phase": ""})) == UNHEALTHY
        assert ri.interpret_health(obj(self.GVK, spec={})) == UNHEALTHY
        assert ri.interpret_health(
            obj(self.GVK, status={"phase": "Running", "failed": "Error"})
        ) == UNHEALTHY

    def test_retain_suspend_and_status(self):
        desired = obj(self.GVK, spec={})
        observed = obj(self.GVK, spec={"suspend": True},
                       status={"phase": "Running"})
        out = interp().retain(desired, observed)
        assert out.get("spec", "suspend") is True
        assert out.get("status", "phase") == "Running"

    def test_dependencies(self):
        o = obj(self.GVK, spec={
            "executor": {"serviceAccountName": "exec-sa"},
            "serviceAccountName": "wf-sa",
            "volumeClaimTemplates": [{"metadata": {"name": "work"}}],
            "volumes": [
                {"name": "v1", "configMap": {"name": "wf-config"}},
                {"name": "v2", "secret": {"name": "wf-secret"}},
                {"name": "v3", "persistentVolumeClaim": {"claimName": "data"}},
            ],
            "imagePullSecrets": [{"name": "pull"}],
        })
        deps = interp().get_dependencies(o)
        got = {(d["kind"], d["name"]) for d in deps}
        assert got == {
            ("ConfigMap", "wf-config"),
            ("Secret", "wf-secret"), ("Secret", "pull"),
            ("ServiceAccount", "exec-sa"), ("ServiceAccount", "wf-sa"),
            ("PersistentVolumeClaim", "work"),
            ("PersistentVolumeClaim", "data"),
        }

    def test_default_service_account_skipped(self):
        o = obj(self.GVK, spec={"serviceAccountName": "default"})
        assert interp().get_dependencies(o) == []


class TestFlinkDeployment:
    GVK = "flink.apache.org/v1beta1/FlinkDeployment"

    def test_health_states(self):
        ri = interp()
        running = obj(self.GVK, status={"jobStatus": {"state": "RUNNING"}})
        assert ri.interpret_health(running) == HEALTHY
        terminal = obj(self.GVK, status={"jobStatus": {"state": "FAILED"}})
        assert ri.interpret_health(terminal) == HEALTHY  # terminal = settled
        ephemeral = obj(self.GVK, status={"jobStatus": {"state": "CREATED"}})
        assert ri.interpret_health(ephemeral) == UNHEALTHY
        ephemeral_err = obj(self.GVK, status={
            "jobStatus": {"state": "CREATED"}, "error": "bad image"})
        assert ri.interpret_health(ephemeral_err) == HEALTHY
        no_job = obj(self.GVK, status={})
        assert ri.interpret_health(no_job) == UNHEALTHY

    def test_replicas_from_parallelism_and_slots(self):
        o = obj(self.GVK, spec={
            "jobManager": {"resource": {"cpu": 1.0, "memory": "2Gi"}},
            "taskManager": {"resource": {"cpu": 2.0, "memory": "4Gi"}},
            "job": {"parallelism": 8},
            "flinkConfiguration": {"taskmanager.numberOfTaskSlots": "2"},
        })
        n, req = interp().get_replicas(o)
        assert n == 1 + 4  # 1 jobManager + ceil(8/2) taskManagers
        assert req.resource_request["cpu"] == 2.0
        assert req.resource_request["memory"] == 4 * 1024.0**3

    def test_replicas_explicit_tm_replicas_take_precedence(self):
        o = obj(self.GVK, spec={
            "jobManager": {"replicas": 2, "resource": {"cpu": 1.0,
                                                       "memory": "1Gi"}},
            "taskManager": {"replicas": 3, "resource": {"cpu": 0.5,
                                                        "memory": "1Gi"}},
            "job": {"parallelism": 100},
            "flinkConfiguration": {"taskmanager.numberOfTaskSlots": "1"},
        })
        n, _ = interp().get_replicas(o)
        assert n == 5

    def test_aggregate_last_wins(self):
        tmpl = obj(self.GVK, status={})
        items = [
            item("m1", {"lifecycleState": "DEPLOYED",
                        "jobStatus": {"state": "RUNNING"}}),
        ]
        st = interp().aggregate_status(tmpl, items).get("status")
        assert st["lifecycleState"] == "DEPLOYED"
        assert st["jobStatus"]["state"] == "RUNNING"


class TestKyverno:
    @pytest.mark.parametrize("gvk", ["kyverno.io/v1/ClusterPolicy",
                                     "kyverno.io/v1/Policy"])
    def test_health_ready_field_then_conditions(self, gvk):
        ri = interp()
        assert ri.interpret_health(obj(gvk, status={"ready": True})) == HEALTHY
        assert ri.interpret_health(obj(gvk, status={"ready": False})) == UNHEALTHY
        cond_ok = obj(gvk, status={"conditions": [
            {"type": "Ready", "status": "True", "reason": "Succeeded"}]})
        assert ri.interpret_health(cond_ok) == HEALTHY
        assert ri.interpret_health(obj(gvk, spec={})) == UNHEALTHY

    def test_aggregate_rulecount_and_conditions(self):
        gvk = "kyverno.io/v1/ClusterPolicy"
        tmpl = obj(gvk, status={"stale": True})
        items = [
            item("m1", {"ready": True,
                        "rulecount": {"validate": 1, "generate": 0,
                                      "mutate": 2, "verifyimages": 0},
                        "conditions": [{"type": "Ready", "status": "True",
                                        "reason": "Succeeded",
                                        "message": "ok"}]}),
            item("m2", {"rulecount": {"validate": 1, "generate": 1,
                                      "mutate": 0, "verifyimages": 0},
                        "conditions": [{"type": "Ready", "status": "True",
                                        "reason": "Succeeded",
                                        "message": "ok"}]}),
        ]
        st = interp().aggregate_status(tmpl, items).get("status")
        assert "stale" not in st  # status is REPLACED, not merged
        assert st["rulecount"] == {"validate": 2, "generate": 1, "mutate": 2,
                                   "verifyimages": 0}
        # same (type,status,reason) → one condition, cluster-prefixed merge
        assert len(st["conditions"]) == 1
        assert st["conditions"][0]["message"] == "m1=ok, m2=ok"


class TestFluxHelmRelease:
    GVK = "helm.toolkit.fluxcd.io/v2beta1/HelmRelease"

    def test_health_requires_reconciliation_succeeded(self):
        ri = interp()
        ok = obj(self.GVK, status={"conditions": [
            {"type": "Ready", "status": "True",
             "reason": "ReconciliationSucceeded"}]})
        assert ri.interpret_health(ok) == HEALTHY
        wrong_reason = obj(self.GVK, status={"conditions": [
            {"type": "Ready", "status": "True", "reason": "Succeeded"}]})
        assert ri.interpret_health(wrong_reason) == UNHEALTHY

    def test_aggregate_revisions_and_guarded_failures(self):
        tmpl = obj(self.GVK, generation=1,
                   status={"failures": 1, "lastAppliedRevision": "v0"})
        items = [
            item("m1", {"lastAppliedRevision": "v1", "failures": 2,
                        "resourceTemplateGeneration": 1,
                        "generation": 1, "observedGeneration": 1}),
        ]
        st = interp().aggregate_status(tmpl, items).get("status")
        assert st["lastAppliedRevision"] == "v1"
        assert st["failures"] == 3  # template 1 + member 2
        assert st["observedGeneration"] == 1

    def test_retain_suspend(self):
        desired = obj(self.GVK, spec={})
        observed = obj(self.GVK, spec={"suspend": True})
        assert interp().retain(desired, observed).get("spec", "suspend") is True

    def test_dependencies(self):
        o = obj(self.GVK, spec={
            "valuesFrom": [
                {"kind": "Secret", "name": "vals-secret"},
                {"kind": "ConfigMap", "name": "vals-cm"},
            ],
            "chart": {"spec": {"verify": {"secretRef": {"name": "cosign"}}}},
            "kubeConfig": {"secretRef": {"name": "kc"}},
            "serviceAccountName": "helm-sa",
        })
        got = {(d["kind"], d["name"]) for d in interp().get_dependencies(o)}
        assert got == {
            ("Secret", "vals-secret"), ("Secret", "cosign"), ("Secret", "kc"),
            ("ConfigMap", "vals-cm"), ("ServiceAccount", "helm-sa"),
        }


class TestFluxKustomization:
    GVK = "kustomize.toolkit.fluxcd.io/v1/Kustomization"

    def test_aggregate_and_deps(self):
        ri = interp()
        tmpl = obj(self.GVK, generation=2, status={"observedGeneration": 1})
        items = [
            item("m1", {"lastAppliedRevision": "main@sha1:abc",
                        "resourceTemplateGeneration": 2,
                        "generation": 4, "observedGeneration": 4}),
        ]
        st = ri.aggregate_status(tmpl, items).get("status")
        assert st["lastAppliedRevision"] == "main@sha1:abc"
        assert st["observedGeneration"] == 2
        o = obj(self.GVK, spec={
            "decryption": {"secretRef": {"name": "sops"}},
            "serviceAccountName": "kust-sa",
        })
        got = {(d["kind"], d["name"]) for d in ri.get_dependencies(o)}
        assert got == {("Secret", "sops"), ("ServiceAccount", "kust-sa")}


class TestFluxSources:
    def test_gitrepository(self):
        ri = interp()
        gvk = "source.toolkit.fluxcd.io/v1/GitRepository"
        ok = obj(gvk, status={"conditions": [
            {"type": "Ready", "status": "True", "reason": "Succeeded"}]})
        assert ri.interpret_health(ok) == HEALTHY
        tmpl = obj(gvk, generation=1, status={})
        items = [item("m1", {"artifact": {"revision": "r1"},
                             "resourceTemplateGeneration": 1,
                             "generation": 1, "observedGeneration": 1})]
        st = ri.aggregate_status(tmpl, items).get("status")
        assert st["artifact"] == {"revision": "r1"}
        o = obj(gvk, spec={"secretRef": {"name": "git-creds"},
                           "verify": {"secretRef": {"name": "gpg"}}})
        got = {d["name"] for d in ri.get_dependencies(o)}
        assert got == {"git-creds", "gpg"}

    def test_bucket_url(self):
        ri = interp()
        gvk = "source.toolkit.fluxcd.io/v1beta2/Bucket"
        tmpl = obj(gvk, generation=1, status={})
        items = [item("m1", {"url": "http://u", "artifact": {"path": "p"},
                             "resourceTemplateGeneration": 1,
                             "generation": 1, "observedGeneration": 1})]
        st = ri.aggregate_status(tmpl, items).get("status")
        assert st["url"] == "http://u"
        o = obj(gvk, spec={"secretRef": {"name": "s3-creds"}})
        assert {d["name"] for d in ri.get_dependencies(o)} == {"s3-creds"}

    def test_helmchart_health_accepts_chart_pull(self):
        ri = interp()
        gvk = "source.toolkit.fluxcd.io/v1beta2/HelmChart"
        ok = obj(gvk, status={"conditions": [
            {"type": "Ready", "status": "True",
             "reason": "ChartPullSucceeded"}]})
        assert ri.interpret_health(ok) == HEALTHY
        tmpl = obj(gvk, generation=1, status={})
        items = [item("m1", {"observedChartName": "nginx",
                             "resourceTemplateGeneration": 1,
                             "generation": 1, "observedGeneration": 1})]
        st = ri.aggregate_status(tmpl, items).get("status")
        assert st["observedChartName"] == "nginx"
        o = obj(gvk, spec={"verify": {"secretRef": {"name": "cosign"}}})
        assert {d["name"] for d in ri.get_dependencies(o)} == {"cosign"}

    def test_helmrepository(self):
        ri = interp()
        gvk = "source.toolkit.fluxcd.io/v1beta2/HelmRepository"
        o = obj(gvk, spec={"secretRef": {"name": "repo-creds"}})
        assert {d["name"] for d in ri.get_dependencies(o)} == {"repo-creds"}

    def test_ocirepository_cert_secret(self):
        ri = interp()
        gvk = "source.toolkit.fluxcd.io/v1beta2/OCIRepository"
        o = obj(gvk, spec={
            "secretRef": {"name": "oci-creds"},
            "verify": {"secretRef": {"name": "cosign"}},
            "certSecretRef": {"name": "tls"},
        })
        assert {d["name"] for d in ri.get_dependencies(o)} == {
            "oci-creds", "cosign", "tls"
        }

    def test_suspend_retention_all_sources(self):
        ri = interp()
        for gvk in [g for g in REFERENCE_SET if "source.toolkit" in g
                    or "fluxcd" in g]:
            desired = obj(gvk, spec={})
            observed = obj(gvk, spec={"suspend": True})
            out = ri.retain(desired, observed)
            assert out.get("spec", "suspend") is True, gvk

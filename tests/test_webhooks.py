"""Admission webhooks (W1): mutating defaults + validation + deletion protection."""
from __future__ import annotations

import pytest

from karmada_tpu.api.meta import ObjectMeta
from karmada_tpu.api.policy import (
    ApplicationFailoverBehavior,
    FailoverBehavior,
    ImageOverrider,
    OverridePolicy,
    OverrideSpec,
    Overriders,
    PlaintextOverrider,
    PropagationPolicy,
    PropagationSpec,
    ResourceSelector,
    RuleWithCluster,
    SpreadConstraint,
)
from karmada_tpu.api.work import BindingSpec, ObjectReference, ResourceBinding
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.testing.fixtures import (
    duplicated_placement,
    new_deployment,
    new_policy,
    selector_for,
)
from karmada_tpu.webhook import AdmissionDenied
from karmada_tpu.webhook.handlers import (
    DELETION_PROTECTION_LABEL,
    NOT_READY_TAINT_KEY,
    UNREACHABLE_TAINT_KEY,
)


@pytest.fixture
def cp():
    return ControlPlane()


def _pp(name="pp", **spec_kw):
    return PropagationPolicy(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(api_version="apps/v1", kind="Deployment")],
            **spec_kw,
        ),
    )


class TestPropagationPolicyWebhook:
    def test_mutating_defaults_tolerations(self, cp):
        created = cp.store.create(_pp())
        keys = {(t.key, t.effect) for t in created.spec.placement.cluster_tolerations}
        assert (NOT_READY_TAINT_KEY, "NoExecute") in keys
        assert (UNREACHABLE_TAINT_KEY, "NoExecute") in keys
        secs = [t.toleration_seconds for t in created.spec.placement.cluster_tolerations]
        assert all(s == 300 for s in secs)

    def test_permanent_id_label_stable_across_updates(self, cp):
        created = cp.store.create(_pp())
        pid = created.metadata.labels["propagationpolicy.karmada.io/permanent-id"]
        assert pid
        created.spec.priority = 5
        updated = cp.store.update(created)
        assert updated.metadata.labels["propagationpolicy.karmada.io/permanent-id"] == pid

    def test_empty_selectors_denied(self, cp):
        bad = PropagationPolicy(metadata=ObjectMeta(name="bad", namespace="default"))
        with pytest.raises(AdmissionDenied, match="resourceSelectors"):
            cp.store.create(bad)

    def test_spread_constraint_validation(self, cp):
        pp = _pp()
        pp.spec.placement.spread_constraints = [
            SpreadConstraint(spread_by_field="region", min_groups=3, max_groups=2)
        ]
        with pytest.raises(AdmissionDenied, match="minGroups"):
            cp.store.create(pp)

    def test_negative_toleration_seconds_denied(self, cp):
        pp = _pp(
            failover=FailoverBehavior(
                application=ApplicationFailoverBehavior(
                    decision_conditions_toleration_seconds=-1
                )
            )
        )
        with pytest.raises(AdmissionDenied, match="tolerationSeconds"):
            cp.store.create(pp)


class TestOverridePolicyWebhook:
    def test_bad_image_component_denied(self, cp):
        op = OverridePolicy(
            metadata=ObjectMeta(name="op", namespace="default"),
            spec=OverrideSpec(
                override_rules=[
                    RuleWithCluster(
                        overriders=Overriders(
                            image_overrider=[ImageOverrider(component="Nope", value="x")]
                        )
                    )
                ]
            ),
        )
        with pytest.raises(AdmissionDenied, match="component"):
            cp.store.create(op)

    def test_bad_plaintext_path_denied(self, cp):
        op = OverridePolicy(
            metadata=ObjectMeta(name="op", namespace="default"),
            spec=OverrideSpec(
                override_rules=[
                    RuleWithCluster(
                        overriders=Overriders(
                            plaintext=[PlaintextOverrider(path="spec/replicas", operator="replace", value=1)]
                        )
                    )
                ]
            ),
        )
        with pytest.raises(AdmissionDenied, match="JSON pointer"):
            cp.store.create(op)

    def test_valid_override_accepted(self, cp):
        op = OverridePolicy(
            metadata=ObjectMeta(name="op", namespace="default"),
            spec=OverrideSpec(
                override_rules=[
                    RuleWithCluster(
                        overriders=Overriders(
                            image_overrider=[ImageOverrider(component="Tag", value="v2")]
                        )
                    )
                ]
            ),
        )
        assert cp.store.create(op) is not None


class TestBindingWebhook:
    def test_rb_gets_permanent_id(self, cp):
        rb = ResourceBinding(
            metadata=ObjectMeta(name="rb", namespace="default"),
            spec=BindingSpec(resource=ObjectReference(kind="Deployment", name="d")),
        )
        created = cp.store.create(rb)
        assert created.metadata.labels.get("resourcebinding.karmada.io/permanent-id")

    def test_rb_without_resource_denied(self, cp):
        rb = ResourceBinding(metadata=ObjectMeta(name="rb", namespace="default"))
        with pytest.raises(AdmissionDenied, match="spec.resource"):
            cp.store.create(rb)


class TestDeletionProtection:
    def test_protected_template_cannot_be_deleted(self, cp):
        dep = new_deployment("default", "web", replicas=1)
        dep.metadata.labels[DELETION_PROTECTION_LABEL] = "Always"
        cp.store.create(dep)
        with pytest.raises(AdmissionDenied, match="protected"):
            cp.store.delete("apps/v1/Deployment", "web", "default")
        # removing the label unblocks deletion
        obj = cp.store.get("apps/v1/Deployment", "web", "default")
        obj.metadata.labels.pop(DELETION_PROTECTION_LABEL)
        cp.store.update(obj)
        cp.store.delete("apps/v1/Deployment", "web", "default")
        assert cp.store.try_get("apps/v1/Deployment", "web", "default") is None


class TestEndToEndWithAdmission:
    def test_full_pipeline_still_converges(self, cp):
        from karmada_tpu.members.member import MemberConfig

        cp.join_member(MemberConfig(name="m1", allocatable={"cpu": 10.0}))
        dep = new_deployment("default", "web", replicas=2)
        cp.store.create(dep)
        cp.store.create(new_policy("default", "pp", [selector_for(dep)], duplicated_placement()))
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert [t.name for t in rb.spec.clusters] == ["m1"]
        works = cp.store.list("Work")
        assert works


def test_field_overrider_validation():
    from karmada_tpu.api.policy import FieldOverrider, FieldPatchOperation
    from karmada_tpu.controlplane import ControlPlane

    cp = ControlPlane()

    def policy_with(name, fo):
        return OverridePolicy(
            metadata=ObjectMeta(name=name, namespace="default"),
            spec=OverrideSpec(override_rules=[
                RuleWithCluster(overriders=Overriders(field_overrider=[fo]))
            ]),
        )

    ok = policy_with("op-ok", FieldOverrider(
        field_path="/data/cfg.json",
        json=[FieldPatchOperation(sub_path="/a", operator="add", value=1)]))
    assert cp.store.create(ok) is not None

    both = policy_with("op-both", FieldOverrider(
        field_path="/data/cfg.json",
        json=[FieldPatchOperation(sub_path="/a", operator="add", value=1)],
        yaml=[FieldPatchOperation(sub_path="/a", operator="add", value=1)]))
    with pytest.raises(AdmissionDenied, match="both json and yaml"):
        cp.store.create(both)

    bad_path = policy_with("op-bad", FieldOverrider(
        field_path="data/cfg.json",
        json=[FieldPatchOperation(sub_path="/a", operator="add", value=1)]))
    with pytest.raises(AdmissionDenied, match="fieldPath"):
        cp.store.create(bad_path)

"""Contract tests for the scheduler sidecar shim (VERDICT r4 missing #4).

Inputs are REFERENCE-SHAPED JSON: what `json.Marshal` of Go
workv1alpha2.ResourceBindingSpec / clusterv1alpha1.Cluster produces
(binding_types.go / cluster types.go JSON tags). Expected placements are
the Go path's answers per pkg/scheduler/core/{assignment,
division_algorithm}.go and util/helper/binding.go's Dispenser — the shim
must be a drop-in ScheduleAlgorithm (generic_scheduler.go:36-38).
"""
from __future__ import annotations

import json
import urllib.request

import pytest

from karmada_tpu.server.scheduler_shim import SchedulerShim, SchedulerShimServer


def cluster_json(name, cpu="100", region="r1", taints=None, allocated="0"):
    return {
        "apiVersion": "cluster.karmada.io/v1alpha1",
        "kind": "Cluster",
        "metadata": {"name": name, "labels": {"fleet": "test"}},
        "spec": {
            "syncMode": "Push",
            "region": region,
            **({"taints": taints} if taints else {}),
        },
        "status": {
            "kubernetesVersion": "v1.30.0",
            "apiEnablements": [
                {"groupVersion": "apps/v1",
                 "resources": [{"name": "deployments", "kind": "Deployment"}]},
            ],
            "conditions": [
                {"type": "Ready", "status": "True", "reason": "ClusterReady"},
            ],
            "resourceSummary": {
                "allocatable": {"cpu": cpu, "memory": "400Gi", "pods": "1000"},
                "allocated": {"cpu": allocated},
            },
        },
    }


def spec_json(name="app", replicas=0, placement=None, cpu_request="100m",
              clusters=None, reschedule=None):
    d = {
        "resource": {"apiVersion": "apps/v1", "kind": "Deployment",
                     "namespace": "default", "name": name},
        "replicas": replicas,
        "replicaRequirements": {
            "resourceRequest": {"cpu": cpu_request},
        },
        "placement": placement or {},
    }
    if clusters:
        d["clusters"] = clusters
    if reschedule:
        d["rescheduleTriggeredAt"] = reschedule
    return d


@pytest.fixture(scope="module")
def shim():
    s = SchedulerShim()
    s.sync_clusters([
        cluster_json("m1", cpu="10"),
        cluster_json("m2", cpu="30", region="r2"),
        cluster_json("m3", cpu="20", region="r2"),
    ])
    return s


def targets_of(result):
    assert "error" not in result, result
    return {tc["name"]: tc.get("replicas", 0)
            for tc in result["suggestedClusters"]}


class TestScheduleContract:
    def test_duplicated_full_replicas_everywhere(self, shim):
        # assignByDuplicatedStrategy (assignment.go:176-182)
        result = shim.schedule(spec_json(replicas=4, placement={
            "clusterAffinity": {"clusterNames": ["m1", "m2", "m3"]},
            "replicaScheduling": {"replicaSchedulingType": "Duplicated"},
        }))
        assert targets_of(result) == {"m1": 4, "m2": 4, "m3": 4}

    def test_static_weight_largest_remainder(self, shim):
        # TakeByWeight (util/helper/binding.go:112-144): 9 by 1:2 -> 3/6
        result = shim.schedule(spec_json(replicas=9, placement={
            "clusterAffinity": {"clusterNames": ["m1", "m2"]},
            "replicaScheduling": {
                "replicaSchedulingType": "Divided",
                "replicaDivisionPreference": "Weighted",
                "weightPreference": {"staticWeightList": [
                    {"targetCluster": {"clusterNames": ["m1"]}, "weight": 1},
                    {"targetCluster": {"clusterNames": ["m2"]}, "weight": 2},
                ]},
            },
        }))
        assert targets_of(result) == {"m1": 3, "m2": 6}

    def test_dynamic_weight_by_available_replicas(self, shim):
        # dynamicDivideReplicas (division_algorithm.go:75-99): free cpu
        # m1=10 m2=30 m3=20 at 1 cpu/replica -> weights 10:30:20; 6 replicas
        # -> 1/3/2
        result = shim.schedule(spec_json(replicas=6, cpu_request="1", placement={
            "clusterAffinity": {"clusterNames": ["m1", "m2", "m3"]},
            "replicaScheduling": {
                "replicaSchedulingType": "Divided",
                "replicaDivisionPreference": "Weighted",
                "weightPreference": {"dynamicWeight": "AvailableReplicas"},
            },
        }))
        assert targets_of(result) == {"m1": 1, "m2": 3, "m3": 2}

    def test_aggregated_packs_fewest_clusters(self, shim):
        # division_algorithm.go:80-90: sort by available desc, truncate to
        # covering prefix: m2(30) alone covers 8
        result = shim.schedule(spec_json(replicas=8, cpu_request="1", placement={
            "clusterAffinity": {"clusterNames": ["m1", "m2", "m3"]},
            "replicaScheduling": {
                "replicaSchedulingType": "Divided",
                "replicaDivisionPreference": "Aggregated",
            },
        }))
        assert targets_of(result) == {"m2": 8}

    def test_taint_filters_untolerated_cluster(self):
        shim = SchedulerShim()
        shim.sync_clusters([
            cluster_json("ok", cpu="10"),
            cluster_json("tainted", cpu="10", taints=[
                {"key": "maintenance", "value": "true", "effect": "NoSchedule"},
            ]),
        ])
        result = shim.schedule(spec_json(replicas=2, placement={
            "clusterAffinity": {"clusterNames": ["ok", "tainted"]},
            "replicaScheduling": {"replicaSchedulingType": "Duplicated"},
        }))
        assert set(targets_of(result)) == {"ok"}

        # with a matching toleration the taint no longer filters
        result = shim.schedule(spec_json(replicas=2, placement={
            "clusterAffinity": {"clusterNames": ["ok", "tainted"]},
            "clusterTolerations": [
                {"key": "maintenance", "operator": "Equal", "value": "true",
                 "effect": "NoSchedule"},
            ],
            "replicaScheduling": {"replicaSchedulingType": "Duplicated"},
        }))
        assert set(targets_of(result)) == {"ok", "tainted"}

    def test_unschedulable_is_an_outcome_not_an_error(self, shim):
        # capacity 60 total at 1cpu; 1000 replicas cannot fit ->
        # framework.FitError equivalent
        result = shim.schedule(spec_json(replicas=1000, cpu_request="1", placement={
            "clusterAffinity": {"clusterNames": ["m1", "m2", "m3"]},
            "replicaScheduling": {
                "replicaSchedulingType": "Divided",
                "replicaDivisionPreference": "Weighted",
                "weightPreference": {"dynamicWeight": "AvailableReplicas"},
            },
        }))
        assert result.get("unschedulable") is True
        assert result.get("error")

    def test_steady_scale_up_keeps_prior_clusters_first(self, shim):
        # assignment.go:120-173 resortAvailableClusters: previous clusters
        # retain their replicas; only the delta disperses
        result = shim.schedule(spec_json(
            replicas=12, cpu_request="1",
            clusters=[{"name": "m3", "replicas": 10}],
            placement={
                "clusterAffinity": {"clusterNames": ["m1", "m2", "m3"]},
                "replicaScheduling": {
                    "replicaSchedulingType": "Divided",
                    "replicaDivisionPreference": "Aggregated",
                },
            }))
        got = targets_of(result)
        assert got.get("m3", 0) >= 10  # stickiness held
        assert sum(got.values()) == 12

    def test_batch_matches_singular(self, shim):
        specs = [
            spec_json("a", replicas=4, placement={
                "clusterAffinity": {"clusterNames": ["m1", "m2", "m3"]},
                "replicaScheduling": {"replicaSchedulingType": "Duplicated"},
            }),
            spec_json("b", replicas=9, placement={
                "clusterAffinity": {"clusterNames": ["m1", "m2"]},
                "replicaScheduling": {
                    "replicaSchedulingType": "Divided",
                    "replicaDivisionPreference": "Weighted",
                    "weightPreference": {"staticWeightList": [
                        {"targetCluster": {"clusterNames": ["m1"]}, "weight": 1},
                        {"targetCluster": {"clusterNames": ["m2"]}, "weight": 2},
                    ]},
                },
            }),
        ]
        batch = shim.schedule_batch([{"spec": s} for s in specs])
        singular = [shim.schedule(s) for s in specs]
        assert [targets_of(r) for r in batch] == [targets_of(r) for r in singular]


class TestWireParityFuzz:
    """Typed → reference-JSON (api/k8sjson to_json mirrors) → wire → shim
    must place identically to the in-process ArrayScheduler on the same
    typed objects — every strategy family in one randomized batch — and the
    marshal/parse pair must be a JSON fixpoint."""

    def test_randomized_wire_parity_and_json_fixpoint(self):
        import __graft_entry__ as ge

        from karmada_tpu.api import k8sjson

        sched, _, bindings = ge._example_problem(n_clusters=24, n_bindings=60)
        # pin each binding's identity to its template uid so the
        # deterministic tie seed survives the wire (the shim reconstructs
        # metadata from the spec)
        for b in bindings:
            b.spec.resource.uid = b.metadata.uid
        want = sched.schedule(bindings)

        cluster_docs = [k8sjson.cluster_to_json(c) for c in sched.clusters]
        for doc in cluster_docs:
            assert k8sjson.cluster_to_json(k8sjson.cluster_from_json(doc)) == doc
        spec_docs = [k8sjson.binding_spec_to_json(b.spec) for b in bindings]
        for doc in spec_docs:
            assert k8sjson.binding_spec_to_json(
                k8sjson.binding_spec_from_json(doc)
            ) == doc

        shim = SchedulerShim()
        assert shim.sync_clusters(cluster_docs) == len(cluster_docs)
        got = shim.schedule_batch([{"spec": d} for d in spec_docs])
        assert len(got) == len(want)
        for i, (w, g) in enumerate(zip(want, got)):
            if w.error:
                assert g.get("unschedulable"), (i, w.error, g)
                continue
            assert {t.name: t.replicas for t in w.targets} == {
                tc["name"]: tc.get("replicas", 0)
                for tc in g["suggestedClusters"]
            }, f"row {i} diverged over the wire"

    def test_fixpoint_edge_shapes(self):
        """Shapes where marshal and parse disagree on defaults: empty
        selector, empty toleration operator, minGroups 0."""
        from karmada_tpu.api import k8sjson
        from karmada_tpu.api import policy as pol
        from karmada_tpu.api.meta import LabelSelector

        p = pol.Placement(
            cluster_affinity=pol.ClusterAffinity(
                label_selector=LabelSelector()
            ),
            cluster_tolerations=[pol.Toleration(key="k", operator="")],
            spread_constraints=[
                pol.SpreadConstraint(
                    spread_by_field=pol.SPREAD_BY_FIELD_CLUSTER, min_groups=0
                )
            ],
        )
        doc = k8sjson.placement_to_json(p)
        assert k8sjson.placement_to_json(
            k8sjson.placement_from_json(doc)
        ) == doc
        assert doc["clusterTolerations"][0]["operator"] == "Equal"
        assert doc["spreadConstraints"][0]["minGroups"] == 1
        assert "labelSelector" not in doc["clusterAffinity"]

    def test_same_object_same_answer(self):
        """uid-seeded tie-break: repeated shim calls for one template are
        idempotent even where the division has exact ties."""
        from karmada_tpu.api import k8sjson  # noqa: F401 - parity of imports

        shim = SchedulerShim()
        shim.sync_clusters([
            cluster_json("m1", cpu="10"), cluster_json("m2", cpu="10"),
        ])
        spec = spec_json(replicas=3, cpu_request="1", placement={
            "clusterAffinity": {"clusterNames": ["m1", "m2"]},
            "replicaScheduling": {
                "replicaSchedulingType": "Divided",
                "replicaDivisionPreference": "Weighted",
                "weightPreference": {"staticWeightList": [
                    {"targetCluster": {"clusterNames": ["m1"]}, "weight": 1},
                    {"targetCluster": {"clusterNames": ["m2"]}, "weight": 1},
                ]},
            },
        })
        spec["resource"]["uid"] = "rb-fixed-uid"
        first = targets_of(shim.schedule(spec))
        for _ in range(3):
            assert targets_of(shim.schedule(spec)) == first


class TestShimOverHttp:
    def test_tls_and_token(self, tmp_path):
        """Cross-host deployment shape: HTTPS from cluster-CA material and
        bearer auth, same contract as the control-plane apiserver."""
        import ssl
        import urllib.error

        from karmada_tpu.server.tlsmaterial import ensure_server_tls, ensure_token

        ctx = ensure_server_tls(str(tmp_path / "tls"), "127.0.0.1")
        token = ensure_token(str(tmp_path / "token"))
        srv = SchedulerShimServer(ssl_context=ctx, token=token)
        port = srv.start()
        assert srv.url.startswith("https://")
        client_ctx = ssl.create_default_context(
            cafile=str(tmp_path / "tls" / "ca.pem")
        )

        def post(path, body, tok):
            req = urllib.request.Request(
                f"{srv.url}{path}", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json",
                         **({"Authorization": f"Bearer {tok}"} if tok else {})},
            )
            with urllib.request.urlopen(req, timeout=30,
                                        context=client_ctx) as r:
                return json.loads(r.read().decode())

        try:
            out = post("/v1/clusters", {"items": [cluster_json("m1")]}, token)
            assert out == {"count": 1}
            with pytest.raises(urllib.error.HTTPError) as e:
                post("/v1/clusters", {"items": []}, "wrong")
            assert e.value.code == 401
            # healthz probe-able without credentials
            req = urllib.request.Request(f"{srv.url}/healthz")
            with urllib.request.urlopen(req, timeout=30,
                                        context=client_ctx) as r:
                assert json.loads(r.read().decode()) == {"ok": True}

            # keep-alive discipline: a 401 with an unread body must not
            # desync the connection for the next (authenticated) request
            import http.client

            conn = http.client.HTTPSConnection(
                "127.0.0.1", port, timeout=30, context=client_ctx
            )
            try:
                body = json.dumps({"items": [cluster_json("m2")]})
                conn.request("POST", "/v1/clusters", body=body, headers={
                    "Content-Type": "application/json",
                    "Authorization": "Bearer wrong",
                })
                resp = conn.getresponse()
                assert resp.status == 401
                resp.read()
                conn.request("POST", "/v1/clusters", body=body, headers={
                    "Content-Type": "application/json",
                    "Authorization": f"Bearer {token}",
                })
                resp = conn.getresponse()
                assert resp.status == 200
                assert json.loads(resp.read().decode()) == {"count": 1}
            finally:
                conn.close()
        finally:
            srv.stop()

    def test_wire_roundtrip(self):
        srv = SchedulerShimServer()
        port = srv.start()
        try:
            def post(path, body):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}{path}",
                    data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=30) as r:
                    return json.loads(r.read().decode())

            out = post("/v1/clusters", {"items": [
                cluster_json("m1", cpu="10"), cluster_json("m2", cpu="30"),
            ]})
            assert out == {"count": 2}

            out = post("/v1/schedule", {"spec": spec_json(replicas=3, placement={
                "clusterAffinity": {"clusterNames": ["m1", "m2"]},
                "replicaScheduling": {"replicaSchedulingType": "Duplicated"},
            })})
            assert {tc["name"]: tc["replicas"]
                    for tc in out["suggestedClusters"]} == {"m1": 3, "m2": 3}

            out = post("/v1/scheduleBatch", {"items": [
                {"spec": spec_json("x", replicas=2, placement={
                    "clusterAffinity": {"clusterNames": ["m1"]},
                    "replicaScheduling": {"replicaSchedulingType": "Duplicated"},
                })},
            ]})
            assert out["results"][0]["suggestedClusters"] == [
                {"name": "m1", "replicas": 2},
            ]

            # schedule before any snapshot: typed error, not a 500
            srv2 = SchedulerShimServer()
            p2 = srv2.start()
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{p2}/v1/schedule",
                    data=json.dumps({"spec": spec_json()}).encode(),
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=30) as r:
                    out = json.loads(r.read().decode())
                assert "no cluster snapshot" in out["error"]
            finally:
                srv2.stop()
        finally:
            srv.stop()

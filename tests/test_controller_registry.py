"""--controllers enable/disable surface (context.go:116-137,
controllermanager.go:217-248)."""
from karmada_tpu.api.meta import CPU, MEMORY
from karmada_tpu.controlplane import (
    CONTROLLER_NAMES,
    CONTROLLERS_DISABLED_BY_DEFAULT,
    ControlPlane,
    is_controller_enabled,
)
from karmada_tpu.members.member import MemberConfig
from karmada_tpu.testing.fixtures import (
    duplicated_placement,
    new_deployment,
    new_policy,
    selector_for,
)

GiB = 1024.0**3


class TestIsControllerEnabled:
    def test_star_enables_non_default_disabled(self):
        assert is_controller_enabled("binding", ["*"])
        assert not is_controller_enabled("hpaScaleTargetMarker", ["*"])

    def test_explicit_name_wins_over_default_disable(self):
        assert is_controller_enabled(
            "hpaScaleTargetMarker", ["*", "hpaScaleTargetMarker"]
        )

    def test_minus_disables(self):
        assert not is_controller_enabled("binding", ["*", "-binding"])

    def test_no_star_means_nothing_on(self):
        assert not is_controller_enabled("binding", ["execution"])
        assert is_controller_enabled("execution", ["execution"])

    def test_all_names_known(self):
        assert CONTROLLERS_DISABLED_BY_DEFAULT <= set(CONTROLLER_NAMES)


class TestDisabledControllerBehavior:
    def _plane(self, controllers):
        cp = ControlPlane(controllers=controllers)
        cp.join_member(MemberConfig(
            name="m1", allocatable={CPU: 16.0, MEMORY: 64 * GiB, "pods": 100.0}
        ))
        return cp

    def test_binding_disabled_means_no_works(self):
        cp = self._plane(["*", "-binding"])
        d = new_deployment("default", "web", replicas=1, cpu=0.1)
        cp.store.create(d)
        cp.store.create(new_policy(
            "default", "pp", [selector_for(d)], duplicated_placement([])
        ))
        cp.settle()
        # detector + scheduler still run: the RB exists and is scheduled
        rb = cp.store.get("ResourceBinding", "web-deployment", "default")
        assert rb.spec.clusters
        # ...but no binding controller ⇒ no Work objects materialize
        assert not cp.store.list("Work")

    def test_explicit_list_still_schedules(self):
        """The scheduler is its own binary in the reference — an explicit
        --controllers list (no '*', no mention of it) must not turn it off."""
        cp = self._plane(["binding", "execution", "workStatus"])
        assert cp.scheduler is not None
        d = new_deployment("default", "web", replicas=1, cpu=0.1)
        cp.store.create(d)
        cp.store.create(new_policy(
            "default", "pp", [selector_for(d)], duplicated_placement([])
        ))
        cp.settle()
        assert cp.store.get(
            "ResourceBinding", "web-deployment", "default"
        ).spec.clusters

    def test_scheduler_opt_out(self):
        cp = self._plane(["*", "-scheduler"])
        assert cp.scheduler is None
        d = new_deployment("default", "web", replicas=1, cpu=0.1)
        cp.store.create(d)
        cp.store.create(new_policy(
            "default", "pp", [selector_for(d)], duplicated_placement([])
        ))
        cp.settle()
        rb = cp.store.get("ResourceBinding", "web-deployment", "default")
        assert not rb.spec.clusters  # pending until a scheduler attaches

    def test_default_plane_unaffected(self):
        cp = self._plane(None)
        d = new_deployment("default", "web", replicas=1, cpu=0.1)
        cp.store.create(d)
        cp.store.create(new_policy(
            "default", "pp", [selector_for(d)], duplicated_placement([])
        ))
        cp.settle()
        assert cp.store.list("Work")
        assert cp.hpa_scale_target_marker is None  # default-disabled
        assert cp.deployment_replicas_syncer is None


def test_unknown_controller_name_rejected():
    import pytest

    with pytest.raises(ValueError, match="unknown controller"):
        ControlPlane(controllers=["*", "-bindng"])  # typo


def test_unified_auth_disable_fails_closed():
    """Disabling the unifiedAuth SYNC controller must not bypass proxy
    authorization — only the RBAC propagation to members stops."""
    import pytest

    from karmada_tpu.proxy import ForbiddenError

    cp = ControlPlane(controllers=["*", "-unifiedAuth"])
    cp.join_member(MemberConfig(
        name="m1", allocatable={CPU: 16.0, MEMORY: 64 * GiB, "pods": 100.0}
    ))
    with pytest.raises(ForbiddenError):
        cp.cluster_proxy.request(
            "m1", "GET", "apps/v1", "Deployment", name="x",
            subject={"kind": "User", "name": "mallory"},
        )
    # grants still enforce (the data plane is alive, the sync loop is not):
    # alice passes authorization and fails only on the missing object
    from karmada_tpu.proxy import ProxyError

    cp.unified_auth_controller.grant("User", "alice")
    with pytest.raises(ProxyError, match="not found"):
        cp.cluster_proxy.request(
            "m1", "GET", "apps/v1", "Deployment", name="x",
            subject={"kind": "User", "name": "alice"},
        )
    # ...and the sync side is genuinely off: no impersonation Work was synced
    assert not [
        w for w in cp.store.list("Work")
        if "impersonator" in w.metadata.name
    ]

"""Failover family tests (F1-F5): taint-based eviction, application failover,
graceful eviction assessment, workload rebalancer, remedy.

Mirrors the reference's test approach (taint_manager_test.go,
rb_application_failover_controller_test.go, evictiontask_test.go,
workloadrebalancer_controller_test.go): fake clusters + fabricated conditions,
deterministic clocks instead of wall-time sleeps.
"""
from karmada_tpu.api.apps import (
    REASON_REFERENCED_BINDING_NOT_FOUND,
    REBALANCE_FAILED,
    REBALANCE_SUCCESSFUL,
    RebalancerObjectReference,
    WorkloadRebalancer,
    WorkloadRebalancerSpec,
)
from karmada_tpu.api.cluster import EFFECT_NO_EXECUTE, TAINT_CLUSTER_NOT_READY, Taint
from karmada_tpu.api.meta import CPU, MEMORY, ObjectMeta
from karmada_tpu.api.policy import (
    ApplicationFailoverBehavior,
    FailoverBehavior,
    PURGE_MODE_GRACIOUSLY,
    PURGE_MODE_IMMEDIATELY,
    StatePreservation,
    StatePreservationRule,
    Toleration,
)
from karmada_tpu.api.remedy import (
    ACTION_TRAFFIC_CONTROL,
    ClusterConditionRequirement,
    DecisionMatch,
    Remedy,
    RemedySpec,
)
from karmada_tpu.controllers.failover import parse_json_path
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.features import (
    FAILOVER,
    FeatureGates,
    STATEFUL_FAILOVER_INJECTION,
)
from karmada_tpu.members.member import MemberConfig
from karmada_tpu.runtime.controller import Clock
from karmada_tpu.testing.fixtures import (
    duplicated_placement,
    new_deployment,
    new_policy,
    selector_for,
    static_weight_placement,
)

GiB = 1024.0**3


def failover_plane(**gate_overrides) -> ControlPlane:
    gates = FeatureGates({FAILOVER: True, **gate_overrides})
    cp = ControlPlane(clock=Clock(fixed=1000.0), gates=gates)
    for i in range(1, 4):
        cp.join_member(
            MemberConfig(
                name=f"member{i}",
                region=f"region-{i % 2}",
                allocatable={CPU: 100.0, MEMORY: 400 * GiB, "pods": 1000.0},
            )
        )
    return cp


def deploy_nginx(cp: ControlPlane, placement=None, failover=None, replicas=2):
    deploy = new_deployment("default", "nginx", replicas=replicas, cpu=0.1)
    cp.store.create(deploy)
    policy = new_policy(
        "default", "nginx-pp", [selector_for(deploy)], placement or duplicated_placement([])
    )
    if failover is not None:
        policy.spec.failover = failover
    cp.store.create(policy)
    cp.settle()
    return cp.store.get("ResourceBinding", "nginx-deployment", "default")


# ---------------------------------------------------------------------------
# Taint manager (F1)
# ---------------------------------------------------------------------------


def test_noexecute_taint_evicts_untolerated_binding():
    """Divided placement: the scheduler has no re-schedule trigger when a
    taint lands (assigned == desired), so the taint manager drives the
    eviction — the case the reference controller exists for."""
    cp = failover_plane()
    rb = deploy_nginx(
        cp, placement=static_weight_placement({"member1": 1, "member2": 2}), replicas=9
    )
    assert {t.name: t.replicas for t in rb.spec.clusters} == {"member1": 3, "member2": 6}

    # member2 unhealthy ⇒ the eviction task can't be assessed away yet
    # (replacement not fully healthy) so we can observe it mid-flight
    cp.members["member2"].set_healthy(False)
    cp.settle()

    cluster = cp.store.get("Cluster", "member1")
    cluster.spec.taints.append(
        Taint(key="disk-pressure", effect=EFFECT_NO_EXECUTE, time_added=cp.runtime.clock.now())
    )
    cp.store.update(cluster)
    cp.settle()

    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    assert "member1" not in {t.name for t in rb.spec.clusters}
    # GracefulEviction gate defaults on ⇒ Graciously task recorded
    tasks = rb.spec.graceful_eviction_tasks
    assert [t.from_cluster for t in tasks] == ["member1"]
    assert tasks[0].purge_mode == PURGE_MODE_GRACIOUSLY
    assert tasks[0].reason == "TaintUntolerated"
    assert tasks[0].producer == "TaintManager"
    assert tasks[0].replicas == 3  # replicas snapshot of the evicted target
    # the old copy keeps running during graceful eviction
    assert cp.members["member1"].get("apps/v1", "Deployment", "nginx", "default") is not None
    # the freed replicas were re-dispensed to the remaining weighted cluster
    assert {t.name: t.replicas for t in rb.spec.clusters} == {"member2": 9}

    # replacement becomes healthy ⇒ task assessed away, old copy removed
    cp.members["member2"].set_healthy(True)
    cp.settle()
    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    assert not rb.spec.graceful_eviction_tasks
    assert cp.members["member1"].get("apps/v1", "Deployment", "nginx", "default") is None


def test_noexecute_taint_toleration_window():
    cp = failover_plane()
    placement = static_weight_placement({"member1": 1, "member2": 2})
    placement.cluster_tolerations = [
        Toleration(key="disk-pressure", operator="Exists", effect=EFFECT_NO_EXECUTE,
                   toleration_seconds=60)
    ]
    rb = deploy_nginx(cp, placement=placement, replicas=9)

    cluster = cp.store.get("Cluster", "member1")
    cluster.spec.taints.append(
        Taint(key="disk-pressure", effect=EFFECT_NO_EXECUTE, time_added=cp.runtime.clock.now())
    )
    cp.store.update(cluster)
    cp.settle()

    # within the window: still scheduled on member1
    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    assert "member1" in {t.name for t in rb.spec.clusters}

    cp.tick(61)
    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    assert {t.name: t.replicas for t in rb.spec.clusters} == {"member2": 9}


def test_forever_toleration_never_evicts():
    cp = failover_plane()
    placement = static_weight_placement({"member1": 1, "member2": 2})
    placement.cluster_tolerations = [
        Toleration(key="disk-pressure", operator="Exists", effect=EFFECT_NO_EXECUTE)
    ]
    deploy_nginx(cp, placement=placement, replicas=9)
    cluster = cp.store.get("Cluster", "member1")
    cluster.spec.taints.append(
        Taint(key="disk-pressure", effect=EFFECT_NO_EXECUTE, time_added=cp.runtime.clock.now())
    )
    cp.store.update(cluster)
    cp.settle()
    cp.tick(3600)
    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    assert "member1" in {t.name for t in rb.spec.clusters}


def test_cluster_condition_taints_and_eviction_flow():
    """NotReady condition ⇒ NoSchedule taint now, NoExecute after the
    failover eviction timeout ⇒ taint manager evicts ⇒ scheduler re-places."""
    cp = failover_plane()
    deploy_nginx(cp)
    # sustained NotReady: observed past the condition debounce threshold
    cp.set_member_ready("member2", False)
    cp.tick(seconds=31)
    cp.set_member_ready("member2", False)
    cp.settle()

    cluster = cp.store.get("Cluster", "member2")
    taint_effects = {(t.key, t.effect) for t in cluster.spec.taints}
    assert (TAINT_CLUSTER_NOT_READY, "NoSchedule") in taint_effects
    assert (TAINT_CLUSTER_NOT_READY, EFFECT_NO_EXECUTE) not in taint_effects

    cp.tick(301)  # past --failover-eviction-timeout (5m)
    cluster = cp.store.get("Cluster", "member2")
    taint_effects = {(t.key, t.effect) for t in cluster.spec.taints}
    assert (TAINT_CLUSTER_NOT_READY, EFFECT_NO_EXECUTE) in taint_effects

    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    assert "member2" not in {t.name for t in rb.spec.clusters}
    assert {t.name for t in rb.spec.clusters} == {"member1", "member3"}


# ---------------------------------------------------------------------------
# Application failover (F2)
# ---------------------------------------------------------------------------


def app_failover(toleration=30, purge=PURGE_MODE_GRACIOUSLY, state_rules=None):
    return FailoverBehavior(
        application=ApplicationFailoverBehavior(
            decision_conditions_toleration_seconds=toleration,
            purge_mode=purge,
            state_preservation=(
                StatePreservation(rules=state_rules) if state_rules else None
            ),
        )
    )


def test_application_failover_evicts_after_toleration():
    cp = failover_plane()
    deploy_nginx(
        cp,
        placement=static_weight_placement({"member1": 1, "member3": 1}),
        failover=app_failover(toleration=30),
        replicas=4,
    )
    # inject failure on member3 only
    cp.members["member3"].set_healthy(False)
    cp.settle()
    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    unhealthy = [i for i in rb.status.aggregated_status if i.health == "Unhealthy"]
    assert [i.cluster_name for i in unhealthy] == ["member3"]
    # toleration window still open
    assert "member3" in {t.name for t in rb.spec.clusters}

    cp.tick(31)
    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    assert "member3" not in {t.name for t in rb.spec.clusters}
    # freed replicas moved to the healthy weighted cluster
    assert {t.name: t.replicas for t in rb.spec.clusters} == {"member1": 4}


def test_application_failover_recovery_cancels_eviction():
    cp = failover_plane()
    deploy_nginx(cp, failover=app_failover(toleration=300))
    cp.members["member3"].set_healthy(False)
    cp.settle()
    cp.tick(100)
    # recovers inside the window
    cp.set_member_ready("member3", True)
    cp.settle()
    cp.tick(300)
    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    assert "member3" in {t.name for t in rb.spec.clusters}
    assert not rb.spec.graceful_eviction_tasks


# ---------------------------------------------------------------------------
# Graceful eviction (F3)
# ---------------------------------------------------------------------------


def test_graceful_eviction_resolves_when_replacement_healthy():
    cp = failover_plane()
    deploy_nginx(cp)
    cluster = cp.store.get("Cluster", "member1")
    cluster.spec.taints.append(
        Taint(key="bad", effect=EFFECT_NO_EXECUTE, time_added=cp.runtime.clock.now())
    )
    cp.store.update(cluster)
    cp.settle()

    # remaining targets are healthy, so the task resolves at the fixpoint
    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    assert not rb.spec.graceful_eviction_tasks
    # and the member1 workload is gone
    assert cp.members["member1"].get("apps/v1", "Deployment", "nginx", "default") is None


def test_graceful_eviction_grace_period_expiry():
    cp = failover_plane()
    deploy_nginx(
        cp, placement=static_weight_placement({"member1": 1, "member2": 2}), replicas=9
    )
    # make everything unhealthy so "replacement healthy" can never fire
    for m in cp.members.values():
        m.set_healthy(False)
    cp.settle()
    cluster = cp.store.get("Cluster", "member1")
    cluster.spec.taints.append(
        Taint(key="bad", effect=EFFECT_NO_EXECUTE, time_added=cp.runtime.clock.now())
    )
    cp.store.update(cluster)
    cp.settle()
    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    assert [t.from_cluster for t in rb.spec.graceful_eviction_tasks] == ["member1"]

    cp.tick(601)  # default 10m grace period
    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    assert not rb.spec.graceful_eviction_tasks


def test_suppress_deletion_holds_task():
    cp = failover_plane()
    rb = deploy_nginx(cp)
    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    from karmada_tpu.controllers.failover import graceful_evict_cluster

    graceful_evict_cluster(
        rb.spec, "member1",
        purge_mode="Never", producer="test", reason="test",
        suppress_deletion=True,
    )
    cp.store.update(rb)
    cp.settle()
    cp.tick(10_000)
    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    assert [t.from_cluster for t in rb.spec.graceful_eviction_tasks] == ["member1"]
    # user confirms deletion
    rb.spec.graceful_eviction_tasks[0].suppress_deletion = False
    cp.store.update(rb)
    cp.settle()
    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    assert not rb.spec.graceful_eviction_tasks


# ---------------------------------------------------------------------------
# State preservation (StatefulFailoverInjection)
# ---------------------------------------------------------------------------


def test_parse_json_path():
    status = {"a": {"b": [{"c": 5}, {"c": "x"}]}, "ready": True}
    assert parse_json_path(status, "{.a.b[0].c}") == "5"
    assert parse_json_path(status, ".a.b[1].c") == "x"
    assert parse_json_path(status, "{.ready}") == "true"
    assert parse_json_path(status, "{.missing}") is None


def test_stateful_failover_injection():
    """Single-cluster app (Duplicated + spread maxGroups=1) fails over to a
    fresh cluster; the preserved status state rides along as labels."""
    from karmada_tpu.api.policy import SpreadConstraint

    cp = failover_plane(**{STATEFUL_FAILOVER_INJECTION: True})
    placement = duplicated_placement([])
    placement.spread_constraints = [
        SpreadConstraint(spread_by_field="cluster", min_groups=1, max_groups=1)
    ]
    rb = deploy_nginx(
        cp,
        placement=placement,
        failover=app_failover(
            toleration=10,
            purge=PURGE_MODE_IMMEDIATELY,
            state_rules=[StatePreservationRule(alias_label_name="failover.io/ready", json_path="{.readyReplicas}")],
        ),
    )
    assert len(rb.spec.clusters) == 1
    first = rb.spec.clusters[0].name

    # every member unhealthy: the replacement can't turn Healthy, so the
    # eviction task stays observable after the failover completes
    for m in cp.members.values():
        m.set_healthy(False)
    cp.settle()
    cp.tick(11)

    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    new_targets = {t.name for t in rb.spec.clusters}
    assert first not in new_targets and len(new_targets) == 1
    task = rb.spec.graceful_eviction_tasks[0]
    assert task.purge_mode == PURGE_MODE_IMMEDIATELY
    assert task.preserved_label_state == {"failover.io/ready": "0"}
    assert first in task.cluster_before_failover

    # the preserved state is injected into the new cluster's workload labels
    target = next(iter(new_targets))
    obj = cp.members[target].get("apps/v1", "Deployment", "nginx", "default")
    assert obj is not None
    assert obj.get("metadata", "labels", "failover.io/ready") == "0"


# ---------------------------------------------------------------------------
# Workload rebalancer (F4)
# ---------------------------------------------------------------------------


def test_workload_rebalancer_triggers_fresh_reschedule():
    cp = failover_plane()
    deploy_nginx(cp)
    rb0 = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    assert rb0.spec.reschedule_triggered_at is None

    cp.store.create(
        WorkloadRebalancer(
            metadata=ObjectMeta(name="rebalance-1"),
            spec=WorkloadRebalancerSpec(
                workloads=[
                    RebalancerObjectReference(
                        api_version="apps/v1", kind="Deployment",
                        namespace="default", name="nginx",
                    ),
                    RebalancerObjectReference(
                        api_version="apps/v1", kind="Deployment",
                        namespace="default", name="ghost",
                    ),
                ]
            ),
        )
    )
    cp.settle()

    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    assert rb.spec.reschedule_triggered_at is not None

    rebalancer = cp.store.get("WorkloadRebalancer", "rebalance-1")
    by_name = {o.workload.name: o for o in rebalancer.status.observed_workloads}
    assert by_name["nginx"].result == REBALANCE_SUCCESSFUL
    assert by_name["ghost"].result == REBALANCE_FAILED
    assert by_name["ghost"].reason == REASON_REFERENCED_BINDING_NOT_FOUND
    assert rebalancer.status.finish_time is not None


def test_workload_rebalancer_retries_failed_workloads():
    """A workload whose binding appears later flips Failed → Successful on
    the next reconcile, and the transition is persisted."""
    cp = failover_plane()
    cp.store.create(
        WorkloadRebalancer(
            metadata=ObjectMeta(name="rebalance-late"),
            spec=WorkloadRebalancerSpec(
                workloads=[
                    RebalancerObjectReference(
                        api_version="apps/v1", kind="Deployment",
                        namespace="default", name="nginx",
                    )
                ]
            ),
        )
    )
    cp.settle()
    rebalancer = cp.store.get("WorkloadRebalancer", "rebalance-late")
    assert rebalancer.status.observed_workloads[0].result == REBALANCE_FAILED

    deploy_nginx(cp)  # binding exists now
    cp.rebalancer_controller.controller.enqueue("rebalance-late")
    cp.settle()
    rebalancer = cp.store.get("WorkloadRebalancer", "rebalance-late")
    assert rebalancer.status.observed_workloads[0].result == REBALANCE_SUCCESSFUL
    rb = cp.store.get("ResourceBinding", "nginx-deployment", "default")
    assert rb.spec.reschedule_triggered_at is not None


def test_workload_rebalancer_ttl_cleanup():
    cp = failover_plane()
    deploy_nginx(cp)
    cp.store.create(
        WorkloadRebalancer(
            metadata=ObjectMeta(name="rebalance-ttl"),
            spec=WorkloadRebalancerSpec(
                workloads=[
                    RebalancerObjectReference(
                        api_version="apps/v1", kind="Deployment",
                        namespace="default", name="nginx",
                    )
                ],
                ttl_seconds_after_finished=60,
            ),
        )
    )
    cp.settle()
    assert cp.store.try_get("WorkloadRebalancer", "rebalance-ttl") is not None
    cp.tick(61)
    assert cp.store.try_get("WorkloadRebalancer", "rebalance-ttl") is None


# ---------------------------------------------------------------------------
# Remedy (F5)
# ---------------------------------------------------------------------------


def test_remedy_actions_follow_cluster_conditions():
    cp = failover_plane()
    cp.store.create(
        Remedy(
            metadata=ObjectMeta(name="traffic-remedy"),
            spec=RemedySpec(
                decision_matches=[
                    DecisionMatch(
                        cluster_condition_match=ClusterConditionRequirement(
                            condition_type="Ready", operator="Equal", condition_status="False"
                        )
                    )
                ],
                actions=[ACTION_TRAFFIC_CONTROL],
            ),
        )
    )
    cp.settle()
    assert cp.store.get("Cluster", "member1").status.remedy_actions == []

    cp.set_member_ready("member1", False)
    cp.tick(seconds=31)
    cp.set_member_ready("member1", False)
    cp.settle()
    assert cp.store.get("Cluster", "member1").status.remedy_actions == [ACTION_TRAFFIC_CONTROL]
    assert cp.store.get("Cluster", "member2").status.remedy_actions == []

    # recovery is debounced (cluster_condition_cache.go:44-84): a single
    # fresh True observation is retained until it has held success-threshold
    cp.set_member_ready("member1", True)
    cp.settle()
    assert cp.store.get("Cluster", "member1").status.remedy_actions == [ACTION_TRAFFIC_CONTROL]
    cp.tick(seconds=31)
    cp.set_member_ready("member1", True)
    cp.settle()
    assert cp.store.get("Cluster", "member1").status.remedy_actions == []


def _ready_status(cluster):
    from karmada_tpu.api.cluster import CLUSTER_CONDITION_READY

    for c in cluster.status.conditions:
        if c.type == CLUSTER_CONDITION_READY:
            return c.status
    return None


def test_ready_condition_flap_suppression():
    """A lease/probe flap INSIDE the failure threshold must not flip the
    recorded Ready condition or fire any eviction
    (ref cluster_condition_cache.go:44-84)."""
    cp = failover_plane()
    deploy_nginx(cp)

    # seed the cache with a steady True observation (the status controller
    # observes every cycle in the reference)
    cp.set_member_ready("member1", True)
    assert _ready_status(cp.store.get("Cluster", "member1")) == "True"

    # flap: NotReady observed, then Ready again 5s later (inside threshold)
    cp.set_member_ready("member1", False)
    cp.settle()
    cluster = cp.store.get("Cluster", "member1")
    assert _ready_status(cluster) == "True"  # retained, never flipped
    assert not cluster.spec.taints  # no not-ready taint -> no eviction path
    cp.tick(seconds=5)
    cp.set_member_ready("member1", True)
    cp.settle()
    cluster = cp.store.get("Cluster", "member1")
    assert _ready_status(cluster) == "True"
    assert not cluster.spec.taints
    for rb in cp.store.list("ResourceBinding"):
        assert not rb.spec.graceful_eviction_tasks

    # a SUSTAINED failure (observed again after the threshold) does flip
    cp.set_member_ready("member1", False)
    cp.tick(seconds=31)
    cp.set_member_ready("member1", False)
    cp.settle()
    assert _ready_status(cp.store.get("Cluster", "member1")) == "False"

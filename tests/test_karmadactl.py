"""karmadactl CLI (U7): join/cordon/taint/get/top/interpret/promote/rebalance."""
from __future__ import annotations

import json

import pytest

from karmada_tpu.cli.karmadactl import CLIError, run
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.runtime.controller import Clock
from karmada_tpu.members.member import MemberConfig
from karmada_tpu.testing.fixtures import (
    duplicated_placement,
    new_deployment,
    new_policy,
    selector_for,
)


@pytest.fixture
def cp():
    return ControlPlane()


def propagate_web(cp, replicas=2):
    dep = new_deployment("default", "web", replicas=replicas, cpu=0.1)
    cp.store.create(dep)
    cp.store.create(new_policy("default", "pp-web", [selector_for(dep)], duplicated_placement()))
    cp.settle()
    return dep


class TestLifecycle:
    def test_join_get_unjoin(self, cp):
        out = run(cp, ["join", "m1", "--region", "us-east1"])
        assert "joined" in out
        out = run(cp, ["get", "clusters"])
        assert "m1" in out and "Push" in out and "True" in out
        assert run(cp, ["unjoin", "m1"]).startswith("cluster m1 unjoined")
        assert "m1" not in run(cp, ["get", "clusters"])

    def test_register_pull_mode(self, cp):
        # register requires the token/CSR bootstrap (register.go:304-308)
        token = run(cp, ["token", "create"])
        ca_hash = cp.pki.cert_hash()
        run(cp, ["register", "edge-1", "--token", token,
                 "--discovery-token-ca-cert-hash", ca_hash])
        assert "Pull" in run(cp, ["get", "clusters"])
        # the agent got a CA-signed identity cert at join
        agent = cp.agents["edge-1"]
        assert agent.cert is not None
        assert agent.cert.common_name == "system:node:edge-1"
        run(cp, ["unregister", "edge-1"])

    def test_join_duplicate_fails(self, cp):
        run(cp, ["join", "m1"])
        with pytest.raises(CLIError):
            run(cp, ["join", "m1"])


class TestCordonTaint:
    def test_cordon_excludes_from_scheduling(self, cp):
        run(cp, ["join", "m1"])
        run(cp, ["join", "m2"])
        run(cp, ["cordon", "m2"])
        propagate_web(cp)
        rb = next(iter(cp.store.list("ResourceBinding")))
        names = [t.name for t in rb.spec.clusters]
        assert names == ["m1"]
        run(cp, ["uncordon", "m2"])
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert sorted(t.name for t in rb.spec.clusters) == ["m1", "m2"]

    def test_taint_add_remove(self, cp):
        run(cp, ["join", "m1"])
        run(cp, ["taint", "clusters", "m1", "dedicated=infra:NoSchedule"])
        cluster = cp.store.get("Cluster", "m1")
        assert any(t.key == "dedicated" and t.effect == "NoSchedule" for t in cluster.spec.taints)
        run(cp, ["taint", "clusters", "m1", "dedicated=infra:NoSchedule-"])
        cluster = cp.store.get("Cluster", "m1")
        assert not cluster.spec.taints

    def test_taint_bad_spec(self, cp):
        run(cp, ["join", "m1"])
        with pytest.raises(CLIError):
            run(cp, ["taint", "clusters", "m1", "no-effect"])


class TestGetDescribeTop:
    def test_get_bindings_and_describe(self, cp):
        run(cp, ["join", "m1"])
        propagate_web(cp)
        out = run(cp, ["get", "rb"])
        assert "web" in out and "m1:2" in out
        desc = run(cp, ["describe", "cluster", "m1"])
        assert json.loads(desc)["metadata"]["name"] == "m1"

    def test_get_from_member_cluster(self, cp):
        run(cp, ["join", "m1"])
        propagate_web(cp)
        out = run(cp, ["get", "deployments", "--cluster", "m1"])
        assert "web" in out and "m1" in out

    def test_top(self, cp):
        cp.join_member(MemberConfig(name="m1", allocatable={"cpu": 10.0, "memory": 40.0},
                                    allocated={"cpu": 5.0, "memory": 10.0}))
        out = run(cp, ["top"])
        assert "5/10" in out and "50%" in out

    def test_get_events(self, cp):
        run(cp, ["join", "m1"])
        propagate_web(cp)
        out = run(cp, ["get", "events"])
        assert "ScheduleBindingSucceed" in out


class TestInterpretApplyPromote:
    def test_interpret_replica(self, cp, tmp_path):
        dep = new_deployment("default", "web", replicas=7, cpu=0.5)
        f = tmp_path / "dep.json"
        f.write_text(json.dumps(dep.to_dict()))
        out = run(cp, ["interpret", "--operation", "replica", "-f", str(f)])
        assert json.loads(out)["replicas"] == 7

    def test_apply_all_clusters(self, cp, tmp_path):
        run(cp, ["join", "m1"])
        run(cp, ["join", "m2"])
        dep = new_deployment("default", "api", replicas=1)
        f = tmp_path / "dep.json"
        f.write_text(json.dumps(dep.to_dict()))
        out = run(cp, ["apply", "-f", str(f), "--all-clusters"])
        assert "applied" in out
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert sorted(t.name for t in rb.spec.clusters) == ["m1", "m2"]

    def test_apply_yaml_manifest(self, cp, tmp_path):
        run(cp, ["join", "m1"])
        f = tmp_path / "dep.yaml"
        f.write_text(
            "apiVersion: apps/v1\nkind: Deployment\nmetadata:\n"
            "  name: web\n  namespace: default\nspec:\n  replicas: 1\n"
        )
        out = run(cp, ["apply", "-f", str(f), "--all-clusters"])
        assert "applied" in out
        assert cp.store.try_get("apps/v1/Deployment", "web", "default") is not None

    def test_get_watch_streams_events(self, cp):
        """get -w: replayed ADDED lines for existing objects, live events
        for churn during the window."""
        import threading

        from karmada_tpu.cli.karmadactl import cmd_watch

        run(cp, ["join", "m1"])
        dep = new_deployment("default", "pre", replicas=1)
        cp.store.create(dep)
        lines: list[str] = []
        watching = threading.Event()

        def sink(line: str) -> None:
            lines.append(line)
            watching.set()  # first replayed line = subscription is live

        def churn():
            assert watching.wait(5.0)
            cp.store.create(new_deployment("default", "live", replicas=1))
            cp.store.delete("apps/v1/Deployment", "pre", "default")

        t = threading.Thread(target=churn)
        t.start()
        out = cmd_watch(cp, "deployments", seconds=1.0, sink=sink)
        t.join()
        assert any(ln.startswith("ADDED") and ln.endswith("pre")
                   for ln in lines), lines
        assert any(ln.startswith("ADDED") and ln.endswith("live")
                   for ln in lines), lines
        assert any(ln.startswith("DELETED") and ln.endswith("pre")
                   for ln in lines), lines
        assert "event(s)" in out

    def test_apply_multidoc_yaml(self, cp, tmp_path):
        run(cp, ["join", "m1"])
        f = tmp_path / "bundle.yaml"
        f.write_text(
            "apiVersion: apps/v1\nkind: Deployment\nmetadata:\n"
            "  name: a\n  namespace: default\nspec:\n  replicas: 1\n"
            "---\n"
            "apiVersion: apps/v1\nkind: Deployment\nmetadata:\n"
            "  name: b\n  namespace: default\nspec:\n  replicas: 1\n"
        )
        out = run(cp, ["apply", "-f", str(f)])
        assert "Deployment/a applied" in out and "Deployment/b applied" in out

    def test_apply_non_manifest_file_is_a_cli_error(self, cp, tmp_path):
        f = tmp_path / "notes.txt"
        f.write_text("just some plain text\n")
        with pytest.raises(CLIError, match="expected manifest"):
            run(cp, ["apply", "-f", str(f)])

    def test_promote(self, cp):
        run(cp, ["join", "m1"])
        run(cp, ["join", "m2"])
        member = cp.members["m1"]
        member.apply_manifest(new_deployment("default", "legacy", replicas=3).to_dict())
        out = run(cp, ["promote", "deployment", "legacy", "-C", "m1", "-n", "default"])
        assert "promoted" in out
        assert cp.store.try_get("apps/v1/Deployment", "legacy", "default") is not None
        rb = [b for b in cp.store.list("ResourceBinding") if b.spec.resource.name == "legacy"]
        assert rb and [t.name for t in rb[0].spec.clusters] == ["m1"]


class TestReschedulingCommands:
    def test_deschedule_runs(self, cp):
        assert run(cp, ["deschedule"]).startswith("descheduled")

    def test_rebalance_triggers_fresh_schedule(self, cp):
        run(cp, ["join", "m1"])
        propagate_web(cp)
        out = run(cp, ["rebalance", "apps/v1:Deployment:default:web"])
        assert "WorkloadRebalancer" in out
        rebalancers = cp.store.list("WorkloadRebalancer")
        assert rebalancers and rebalancers[0].status.observed_workloads


class TestProxyCommands:
    def test_logs_and_exec(self, cp):
        run(cp, ["join", "m1"])
        propagate_web(cp)
        out = run(cp, ["logs", "web", "-C", "m1"])
        assert "ready=2" in out
        out = run(cp, ["exec", "web", "-C", "m1", "ls"])
        assert "m1/default/web" in out

    def test_logs_missing_workload(self, cp):
        run(cp, ["join", "m1"])
        with pytest.raises(CLIError):
            run(cp, ["logs", "nope", "-C", "m1"])

    def test_addons(self, cp):
        out = run(cp, ["addons"])
        assert "karmada-search" in out and "enabled" in out


class TestInitDeinitTokenFlow:
    """karmadactl init/deinit + token/CSR bootstrap + agent cert rotation
    (ref pkg/karmadactl/cmdinit, register/register.go:70-308,
    controllers/certificate/cert_rotation_controller.go)."""

    def test_init_creates_plane_and_deinit_tears_down(self):
        from karmada_tpu.cli.karmadactl import CLIError, Management, cmd_deinit, cmd_init

        mgmt = Management(clock=Clock(fixed=100.0))
        out = cmd_init(mgmt, "prod")
        assert "control plane prod installed" in out
        assert "--token" in out and "--discovery-token-ca-cert-hash sha256:" in out
        plane = mgmt.plane("prod")
        assert plane is not None
        # the plane actually works: join + propagate
        assert "joined" in run(plane, ["join", "m1"])

        with pytest.raises(CLIError, match="already installed"):
            cmd_init(mgmt, "prod")
        assert "removed" in cmd_deinit(mgmt, "prod")
        assert mgmt.plane("prod") is None
        with pytest.raises(CLIError, match="not found"):
            cmd_deinit(mgmt, "prod")

    def test_failed_init_is_retryable(self, tmp_path):
        """A bad --emit-dir fails the install workflow with the task path in
        the error, and a corrected re-run under the same name succeeds."""
        from karmada_tpu.cli.karmadactl import CLIError, Management, cmd_init

        # a file where a directory is needed blocks even a root test run
        blocker = tmp_path / "blocked"
        blocker.write_text("")
        target = str(blocker / "sub")
        mgmt = Management()
        with pytest.raises(CLIError, match="artifacts"):
            cmd_init(mgmt, "prod", emit_dir=target)
        assert mgmt.plane("prod") is None
        out = cmd_init(mgmt, "prod", emit_dir=str(tmp_path / "good"))
        assert "control plane prod installed" in out
        assert (tmp_path / "good" / "prod-daemon.sh").exists()

    def test_register_token_validation(self, cp):
        from karmada_tpu.cli.karmadactl import CLIError

        with pytest.raises(CLIError, match="token is required"):
            run(cp, ["register", "edge-2"])
        with pytest.raises(CLIError, match="invalid bootstrap token"):
            run(cp, ["register", "edge-2", "--token", "bad.token",
                     "--discovery-token-unsafe-skip-ca-verification"])
        token = run(cp, ["token", "create"])
        with pytest.raises(CLIError, match="need to verify CACertHashes"):
            run(cp, ["register", "edge-2", "--token", token])
        with pytest.raises(CLIError, match="does not match"):
            run(cp, ["register", "edge-2", "--token", token,
                     "--discovery-token-ca-cert-hash", "sha256:deadbeef"])
        # unsafe skip works like the reference flag
        out = run(cp, ["register", "edge-2", "--token", token,
                       "--discovery-token-unsafe-skip-ca-verification"])
        assert "registered" in out

    def test_token_expiry_and_lifecycle(self, cp):
        token = run(cp, ["token", "create"])
        assert token.partition(".")[0] in run(cp, ["token", "list"])
        cp.runtime.clock.advance(25 * 3600)  # past the 24h TTL
        from karmada_tpu.cli.karmadactl import CLIError

        with pytest.raises(CLIError, match="expired"):
            run(cp, ["register", "edge-3", "--token", token,
                     "--discovery-token-unsafe-skip-ca-verification"])
        token2 = run(cp, ["token", "create"])
        assert "deleted" in run(cp, ["token", "delete", token2])
        with pytest.raises(CLIError, match="not found"):
            run(cp, ["token", "delete", token2])

    def test_print_register_command(self, cp):
        out = run(cp, ["token", "create", "--print-register-command"])
        assert out.startswith("karmadactl register")
        assert "--discovery-token-ca-cert-hash sha256:" in out

    def test_agent_cert_rotation(self, cp):
        token = cp.bootstrap_tokens.create().token
        run(cp, ["register", "edge-r", "--token", token,
                 "--discovery-token-ca-cert-hash", cp.pki.cert_hash()])
        agent = cp.agents["edge-r"]
        first = agent.cert
        assert first.remaining_ratio(cp.runtime.clock.now()) > 0.9

        # inside the threshold: no rotation
        cp.tick(seconds=0.5 * (first.not_after - first.not_before))
        assert agent.cert is first
        # past 90% of the lifetime: the rotation controller re-issues
        cp.tick(seconds=0.45 * (first.not_after - first.not_before))
        assert agent.cert is not first
        assert agent.cert.not_after > first.not_after
        assert agent.cert.common_name == "system:node:edge-r"
        assert cp.cert_rotation_controller.rotations == 1


class TestGenericVerbs:
    """kubectl-style verbs (pkg/karmadactl/{create,delete,annotate,label,
    patch,edit,apiresources,explain,options,completion,attach})."""

    def test_create_and_delete(self, cp, tmp_path):
        run(cp, ["join", "m1"])
        f = tmp_path / "cm.json"
        f.write_text(json.dumps({
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": "settings", "namespace": "default"},
            "data": {"a": "1"},
        }))
        assert "created" in run(cp, ["create", "-f", str(f)])
        assert cp.store.try_get("v1/ConfigMap", "settings", "default") is not None
        assert "deleted" in run(cp, ["delete", "v1/ConfigMap", "settings", "-n", "default"])
        assert cp.store.try_get("v1/ConfigMap", "settings", "default") is None
        with pytest.raises(CLIError, match="not found"):
            run(cp, ["delete", "v1/ConfigMap", "settings", "-n", "default"])

    def test_annotate_and_label(self, cp):
        run(cp, ["join", "m1"])
        run(cp, ["annotate", "cluster", "m1", "team=infra"])
        assert cp.store.get("Cluster", "m1").metadata.annotations["team"] == "infra"
        run(cp, ["annotate", "cluster", "m1", "team-"])
        assert "team" not in cp.store.get("Cluster", "m1").metadata.annotations
        run(cp, ["label", "cluster", "m1", "tier=gold", "env=prod"])
        labels = cp.store.get("Cluster", "m1").metadata.labels
        assert labels["tier"] == "gold" and labels["env"] == "prod"

    def test_patch_merge_semantics(self, cp):
        run(cp, ["join", "m1"])
        propagate_web(cp, replicas=2)
        run(cp, ["patch", "apps/v1/Deployment", "web", "-n", "default",
                 "-p", json.dumps({"spec": {"replicas": 6}})])
        cp.settle()
        obj = cp.store.get("apps/v1/Deployment", "web", "default")
        assert int(obj.get("spec", "replicas")) == 6
        # and the change actually reschedules
        rb = cp.store.get("ResourceBinding", "web-deployment", "default")
        assert sum(t.replicas for t in rb.spec.clusters) == 6
        # metadata patches must survive sync_meta (null deletes a label)
        run(cp, ["label", "apps/v1/Deployment", "web", "-n", "default", "team=a"])
        run(cp, ["patch", "apps/v1/Deployment", "web", "-n", "default",
                 "-p", json.dumps({"metadata": {"labels": {"team": None}}})])
        obj = cp.store.get("apps/v1/Deployment", "web", "default")
        assert "team" not in obj.metadata.labels

    def test_edit_replaces_template(self, cp, tmp_path):
        run(cp, ["join", "m1"])
        dep = propagate_web(cp, replicas=2)
        edited = dep.to_dict()
        edited["spec"]["replicas"] = 4
        f = tmp_path / "web.json"
        f.write_text(json.dumps(edited))
        assert "edited" in run(cp, ["edit", "apps/v1/Deployment", "web",
                                    "-n", "default", "-f", str(f)])
        cp.settle()
        rb = cp.store.get("ResourceBinding", "web-deployment", "default")
        assert sum(t.replicas for t in rb.spec.clusters) == 4

    def test_apiresources_explain_options_completion(self, cp):
        run(cp, ["join", "m1"])
        propagate_web(cp)
        kinds = run(cp, ["api-resources"])
        assert "Cluster" in kinds and "ResourceBinding" in kinds
        assert "resourceSelectors" in run(cp, ["explain", "propagationpolicies"])
        with pytest.raises(CLIError):
            run(cp, ["explain", "nonsense"])
        assert "--namespace" in run(cp, ["options"])
        assert "complete -F" in run(cp, ["completion"])

    def test_attach(self, cp):
        run(cp, ["join", "m1"])
        propagate_web(cp)
        assert "ready=2" in run(cp, ["attach", "web", "-C", "m1"])


class TestOutputFormats:
    """`karmadactl get -o json|yaml|name|wide` printers
    (pkg/printers/tablegenerator.go seam)."""

    @pytest.fixture()
    def plane(self):
        from karmada_tpu.controlplane import ControlPlane
        from karmada_tpu.members.member import MemberConfig

        cp = ControlPlane()
        cp.join_member(MemberConfig(name="m1", provider="aws",
                                    region="us-1", zone="us-1a",
                                    allocatable={"cpu": 10.0}))
        cp.join_member(MemberConfig(name="m2", allocatable={"cpu": 10.0}))
        return cp

    def test_json_single_object(self, plane):
        import json as _json

        out = run(plane, ["get", "clusters", "m1", "-o", "json"])
        doc = _json.loads(out)
        assert doc["metadata"]["name"] == "m1"
        assert doc["kind"] == "Cluster"

    def test_json_list_wrapping(self, plane):
        import json as _json

        doc = _json.loads(run(plane, ["get", "clusters", "-o", "json"]))
        assert doc["kind"] == "List"
        assert [i["metadata"]["name"] for i in doc["items"]] == ["m1", "m2"]

    def test_yaml(self, plane):
        import yaml as _yaml

        doc = _yaml.safe_load(run(plane, ["get", "clusters", "m2", "-o", "yaml"]))
        assert doc["metadata"]["name"] == "m2"

    def test_name_format(self, plane):
        out = run(plane, ["get", "clusters", "-o", "name"])
        assert out.splitlines() == ["cluster/m1", "cluster/m2"]

    def test_wide_adds_columns(self, plane):
        out = run(plane, ["get", "clusters", "-o", "wide"])
        header = out.splitlines()[0]
        for col in ("PROVIDER", "REGION", "ZONE"):
            assert col in header
        assert "aws" in out and "us-1a" in out
        narrow = run(plane, ["get", "clusters"])
        assert "PROVIDER" not in narrow

    def test_unknown_format_rejected(self, plane):
        with pytest.raises(CLIError, match="output format"):
            run(plane, ["get", "clusters", "-o", "toml"])


class TestInterpretCustomizations:
    """`karmadactl interpret` against a customization FILE — the
    reference's validate-and-test flow (pkg/karmadactl/interpret)."""

    RIC = {
        "apiVersion": "config.karmada.io/v1alpha1",
        "kind": "ResourceInterpreterCustomization",
        "metadata": {"name": "test-lua"},
        "spec": {
            "target": {"apiVersion": "example.io/v1", "kind": "App"},
            "customizations": {
                "replicaResource": {"luaScript": (
                    "function GetReplicas(obj)\n"
                    "  return obj.spec.replicas, nil\n"
                    "end")},
                "healthInterpretation": {"luaScript": (
                    "function InterpretHealth(obj)\n"
                    "  return obj.status.ready == true\n"
                    "end")},
                "statusAggregation": {"luaScript": (
                    "function AggregateStatus(desiredObj, statusItems)\n"
                    "  if desiredObj.status == nil then desiredObj.status = {} end\n"
                    "  local total = 0\n"
                    "  for i = 1, #statusItems do\n"
                    "    total = total + statusItems[i].status.ready\n"
                    "  end\n"
                    "  desiredObj.status.ready = total\n"
                    "  return desiredObj\n"
                    "end")},
            },
        },
    }

    def _write(self, tmp_path, name, doc):
        import json as _json

        p = tmp_path / name
        p.write_text(_json.dumps(doc))
        return str(p)

    def test_check_ok(self, tmp_path):
        cp = ControlPlane()
        f = self._write(tmp_path, "ric.json", self.RIC)
        out = run(cp, ["interpret", "-f", f, "--check"])
        assert "replica_resource: ok (lua)" in out
        assert "INVALID" not in out

    def test_check_rejects_bad_script(self, tmp_path):
        import copy

        bad = copy.deepcopy(self.RIC)
        bad["spec"]["customizations"]["healthInterpretation"]["luaScript"] = (
            "function InterpretHealth(obj) retur true end"
        )
        cp = ControlPlane()
        f = self._write(tmp_path, "bad.json", bad)
        with pytest.raises(CLIError, match="INVALID"):
            run(cp, ["interpret", "-f", f, "--check"])

    def test_operation_through_customization(self, tmp_path):
        cp = ControlPlane()
        f = self._write(tmp_path, "ric.json", self.RIC)
        observed = self._write(tmp_path, "observed.json", {
            "apiVersion": "example.io/v1", "kind": "App",
            "metadata": {"name": "a", "namespace": "default"},
            "spec": {"replicas": 7}, "status": {"ready": True},
        })
        out = json.loads(run(cp, [
            "interpret", "-f", f, "--operation", "interpretReplica",
            "--observed-file", observed,
        ]))
        assert out["replicas"] == 7
        out = json.loads(run(cp, [
            "interpret", "-f", f, "--operation", "interpretHealth",
            "--observed-file", observed,
        ]))
        assert out["healthy"] == "Healthy"

    def test_aggregate_status_with_status_file(self, tmp_path):
        cp = ControlPlane()
        f = self._write(tmp_path, "ric.json", self.RIC)
        observed = self._write(tmp_path, "observed.json", {
            "apiVersion": "example.io/v1", "kind": "App",
            "metadata": {"name": "a", "namespace": "default"},
            "spec": {"replicas": 2},
        })
        status = self._write(tmp_path, "status.json", [
            {"clusterName": "m1", "status": {"ready": 2}},
            {"clusterName": "m2", "status": {"ready": 1}},
        ])
        out = json.loads(run(cp, [
            "interpret", "-f", f, "--operation", "aggregateStatus",
            "--observed-file", observed, "--status-file", status,
        ]))
        assert out["status"]["ready"] == 3

    def test_retain_requires_desired_file(self, tmp_path):
        cp = ControlPlane()
        f = self._write(tmp_path, "ric.json", self.RIC)
        observed = self._write(tmp_path, "obs.json", {
            "apiVersion": "example.io/v1", "kind": "App",
            "metadata": {"name": "a"}, "spec": {}})
        with pytest.raises(CLIError, match="--desired-file"):
            run(cp, ["interpret", "-f", f, "--operation", "retain",
                     "--observed-file", observed])

    def test_reference_shipped_yaml_checks(self):
        """The reference's own shipped CloneSet customizations.yaml passes
        --check unmodified (Lua compatibility, end to end through the CLI)."""
        import os

        path = ("/root/reference/pkg/resourceinterpreter/default/thirdparty/"
                "resourcecustomizations/apps.kruise.io/v1alpha1/CloneSet/"
                "customizations.yaml")
        if not os.path.exists(path):
            pytest.skip("reference tree not present")
        cp = ControlPlane()
        out = run(cp, ["interpret", "-f", path, "--check"])
        assert out.count("ok (lua)") >= 5


def test_top_pods_lists_member_workloads():
    from karmada_tpu.testing.fixtures import (
        duplicated_placement,
        new_deployment,
        new_policy,
        selector_for,
    )

    cp = ControlPlane()
    cp.join_member(MemberConfig(name="m1", allocatable={"cpu": 50.0}))
    cp.join_member(MemberConfig(name="m2", allocatable={"cpu": 50.0}))
    dep = new_deployment("default", "web", replicas=2)
    cp.store.create(dep)
    cp.store.create(new_policy("default", "pp", [selector_for(dep)],
                               duplicated_placement(["m1", "m2"])))
    cp.settle()
    out = run(cp, ["top", "pods"])
    lines = out.splitlines()
    assert lines[0].split()[:3] == ["CLUSTER", "NAMESPACE", "WORKLOAD"]
    body = "\n".join(lines[1:])
    assert "m1" in body and "m2" in body and "Deployment/web" in body
    # namespace filter
    assert "web" not in run(cp, ["top", "pods", "-n", "other"])

"""karmadactl CLI (U7): join/cordon/taint/get/top/interpret/promote/rebalance."""
from __future__ import annotations

import json

import pytest

from karmada_tpu.cli.karmadactl import CLIError, run
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.members.member import MemberConfig
from karmada_tpu.testing.fixtures import (
    duplicated_placement,
    new_deployment,
    new_policy,
    selector_for,
)


@pytest.fixture
def cp():
    return ControlPlane()


def propagate_web(cp, replicas=2):
    dep = new_deployment("default", "web", replicas=replicas, cpu=0.1)
    cp.store.create(dep)
    cp.store.create(new_policy("default", "pp-web", [selector_for(dep)], duplicated_placement()))
    cp.settle()
    return dep


class TestLifecycle:
    def test_join_get_unjoin(self, cp):
        out = run(cp, ["join", "m1", "--region", "us-east1"])
        assert "joined" in out
        out = run(cp, ["get", "clusters"])
        assert "m1" in out and "Push" in out and "True" in out
        assert run(cp, ["unjoin", "m1"]).startswith("cluster m1 unjoined")
        assert "m1" not in run(cp, ["get", "clusters"])

    def test_register_pull_mode(self, cp):
        run(cp, ["register", "edge-1"])
        assert "Pull" in run(cp, ["get", "clusters"])
        run(cp, ["unregister", "edge-1"])

    def test_join_duplicate_fails(self, cp):
        run(cp, ["join", "m1"])
        with pytest.raises(CLIError):
            run(cp, ["join", "m1"])


class TestCordonTaint:
    def test_cordon_excludes_from_scheduling(self, cp):
        run(cp, ["join", "m1"])
        run(cp, ["join", "m2"])
        run(cp, ["cordon", "m2"])
        propagate_web(cp)
        rb = next(iter(cp.store.list("ResourceBinding")))
        names = [t.name for t in rb.spec.clusters]
        assert names == ["m1"]
        run(cp, ["uncordon", "m2"])
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert sorted(t.name for t in rb.spec.clusters) == ["m1", "m2"]

    def test_taint_add_remove(self, cp):
        run(cp, ["join", "m1"])
        run(cp, ["taint", "clusters", "m1", "dedicated=infra:NoSchedule"])
        cluster = cp.store.get("Cluster", "m1")
        assert any(t.key == "dedicated" and t.effect == "NoSchedule" for t in cluster.spec.taints)
        run(cp, ["taint", "clusters", "m1", "dedicated=infra:NoSchedule-"])
        cluster = cp.store.get("Cluster", "m1")
        assert not cluster.spec.taints

    def test_taint_bad_spec(self, cp):
        run(cp, ["join", "m1"])
        with pytest.raises(CLIError):
            run(cp, ["taint", "clusters", "m1", "no-effect"])


class TestGetDescribeTop:
    def test_get_bindings_and_describe(self, cp):
        run(cp, ["join", "m1"])
        propagate_web(cp)
        out = run(cp, ["get", "rb"])
        assert "web" in out and "m1:2" in out
        desc = run(cp, ["describe", "cluster", "m1"])
        assert json.loads(desc)["metadata"]["name"] == "m1"

    def test_get_from_member_cluster(self, cp):
        run(cp, ["join", "m1"])
        propagate_web(cp)
        out = run(cp, ["get", "deployments", "--cluster", "m1"])
        assert "web" in out and "m1" in out

    def test_top(self, cp):
        cp.join_member(MemberConfig(name="m1", allocatable={"cpu": 10.0, "memory": 40.0},
                                    allocated={"cpu": 5.0, "memory": 10.0}))
        out = run(cp, ["top"])
        assert "5/10" in out and "50%" in out

    def test_get_events(self, cp):
        run(cp, ["join", "m1"])
        propagate_web(cp)
        out = run(cp, ["get", "events"])
        assert "ScheduleBindingSucceed" in out


class TestInterpretApplyPromote:
    def test_interpret_replica(self, cp, tmp_path):
        dep = new_deployment("default", "web", replicas=7, cpu=0.5)
        f = tmp_path / "dep.json"
        f.write_text(json.dumps(dep.to_dict()))
        out = run(cp, ["interpret", "--operation", "replica", "-f", str(f)])
        assert json.loads(out)["replicas"] == 7

    def test_apply_all_clusters(self, cp, tmp_path):
        run(cp, ["join", "m1"])
        run(cp, ["join", "m2"])
        dep = new_deployment("default", "api", replicas=1)
        f = tmp_path / "dep.json"
        f.write_text(json.dumps(dep.to_dict()))
        out = run(cp, ["apply", "-f", str(f), "--all-clusters"])
        assert "applied" in out
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert sorted(t.name for t in rb.spec.clusters) == ["m1", "m2"]

    def test_promote(self, cp):
        run(cp, ["join", "m1"])
        run(cp, ["join", "m2"])
        member = cp.members["m1"]
        member.apply_manifest(new_deployment("default", "legacy", replicas=3).to_dict())
        out = run(cp, ["promote", "deployment", "legacy", "-C", "m1", "-n", "default"])
        assert "promoted" in out
        assert cp.store.try_get("apps/v1/Deployment", "legacy", "default") is not None
        rb = [b for b in cp.store.list("ResourceBinding") if b.spec.resource.name == "legacy"]
        assert rb and [t.name for t in rb[0].spec.clusters] == ["m1"]


class TestReschedulingCommands:
    def test_deschedule_runs(self, cp):
        assert run(cp, ["deschedule"]).startswith("descheduled")

    def test_rebalance_triggers_fresh_schedule(self, cp):
        run(cp, ["join", "m1"])
        propagate_web(cp)
        out = run(cp, ["rebalance", "apps/v1:Deployment:default:web"])
        assert "WorkloadRebalancer" in out
        rebalancers = cp.store.list("WorkloadRebalancer")
        assert rebalancers and rebalancers[0].status.observed_workloads


class TestProxyCommands:
    def test_logs_and_exec(self, cp):
        run(cp, ["join", "m1"])
        propagate_web(cp)
        out = run(cp, ["logs", "web", "-C", "m1"])
        assert "ready=2" in out
        out = run(cp, ["exec", "web", "-C", "m1", "ls"])
        assert "m1/default/web" in out

    def test_logs_missing_workload(self, cp):
        run(cp, ["join", "m1"])
        with pytest.raises(CLIError):
            run(cp, ["logs", "nope", "-C", "m1"])

    def test_addons(self, cp):
        out = run(cp, ["addons"])
        assert "karmada-search" in out and "enabled" in out

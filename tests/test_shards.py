"""Sharded scheduler plane (sched/shards/): the rendezvous shard map
(deterministic, balanced, bounded movement on resize), per-shard ownership
and handoff through the admission-epoch fence (no binding is ever solved
by two shards in the same epoch — exactly once across a concurrent
resize AND across a leader kill mid-micro-batch), the cross-shard gang
commit (PR-13 all-or-nothing verbatim across shards: one rv-checked
batch, any veto aborts every row and re-admits the cohort uncharged),
and the status surface (`karmadactl get shards`, gauge-row retirement)."""
from __future__ import annotations

import threading
import time

import pytest

from karmada_tpu.api.sharding import (
    KIND_SHARD_GANG_PROPOSAL,
    SHARD_NAMESPACE,
    shard_lease_name,
)
from karmada_tpu.api.work import (
    CONDITION_SCHEDULED,
    REASON_GANG_TIMEOUT,
)
from karmada_tpu.metrics import (
    shard_bindings,
    shard_handoffs,
    shard_queue_depth,
    xshard_gang_commits,
)
from karmada_tpu.runtime.controller import Clock, Runtime
from karmada_tpu.sched.shards import (
    ShardedDaemon,
    ShardMap,
    shard_of,
    shard_of_binding,
    shard_of_gang,
)
from karmada_tpu.sched.shards.fairness import ClusterFairnessBudget
from karmada_tpu.store.store import Store
from karmada_tpu.testing.fixtures import synthetic_fleet
from tests.test_parallel import dyn_placement, make_binding

N_CLUSTERS = 5


def fleet_store(clock=None, n=N_CLUSTERS):
    store = Store()
    for c in synthetic_fleet(n, seed=9):
        store.create(c)
    return store


def gang_binding(name, gname, size, replicas=2, ns="default"):
    rb = make_binding(name, replicas, dyn_placement(), cpu=0.1, ns=ns)
    rb.spec.gang_name = gname
    rb.spec.gang_size = size
    return rb


class _PlacementLog:
    """Watch-side exactly-once ledger: one entry per empty->placed
    transition of each binding (the observable form of 'no binding is
    solved by two shards in the same epoch' — a double solve would have
    to commit a second placement write)."""

    def __init__(self, store):
        self.commits: dict[str, int] = {}
        self._placed: dict[str, bool] = {}
        self._lock = threading.Lock()
        store.watch("ResourceBinding", self._on_event, replay=True)

    def _on_event(self, event, rb):
        key = rb.metadata.key()
        placed = bool(rb.spec.clusters)
        with self._lock:
            if placed and not self._placed.get(key, False):
                self.commits[key] = self.commits.get(key, 0) + 1
            self._placed[key] = placed

    def doubles(self):
        return {k: n for k, n in self.commits.items() if n > 1}


def drain(stacks, rounds=16):
    """Deterministic single-thread drive: quiescent-serve every shard,
    then run every cross-shard coordinator tick, until a full round makes
    no progress (mirrors ControlPlane.settle's fixpoint)."""
    for _ in range(rounds):
        progress = 0
        for daemon, service in stacks:
            progress += service.serve(quiescent=True)
        for daemon, _service in stacks:
            progress += daemon.xshards.tick()
        if not progress:
            return
    raise AssertionError("sharded drain did not reach a fixpoint")


def make_stacks(store, total, clock=None, **daemon_kwargs):
    stacks = []
    for i in range(total):
        d = ShardedDaemon(store, Runtime(clock=clock), i, total,
                          aot_prewarm=False, **daemon_kwargs)
        stacks.append((d, d.streaming(batch_delay=0.0)))
    return stacks


def teardown_stacks(stacks):
    for d, _s in stacks:
        d.detach()


class TestShardMap:
    def test_deterministic_and_in_range(self):
        for total in (1, 2, 3, 8):
            for i in range(200):
                s = shard_of(f"ns/key-{i}", total)
                assert 0 <= s < total
                assert s == shard_of(f"ns/key-{i}", total)

    def test_total_one_is_identity(self):
        assert all(shard_of(f"k{i}", 1) == 0 for i in range(50))

    def test_balanced(self):
        total = 4
        counts = [0] * total
        for i in range(8000):
            counts[shard_of(f"ns/uid-{i}", total)] += 1
        lo, hi = min(counts), max(counts)
        # rendezvous over blake2b: each slot near 2000 +- a few percent
        assert lo > 1600 and hi < 2400, counts

    def test_bounded_movement_on_resize(self):
        keys = [f"ns/uid-{i}" for i in range(6000)]
        for total in (2, 4):
            moved = sum(
                1 for k in keys
                if shard_of(k, total) != shard_of(k, total + 1)
            )
            # rendezvous moves ~1/(N+1) of the keyspace; a modulo map
            # would reshuffle nearly everything
            expect = len(keys) / (total + 1)
            assert moved < expect * 1.3, (total, moved)

    def test_binding_key_is_ns_uid(self):
        rb = make_binding("app", 2, dyn_placement())
        total = 5
        want = shard_of(
            f"{rb.metadata.namespace}/{rb.metadata.uid}", total)
        assert shard_of_binding(rb, total) == want
        m = ShardMap(want, total)
        assert m.mine(rb) and m.owner(rb) == want

    def test_gang_coordinator_deterministic(self):
        c = shard_of_gang("default", "g1", 4)
        assert 0 <= c < 4
        assert ShardMap(0, 4).coordinator("default", "g1") == c

    def test_shardmap_validates(self):
        with pytest.raises(ValueError):
            ShardMap(2, 2)
        with pytest.raises(ValueError):
            ShardMap(0, 0)


class TestShardedOwnership:
    """Each shard admits exactly its slice; the union places everything
    exactly once."""

    def test_slices_partition_and_place(self):
        store = fleet_store()
        log = _PlacementLog(store)
        stacks = make_stacks(store, 2)
        bindings = [
            make_binding(f"own-{i}", 2 + i % 3, dyn_placement(), cpu=0.2)
            for i in range(18)
        ]
        for rb in bindings:
            store.create(rb)
        drain(stacks)
        placed = [rb for rb in store.list("ResourceBinding")
                  if rb.spec.clusters]
        assert len(placed) == len(bindings)
        assert not log.doubles()
        d0, d1 = stacks[0][0], stacks[1][0]
        assert d0.owned_count() + d1.owned_count() == len(bindings)
        assert d0.owned_count() > 0 and d1.owned_count() > 0
        # the slices are the map's, not arrival order's
        for rb in store.list("ResourceBinding"):
            owner = shard_of_binding(rb, 2)
            assert (rb.metadata.key() in stacks[owner][0]._owned)
        teardown_stacks(stacks)

    def test_owned_index_drops_deleted(self):
        store = fleet_store()
        stacks = make_stacks(store, 2)
        rb = make_binding("gone", 2, dyn_placement(), cpu=0.1)
        store.create(rb)
        drain(stacks)
        owner = stacks[shard_of_binding(rb, 2)][0]
        assert rb.metadata.key() in owner._owned
        store.delete("ResourceBinding", "gone", "default")
        assert rb.metadata.key() not in owner._owned
        teardown_stacks(stacks)


class TestConcurrentHandoff:
    """The pinned exactly-once test: a resize mid-stream moves keyspace
    between LIVE shards and nothing is ever solved by two shards in the
    same admission epoch (no double placement commit), nothing is lost."""

    def test_resize_mid_stream_exactly_once(self):
        store = fleet_store()
        log = _PlacementLog(store)
        stacks = make_stacks(store, 2)
        for i in range(16):
            store.create(make_binding(f"pre-{i}", 2, dyn_placement(),
                                      cpu=0.2))
        # first wave admits and places under the 2-shard map
        drain(stacks)
        before = shard_handoffs.value(reason="resize")
        # grow to 3 shards while a second wave is already dirty: the
        # moved keys are fenced off the losing shards and re-admitted on
        # the gaining one through the ordinary level-triggered path
        for i in range(16):
            store.create(make_binding(f"mid-{i}", 2, dyn_placement(),
                                      cpu=0.2))
        d2 = ShardedDaemon(store, Runtime(), 2, 3, aot_prewarm=False)
        grown = [(d2, d2.streaming(batch_delay=0.0))]
        moved = 0
        for d, _s in stacks:
            moved += d.set_total(3)
        d2.relist()
        stacks = stacks + grown
        drain(stacks)
        assert moved > 0
        assert shard_handoffs.value(reason="resize") >= before + moved
        placed = [rb for rb in store.list("ResourceBinding")
                  if rb.spec.clusters]
        assert len(placed) == 32
        assert not log.doubles()
        # post-resize ownership is the 3-way map everywhere
        for rb in store.list("ResourceBinding"):
            owner = shard_of_binding(rb, 3)
            for i, (d, _s) in enumerate(stacks):
                assert (rb.metadata.key() in d._owned) == (i == owner)
        teardown_stacks(stacks)

    def test_set_total_refuses_orphan_slot(self):
        store = fleet_store()
        d = ShardedDaemon(store, Runtime(), 1, 2, aot_prewarm=False)
        with pytest.raises(ValueError):
            d.set_total(1)
        d.detach()


class TestLeaderKill:
    """Kill the shard leader mid-micro-batch: its in-flight bindings
    re-place EXACTLY ONCE under the successor (lease handoff on the
    karmada-sched-shard-0 lease; the deposed leader's stragglers lose to
    the epoch/rv fence, the successor's relist re-admits the rest)."""

    @staticmethod
    def _contender(store, identity, leading):
        from karmada_tpu.coordination.elector import (
            Elector,
            LocalLeaseClient,
        )
        from karmada_tpu.coordination.lease import LeaseCoordinator

        daemon = ShardedDaemon(store, Runtime(), 0, 1, aot_prewarm=False)
        service = daemon.streaming(batch_delay=0.0)
        elector = Elector(
            LocalLeaseClient(LeaseCoordinator(store)),
            shard_lease_name(0), identity,
            lease_duration=0.6,
            on_started_leading=lambda t: (
                daemon.xshards.start(), daemon.relist(), leading.set()),
            on_stopped_leading=lambda r: (
                leading.clear(), daemon.xshards.stop()),
        )
        return daemon, service, elector

    def test_successor_places_in_flight_exactly_once(self):
        store = fleet_store()
        log = _PlacementLog(store)
        a_lead, b_lead = threading.Event(), threading.Event()
        a_d, a_svc, a_el = self._contender(store, "leader-a", a_lead)
        b_d, b_svc, b_el = self._contender(store, "leader-b", b_lead)

        threads = []
        done = threading.Event()

        def serve(svc, lead):
            def run():
                while not done.is_set():
                    if lead.is_set():
                        try:
                            svc.serve(should_stop=lambda: (
                                not lead.is_set() or done.is_set()))
                        except Exception:  # noqa: BLE001 - assert on state
                            pass
                    else:
                        time.sleep(0.01)
            t = threading.Thread(target=run, daemon=True)
            threads.append(t)
            t.start()

        a_el.step()
        a_el.run()
        assert a_lead.wait(5.0), "first contender must lead"
        b_el.step()  # loses: lease held
        b_el.run()
        assert not b_lead.is_set()
        serve(a_svc, a_lead)
        serve(b_svc, b_lead)
        n = 30
        for i in range(n):
            store.create(make_binding(f"kill-{i}", 2, dyn_placement(),
                                      cpu=0.2))
        # wait until the leader is mid-stream (some but not necessarily
        # all placed), then kill it WITHOUT releasing the lease: the
        # successor must wait out the TTL and take over by expiry
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if sum(log.commits.values()) >= 1:
                break
            time.sleep(0.005)
        a_el.stop(release=False)
        a_lead.clear()
        a_svc.stop()
        assert b_lead.wait(10.0), "successor must take the expired lease"
        deadline = time.monotonic() + 25.0
        while time.monotonic() < deadline:
            placed = sum(1 for rb in store.list("ResourceBinding")
                         if rb.spec.clusters)
            if placed == n:
                break
            time.sleep(0.02)
        done.set()
        b_svc.stop()
        b_el.stop(release=True)
        for t in threads:
            t.join(timeout=10.0)
        placed = [rb for rb in store.list("ResourceBinding")
                  if rb.spec.clusters]
        assert len(placed) == n, f"only {len(placed)}/{n} placed"
        assert not log.doubles(), log.doubles()
        assert shard_handoffs.value(reason="takeover") >= float(n)
        a_d.detach()
        b_d.detach()


class TestCrossShardGang:
    """All-or-nothing across shards: one rv-checked batch commit by the
    deterministic coordinator shard; any stale-rv veto aborts every row
    and the gang re-admits uncharged; the store NEVER holds a partial
    gang."""

    @staticmethod
    def spanning_gang(total, size=3, ns="default"):
        """A gang whose members hash to more than one shard."""
        for salt in range(200):
            gname = f"xg{salt}"
            rbs = [gang_binding(f"{gname}-m{i}", gname, size, ns=ns)
                   for i in range(size)]
            if len({shard_of_binding(rb, total) for rb in rbs}) > 1:
                return gname, rbs
        raise AssertionError("no spanning gang found")

    def test_commits_whole_cohort_atomically(self):
        store = fleet_store()
        stacks = make_stacks(store, 2)
        before = xshard_gang_commits.value(outcome="committed")
        gname, rbs = self.spanning_gang(2)
        for rb in rbs:
            store.create(rb)
        drain(stacks)
        fresh = [store.try_get("ResourceBinding", rb.metadata.name,
                               "default") for rb in rbs]
        assert all(rb.spec.clusters for rb in fresh)
        # one atomic batch: the members' placement rvs are contiguous
        rvs = sorted(rb.metadata.resource_version for rb in fresh)
        assert rvs[-1] - rvs[0] == len(rvs) - 1, rvs
        assert xshard_gang_commits.value(outcome="committed") == before + 1
        assert not store.list(KIND_SHARD_GANG_PROPOSAL, SHARD_NAMESPACE)
        teardown_stacks(stacks)

    def test_stale_rv_race_aborts_all_then_readmits(self):
        store = fleet_store()
        stacks = make_stacks(store, 2)
        gname, rbs = self.spanning_gang(2)
        for rb in rbs:
            store.create(rb)
        # members solve and PUBLISH, but hold the coordinator: seed the
        # race by moving one member's rv mid-assembly
        for _d, s in stacks:
            s.serve(quiescent=True)
        assert store.list(KIND_SHARD_GANG_PROPOSAL, SHARD_NAMESPACE)
        victim = store.try_get("ResourceBinding", rbs[0].metadata.name,
                               "default")
        victim.metadata.labels = dict(victim.metadata.labels or {},
                                      raced="yes")
        store.update(victim)
        before = xshard_gang_commits.value(outcome="aborted")
        coord = stacks[shard_of_gang("default", gname, 2)][0]
        assert coord.xshards.tick() == 1
        assert xshard_gang_commits.value(outcome="aborted") == before + 1
        # NEVER partial: the abort left no member placed
        for rb in rbs:
            cur = store.try_get("ResourceBinding", rb.metadata.name,
                                "default")
            assert not cur.spec.clusters, "partial gang reached the store"
        # uncharged re-admission converges: next drain re-solves against
        # the moved rv and commits the whole cohort
        drain(stacks)
        fresh = [store.try_get("ResourceBinding", rb.metadata.name,
                               "default") for rb in rbs]
        assert all(rb.spec.clusters for rb in fresh)
        rvs = sorted(rb.metadata.resource_version for rb in fresh)
        assert rvs[-1] - rvs[0] == len(rvs) - 1
        teardown_stacks(stacks)

    def test_incomplete_cohort_times_out(self):
        clock = Clock(fixed=100.0)
        store = fleet_store(clock=clock)
        stacks = make_stacks(store, 2, clock=clock,
                             gang_wait_seconds=5.0)
        gname, rbs = self.spanning_gang(2, size=3)
        # only 2 of 3 members ever arrive
        for rb in rbs[:2]:
            store.create(rb)
        drain(stacks)
        assert store.list(KIND_SHARD_GANG_PROPOSAL, SHARD_NAMESPACE)
        before = xshard_gang_commits.value(outcome="timeout")
        clock.advance(6.0)
        drain(stacks)
        assert xshard_gang_commits.value(outcome="timeout") == before + 1
        for rb in rbs[:2]:
            cur = store.try_get("ResourceBinding", rb.metadata.name,
                                "default")
            assert not cur.spec.clusters
            conds = {c.type: c for c in cur.status.conditions}
            sched = conds.get(CONDITION_SCHEDULED)
            assert sched is not None and sched.status == "False"
            assert sched.reason == REASON_GANG_TIMEOUT
        teardown_stacks(stacks)


class TestStatusSurface:
    def test_publish_and_retire_gauge_rows(self):
        store = fleet_store()
        d = ShardedDaemon(store, Runtime(), 0, 2, aot_prewarm=False)
        svc = d.streaming(batch_delay=0.0)
        for i in range(6):
            store.create(make_binding(f"st-{i}", 2, dyn_placement(),
                                      cpu=0.1))
        svc.serve(quiescent=True)
        d.publish_status(leader="me", token=7, force=True)
        objs = store.list("SchedulerShard", SHARD_NAMESPACE)
        assert len(objs) == 1
        st = objs[0].status
        assert st.leader == "me" and st.fencing_token == 7
        assert st.shards_total == 2
        assert st.bindings == d.owned_count()
        assert shard_bindings.value(shard="0") == float(d.owned_count())
        # retirement removes the series AND the object: no stale rows
        d.retire_status()
        from karmada_tpu.metrics import _label_key
        assert _label_key({"shard": "0"}) not in shard_bindings._values
        assert _label_key({"shard": "0"}) not in shard_queue_depth._values
        assert not store.list("SchedulerShard", SHARD_NAMESPACE)
        d.detach()

    def test_karmadactl_get_shards_table(self):
        from types import SimpleNamespace

        from karmada_tpu.cli.karmadactl import cmd_get

        store = fleet_store()
        for i in (1, 0):
            d = ShardedDaemon(store, Runtime(), i, 2, aot_prewarm=False)
            d.publish_status(leader=f"sched-{i}", token=3 + i, force=True)
            d.detach()
        cp = SimpleNamespace(store=store, members={})
        out = cmd_get(cp, "shards")
        lines = out.splitlines()
        assert lines[0].split() == ["SHARD", "LEADER", "EPOCH", "QUEUE",
                                    "BINDINGS", "LAST-SOLVE"]
        # sorted by slot regardless of publish order
        assert lines[1].startswith("0/2") and "sched-0" in lines[1]
        assert lines[2].startswith("1/2") and "sched-1" in lines[2]
        wide = cmd_get(cp, "schedulershards", output="wide")
        assert "TOKEN" in wide.splitlines()[0]
        assert "HANDOFF" in wide.splitlines()[0]
        for alias in ("shard", "schedulershard"):
            assert cmd_get(cp, alias).splitlines()[0] == lines[0]

    def test_elections_role_names_shard_leases(self):
        from karmada_tpu.api.coordination import (
            LeaderLease,
            LeaderLeaseSpec,
        )
        from karmada_tpu.api.meta import ObjectMeta
        from karmada_tpu.cli.karmadactl import _elections_table

        now = time.time()
        leases = [
            LeaderLease(
                metadata=ObjectMeta(name=shard_lease_name(1),
                                    namespace="karmada-system"),
                spec=LeaderLeaseSpec(holder_identity="sched-b",
                                     fencing_token=4, renew_time=now,
                                     lease_duration_seconds=10),
            ),
            LeaderLease(
                metadata=ObjectMeta(name="karmada-scheduler",
                                    namespace="karmada-system"),
                spec=LeaderLeaseSpec(holder_identity="sched-a",
                                     fencing_token=2, renew_time=now,
                                     lease_duration_seconds=10),
            ),
        ]
        out = _elections_table(leases, repl={"role": "single"})
        by_name = {l.split()[0]: l for l in out.splitlines()[1:]}
        assert by_name[shard_lease_name(1)].split()[-1] == "shard-1"
        assert by_name["karmada-scheduler"].split()[-1] == "single"


class TestFairnessBudget:
    def test_caps_concurrent_legs_per_cluster(self):
        budget = ClusterFairnessBudget(limit=2)
        acquired, errs = [], []
        start = threading.Barrier(4)

        def leg():
            try:
                start.wait(timeout=5.0)
                with budget.leg("m1"):
                    acquired.append(time.monotonic())
                    time.sleep(0.15)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=leg) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10.0)
        assert not errs
        assert len(acquired) == 4
        # with limit=2, the 4 legs ran as (at least) two waves
        acquired.sort()
        assert acquired[2] - acquired[0] > 0.1
        assert budget.waits >= 1
        # other clusters draw from their own pool
        with budget.leg("m2"):
            pass
        budget.forget("m1")


# ---------------------------------------------------------------------------
# slow path: the bench acceptance line, end to end
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestShardsSmokeScript:
    def test_shards_smoke(self):
        """scripts/shards_smoke.sh: the `shards` bench config — burst
        throughput >= 1.7x at 2 shards and >= 3x at 4 vs one shard with
        the paced p99 within 1.25x, cross-shard gangs committing as one
        rv-checked batch each (O(1)-in-K rounds, seeded stale-rv abort
        leaving nothing placed) — asserted from the emitted JSON line."""
        import os
        import subprocess

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            ["bash", "scripts/shards_smoke.sh"],
            capture_output=True, text=True, timeout=900, cwd=repo,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "SHARDS OK" in r.stdout

"""Standalone daemon entry points (the reference's cmd/ binaries):
`python -m karmada_tpu.agent` and `python -m karmada_tpu.estimator` as
real OS processes, driven over their wire surfaces."""
from __future__ import annotations

import sys
import time

import pytest

from karmada_tpu.api.meta import CPU
from karmada_tpu.api.work import ReplicaRequirements
from karmada_tpu.server.remote import RemoteControlPlane
from karmada_tpu.testing.daemon import reaping, spawn_daemon, spawn_process
from karmada_tpu.testing.fixtures import (
    duplicated_placement,
    new_deployment,
    new_policy,
    selector_for,
)


def wait_until(pred, timeout=30.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


class TestAgentDaemon:
    def test_two_process_topology(self):
        """Control-plane daemon + agent daemon as separate OS processes:
        the agent registers, receives the Work over its watch stream,
        applies it to its member, and reflects status back — observable
        centrally through work.status (agent.go:248-433)."""
        cp_proc, url = spawn_daemon("--members", "0", "--tick-interval", "0.5")
        with reaping(cp_proc) as reap:
            agent_proc, _ = spawn_process(
                [sys.executable, "-m", "karmada_tpu.agent",
                 "--server", url, "--cluster", "edge-d",
                 "--region", "edge", "--interval", "0.2"],
                r"registered", label="agent",
            )
            reap(agent_proc)

            rcp = RemoteControlPlane(url)
            assert wait_until(
                lambda: rcp.store.try_get("Cluster", "edge-d") is not None
            )
            dep = new_deployment("default", "edge-app", replicas=2, cpu=0.1)
            rcp.store.create(dep)
            rcp.store.create(new_policy(
                "default", "edge-pp", [selector_for(dep)],
                duplicated_placement(["edge-d"]),
            ))

            def applied():
                works = rcp.store.list("Work", "karmada-es-edge-d")
                return any(w.status.manifest_statuses for w in works)

            assert wait_until(applied, timeout=45.0), \
                "agent never reflected status into the Work"


class TestSecuredAgentDaemon:
    def test_two_process_topology_tls(self, tmp_path):
        """The same topology with the transport secured end to end:
        HTTPS + bearer token on both the CLI-shaped flags the agent
        daemon exposes."""
        tls_dir = str(tmp_path / "tls")
        cp_proc, url = spawn_daemon(
            "--members", "0", "--tick-interval", "0.5",
            "--tls-dir", tls_dir, "--token-file", str(tmp_path / "token"),
            scheme="https",
        )
        with reaping(cp_proc) as reap:
            token = (tmp_path / "token").read_text().strip()
            agent_proc, _ = spawn_process(
                [sys.executable, "-m", "karmada_tpu.agent",
                 "--server", url, "--cluster", "edge-s",
                 "--interval", "0.2", "--bearer-token", token,
                 "--cacert", f"{tls_dir}/ca.pem"],
                r"registered", label="agent-tls",
            )
            reap(agent_proc)
            rcp = RemoteControlPlane(url, token=token,
                                     cafile=f"{tls_dir}/ca.pem")
            assert wait_until(
                lambda: rcp.store.try_get("Cluster", "edge-s") is not None
            )
            assert rcp.store.get("Cluster", "edge-s").spec.sync_mode == "Pull"


class TestDeschedulerDaemon:
    def test_once_sweep_over_the_wire(self):
        """cmd/descheduler shape: a standalone process lists bindings over
        the control-plane API and fans out to the estimator daemon over
        gRPC. With every member healthy the sweep updates nothing — the
        assertion is the full wiring crossing both process boundaries."""
        pytest.importorskip("grpc")
        import subprocess

        from tests.test_scheduler_core import dyn_placement

        cp_proc, url = spawn_daemon("--members", "2", "--tick-interval", "0.5")
        with reaping(cp_proc) as reap:
            est_proc, m = spawn_process(
                [sys.executable, "-m", "karmada_tpu.estimator",
                 "--cluster", "member1", "--cluster", "member2",
                 "--nodes", "5", "--port", "0"],
                r"serving on :(\d+)", label="estimator",
            )
            reap(est_proc)
            est_port = int(m.group(1))

            rcp = RemoteControlPlane(url)
            dep = new_deployment("default", "web", replicas=4, cpu=0.5)
            rcp.store.create(dep)
            rcp.store.create(new_policy(
                "default", "pp", [selector_for(dep)], dyn_placement()
            ))
            rcp.settle()
            assert wait_until(lambda: any(
                rb.spec.clusters
                for rb in rcp.store.list("ResourceBinding", "default")
            ))

            r = subprocess.run(
                [sys.executable, "-m", "karmada_tpu.descheduler",
                 "--server", url, "--once",
                 "--estimator", f"member1=127.0.0.1:{est_port}",
                 "--estimator", f"member2=127.0.0.1:{est_port}"],
                capture_output=True, text=True, timeout=120,
            )
            assert r.returncode == 0, r.stdout + r.stderr
            assert "descheduled 0 binding(s)" in r.stdout, r.stdout


class TestSchedulerDaemon:
    def test_scheduler_attaches_to_schedulerless_plane(self):
        """The north-star deployment: a scheduler-less serving daemon
        (--controllers '*,-scheduler') plus `python -m karmada_tpu.sched`
        as its own process. Bindings stay unscheduled until the remote
        scheduler attaches, then placements and Works appear."""
        cp_proc, url = spawn_daemon(
            "--members", "2", "--tick-interval", "0.5",
            "--controllers", "*,-scheduler",
        )
        with reaping(cp_proc) as reap:
            rcp = RemoteControlPlane(url)
            dep = new_deployment("default", "web", replicas=4, cpu=0.5)
            rcp.store.create(dep)
            rcp.store.create(new_policy(
                "default", "pp", [selector_for(dep)],
                duplicated_placement([]),
            ))
            rcp.settle()

            def rb():
                rbs = rcp.store.list("ResourceBinding", "default")
                return rbs[0] if rbs else None

            assert wait_until(lambda: rb() is not None)
            assert not rb().spec.clusters, "scheduled without a scheduler?"

            sched_proc, _ = spawn_process(
                [sys.executable, "-m", "karmada_tpu.sched",
                 "--server", url, "--platform", "cpu"],
                r"attached", label="scheduler",
            )
            reap(sched_proc)
            assert wait_until(
                lambda: rb() is not None and len(rb().spec.clusters) == 2,
                timeout=60.0,
            ), "remote scheduler never placed the binding"
            assert wait_until(lambda: len(
                rcp.store.list("Work", "karmada-es-member1")
            ) > 0), "placement never materialized as Works"


class TestEstimatorDaemon:
    def test_grpc_daemon_answers_stock_contract(self):
        pytest.importorskip("grpc")
        from karmada_tpu.estimator.service import GrpcSchedulerEstimator

        proc, m = spawn_process(
            [sys.executable, "-m", "karmada_tpu.estimator",
             "--cluster", "m1", "--nodes", "20", "--port", "0"],
            r"serving on :(\d+)", label="estimator",
        )
        try:
            port = int(m.group(1))
            client = GrpcSchedulerEstimator(
                lambda c: f"127.0.0.1:{port}" if c == "m1" else None
            )
            req = ReplicaRequirements(resource_request={CPU: 2.0})
            got = client.max_available_replicas(["m1"], req, 10_000)
            # 20 synthetic nodes x 16 cpu / 2 cpu-per-replica = 160
            assert got[0] == 160, got
        finally:
            proc.terminate()
            proc.wait(timeout=15)

"""Replicated control-plane store (store/replication.py, docs/HA.md):
fenced log shipping, quorum writes, follower reads, seal-and-promote.

The acceptance property this suite pins is rv-EXACTNESS: a follower at any
acked rv holds the leader's byte-identical state — same store bytes, same
watch-cache event stream, same paginated snapshot pages — because every
log entry replays the leader's commits with their original rvs and event
types through the same under-lock sink the leader's own watch cache rides.
"""
from __future__ import annotations

import json
import threading
import time

import pytest

from karmada_tpu import faults
from karmada_tpu.api.unstructured import Unstructured
from karmada_tpu.faults.plan import FaultPlan, FaultRule
from karmada_tpu.server import codec
from karmada_tpu.server.apiserver import ControlPlaneServer
from karmada_tpu.server.remote import LeaderRedirect, RemoteControlPlane, RemoteStore
from karmada_tpu.store.replication import (
    REPLICATION_LEASE,
    QuorumTimeoutError,
    ReplicaClient,
    ReplicaControlPlane,
    ReplicationError,
    ReplicationManager,
    StaleAppendError,
    seal_and_promote,
)
from karmada_tpu.store.store import ReplicationGapError, Store

KIND = "v1/ConfigMap"


def cm(i, t=""):
    return Unstructured({
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": f"obj-{i:04d}", "namespace": "repl"},
        "data": {"t": t},
    })


def state_dump(store) -> list[str]:
    return sorted(
        json.dumps(codec.encode(o), sort_keys=True)
        for kind in store.kinds() for o in store.list(kind)
    )


def follower_server():
    cp = ReplicaControlPlane()
    srv = ControlPlaneServer(cp)
    srv.start()
    return srv


def wait_until(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class _Group:
    """leader server (+ manager) and N follower servers, all in-process."""

    def __init__(self, n_followers=2, mode="quorum", quorum=None, **kw):
        self.followers = [follower_server() for _ in range(n_followers)]
        self.leader_cp = ReplicaControlPlane()
        # the replication lease fences the append stream; acquiring it
        # BEFORE attach means the lease object itself replicates (token
        # monotonicity survives failover)
        lease, ok = self.leader_cp.coordinator.acquire(
            REPLICATION_LEASE, "leader-0", kw.pop("lease_duration", 10.0))
        assert ok
        self.manager = ReplicationManager(
            self.leader_cp.store, [f.url for f in self.followers],
            mode=mode,
            quorum=(n_followers if quorum is None else quorum),
            token=lease.spec.fencing_token, identity="leader-0", **kw,
        )
        self.leader = ControlPlaneServer(self.leader_cp,
                                         replication=self.manager)
        self.leader.start()
        self.manager.advertise_url = self.leader.url
        # deterministic base: every follower finished its bootstrap sync
        # (the initial snapshot at the attach floor) before the test
        # writes, so everything after base_rv replays as pure log entries
        assert wait_until(lambda: all(
            p.acked_rv >= self.store.current_rv
            for p in self.manager.peers))
        self.base_rv = self.store.current_rv

    @property
    def store(self):
        return self.leader_cp.store

    def close(self):
        self.leader.stop()
        for f in self.followers:
            f.stop()


@pytest.fixture
def group():
    g = _Group()
    yield g
    g.close()


class TestRvExactness:
    def test_follower_state_and_event_stream_byte_identical(self, group):
        store = group.store
        for i in range(30):
            store.create(cm(i, "v1"))
        store.create_batch([cm(100 + i) for i in range(8)])
        store.update_batch([cm(i, "v2") for i in range(0, 30, 3)])
        store.delete(KIND, "obj-0001", "repl")
        store.apply(cm(7, "v3"))

        tip = store.current_rv
        leader_cache = group.leader._watch_cache
        l_events, _, ok = leader_cache.events_since(group.base_rv, limit=0)
        assert ok and l_events

        for f in group.followers:
            fstore = f.cp.store
            # quorum=all: every write above returned only after both
            # followers applied+fsync'd it — no wait needed here
            assert fstore.current_rv == tip
            assert state_dump(fstore) == state_dump(store)
            f_events, _, ok = f._watch_cache.events_since(group.base_rv,
                                                          limit=0)
            assert ok
            # the watch-cache ring: same rvs, same event types, same wire
            # bytes at every acked rv
            assert [e.line() for e in f_events] == \
                [e.line() for e in l_events]

    def test_paginated_snapshots_identical(self, group):
        store = group.store
        for i in range(25):
            store.create(cm(i))
        l_rv, l_items, l_tok = group.leader._watch_cache.list_page(
            KIND, "repl", 10)
        for f in group.followers:
            rv, items, tok = f._watch_cache.list_page(KIND, "repl", 10)
            assert rv == l_rv
            assert items == l_items
        # crawl a full follower list over the wire and diff it against the
        # leader's — revision-consistent page pinning on the replica
        remote = RemoteStore(group.followers[0].url, page_size=7)
        got = sorted(json.dumps(codec.encode(o), sort_keys=True)
                     for o in remote.list(KIND, "repl"))
        want = sorted(json.dumps(codec.encode(o), sort_keys=True)
                      for o in store.list(KIND, "repl"))
        assert got == want

    def test_late_follower_catches_up_via_snapshot(self, group):
        store = group.store
        for i in range(12):
            store.create(cm(i))
        late = follower_server()
        try:
            group.manager.peers.append(
                type(group.manager.peers[0])(
                    late.url, ReplicaClient(late.url)))
            p = group.manager.peers[-1]
            t = threading.Thread(target=group.manager._peer_loop, args=(p,),
                                 daemon=True)
            p.thread = t
            t.start()
            assert wait_until(
                lambda: late.cp.store.current_rv == store.current_rv)
            assert state_dump(late.cp.store) == state_dump(store)
            assert p.snapshots >= 1  # joined past the floor: snapshot first
            # and the stream continues with ordinary entries
            store.create(cm(500))
            assert wait_until(
                lambda: late.cp.store.current_rv == store.current_rv)
        finally:
            late.stop()


class TestQuorumWrites:
    def test_write_returns_after_quorum_fsync(self, group):
        out = group.store.create(cm(0))
        rv = out.metadata.resource_version
        # no wait: the create() above could not have returned otherwise
        for f in group.followers:
            assert f.cp.store.current_rv >= rv

    def test_quorum_timeout_fails_loudly(self):
        g = _Group(n_followers=1, mode="quorum", quorum=1, ack_timeout=0.5)
        try:
            g.store.create(cm(0))  # healthy
            g.followers[0].stop()
            with pytest.raises((QuorumTimeoutError, ReplicationError)):
                g.store.create(cm(1))
        finally:
            g.leader.stop()

    def test_async_mode_does_not_block_on_dead_follower(self):
        g = _Group(n_followers=1, mode="async")
        try:
            g.followers[0].stop()
            t0 = time.perf_counter()
            g.store.create(cm(0))
            assert time.perf_counter() - t0 < 5.0  # bounded-lag gate only
        finally:
            g.leader.stop()


class TestFencing:
    def test_stale_append_409s_like_a_stale_write(self, group):
        fol = group.followers[0]
        client = ReplicaClient(fol.url)
        stale_token = group.manager.token - 1
        with pytest.raises(StaleAppendError):
            client.append({
                "token": stale_token, "leader": "ghost",
                "leader_url": "http://ghost",
                "entries": [{"start_rv": 1, "end_rv": 1, "records": [
                    {"kind": KIND, "event": "ADDED", "rv": 1,
                     "obj": codec.encode(cm(0))},
                ]}],
            })

    def test_gap_409_carries_expected_rv(self, group):
        group.store.create(cm(0))
        fol = group.followers[0]
        expect = fol.cp.store.current_rv + 1
        client = ReplicaClient(fol.url)
        with pytest.raises(ReplicationGapError) as ei:
            client.append({
                "token": group.manager.token + 1, "leader": "x",
                "leader_url": "",
                "entries": [{"start_rv": expect + 5, "end_rv": expect + 5,
                             "records": [
                                 {"kind": KIND, "event": "ADDED",
                                  "rv": expect + 5,
                                  "obj": codec.encode(cm(9))}]}],
            })
        assert ei.value.expected_rv == expect

    def test_follower_writes_redirect_to_leader(self, group):
        group.store.create(cm(0))
        # dialing a follower with a write: the 409 carries leader_url and
        # RemoteStore re-points automatically
        remote = RemoteStore(group.followers[0].url)
        out = remote.create(cm(77))
        assert out.metadata.resource_version == group.store.current_rv
        assert remote.base_url == group.leader.url
        # batch writes take the same redirect
        remote2 = RemoteStore(group.followers[1].url)
        outs = remote2.create_batch([cm(88), cm(89)])
        assert len(outs) == 2
        assert remote2.base_url == group.leader.url


class TestFollowerReads:
    def test_min_rv_read_barrier_blocks_then_serves(self, group):
        store = group.store
        store.create(cm(0))
        target_rv = store.current_rv + 3
        remote = RemoteStore(group.followers[0].url)
        results = {}

        def reader():
            t0 = time.perf_counter()
            objs = remote.list(KIND, "repl", min_rv=target_rv)
            results["elapsed"] = time.perf_counter() - t0
            results["n"] = len(objs)

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        time.sleep(0.4)  # the barrier must be HOLDING the read open
        assert "n" not in results
        for i in range(1, 4):
            store.create(cm(i))
        t.join(timeout=10)
        assert results["n"] == 4
        assert results["elapsed"] >= 0.3

    def test_read_preference_follower_round_robins(self, group):
        from karmada_tpu.metrics import reads_served

        for i in range(4):
            group.store.create(cm(i))
        before = reads_served.value(role="follower")
        remote = RemoteStore(group.leader.url,
                             replicas=[f.url for f in group.followers],
                             read_preference="follower")
        for i in range(4):
            assert remote.get(KIND, f"obj-{i:04d}", "repl") is not None
        assert reads_served.value(role="follower") >= before + 4

    def test_watch_from_replica_delivers_leader_writes(self, group):
        got = []
        evt = threading.Event()

        def handler(event, obj):
            got.append((event, obj.metadata.name))
            if len(got) >= 3:
                evt.set()

        remote = RemoteStore(group.leader.url,
                             replicas=[group.followers[0].url],
                             read_preference="follower")
        try:
            remote.watch(KIND, handler, replay=False)
            time.sleep(0.3)  # stream attached to the follower
            for i in range(3):
                group.store.create(cm(i))
            assert evt.wait(10.0)
            assert {n for _, n in got} == {f"obj-{i:04d}" for i in range(3)}
        finally:
            remote.close()

    def test_replica_read_falls_back_to_leader_when_replica_dies(self, group):
        group.store.create(cm(0))
        remote = RemoteStore(group.leader.url,
                             replicas=[group.followers[0].url],
                             read_preference="follower")
        group.followers[0].stop()
        assert remote.get(KIND, "obj-0000", "repl") is not None


class TestFailover:
    def test_sigkilled_leader_promotion_loses_zero_quorum_acked_writes(self):
        # follower A is in the quorum path; follower B is added as a peer
        # only AFTER failover (the lagging-peer catch-up leg)
        a = follower_server()
        b = follower_server()
        leader_cp = ReplicaControlPlane()
        lease, ok = leader_cp.coordinator.acquire(
            REPLICATION_LEASE, "leader-0", 0.3)
        assert ok
        mgr = ReplicationManager(
            leader_cp.store, [a.url], mode="quorum", quorum=1,
            token=lease.spec.fencing_token, identity="leader-0",
        )
        leader = ControlPlaneServer(leader_cp, replication=mgr)
        leader.start()
        mgr.advertise_url = leader.url
        try:
            acked = []
            for i in range(20):
                out = leader_cp.store.create(cm(i))
                acked.append(out.metadata.resource_version)
            # "SIGKILL": the leader vanishes without sealing or releasing
            # anything — no clean shutdown path runs
            leader.stop()
            time.sleep(0.4)  # the 0.3s lease TTL lapses

            # promotion targets the max-rv follower (here: A, the only
            # acked peer — and follower state is a contiguous log prefix,
            # so max-rv contains every quorum-acked entry)
            new_mgr = seal_and_promote(
                a, [b.url], identity="follower-a", mode="quorum", quorum=1)
            try:
                # the replicated lease counter continued: strictly higher
                # fencing token than the dead leader's
                assert new_mgr.token > mgr.token
                # zero quorum-acked writes lost
                store_a = a.cp.store
                assert store_a.current_rv >= max(acked)
                for i in range(20):
                    assert store_a.try_get(KIND, f"obj-{i:04d}", "repl") \
                        is not None
                # the new leader serves writes; B catches up from the same
                # append stream (snapshot + rv offset)
                out = store_a.create(cm(900, "post-failover"))
                assert wait_until(
                    lambda: b.cp.store.current_rv
                    >= out.metadata.resource_version)
                assert state_dump(b.cp.store) == state_dump(store_a)
            finally:
                new_mgr.close()
        finally:
            for s in (a, b):
                s.stop()

    def test_deposed_leaders_stale_appends_are_fenced(self):
        a = follower_server()
        leader_cp = ReplicaControlPlane()
        lease, _ = leader_cp.coordinator.acquire(
            REPLICATION_LEASE, "leader-0", 0.2)
        mgr = ReplicationManager(
            leader_cp.store, [a.url], mode="async", quorum=1,
            token=lease.spec.fencing_token, identity="leader-0",
        )
        mgr.attach()
        try:
            leader_cp.store.create(cm(0))
            assert wait_until(
                lambda: a.cp.store.current_rv
                == leader_cp.store.current_rv)
            time.sleep(0.3)  # TTL lapses; the old leader does NOT notice
            new_mgr = seal_and_promote(
                a, [], identity="follower-a", mode="async")
            try:
                # the paused ex-leader resumes and ships another entry:
                # the sealed, re-fenced follower must 409 it and the old
                # manager must depose itself
                leader_cp.store.create(cm(1, "stale"))
                assert wait_until(lambda: mgr.deposed, timeout=5.0)
                assert a.cp.store.try_get(KIND, "obj-0001", "repl") is None
            finally:
                new_mgr.close()
        finally:
            mgr.close()
            a.stop()


class TestChaosShipping:
    def test_seeded_faults_on_the_replication_boundary_heal(self):
        """A seeded FaultPlan partitions the leader->follower HTTP site
        for a window of ship attempts: shipping retries with backoff and
        the follower converges to the leader's exact bytes after heal."""
        a = follower_server()
        from urllib.parse import urlparse

        target = urlparse(a.url).netloc
        faults.install(FaultPlan(seed=7, rules=[
            FaultRule(boundary="http", target=target, kind="partition",
                      after=1, heal_after=5),
        ]))
        leader_cp = ReplicaControlPlane()
        lease, _ = leader_cp.coordinator.acquire(
            REPLICATION_LEASE, "leader-0")
        mgr = ReplicationManager(
            leader_cp.store, [a.url], mode="async", quorum=1,
            token=lease.spec.fencing_token, identity="leader-0",
        )
        mgr.attach()
        try:
            for i in range(10):
                leader_cp.store.create(cm(i))
            assert wait_until(
                lambda: a.cp.store.current_rv == leader_cp.store.current_rv,
                timeout=20.0)
            assert state_dump(a.cp.store) == state_dump(leader_cp.store)
        finally:
            mgr.close()
            a.stop()
            faults.reset()


class TestStatusSurfaces:
    def test_replication_status_route_and_cli(self, group):
        group.store.create(cm(0))
        assert wait_until(lambda: all(
            f.cp.store.current_rv == group.store.current_rv
            for f in group.followers))
        rcp = RemoteControlPlane(group.leader.url)
        st = rcp.replication_status()
        assert st["role"] == "leader"
        assert st["mode"] == "quorum"
        assert len(st["peers"]) == 2
        assert all(p["lag_rvs"] == 0 for p in st["peers"])
        fst = RemoteControlPlane(group.followers[0].url).replication_status()
        assert fst["role"] == "follower"
        assert fst["applied_rv"] == group.store.current_rv

        from karmada_tpu.cli.karmadactl import run

        out = run(rcp, ["replication", "status"])
        assert "role: leader" in out
        assert "FOLLOWER" in out and "LAG" in out
        out = run(RemoteControlPlane(group.followers[0].url),
                  ["replication", "status"])
        assert "role: follower" in out

    def test_elections_printer_grows_role_column(self, group):
        from karmada_tpu.cli.karmadactl import run

        rcp = RemoteControlPlane(group.leader.url)
        out = run(rcp, ["elections"])
        assert "ROLE" in out
        assert "leader@rv" in out
        out = run(rcp, ["get", "leaderleases"])
        assert "ROLE" in out and REPLICATION_LEASE in out


class TestReviewHardening:
    def test_watcher_bus_still_notified_when_quorum_times_out(self):
        """A quorum-timeout write surfaces its error to the mutator, but
        the object IS committed (and locally durable) — kind/all
        watchers must still receive the event, or every level-triggered
        subscriber silently desyncs from served state."""
        g = _Group(n_followers=1, mode="quorum", quorum=1, ack_timeout=0.4)
        try:
            got = []
            g.store.watch(KIND, lambda ev, o: got.append(o.metadata.name),
                          replay=False)
            g.followers[0].stop()
            with pytest.raises((QuorumTimeoutError, ReplicationError)):
                g.store.create(cm(1))
            assert "obj-0001" in got
            assert g.store.try_get(KIND, "obj-0001", "repl") is not None
        finally:
            g.leader.stop()

    def test_revive_after_depose_resumes_shipping(self):
        """A leader that lost its lease without a successor (GC pause)
        re-elects and must SHIP again — depose() let the peer threads
        exit, so revive() restarts them and drains the backlog."""
        a = follower_server()
        leader_cp = ReplicaControlPlane()
        lease, _ = leader_cp.coordinator.acquire(
            REPLICATION_LEASE, "leader-0")
        mgr = ReplicationManager(
            leader_cp.store, [a.url], mode="async",
            token=lease.spec.fencing_token, identity="leader-0",
        )
        mgr.attach()
        try:
            leader_cp.store.create(cm(0))
            assert wait_until(
                lambda: a.cp.store.current_rv == leader_cp.store.current_rv)
            mgr.depose("renewal missed")
            with pytest.raises(ReplicationError):
                leader_cp.store.create(cm(1))  # deposed: writes fail loudly
            mgr.revive(lease.spec.fencing_token + 1)
            leader_cp.store.create(cm(2))
            assert wait_until(
                lambda: a.cp.store.current_rv == leader_cp.store.current_rv)
            assert state_dump(a.cp.store) == state_dump(leader_cp.store)
        finally:
            mgr.close()
            a.stop()

    def test_follower_mode_rejects_writes_before_first_append(self):
        """--follower boots write-rejecting: a client write accepted in
        the window before the leader's first append would mint a local
        rv and fork the replicated log. With no leader to redirect to
        yet the rejection is a 503, NOT a bare 409 — a 409 would read as
        an object conflict to `except ConflictError: pass` callers."""
        from karmada_tpu.server.remote import RemoteError

        cp = ReplicaControlPlane()
        srv = ControlPlaneServer(cp, follower=True)
        srv.start()
        try:
            remote = RemoteStore(srv.url)
            with pytest.raises(RemoteError, match="503"):
                remote.create(cm(0))
            assert cp.store.current_rv == 0
            # reads still serve
            assert remote.list(KIND, "repl") == []
            st = RemoteControlPlane(srv.url).replication_status()
            assert st["role"] in ("follower", "candidate")
        finally:
            srv.stop()

    def test_lease_writes_redirect_off_followers(self, group):
        """An election CAS is a store write: a follower must not mint a
        local rv for it (the rv fork the lease exemption comment used to
        allow). The elector's RemoteStore lease calls follow the
        redirect to the leader instead."""
        remote = RemoteStore(group.followers[0].url)
        lease, acquired = remote.acquire_lease("test-elect", "me", 5.0)
        assert acquired
        # the write landed on the LEADER and replicated back — follower
        # rv continuity intact, no local fork
        assert group.store.try_get(
            "LeaderLease", "test-elect", "karmada-system") is not None
        assert wait_until(lambda: all(
            f.cp.store.current_rv == group.store.current_rv
            for f in group.followers))
        assert state_dump(group.followers[0].cp.store) == \
            state_dump(group.store)

    def test_leader_restart_probes_instead_of_snapshotting(self):
        """An in-sync follower re-contacted by a restarted leader must
        cost a PROBE (empty append), not a full state snapshot + WAL
        rewrite per follower per restart."""
        a = follower_server()
        leader_cp = ReplicaControlPlane()
        mgr = ReplicationManager(
            leader_cp.store, [a.url], mode="async", token=1,
            identity="leader-0",
        )
        mgr.attach()
        try:
            leader_cp.store.create(cm(0))
            assert wait_until(
                lambda: a.cp.store.current_rv == leader_cp.store.current_rv)
        finally:
            mgr.close()
        mgr2 = ReplicationManager(
            leader_cp.store, [a.url], mode="async", token=2,
            identity="leader-0b",
        )
        mgr2.attach()
        try:
            leader_cp.store.create(cm(1))
            assert wait_until(
                lambda: a.cp.store.current_rv == leader_cp.store.current_rv)
            assert mgr2.peers[0].snapshots == 0
            assert state_dump(a.cp.store) == state_dump(leader_cp.store)
        finally:
            mgr2.close()
            a.stop()

    def test_forked_follower_is_quarantined_not_silently_acked(self):
        """A follower whose store ran AHEAD of the leader's log (it
        minted local rvs) must be quarantined with a loud error — the
        old rewind path marked it caught-up with lag 0 while the two
        stores disagreed at the same rv."""
        a = follower_server()
        leader_cp = ReplicaControlPlane()
        mgr = ReplicationManager(
            leader_cp.store, [a.url], mode="async", token=5,
            identity="leader-0",
        )
        # fork: the "follower" writes locally before any shipping
        for i in range(10):
            a.cp.store.create(cm(i, "forked"))
        # make it look like a follower that accepted a leader before
        fol = a._ensure_follower()
        fol.max_token = 4
        mgr.attach()
        try:
            leader_cp.store.create(cm(99))
            assert wait_until(
                lambda: mgr.peers[0].diverged, timeout=10.0)
            st = mgr.status()
            assert st["peers"][0]["diverged"]
            assert "diverged" in st["peers"][0]["last_error"]
        finally:
            mgr.close()
            a.stop()


class TestReviewHardeningSecondPass:
    def test_lost_promotion_rolls_the_seal_back(self):
        """Two operators promoting concurrently: the loser's
        seal_and_promote raises AND unseals — it must go back to
        accepting the winner's appends and rejecting client writes, not
        sit sealed (write-accepting, append-409ing)."""
        a = follower_server()
        leader_cp = ReplicaControlPlane()
        lease, _ = leader_cp.coordinator.acquire(
            REPLICATION_LEASE, "leader-0")  # long TTL: election un-winnable
        mgr = ReplicationManager(
            leader_cp.store, [a.url], mode="async",
            token=lease.spec.fencing_token, identity="leader-0",
        )
        mgr.attach()
        try:
            leader_cp.store.create(cm(0))
            assert wait_until(
                lambda: a.cp.store.current_rv == leader_cp.store.current_rv)
            with pytest.raises(ReplicationError):
                seal_and_promote(a, [], identity="loser")  # lease held
            assert not a._follower.sealed
            # the real leader's stream keeps applying
            leader_cp.store.create(cm(1))
            assert wait_until(
                lambda: a.cp.store.current_rv == leader_cp.store.current_rv)
            # and client writes still bounce to the leader
            with pytest.raises(Exception):
                RemoteStore(a.url).create(cm(2))
        finally:
            mgr.close()
            a.stop()

    def test_simulate_is_blocked_on_followers(self, group):
        from karmada_tpu.server.apiserver import ControlPlaneServer

        assert "/simulate" in ControlPlaneServer._FOLLOWER_BLOCKED
        # over the wire: a follower answers 409 before touching cp.simulate
        from karmada_tpu.store.store import ConflictError

        rs = RemoteStore(group.followers[0].url)
        with pytest.raises(ConflictError):
            rs._call("POST", "/simulate", {"request": None})

    def test_revive_races_no_lost_shipper(self):
        """Depose/revive churn must never strand a peer without a
        shipping loop (the loops PARK while deposed instead of exiting)."""
        a = follower_server()
        leader_cp = ReplicaControlPlane()
        mgr = ReplicationManager(
            leader_cp.store, [a.url], mode="async", token=1,
            identity="leader-0",
        )
        mgr.attach()
        try:
            for i in range(5):
                mgr.depose("churn")
                mgr.revive(2 + i)
            leader_cp.store.create(cm(0))
            assert wait_until(
                lambda: a.cp.store.current_rv == leader_cp.store.current_rv)
            assert mgr.peers[0].thread.is_alive()
        finally:
            mgr.close()
            a.stop()


class TestReviewHardeningThirdPass:
    def test_concurrent_promotions_resolve_to_one_leader(self):
        """Two operators promote A and B concurrently: both local
        acquires mint EQUAL tokens (independent replicated lease
        copies), so the claim's identity tiebreak must resolve to
        exactly one leader — the loser closes its manager, re-syncs from
        a snapshot (its local lease rv forked the log), and follows."""
        a = follower_server()
        b = follower_server()
        leader_cp = ReplicaControlPlane()
        lease, _ = leader_cp.coordinator.acquire(
            REPLICATION_LEASE, "leader-0", 0.3)
        mgr = ReplicationManager(
            leader_cp.store, [a.url, b.url], mode="quorum", quorum=2,
            token=lease.spec.fencing_token, identity="leader-0",
        )
        mgr.attach()
        try:
            for i in range(10):
                leader_cp.store.create(cm(i))
            mgr.close()  # leader dies
            time.sleep(0.4)  # TTL lapses
            mgr_a = seal_and_promote(a, [b.url], identity="promo-a",
                                     mode="async")
            mgr_b = seal_and_promote(b, [a.url], identity="promo-b",
                                     mode="async")
            assert mgr_a.token == mgr_b.token  # the equal-token tie
            try:
                # "promo-b" outranks "promo-a" at equal tokens: A yields
                assert wait_until(lambda: mgr_a.deposed, timeout=10.0)
                assert a._repl is None  # closed, not just deposed
                # B's stream re-syncs A (snapshot past the forked lease
                # rv) and keeps shipping
                out = b.cp.store.create(cm(77, "winner"))
                assert wait_until(
                    lambda: a.cp.store.current_rv
                    >= out.metadata.resource_version, timeout=10.0)
                assert state_dump(a.cp.store) == state_dump(b.cp.store)
                assert a._replication_role() == "follower"
                assert b._replication_role() == "leader"
            finally:
                mgr_b.close()
                mgr_a.close()
        finally:
            mgr.close()
            a.stop()
            b.stop()

    def test_outranked_leader_server_applies_higher_claim_appends(self):
        """An ex-leader SERVER whose manager is still attached must not
        500 the new leader's appends (a deposed-but-subscribed manager
        raised out of every replicated apply): yielding closes the
        manager and the appends commit cleanly."""
        old_cp = ReplicaControlPlane()
        old_mgr = ReplicationManager(
            old_cp.store, [], mode="async", token=1, identity="old-leader")
        old_srv = ControlPlaneServer(old_cp, replication=old_mgr)
        old_srv.start()
        new_store_cp = ReplicaControlPlane()
        new_mgr = ReplicationManager(
            new_store_cp.store, [old_srv.url], mode="async", token=2,
            identity="new-leader",
        )
        new_mgr.attach()
        try:
            for i in range(5):
                new_store_cp.store.create(cm(i))
            assert wait_until(
                lambda: old_cp.store.current_rv
                == new_store_cp.store.current_rv, timeout=10.0)
            assert old_srv._repl is None
            assert old_srv._replication_role() == "follower"
            assert state_dump(old_cp.store) == state_dump(new_store_cp.store)
            # the new leader never saw a 500-retry storm: appends landed
            assert new_mgr.peers[0].appends >= 1
            assert not new_mgr.peers[0].last_error
        finally:
            new_mgr.close()
            old_srv.stop()

    def test_async_writes_do_not_stall_on_a_dead_follower(self):
        """A single unreachable follower must not tax every async write
        with the bounded-lag wait — the gate only waits on peers that
        are actually shippable."""
        leader_cp = ReplicaControlPlane()
        mgr = ReplicationManager(
            leader_cp.store, ["http://127.0.0.1:9"],  # nothing listens
            mode="async", token=1, identity="leader-0", max_async_lag=4,
        )
        mgr.attach()
        try:
            t0 = time.perf_counter()
            for i in range(50):
                leader_cp.store.create(cm(i))
            assert time.perf_counter() - t0 < 5.0  # no per-write 1s stall
        finally:
            mgr.close()


# -- the smoke wrapper (slow path) -----------------------------------------


@pytest.mark.slow
class TestReplicaSmokeScript:
    def test_replica_smoke(self):
        """scripts/replica_smoke.sh: the leader + 2-follower group at the
        10k-watcher point — read scaling, quorum-write retention,
        rv-exactness digests, and the seal-and-promote failover leg,
        asserted from the emitted JSON line."""
        import os
        import subprocess

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            ["bash", "scripts/replica_smoke.sh"],
            capture_output=True, text=True, timeout=900, cwd=repo,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "REPLICA OK" in r.stdout


class TestStorePrimitives:
    def test_apply_replicated_rejects_partial_entries(self):
        s = Store()
        s.create(cm(0))
        recs = []
        for rv, name in ((2, "a"), (4, "b")):  # rv 3 missing
            o = cm(1)
            o.metadata.name = name
            o.metadata.resource_version = rv
            recs.append((KIND, "ADDED", o))
        with pytest.raises(ReplicationGapError):
            s.apply_replicated(recs)
        # nothing applied: continuity validated before any commit
        assert s.current_rv == 1
        assert s.try_get(KIND, "a", "repl") is None

    def test_load_snapshot_moves_forward_only_and_deletes_vanished(self):
        s = Store()
        s.create(cm(0))
        s.create(cm(1))
        deleted = []
        s.watch(KIND, lambda ev, o: deleted.append((ev, o.metadata.name)),
                replay=False)
        snap_obj = cm(2, "snap")
        snap_obj.metadata.resource_version = 9
        snap_obj.metadata.uid = "u-snap"
        s.load_snapshot(10, [snap_obj])
        assert s.current_rv == 10
        assert s.try_get(KIND, "obj-0000", "repl") is None
        assert s.try_get(KIND, "obj-0002", "repl") is not None
        assert ("DELETED", "obj-0000") in deleted
        with pytest.raises(Exception):
            s.load_snapshot(5, [])  # backwards: refused

"""Compile economics: shape-bucketed padding parity + compile counting.

The fleet axis C pads to the shape_bucket lattice with dead pad clusters
and the batch axis B pads to the same lattice (sched/core.py), so fleet
growth and binding churn INSIDE a bucket re-use every compiled program.
This suite pins the two claims that make that sound:

1. **Bit-identical decisions**: bucket-padded solves equal exact-shape
   solves (`ArrayScheduler(bucket_cols=False)` is the exact-width
   reference) across mixed strategies, spread constraints, churn, the
   mesh/autoshard path, incremental replay, and degraded (stale-column)
   estimator rounds.
2. **Zero new compiles inside a bucket**: a second round at a different
   (B, C) inside the same buckets triggers no XLA compile, asserted via
   the `karmada_jit_cache_misses_total` counter the jax.monitoring hook
   feeds (sched/compilecache.py).

Plus the persistent-cache and AOT-prewarm plumbing: compiles served from
disk count as `karmada_jit_persistent_cache_hits_total`, and an AOT pass
(sched/aot.py) populates the cache so a cleared process re-uses it.
"""
from __future__ import annotations

import numpy as np
import pytest

import jax

from karmada_tpu.api.policy import SpreadConstraint
from karmada_tpu.models.batch import shape_bucket, shape_floor
from karmada_tpu.parallel import make_mesh
from karmada_tpu.sched import compilecache
from karmada_tpu.sched.core import ArrayScheduler
from karmada_tpu.sched.pipeline import chunk_spans, plan_chunk_rows
from karmada_tpu.testing.fixtures import synthetic_fleet
from tests.test_incremental import assert_same_decisions, mixed_bindings
from tests.test_parallel import dyn_placement, make_binding


# ---------------------------------------------------------------------------
# lattice unit tests
# ---------------------------------------------------------------------------


def test_shape_bucket_lattice():
    assert shape_bucket(1) == 8
    assert shape_bucket(8) == 8
    assert shape_bucket(9) == 12
    assert shape_bucket(13) == 16
    assert shape_bucket(100) == 128
    assert shape_bucket(1500) == 1536
    assert shape_bucket(3000) == 3072
    assert shape_bucket(4096) == 4096
    # past 4096: 1024-steps (pad waste stays ~2.5% where O(B·C) hurts)
    assert shape_bucket(5000) == 5120
    assert shape_bucket(10000) == 10240
    assert shape_bucket(20000) == 20480
    assert shape_bucket(40000) == 40960
    for n in range(1, 6000, 7):
        b = shape_bucket(n)
        assert b >= n
        assert shape_bucket(b) == b  # lattice points are fixpoints
        assert b <= 2 * n or n < 8  # bounded pad waste


def test_shape_floor():
    assert shape_floor(8) == 8
    assert shape_floor(100) == 96
    assert shape_floor(2048) == 2048
    assert shape_floor(12288) == 12288
    assert shape_floor(13421) == 13312
    for cap in range(8, 6000, 11):
        f = shape_floor(cap)
        assert f <= cap
        assert shape_bucket(f) == f  # floors land on the lattice


def test_plan_chunk_rows_equalizes():
    # the 40k×20k flagship schedule: greedy was 12288×3 + 3136 (two
    # compiled shapes); equalized is 10240×4 — one shape, fewer pad rows
    rows = plan_chunk_rows(40000, 12288)
    assert rows == 10240
    spans = chunk_spans(40000, rows)
    assert len(spans) == 4
    assert {shape_bucket(e - s) for s, e in spans} == {10240}
    # under-cap rounds stay one chunk
    assert plan_chunk_rows(100, 12288) == 12288
    # never exceeds the cap
    assert plan_chunk_rows(10**6, 6144) <= 6144


# ---------------------------------------------------------------------------
# padding parity: bucket-padded == exact-shape, bit for bit
# ---------------------------------------------------------------------------


def spread_placement(min_groups=2):
    p = dyn_placement()
    p.spread_constraints = [
        SpreadConstraint(spread_by_field="region", min_groups=min_groups)
    ]
    return p


def parity_bindings(names):
    bindings = mixed_bindings(names)
    bindings += [
        make_binding(f"spread-{i}", 6 + i, spread_placement(2 + i % 2),
                     cpu=0.25)
        for i in range(4)
    ]
    return bindings


@pytest.fixture()
def fleet():
    clusters = synthetic_fleet(19, seed=5)  # pads to width 24
    return clusters, [c.name for c in clusters]


def test_fleet_pads_to_lattice(fleet):
    clusters, _ = fleet
    padded = ArrayScheduler(clusters)
    exact = ArrayScheduler(clusters, bucket_cols=False)
    assert padded.n_real_clusters == 19
    assert len(padded.fleet.names) == 24
    assert len(exact.fleet.names) == 19
    # pad clusters are dead: never Ready, never feasible
    assert not padded.fleet.alive[19:].any()


def test_parity_single_chip(fleet):
    clusters, names = fleet
    bindings = parity_bindings(names)
    padded = ArrayScheduler(clusters)
    exact = ArrayScheduler(clusters, bucket_cols=False)
    got = padded.schedule(bindings)
    want = exact.schedule(bindings)
    assert_same_decisions(got, want)
    # feasible sets never leak pad cluster names
    for d in got:
        assert all(not n.startswith("__shape-pad") for n in d.feasible)


def test_parity_across_churn(fleet):
    """Cluster status churn (the dirty-column path) and membership growth
    WITHIN the bucket: the padded scheduler keeps its program shapes, the
    exact one re-encodes — decisions stay bit-identical throughout."""
    import copy

    clusters, names = fleet
    bindings = parity_bindings(names)
    padded = ArrayScheduler(clusters)
    exact = ArrayScheduler(clusters, bucket_cols=False)
    assert_same_decisions(padded.schedule(bindings), exact.schedule(bindings))

    # status churn on two clusters (dirty-column fast path)
    churned = [copy.deepcopy(c) for c in clusters]
    for c in churned[:2]:
        rs = c.status.resource_summary
        if rs is not None:
            rs.allocated["cpu"] = rs.allocated.get("cpu", 0.0) + 8.0
    padded.set_clusters(churned, dirty_names={churned[0].name, churned[1].name})
    exact.set_clusters(churned, dirty_names={churned[0].name, churned[1].name})
    assert_same_decisions(padded.schedule(bindings), exact.schedule(bindings))

    # membership growth inside the bucket (19 -> 21, width stays 24)
    grown = churned + synthetic_fleet(23, seed=11)[19:21]
    padded.set_clusters(grown)
    exact.set_clusters(grown)
    assert len(padded.fleet.names) == 24
    assert_same_decisions(padded.schedule(bindings), exact.schedule(bindings))


def test_parity_mesh(fleet):
    clusters, names = fleet
    bindings = parity_bindings(names)
    mesh = make_mesh(jax.devices())
    padded = ArrayScheduler(clusters, mesh=mesh)
    exact = ArrayScheduler(clusters, mesh=mesh, bucket_cols=False)
    # bucketed width is also mesh-divisible
    from karmada_tpu.parallel.mesh import AXIS_CLUSTERS

    assert len(padded.fleet.names) % mesh.shape[AXIS_CLUSTERS] == 0
    assert_same_decisions(padded.schedule(bindings), exact.schedule(bindings))


def test_parity_autoshard(fleet):
    """Oversized rounds re-place the fleet on a mesh (autoshard): the
    bucketed width must survive the re-placement with identical decisions."""
    clusters, names = fleet
    bindings = parity_bindings(names)
    padded = ArrayScheduler(clusters)
    exact = ArrayScheduler(clusters, bucket_cols=False)
    padded.max_bc_elems = 16  # force the oversized classification
    exact.max_bc_elems = 16
    got = padded.schedule(bindings)
    want = exact.schedule(bindings)
    assert padded.mesh is not None  # engaged (conftest provides 8 devices)
    assert_same_decisions(got, want)


def test_parity_incremental_replay(fleet):
    """Replay must engage identically on the padded scheduler (estimator
    digests hash the caller's [B, C_real] matrix before padding) and the
    replayed decisions must equal an exact-shape cold solve."""
    clusters, names = fleet
    bindings = parity_bindings(names)
    B = len(bindings)
    extra = np.full((B, 19), 40, np.int32)
    padded = ArrayScheduler(clusters)
    exact = ArrayScheduler(clusters, bucket_cols=False)
    padded.schedule_incremental(bindings, extra_avail=extra)
    got = padded.schedule_incremental(bindings, extra_avail=extra)
    assert padded.last_round_stats["replayed"] == B
    assert padded.last_round_stats["jit_compiles"] == 0
    want = exact.schedule(bindings, extra_avail=extra)
    assert_same_decisions(got, want)


def test_parity_degraded_columns(fleet):
    """Degraded rounds serve breaker-open members' columns as age-penalized
    stale answers inside extra_avail (faults/staleness.py) — pure array
    over the same channel, so parity must hold with a mix of live, stale
    (penalized), and discarded (-1) columns."""
    clusters, names = fleet
    bindings = parity_bindings(names)
    B = len(bindings)
    rng = np.random.default_rng(3)
    extra = rng.integers(0, 50, size=(B, 19)).astype(np.int32)
    extra[:, 4] = np.maximum(extra[:, 4] >> 3, 0)  # stale: age-penalized
    extra[:, 7] = -1  # discarded column
    padded = ArrayScheduler(clusters)
    exact = ArrayScheduler(clusters, bucket_cols=False)
    assert_same_decisions(
        padded.schedule(bindings, extra_avail=extra),
        exact.schedule(bindings, extra_avail=extra),
    )


def test_parity_chunked_pipeline(fleet):
    """The pipelined chunked executor over a bucket-padded fleet: chunk
    planning + padding must compose with bit-identical decisions."""
    clusters, names = fleet
    bindings = parity_bindings(names) * 3  # 54 rows
    padded = ArrayScheduler(clusters, autoshard=False)
    exact = ArrayScheduler(clusters, bucket_cols=False, autoshard=False)
    padded.max_bc_elems = 16 * len(padded.fleet.names)  # force chunking
    exact.max_bc_elems = 16 * len(exact.fleet.names)
    got = padded.schedule(bindings)
    want = exact.schedule(bindings)
    assert padded.last_pipeline_stats["chunks"] > 1
    assert_same_decisions(got, want)


# ---------------------------------------------------------------------------
# compile counting: zero new compiles inside a bucket
# ---------------------------------------------------------------------------


def test_same_bucket_shape_change_zero_compiles():
    clusters = synthetic_fleet(13, seed=2)
    sched = ArrayScheduler(clusters)
    bindings = [
        make_binding(f"a{i}", 3, dyn_placement(), cpu=0.5) for i in range(13)
    ]
    sched.schedule(bindings)  # warm round: compiles the bucket's programs

    # fleet grows 13 -> 15 (width bucket 16 unchanged) AND the round grows
    # B 13 -> 15 (row bucket 16 unchanged): zero new XLA compiles
    grown = clusters + synthetic_fleet(16, seed=9)[13:15]
    sched.set_clusters(grown)
    bindings2 = bindings + [
        make_binding(f"b{i}", 3, dyn_placement(), cpu=0.5) for i in range(2)
    ]
    snap = compilecache.compile_counts()
    decisions = sched.schedule(bindings2)
    delta = compilecache.compile_delta(snap)
    assert delta["jit_compiles"] == 0, delta
    assert sched.last_compile_stats["jit_compiles"] == 0
    assert sum(d.ok for d in decisions) == len(bindings2)
    # and the zero-compile round still solved against the GROWN fleet
    # (bit-identical to an exact-width cold solve over it)
    exact = ArrayScheduler(grown, bucket_cols=False)
    assert_same_decisions(decisions, exact.schedule(bindings2))


def test_round_stats_carry_compile_keys():
    clusters = synthetic_fleet(9, seed=4)
    sched = ArrayScheduler(clusters)
    bindings = [
        make_binding(f"c{i}", 2, dyn_placement(), cpu=0.25) for i in range(4)
    ]
    sched.schedule_incremental(bindings)
    stats = sched.last_round_stats
    for key in ("jit_compiles", "jit_compile_seconds",
                "jit_persistent_cache_hits"):
        assert key in stats
    # a first-ever shape must have compiled something and metered it
    assert compilecache.compile_counts()["jit_compiles"] > 0


def test_compile_metrics_on_metrics_endpoint():
    from karmada_tpu.metrics import registry

    text = registry.render()
    assert "karmada_jit_compile_seconds" in text
    assert "karmada_jit_cache_misses_total" in text


# ---------------------------------------------------------------------------
# persistent cache + AOT prewarm
# ---------------------------------------------------------------------------


def test_resolve_cache_dir_precedence():
    env: dict = {}
    # flag > env > data-dir default > disabled
    assert compilecache.resolve_cache_dir("/x", "/d", env) == "/x"
    assert compilecache.resolve_cache_dir(
        "", "/d", {"KARMADA_TPU_COMPILE_CACHE": "/e"}
    ) == "/e"
    assert compilecache.resolve_cache_dir("", "/d", env).endswith(
        "compile-cache"
    )
    assert compilecache.resolve_cache_dir("", "", env) == ""
    # explicit off beats the data-dir default
    assert compilecache.resolve_cache_dir("off", "/d", env) == ""
    assert compilecache.resolve_cache_dir(
        "", "/d", {"KARMADA_TPU_COMPILE_CACHE": "off"}
    ) == ""


@pytest.fixture()
def cache_dir(tmp_path):
    path = str(tmp_path / "compile-cache")
    compilecache.enable_persistent_cache(path)
    try:
        yield path
    finally:
        compilecache.disable_persistent_cache()


def test_persistent_cache_serves_cleared_process(cache_dir):
    """In-process stand-in for a process restart: compile, drop every
    in-memory executable cache (jax.clear_caches), re-dispatch — the
    programs must come back from disk (persistent hits), not XLA."""
    clusters = synthetic_fleet(11, seed=7)
    sched = ArrayScheduler(clusters)
    bindings = [
        make_binding(f"p{i}", 3, dyn_placement(), cpu=0.5) for i in range(6)
    ]
    # earlier tests may have compiled these shapes already (in-memory);
    # drop them so this round compiles and WRITES the fresh cache dir
    jax.clear_caches()
    want = sched.schedule(bindings)
    assert compilecache.cache_entries(cache_dir) > 0
    jax.clear_caches()
    snap = compilecache.compile_counts()
    got = sched.schedule(bindings)
    delta = compilecache.compile_delta(snap)
    assert delta["jit_persistent_cache_hits"] > 0, delta
    assert_same_decisions(got, want)


def test_aot_prewarm_populates_cache_for_real_round(cache_dir):
    """The standby's AOT pass must compile the shapes the real round will
    dispatch: prewarm with the live binding snapshot, clear the in-memory
    caches (the takeover process analogue), then run the round — its
    filter-kernel program must be a disk hit."""
    from karmada_tpu.sched.aot import prewarm_schedule

    clusters = synthetic_fleet(11, seed=8)
    sched = ArrayScheduler(clusters)
    bindings = [
        make_binding(f"q{i}", 3, dyn_placement(), cpu=0.5) for i in range(9)
    ]
    stats = prewarm_schedule(sched, bindings)
    assert stats["row_buckets"], stats
    assert stats["jit_compiles"] > 0
    jax.clear_caches()
    snap = compilecache.compile_counts()
    decisions = sched.schedule(bindings)
    delta = compilecache.compile_delta(snap)
    assert delta["jit_persistent_cache_hits"] > 0, delta
    assert sum(d.ok for d in decisions) == len(bindings)


def test_daemon_prewarm_runs_aot(cache_dir):
    """SchedulerDaemon.prewarm(wait_aot=True) runs the lattice pass for the
    current fleet epoch exactly once, records stats, and abandon_prewarm
    re-arms it for the next standby period."""
    from karmada_tpu.runtime.controller import Runtime
    from karmada_tpu.sched.scheduler import SchedulerDaemon
    from karmada_tpu.store.store import Store

    store = Store()
    for c in synthetic_fleet(7, seed=6):
        store.create(c)
    for i in range(5):
        store.create(make_binding(f"d{i}", 2, dyn_placement(), cpu=0.25))
    daemon = SchedulerDaemon(store, Runtime(), aot_prewarm=True)
    daemon.prewarm(wait_aot=True)
    assert daemon.last_prewarm_stats.get("row_buckets"), (
        daemon.last_prewarm_stats
    )
    epoch = daemon.last_prewarm_stats["epoch"]
    # idempotent per epoch: a second call must not start a new pass
    daemon.prewarm(wait_aot=True)
    assert daemon.last_prewarm_stats["epoch"] == epoch
    daemon.abandon_prewarm()
    assert daemon._aot_epoch == -1  # re-armed
    # back on standby at the SAME fleet epoch: the pass must re-run (the
    # dry-solve epoch gate must not swallow it) — persistent-cache hits
    # make the re-walk cheap
    daemon.prewarm(wait_aot=True)
    assert daemon._aot_epoch == epoch

"""Fleet-scale chaos soak (docs/ROBUSTNESS.md "Fleet soak").

Composes the FULL daemon topology — replicated server group (leader +
quorum followers), sharded streaming scheduler plane over HTTP, pull
agents + estimators per member, elasticity daemon, descheduler, and the
detector/binding/status controllers — then replays a seeded multi-tenant
traffic program while a `FaultPlan` injects chaos on all three process
boundaries plus whole-process faults (leader kill with seal-and-promote,
shard kill with map-resize handoff, follower partition past the log ring,
estimator blackouts). A continuous invariant checker holds the composed
system to the contracts no unit test composes: zero lost quorum-acked
writes, exactly-once admission per (uid, epoch), no partial gang at any
sampled rv, bounded-window convergence after every wave, bounded
threads/queues across waves, and a healthy event-loop wire plane (no
stuck sockets, per-socket queues within their byte bound).
"""
from .harness import SoakHarness, SoakProfile, run_soak, verdict_schema_ok
from .invariants import (
    AdmissionLedger,
    GangIntegrity,
    ResourceBounds,
    WireHealth,
    WriteLedger,
)
from .topology import SoakTopology

__all__ = [
    "AdmissionLedger",
    "GangIntegrity",
    "ResourceBounds",
    "SoakHarness",
    "SoakProfile",
    "SoakTopology",
    "WireHealth",
    "WriteLedger",
    "run_soak",
    "verdict_schema_ok",
]

"""The soak's composed topology: every daemon the reference pipeline
deploys, wired the way production wires them.

Three tiers, real HTTP between them:

  server group   leader `ReplicaControlPlane` + `ControlPlaneServer`
                 shipping a quorum append stream to N follower servers
                 (store/replication.py, docs/HA.md)
  plane stack    the controllers that live in the leader process:
                 detector, binding controller, pull agents, work/binding
                 status controllers, elasticity daemon, descheduler, and
                 the trace collector — driven by a settle thread against
                 the CURRENT leader's in-process store (rebuilt wholesale
                 on promotion, exactly like a standby operator taking over)
  scheduler      a `ShardPlane` of N elected shard leaders over a
                 `RemoteStore` pointed at the server group — the daemon
                 deployment shape (sched/__main__.py), so scheduler
                 traffic crosses the http boundary and failovers exercise
                 the leader-redirect convergence path

Process faults operate on this object: `kill_leader()` seal-and-promotes
the max-applied follower and spawns a fresh (snapshot-bootstrapped)
replacement, `kill_shard()`/`restore_shards()` drive map-resize handoff,
`partition_follower()` flips the apiserver's chaos valve, and
`set_estimator_blackout()` darkens every member estimator leg at once.
"""
from __future__ import annotations

import logging
import threading
import time

from ..agent.agent import KarmadaAgent
from ..api.meta import CPU, MEMORY
from ..controllers.binding import BindingController
from ..controllers.status import BindingStatusController, WorkStatusController
from ..detector.detector import ResourceDetector
from ..elastic.aggregator import build_metrics_report, publish_report
from ..elastic.daemon import ElasticityDaemon
from ..descheduler.descheduler import Descheduler
from ..estimator.client import EstimatorRegistry
from ..faults.policy import BreakerRegistry
from ..interpreter.interpreter import ResourceInterpreter
from ..members.member import InMemoryMember, MemberConfig, cluster_object_for
from ..runtime.controller import Clock, Runtime
from ..sched.shards.daemon import ShardPlane
from ..server.apiserver import ControlPlaneServer
from ..server.remote import RemoteStore
from ..store.replication import (
    REPLICATION_LEASE,
    ReplicaControlPlane,
    ReplicationManager,
    seal_and_promote,
)
from ..tracing import TraceCollector

log = logging.getLogger(__name__)

GiB = 1024.0**3

# small ring on purpose: a follower partitioned for one traffic slice lags
# past it and must catch up via the snapshot path, not the append stream
SOAK_LOG_ENTRIES = 8


def _state_dump(store) -> list[str]:
    from ..server import codec
    import json

    return sorted(
        json.dumps(codec.encode(o), sort_keys=True)
        for kind in store.kinds() for o in store.list(kind)
    )


def wait_until(pred, timeout: float = 30.0, interval: float = 0.02) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return bool(pred())


class SoakEstimator:
    """One estimator leg per member cluster, with a blackout valve.

    Answers a flat per-cluster availability (capacity generosity keeps the
    soak's convergence contract about CORRECTNESS, not scarcity), runs the
    gRPC-boundary chaos hook per leg, and feeds the shared breaker exactly
    like the wire client — so an installed FaultPlan or a blackout opens
    breakers and pushes the registry into degraded (staleness) mode."""

    SENTINEL = -1

    def __init__(self, blackout: threading.Event, breakers: BreakerRegistry,
                 capacity: int = 50):
        self.blackout = blackout
        self.breakers = breakers
        self.capacity = capacity

    def max_available_replicas(self, clusters, requirements, replicas):
        from .. import faults

        out = []
        for c in clusters:
            br = self.breakers.for_member(c)
            if not br.allow():
                out.append(self.SENTINEL)
                continue
            try:
                faults.check(faults.BOUNDARY_GRPC, c)
                if self.blackout.is_set():
                    raise RuntimeError("estimator blackout")
            except Exception:  # noqa: BLE001 - every leg failure is a trip
                br.record_failure()
                out.append(self.SENTINEL)
                continue
            br.record_success()
            out.append(self.capacity)
        return out


class _PlaneStack:
    """The leader-process controller set over one in-process store, driven
    to fixpoint by a settle thread. Discarded and rebuilt on promotion —
    controller state is all derivable from the (replicated) store."""

    def __init__(self, store, members: dict[str, InMemoryMember],
                 clock: Clock, registry: EstimatorRegistry):
        self.store = store
        self.members = members
        self.clock = clock
        self.collector = TraceCollector(store)
        self.collector.attach()
        self.rt = Runtime(clock=clock)
        self.interp = ResourceInterpreter()
        self.interp.load_thirdparty()
        ResourceDetector(store, self.interp, self.rt)
        BindingController(store, self.interp, self.rt)
        self.agents = [
            KarmadaAgent(store, m, self.interp, self.rt)
            for m in members.values()
        ]
        self.ws = WorkStatusController(store, members, self.interp, self.rt)
        for m in members.values():
            self.ws.watch_member(m)
        BindingStatusController(store, self.interp, self.rt)
        self.elastic = ElasticityDaemon(
            store, clock, interpreter=self.interp,
            hysteresis=False, preflight=False,
        )
        self.desched = Descheduler(store, registry, clock=clock,
                                   interval=0.5)
        self.errors: list[str] = []
        self._stop = threading.Event()
        self._last_collect = 0.0
        self._thread = threading.Thread(
            target=self._run, name="soak-plane-settle", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.rt.settle()
                now = self.clock.now()
                if now - self._last_collect >= 0.2:
                    self._last_collect = now
                    for m in self.members.values():
                        publish_report(self.store,
                                       build_metrics_report(m, now))
                    self.elastic.step(now)
                    self.desched.tick()
            except Exception as e:  # noqa: BLE001 - soak counts, not dies
                log.exception("plane settle error")
                self.errors.append(f"{type(e).__name__}: {e}")
            self._stop.wait(0.05)

    def quiesce(self, timeout: float = 20.0) -> bool:
        """Wait for the runtime queues to drain (fixpoint between waves)."""
        return wait_until(
            lambda: all(len(c.queue) == 0 for c in self.rt.controllers),
            timeout,
        )

    def queue_depth(self) -> int:
        return sum(len(c.queue) for c in self.rt.controllers)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10.0)


class SoakTopology:
    def __init__(self, *, n_members: int = 4, n_followers: int = 2,
                 n_shards: int = 2, lease_duration: float = 2.0,
                 estimator_capacity: int = 50):
        self.clock = Clock()
        self.lease_duration = lease_duration
        self.estimator_blackout = threading.Event()
        self.estimator_capacity = estimator_capacity
        self._promotions = 0
        self._clients: list[RemoteStore] = []

        self.members: dict[str, InMemoryMember] = {}
        for i in range(n_members):
            cfg = MemberConfig(
                name=f"member-{i}", sync_mode="Pull",
                allocatable={CPU: 64.0, MEMORY: 256 * GiB, "pods": 2000.0},
            )
            self.members[cfg.name] = InMemoryMember(cfg)

        # -- server group -------------------------------------------------
        self.followers: list[ControlPlaneServer] = [
            self._new_follower() for _ in range(n_followers)
        ]
        self.leader_cp = ReplicaControlPlane()
        lease, ok = self.leader_cp.coordinator.acquire(
            REPLICATION_LEASE, "soak-leader-0", lease_duration)
        assert ok, "fresh plane must win its own replication lease"
        self.manager = ReplicationManager(
            self.leader_cp.store, [f.url for f in self.followers],
            mode="quorum", quorum=1, token=lease.spec.fencing_token,
            identity="soak-leader-0", max_entries=SOAK_LOG_ENTRIES,
        )
        self.leader = ControlPlaneServer(self.leader_cp,
                                         replication=self.manager)
        self.leader.start()
        self.manager.advertise_url = self.leader.url
        assert wait_until(lambda: all(
            p.acked_rv >= self.leader_cp.store.current_rv
            for p in self.manager.peers))

        # cluster objects exist before any controller/scheduler attaches
        for m in self.members.values():
            self.leader_cp.store.create(cluster_object_for(m.config))

        # -- plane stack (controllers in the leader process) --------------
        self.plane = _PlaneStack(self.leader_cp.store, self.members,
                                 self.clock, self._registry())

        # -- scheduler plane over the wire --------------------------------
        self.sched_store = self.client(read_preference="follower")
        self.shards = ShardPlane(
            self.sched_store, n_shards,
            clock=self.clock,
            registry_factory=lambda i: self._registry(),
            gang_wait_seconds=30.0,
            aot_prewarm=False,
            elect=True,
            lease_duration=lease_duration,
            identity="soak-sched",
            batch_delay=0.05,
        )
        self.n_shards = n_shards
        self.shards.start()
        assert self.shards.wait_leading(30.0), "shards must elect"

    # -- construction helpers ---------------------------------------------

    def _new_follower(self) -> ControlPlaneServer:
        srv = ControlPlaneServer(ReplicaControlPlane())
        srv.start()
        return srv

    def _registry(self) -> EstimatorRegistry:
        """A per-consumer estimator registry: shared blackout valve, own
        breakers (a shard tripping its breakers must not blind the
        descheduler's registry, mirroring per-process breaker state)."""
        breakers = BreakerRegistry(failure_threshold=3, open_seconds=1.0)
        reg = EstimatorRegistry(breakers=breakers)
        reg.register_replica_estimator(
            "soak",
            SoakEstimator(self.estimator_blackout, breakers,
                          self.estimator_capacity),
        )
        return reg

    def client(self, read_preference: str = "leader") -> RemoteStore:
        """A new wire client of the server group, tracked so failovers can
        re-point it (the production analog: service discovery moving the
        leader VIP after a promotion)."""
        rs = RemoteStore(
            self.leader.url, timeout=10.0,
            replicas=[f.url for f in self.followers],
            read_preference=read_preference,
        )
        self._clients.append(rs)
        return rs

    @property
    def store(self):
        """The CURRENT leader's in-process store."""
        return self.leader_cp.store

    # -- process faults ----------------------------------------------------

    def kill_leader(self) -> str:
        """SIGKILL-style leader loss: no clean shutdown path runs. The
        max-applied follower is sealed and promoted (zero quorum-acked
        writes lost — follower state is a contiguous log prefix), a fresh
        EMPTY follower replaces it in the group (bootstrapping via the
        snapshot path), the plane stack is rebuilt on the promoted store,
        and every wire client is re-pointed at the new leader."""
        self._promotions += 1
        gen = self._promotions
        self._partition_record = None  # the old group's peers are history
        self.plane.stop()
        self.manager.close()
        self.leader.stop()

        chosen = max(self.followers, key=lambda f: f.cp.store.current_rv)
        survivors = [f for f in self.followers if f is not chosen]
        replacement = self._new_follower()
        peers = [f.url for f in survivors] + [replacement.url]
        new_mgr = seal_and_promote(
            chosen, peers, identity=f"soak-leader-{gen}",
            lease_duration=self.lease_duration,
            mode="quorum", quorum=1, max_entries=SOAK_LOG_ENTRIES,
        )
        self.leader = chosen
        self.leader_cp = chosen.cp
        self.manager = new_mgr
        self.followers = survivors + [replacement]
        self.repoint()
        self.plane = _PlaneStack(self.leader_cp.store, self.members,
                                 self.clock, self._registry())
        return self.leader.url

    def repoint(self) -> None:
        for rs in self._clients:
            rs._set_base(self.leader.url)
            rs._replicas[:] = [f.url for f in self.followers]
            rs._replica_cooldown.clear()

    def kill_shard(self) -> int:
        """Kill the highest shard slot: the plane shrinks by one and the
        survivors re-map the keyspace through the admission-epoch fence."""
        new_total = max(1, self.shards.total - 1)
        return self.shards.resize(new_total)

    def restore_shards(self) -> int:
        return self.shards.resize(self.n_shards)

    def partition_follower(self, idx: int = 0) -> ControlPlaneServer:
        srv = self.followers[idx % len(self.followers)]
        peer = next((p for p in self.manager.peers if p.url == srv.url),
                    None)
        self._partition_record = {
            "srv": srv, "peer": peer,
            "snapshots": peer.snapshots if peer else 0,
        }
        srv.partitioned = True
        return srv

    def verify_partition_catchup(self, timeout: float = 30.0) -> list[str]:
        """Post-heal witness that the partition wave was not vacuous: the
        healed follower must re-converge BYTE-IDENTICALLY to the leader,
        and — because the partition outlasted the (deliberately tiny) log
        ring — through the SNAPSHOT path, not the append stream."""
        rec = getattr(self, "_partition_record", None)
        if rec is None:
            return []
        self._partition_record = None
        srv, peer = rec["srv"], rec["peer"]
        errs: list[str] = []
        tip = self.store.current_rv
        if not wait_until(lambda: srv.cp.store.current_rv >= tip, timeout):
            errs.append(
                f"partitioned follower stuck at rv "
                f"{srv.cp.store.current_rv} < leader tip {tip}")
        if peer is not None and peer.snapshots <= rec["snapshots"]:
            errs.append(
                "partitioned follower caught up without the snapshot "
                "path — the partition never outran the log ring")
        if not wait_until(
            lambda: _state_dump(srv.cp.store) == _state_dump(self.store),
            timeout,
        ):
            errs.append("follower state diverges from leader after heal")
        return errs

    def heal_partitions(self) -> None:
        for f in self.followers:
            f.partitioned = False

    def set_estimator_blackout(self, on: bool) -> None:
        if on:
            self.estimator_blackout.set()
        else:
            self.estimator_blackout.clear()

    # -- teardown -----------------------------------------------------------

    def close(self) -> None:
        try:
            self.shards.close()
        except Exception:  # noqa: BLE001 - teardown best-effort
            log.exception("shard plane close")
        self.plane.stop()
        try:
            self.manager.close()
        except Exception:  # noqa: BLE001
            pass
        self.leader.stop()
        for f in self.followers:
            f.stop()

"""Seeded multi-tenant traffic program for the soak.

Each wave replays a deterministic slice of the tenant mix over the wire
client (so every template/policy write crosses the faulted http
boundary): binding surges, policy/replica churn, gang cohorts, preemptor
waves, diurnal HPA demand, and cluster flaps. All writes go through a
bounded retry (`_must`) because the point of the soak is what the PLANE
does under faults, not whether the driver gives up — and every write
that returns acked is recorded in the WriteLedger the lost-write
invariant checks against the post-failover leader.
"""
from __future__ import annotations

import random
from typing import Optional

from ..api.autoscaling import (
    FederatedHPA,
    FederatedHPASpec,
    HPABehavior,
    ResourceMetricSource,
    ScaleTargetRef,
)
from ..api.cluster import CLUSTER_CONDITION_READY
from ..api.meta import Condition, ObjectMeta, set_condition
from ..api.policy import (
    DIVISION_PREFERENCE_WEIGHTED,
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_SCHEDULING_DIVIDED,
    ClusterAffinity,
    ClusterPreferences,
    Placement,
    ReplicaSchedulingStrategy,
)
from ..api.work import GANG_NAME_LABEL, GANG_SIZE_LABEL
from ..server.remote import RemoteError
from ..store.store import ConflictError
from ..testing.fixtures import (
    duplicated_placement,
    new_deployment,
    new_policy,
    selector_for,
)
from .invariants import WriteLedger

NAMESPACE = "soak"


def dynamic_placement() -> Placement:
    return Placement(
        cluster_affinity=ClusterAffinity(cluster_names=[]),
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=DIVISION_PREFERENCE_WEIGHTED,
            weight_preference=ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
        ),
    )


class TrafficProgram:
    def __init__(self, client, topology, ledger: WriteLedger, *,
                 seed: int = 7, apps: int = 12):
        self.client = client
        self.topology = topology
        self.ledger = ledger
        self.rng = random.Random(seed)
        self.n_base_apps = apps
        self.apps: list[dict] = []       # {name, dyn, replicas, churn}
        self.gangs: list[tuple[str, int]] = []
        self._flapped: list[str] = []
        self.write_failures = 0

    # -- the write funnel ---------------------------------------------------

    def _must(self, op: str, obj, attempts: int = 8):
        """Write through the faulted boundary until it lands (bounded).
        A create whose earlier ambiguous attempt actually landed answers
        409 on the replay — resolved by reading the object back, which is
        the ack. Exhaustion raises: the driver failing to place load is a
        harness bug, not a chaos outcome."""
        last: Optional[Exception] = None
        for _ in range(attempts):
            try:
                if op == "create":
                    out = self.client.create(obj)
                elif op == "apply":
                    out = self.client.apply(obj)
                else:
                    raise ValueError(op)
                self.ledger.record_ack(out)
                return out
            except ConflictError:
                from ..store.store import gvk_of

                cur = self.client.try_get(
                    gvk_of(obj), obj.metadata.name,
                    obj.metadata.namespace or "")
                if cur is not None:
                    self.ledger.record_ack(cur)
                    return cur
                last = ConflictError(f"{op} conflicted and vanished")
            except RemoteError as e:
                self.write_failures += 1
                last = e
        raise RemoteError(f"traffic {op} exhausted retries: {last}")

    def _delete(self, kind: str, name: str, ns: str = NAMESPACE,
                attempts: int = 8) -> None:
        from ..store.store import NotFoundError

        last: Optional[Exception] = None
        for _ in range(attempts):
            try:
                self.client.delete(kind, name, ns)
                self.ledger.record_delete(kind, name, ns)
                return
            except NotFoundError:
                # an ambiguous earlier attempt landed — done
                self.ledger.record_delete(kind, name, ns)
                return
            except RemoteError as e:
                self.write_failures += 1
                try:
                    if self.client.try_get(kind, name, ns) is None:
                        self.ledger.record_delete(kind, name, ns)
                        return
                except RemoteError:
                    pass
                last = e
        raise RemoteError(f"traffic delete exhausted retries: {last}")

    # -- app lifecycle ------------------------------------------------------

    def _make_app(self, name: str, *, dyn: bool, replicas: int,
                  churn: bool = True, priority: Optional[int] = None,
                  preempting: bool = False) -> dict:
        dep = new_deployment(NAMESPACE, name, replicas=replicas, cpu=0.1)
        spec_kw = {}
        if priority is not None:
            spec_kw["scheduler_priority"] = priority
        if preempting:
            spec_kw["scheduler_preemption"] = "PreemptLowerPriority"
        pol = new_policy(
            NAMESPACE, f"{name}-policy", [selector_for(dep)],
            dynamic_placement() if dyn else duplicated_placement(
                list(self.topology.members)),
            **spec_kw,
        )
        self._must("create", dep)
        self._must("create", pol)
        app = {"name": name, "dyn": dyn, "replicas": replicas,
               "churn": churn}
        self.apps.append(app)
        return app

    def bootstrap(self) -> None:
        """The steady-state tenant mix, plus one HPA-governed app whose
        demand the diurnal phases steer (it is excluded from churn so the
        elasticity daemon is its only replica writer)."""
        for i in range(self.n_base_apps):
            self._make_app(f"app-{i:03d}", dyn=(i % 3 == 0),
                           replicas=1 + (i % 4))
        self.hpa_target = self._make_app("hpa-web", dyn=False, replicas=2,
                                         churn=False)
        self._must("create", FederatedHPA(
            metadata=ObjectMeta(name="hpa-web", namespace=NAMESPACE),
            spec=FederatedHPASpec(
                scale_target_ref=ScaleTargetRef(kind="Deployment",
                                                name="hpa-web"),
                min_replicas=1, max_replicas=8,
                metrics=[ResourceMetricSource(
                    name="cpu", target_average_utilization=50)],
                behavior=HPABehavior(
                    scale_up_stabilization_seconds=0.0,
                    scale_down_stabilization_seconds=0.0),
            ),
        ))

    # -- wave phases --------------------------------------------------------

    def surge(self, wave: int, n: int = 4) -> None:
        for i in range(n):
            self._make_app(f"wave{wave}-app-{i}", dyn=(i % 2 == 0),
                           replicas=1 + self.rng.randrange(3))

    def churn(self, n: int = 6) -> None:
        """Replica-scale churn on a random subset: apply rewrites the
        template, the detector bumps the binding generation, the shards
        re-solve — the bread-and-butter reconcile loop under chaos."""
        pool = [a for a in self.apps if a["churn"]]
        self.rng.shuffle(pool)
        for app in pool[:n]:
            app["replicas"] = 1 + self.rng.randrange(5)
            self._must("apply", new_deployment(
                NAMESPACE, app["name"], replicas=app["replicas"], cpu=0.1))

    def gang_cohort(self, wave: int, size: int = 3) -> str:
        """One gang of `size` templates (gang labels flow template ->
        binding through the detector); the scheduler must admit the
        cohort all-or-nothing in ONE cross-shard batch."""
        gname = f"gang-w{wave}"
        deps = [
            new_deployment(
                NAMESPACE, f"{gname}-m{j}", replicas=2, cpu=0.1,
                labels={GANG_NAME_LABEL: gname,
                        GANG_SIZE_LABEL: str(size)},
            )
            for j in range(size)
        ]
        pol = new_policy(
            NAMESPACE, f"{gname}-policy",
            [selector_for(d) for d in deps],
            duplicated_placement(list(self.topology.members)),
        )
        self._must("create", pol)
        for d in deps:
            self._must("create", d)
        self.gangs.append((gname, size))
        return gname

    def preemptor_wave(self, wave: int, n: int = 2) -> None:
        for i in range(n):
            self._make_app(f"wave{wave}-pre-{i}", dyn=True, replicas=2,
                           churn=False, priority=10 + wave,
                           preempting=True)

    def diurnal_demand(self, wave: int) -> None:
        """Even waves are daytime (high per-pod usage -> scale up), odd
        waves are night (idle -> scale down). Usage lands on the members
        directly; the plane's collect loop turns it into
        WorkloadMetricsReports the elasticity daemon consumes."""
        usage = 0.09 if wave % 2 == 0 else 0.01  # vs request 0.1, target 50%
        for m in self.topology.members.values():
            m.set_workload_usage("Deployment", NAMESPACE, "hpa-web",
                                 {"cpu": usage})

    def flap_cluster(self) -> str:
        """Mark one member cluster NotReady through the wire client; the
        heal phase restores it (the scheduler must steer around it in
        between, and convergence is only checked after the heal)."""
        name = self.rng.choice(sorted(self.topology.members))
        self._set_ready(name, False)
        self._flapped.append(name)
        return name

    def _set_ready(self, name: str, ready: bool, attempts: int = 8) -> None:
        last: Optional[Exception] = None
        for _ in range(attempts):
            try:
                cluster = self.client.get("Cluster", name)
                set_condition(cluster.status.conditions, Condition(
                    type=CLUSTER_CONDITION_READY,
                    status="True" if ready else "False",
                    reason="SoakFlap",
                ))
                out = self.client.update(cluster)
                self.ledger.record_ack(out)
                return
            except (RemoteError, ConflictError) as e:
                self.write_failures += 1
                last = e
        raise RemoteError(f"cluster flap exhausted retries: {last}")

    def heal(self) -> None:
        while self._flapped:
            self._set_ready(self._flapped.pop(), True)

    # -- accounting ---------------------------------------------------------

    def retire_wave_apps(self, wave: int) -> None:
        """Delete a slice of this wave's surge apps — delete/recreate
        churn is part of the program, and recorded deletes tell the
        lost-write invariant the absence is intentional."""
        gone = [a for a in self.apps
                if a["name"].startswith(f"wave{wave}-app-")][:2]
        for app in gone:
            self._delete("apps/v1/Deployment", app["name"])
            self._delete("PropagationPolicy", f"{app['name']}-policy")
            self.apps.remove(app)

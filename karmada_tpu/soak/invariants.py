"""Continuous system-level invariants the soak holds the composed
topology to. Each checker is a small standalone object so the violation
fixtures in tests/test_soak.py can plant a lost write / a partial gang /
a double admission against a bare store and prove the checker FIRES —
an invariant checker that cannot fail is not checking anything.

Catalog (docs/ROBUSTNESS.md "Fleet soak"):

  WriteLedger      zero lost quorum-acked writes: every write the traffic
                   driver saw acked at rv R is present (at >= R) on the
                   current leader, across any number of failovers
  AdmissionLedger  exactly-once admission per (uid, epoch): at most one
                   empty->placed commit per (binding uid, observed
                   scheduler generation) across shard handoffs/resizes
  GangIntegrity    no partial gang at any sampled rv: placements of one
                   gang land as ONE transactional batch, so at every
                   batch boundary each gang's live bindings are all
                   placed or all unplaced
  ResourceBounds   no leak across waves: thread count and controller
                   queue depths return below a fixed ceiling after every
                   wave's heal
  WireHealth       event-loop wire plane stays healthy under faults: no
                   stream socket reaped as stuck, no per-socket queue
                   over its byte bound at any wave boundary, and the
                   loop actually served streams (a soak that never
                   exercised the wire plane proves nothing about it)
"""
from __future__ import annotations

import threading
from typing import Any, Optional


class WriteLedger:
    """Traffic-side record of quorum-acked writes, checked against the
    (possibly promoted) leader store. Deletion is recorded too — a key
    the driver deleted is allowed to be gone; any other recorded key
    must exist at an rv >= its acked rv."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._acked: dict[tuple[str, str, str], int] = {}
        self._deleted: set[tuple[str, str, str]] = set()

    @staticmethod
    def _key(obj) -> tuple[str, str, str]:
        from ..store.store import gvk_of

        return (gvk_of(obj), obj.metadata.name,
                obj.metadata.namespace or "")

    def record_ack(self, obj) -> None:
        """Call with the object a (quorum-mode) write RETURNED — its
        resource_version is the acked rv."""
        key = self._key(obj)
        rv = int(obj.metadata.resource_version)
        with self._lock:
            self._deleted.discard(key)
            if rv >= self._acked.get(key, 0):
                self._acked[key] = rv

    def record_delete(self, kind: str, name: str, namespace: str = "") -> None:
        with self._lock:
            key = (kind, name, namespace or "")
            self._acked.pop(key, None)
            self._deleted.add(key)

    def check(self, store) -> list[str]:
        """Violations on `store` (the current leader): acked writes that
        vanished or rolled back. Keys the plane itself legitimately
        rewrites later (status flows, elasticity scaling) still satisfy
        rv >= acked — rvs are monotonic and rewrites only advance them."""
        with self._lock:
            acked = dict(self._acked)
        out = []
        for (kind, name, ns), rv in acked.items():
            cur = store.try_get(kind, name, ns)
            if cur is None:
                out.append(f"lost acked write: {kind} {ns}/{name} "
                           f"(acked rv {rv}) is gone")
            elif int(cur.metadata.resource_version) < rv:
                out.append(
                    f"rolled-back write: {kind} {ns}/{name} at rv "
                    f"{cur.metadata.resource_version} < acked rv {rv}")
        return out


class AdmissionLedger:
    """Watch-side exactly-once ledger, failover-aware.

    Counts empty->placed commits per (binding uid, scheduler observed
    generation) — the admission epoch the shard stamps at placement
    commit. A re-schedule after eviction/preemption bumps the template
    generation, so its commit lands under a NEW epoch; a second commit
    under the SAME epoch is exactly the double-solve the shard handoff
    fence must make impossible. Survives failovers: `attach()` to the
    promoted store replays current state, and the retained `_placed` map
    keeps replayed already-placed bindings from recounting."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._placed: dict[str, bool] = {}
        self._commits: dict[tuple[str, int], int] = {}
        self._store = None

    def attach(self, store) -> None:
        with self._lock:
            if self._store is not None:
                try:
                    self._store.unwatch("ResourceBinding", self._on_event)
                except Exception:  # noqa: BLE001 - old store may be dead
                    pass
            self._store = store
        store.watch("ResourceBinding", self._on_event, replay=True)

    def _on_event(self, event, rb) -> None:
        uid = rb.metadata.uid
        placed = bool(rb.spec.clusters)
        epoch = int(rb.status.scheduler_observed_generation or 0)
        with self._lock:
            if event == "DELETED":
                self._placed.pop(uid, None)
                return
            if placed and not self._placed.get(uid, False):
                k = (uid, epoch)
                self._commits[k] = self._commits.get(k, 0) + 1
            self._placed[uid] = placed

    def doubles(self) -> list[str]:
        with self._lock:
            return [
                f"double admission: uid {uid} epoch {epoch} committed "
                f"empty->placed {n} times"
                for (uid, epoch), n in self._commits.items() if n > 1
            ]


class GangIntegrity:
    """Batch-boundary partial-gang detector.

    Subscribes to `Store.watch_all_batch` — one callback per rv-contiguous
    commit batch, the transactional seam — and AFTER each batch asserts
    every touched gang's live bindings are uniformly placed or uniformly
    unplaced. A per-event watcher would false-positive mid-batch (it sees
    1..K-1 placed inside the atomic gang commit); the batch boundary is
    the rv at which outside observers can actually sample the store."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # gang -> {uid: placed}
        self._gangs: dict[str, dict[str, bool]] = {}
        self._uid_gang: dict[str, str] = {}
        self.violations: list[str] = []
        self._store = None

    def attach(self, store) -> None:
        with self._lock:
            if self._store is not None:
                try:
                    self._store.unwatch_all_batch(self._on_batch)
                except Exception:  # noqa: BLE001 - old store may be dead
                    pass
            self._store = store
            self._gangs.clear()
            self._uid_gang.clear()
        store.watch_all_batch(self._on_batch)
        # seed from current state (subscription precedes the snapshot, so
        # a concurrent batch lands in _on_batch either way; merging by uid
        # makes the overlap idempotent)
        seed = [("ResourceBinding", "ADDED", rb)
                for rb in store.list("ResourceBinding")]
        if seed:
            self._on_batch(seed)

    def _apply(self, kind: str, event: str, obj: Any) -> set[str]:
        if kind != "ResourceBinding":
            return set()
        gname = getattr(obj.spec, "gang_name", "") or ""
        uid = obj.metadata.uid
        touched = set()
        if event == "DELETED" or not gname:
            old = self._uid_gang.pop(uid, None)
            if old is not None:
                self._gangs.get(old, {}).pop(uid, None)
                touched.add(old)
            return touched
        self._uid_gang[uid] = gname
        self._gangs.setdefault(gname, {})[uid] = bool(obj.spec.clusters)
        touched.add(gname)
        return touched

    def _on_batch(self, events: list[tuple[str, str, Any]]) -> None:
        with self._lock:
            touched: set[str] = set()
            for kind, event, obj in events:
                touched |= self._apply(kind, event, obj)
            for g in touched:
                states = list(self._gangs.get(g, {}).values())
                if states and any(states) and not all(states):
                    self.violations.append(
                        f"partial gang {g!r}: {sum(states)}/{len(states)} "
                        f"members placed at a batch boundary")

    def check(self) -> list[str]:
        with self._lock:
            return list(self.violations)


class ResourceBounds:
    """Leak detector across waves: threads and queue depths must return
    under `baseline + headroom` after every heal. A promotion legitimately
    retires one stack and starts another, so the ceiling is rebased (only
    DOWNWARD drift is ever forgiven automatically)."""

    def __init__(self, headroom_threads: int = 24,
                 max_queue_depth: int = 512) -> None:
        self.headroom = headroom_threads
        self.max_queue = max_queue_depth
        self.baseline: Optional[int] = None
        self.samples: list[dict] = []

    def rebase(self) -> None:
        self.baseline = threading.active_count()

    def sample(self, wave: int, queue_depth: int) -> list[str]:
        threads = threading.active_count()
        if self.baseline is None:
            self.baseline = threads
        self.samples.append(
            {"wave": wave, "threads": threads, "queue_depth": queue_depth})
        out = []
        if threads > self.baseline + self.headroom:
            out.append(
                f"thread leak after wave {wave}: {threads} alive "
                f"(baseline {self.baseline} + headroom {self.headroom})")
        if queue_depth > self.max_queue:
            out.append(
                f"queue leak after wave {wave}: depth {queue_depth} "
                f"> {self.max_queue}")
        return out


class WireHealth:
    """Wire-plane health across the server group, sampled at wave
    boundaries (a loop that dies in a failover contributes its last
    sample before the kill). Violations:

    - a stream socket reaped as STUCK: soak clients are cooperative, so
      a socket that stopped accepting bytes for the reap window means
      the loop or a client thread wedged — never expected under chaos
      that only kills/partitions whole processes;
    - a per-socket queue above its byte bound: the loop's `_enqueue`
      seam enforces the bound per frame, so a breach means unbounded
      buffering snuck back in (the exact failure mode the event loop
      exists to prevent).

    `check()` additionally requires that at least one sample saw a live
    or completed stream — a verdict from a topology whose wire plane was
    never exercised would vacuously pass everything above."""

    def __init__(self) -> None:
        self.samples: list[dict] = []
        self.violations: list[str] = []
        self._served = False

    def sample(self, wave: int, servers) -> list[str]:
        """Fold in `watch_loop_stats()` from every live server (servers
        without a loop — threaded mode, stopped — contribute nothing)."""
        out = []
        for srv in servers:
            try:
                st = srv.watch_loop_stats()
            except Exception:  # noqa: BLE001 - a dying server is not a wire bug
                continue
            if not st:
                continue
            url = getattr(srv, "url", "?")
            self.samples.append({"wave": wave, "url": url, **st})
            if st.get("connections", 0) or st.get("closed_total", 0):
                self._served = True
            if st.get("stuck_closed", 0):
                out.append(
                    f"wave {wave}: {url} reaped {st['stuck_closed']} "
                    f"stuck wire socket(s)")
            bound = st.get("queue_bound", 0)
            if bound and st.get("queue_bytes_max", 0) > bound:
                out.append(
                    f"wave {wave}: {url} wire queue at "
                    f"{st['queue_bytes_max']}B exceeds bound {bound}B")
        self.violations.extend(out)
        return out

    def check(self) -> list[str]:
        out = list(self.violations)
        if self.samples and not self._served:
            out.append("wire plane never served a stream: every sampled "
                       "loop saw 0 connections over the whole soak")
        return out


def wait_converged(store, *, namespaces: set[str],
                   timeout: float, interval: float = 0.1) -> list[str]:
    """Bounded-window convergence after a heal: every ResourceBinding in
    the traffic namespaces is placed AND solved at its current template
    generation. Returns [] on convergence, else one line per straggler
    at the deadline."""
    import time as _t

    def stragglers() -> list[str]:
        out = []
        for rb in store.list("ResourceBinding"):
            if (rb.metadata.namespace or "") not in namespaces:
                continue
            sog = int(rb.status.scheduler_observed_generation or 0)
            gen = int(rb.metadata.generation or 0)
            if not rb.spec.clusters:
                out.append(f"unplaced: {rb.metadata.key()}")
            elif sog < gen:
                out.append(
                    f"stale solve: {rb.metadata.key()} observed {sog} < "
                    f"generation {gen}")
        return out

    deadline = _t.monotonic() + timeout
    while _t.monotonic() < deadline:
        if not stragglers():
            return []
        _t.sleep(interval)
    return stragglers()

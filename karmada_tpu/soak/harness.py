"""Phase-scripted soak runner: seeded traffic x seeded chaos x invariants.

One run = bootstrap the full topology, converge a base tenant mix, then
N fault waves. Each wave: install that wave's seeded boundary `FaultPlan`
(http/grpc/apply chaos), drive half the traffic slice, fire the wave's
PROCESS faults (from `FaultPlan.process_events` — leader kill, shard
kill, follower partition, estimator blackout), drive the other half,
heal everything, and hold the system to the invariant catalog inside a
bounded settle window. The run executes under `KARMADA_TPU_LOCKCHECK=1`
and ends with a structured verdict embedding `tracing.slo_report()` —
the JSON line the `soak` bench config emits beside the other BENCH
results (docs/ROBUSTNESS.md "Fleet soak").
"""
from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass

from .. import faults
from ..faults.plan import FaultPlan, FaultRule, ProcessFaultRule
from .invariants import (
    AdmissionLedger,
    GangIntegrity,
    ResourceBounds,
    WireHealth,
    WriteLedger,
    wait_converged,
)
from .topology import SoakTopology
from .traffic import NAMESPACE, TrafficProgram

log = logging.getLogger(__name__)

VERDICT_SCHEMA = "karmada-tpu/soak-verdict/v1"

# the four-wave fault rotation; longer profiles cycle it
WAVE_PATTERN = ("estimator_blackout", "shard_kill", "leader_kill",
                "partition")


@dataclass
class SoakProfile:
    seed: int = 7
    members: int = 4
    followers: int = 2
    shards: int = 2
    apps: int = 10
    waves: int = 4
    settle_window_s: float = 60.0
    soak_minutes: float = 0.0  # > 0: long profile, waves scaled to fill
    estimator_capacity: int = 50

    def effective_waves(self) -> int:
        if self.soak_minutes > 0:
            # ~30 s of traffic+converge per wave is the observed short-
            # profile cadence; the long profile fills the requested time
            return max(self.waves, int(self.soak_minutes * 60 / 30))
        return self.waves


def default_plan(profile: SoakProfile) -> FaultPlan:
    """The soak's process-fault schedule: one pinned process fault per
    wave, rotating leader kill / shard kill / partition / estimator
    blackout — every class fires within any 4 consecutive waves."""
    rules = [
        ProcessFaultRule(kind=WAVE_PATTERN[w % len(WAVE_PATTERN)],
                         target="*", wave=w)
        for w in range(profile.effective_waves())
    ]
    return FaultPlan(seed=profile.seed, process_rules=rules)


def wave_boundary_plan(profile: SoakProfile, wave: int) -> FaultPlan:
    """Fresh per-wave boundary chaos (installed at wave start, reset at
    heal): moderate error rates on all three boundaries plus a small
    latency tax on http — enough to force every retry path without
    starving the bounded-retry traffic funnel."""
    return FaultPlan(
        seed=profile.seed * 1009 + wave,
        rules=[
            FaultRule(boundary="http", kind="error", rate=0.08),
            FaultRule(boundary="http", kind="latency", rate=0.2,
                      latency=0.005),
            FaultRule(boundary="grpc", kind="error", rate=0.10),
            FaultRule(boundary="apply", kind="error", rate=0.08),
        ],
    )


class SoakHarness:
    def __init__(self, profile: SoakProfile | None = None):
        self.profile = profile or SoakProfile()

    # -- process-fault execution -------------------------------------------

    def _fire(self, topo: SoakTopology, event, admission: AdmissionLedger,
              gang: GangIntegrity) -> dict:
        rec = {"kind": event.kind, "target": event.target,
               "wave": event.wave}
        if event.kind == "leader_kill":
            rec["promoted"] = topo.kill_leader()
            # the invariant watchers follow the promotion, like every
            # other consumer of the (replicated) store
            admission.attach(topo.store)
            gang.attach(topo.store)
        elif event.kind == "shard_kill":
            rec["moved"] = topo.kill_shard()
        elif event.kind == "partition":
            rec["follower"] = topo.partition_follower(
                event.wave % max(1, len(topo.followers))).url
        elif event.kind == "estimator_blackout":
            topo.set_estimator_blackout(True)
        return rec

    def _heal(self, topo: SoakTopology, traffic: TrafficProgram) -> None:
        faults.reset()
        topo.heal_partitions()
        topo.set_estimator_blackout(False)
        topo.restore_shards()
        traffic.heal()

    # -- traffic slices -----------------------------------------------------

    def _slice_a(self, traffic: TrafficProgram, wave: int) -> None:
        traffic.diurnal_demand(wave)
        traffic.churn(n=4)
        traffic.surge(wave, n=3)

    def _slice_b(self, traffic: TrafficProgram, wave: int) -> None:
        traffic.gang_cohort(wave, size=3)
        traffic.churn(n=3)
        if wave % 2 == 0:
            traffic.preemptor_wave(wave, n=2)
        else:
            traffic.flap_cluster()
        if wave > 0:
            traffic.retire_wave_apps(wave - 1)

    # -- the run ------------------------------------------------------------

    def run(self) -> dict:
        p = self.profile
        os.environ["KARMADA_TPU_LOCKCHECK"] = "1"
        faults.reset()
        plan = default_plan(p)
        t_start = time.monotonic()

        topo = SoakTopology(
            n_members=p.members, n_followers=p.followers,
            n_shards=p.shards, estimator_capacity=p.estimator_capacity,
        )
        write_ledger = WriteLedger()
        admission = AdmissionLedger()
        gang = GangIntegrity()
        admission.attach(topo.store)
        gang.attach(topo.store)
        bounds = ResourceBounds()
        wire = WireHealth()

        waves: list[dict] = []
        convergence_failures: list[str] = []
        resource_violations: list[str] = []
        replication_failures: list[str] = []
        try:
            traffic = TrafficProgram(topo.client(), topo, write_ledger,
                                     seed=p.seed, apps=p.apps)
            traffic.bootstrap()
            base = wait_converged(topo.store, namespaces={NAMESPACE},
                                  timeout=p.settle_window_s)
            if base:
                convergence_failures.extend(
                    f"bootstrap: {s}" for s in base)
            bounds.rebase()

            for w in range(p.effective_waves()):
                t0 = time.monotonic()
                faults.install(wave_boundary_plan(p, w))
                fired = []
                self._slice_a(traffic, w)
                for ev in plan.process_events(w):
                    fired.append(self._fire(topo, ev, admission, gang))
                self._slice_b(traffic, w)
                self._heal(topo, traffic)
                # a promotion retires one plane stack and starts another:
                # let the thread ceiling follow the NEW baseline, leaks
                # still show as upward drift within later waves
                if any(f["kind"] == "leader_kill" for f in fired):
                    bounds.rebase()
                stragglers = wait_converged(
                    topo.store, namespaces={NAMESPACE},
                    timeout=p.settle_window_s)
                convergence_failures.extend(
                    f"wave {w}: {s}" for s in stragglers)
                replication_failures.extend(
                    f"wave {w}: {s}"
                    for s in topo.verify_partition_catchup())
                topo.shards.quiesce(timeout=20.0)
                topo.plane.quiesce(timeout=20.0)
                resource_violations.extend(
                    bounds.sample(w, topo.plane.queue_depth()))
                wire.sample(w, [topo.leader, *topo.followers])
                waves.append({
                    "wave": w,
                    "process_events": fired,
                    "write_failures": traffic.write_failures,
                    "converged": not stragglers,
                    "stragglers": stragglers[:8],
                    "duration_s": round(time.monotonic() - t0, 3),
                })
        finally:
            faults.reset()
            try:
                topo.close()
            except Exception:  # noqa: BLE001 - verdict over teardown
                log.exception("soak teardown")

        lost = write_ledger.check(topo.store)
        doubles = admission.doubles()
        partial = gang.check()
        wire_violations = wire.check()

        from ..analysis import lockorder

        lock_ok, lock_edges, lock_err = True, 0, ""
        if lockorder.enabled():
            lock_edges = len(lockorder.watchdog.edge_list())
            try:
                lockorder.watchdog.assert_acyclic()
            except Exception as e:  # noqa: BLE001 - report, don't crash
                lock_ok, lock_err = False, str(e)

        from ..tracing import slo_report

        verdict = {
            "schema": VERDICT_SCHEMA,
            "config": {
                "seed": p.seed, "members": p.members,
                "followers": p.followers, "shards": p.shards,
                "apps": p.apps, "waves": p.effective_waves(),
                "settle_window_s": p.settle_window_s,
                "soak_minutes": p.soak_minutes,
            },
            "duration_s": round(time.monotonic() - t_start, 3),
            "waves": waves,
            "invariants": {
                "lost_writes": lost,
                "double_admissions": doubles,
                "partial_gangs": partial,
                "convergence_failures": convergence_failures,
                "resource_violations": resource_violations,
                "replication_failures": replication_failures,
                "wire_violations": wire_violations,
                "plane_errors": topo.plane.errors[:16],
            },
            "resource_samples": bounds.samples,
            "wire_samples": wire.samples,
            "lock_edges": lock_edges,
            "lock_order_error": lock_err,
            "pass_lost_writes": not lost,
            "pass_exactly_once": not doubles,
            "pass_gang_integrity": not partial,
            "pass_convergence": not convergence_failures,
            "pass_resources": not resource_violations,
            "pass_replication": not replication_failures,
            "pass_wire_health": not wire_violations,
            "pass_lock_order": lock_ok,
            "slo": slo_report(),
        }
        verdict["pass"] = all(
            verdict[k] for k in verdict if k.startswith("pass_"))
        return verdict


def run_soak(profile: SoakProfile | None = None) -> dict:
    return SoakHarness(profile).run()


def verdict_schema_ok(verdict: dict) -> bool:
    """Structural validation of a soak verdict (the bench line embeds it;
    emission refuses to publish a malformed one)."""
    try:
        if verdict["schema"] != VERDICT_SCHEMA:
            return False
        for k in ("pass", "pass_lost_writes", "pass_exactly_once",
                  "pass_gang_integrity", "pass_convergence",
                  "pass_resources", "pass_replication",
                  "pass_wire_health", "pass_lock_order"):
            if not isinstance(verdict[k], bool):
                return False
        if not isinstance(verdict["waves"], list) or not verdict["waves"]:
            return False
        for w in verdict["waves"]:
            if not {"wave", "process_events", "converged",
                    "duration_s"} <= set(w):
                return False
        inv = verdict["invariants"]
        for k in ("lost_writes", "double_admissions", "partial_gangs",
                  "convergence_failures", "resource_violations",
                  "replication_failures", "wire_violations"):
            if not isinstance(inv[k], list):
                return False
        slo = verdict["slo"]
        if not isinstance(slo, dict) or "stages" not in slo:
            return False
        return isinstance(verdict["config"]["waves"], int)
    except (KeyError, TypeError):
        return False

"""Fault-tolerance plane: deterministic chaos injection, unified
retry/breaker policy, and degraded-mode estimator staleness
(docs/ROBUSTNESS.md)."""
from .plan import (
    BOUNDARY_APPLY,
    BOUNDARY_GRPC,
    BOUNDARY_HTTP,
    ENV_FAULT_PLAN,
    FaultAction,
    FaultInjector,
    FaultPlan,
    FaultRule,
    InjectedFault,
    PROCESS_KINDS,
    ProcessEvent,
    ProcessFaultRule,
    active,
    check,
    install,
    install_from_env,
    reset,
)
from .policy import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    Backoff,
    BreakerRegistry,
    CircuitBreaker,
    RetryPolicy,
)
from .staleness import (
    MAX_STALENESS_AGE,
    StalenessTracker,
    apply_staleness_penalty,
)

__all__ = [
    "BOUNDARY_APPLY", "BOUNDARY_GRPC", "BOUNDARY_HTTP", "ENV_FAULT_PLAN",
    "FaultAction", "FaultInjector", "FaultPlan", "FaultRule", "InjectedFault",
    "PROCESS_KINDS", "ProcessEvent", "ProcessFaultRule",
    "active", "check", "install", "install_from_env", "reset",
    "CLOSED", "HALF_OPEN", "OPEN",
    "Backoff", "BreakerRegistry", "CircuitBreaker", "RetryPolicy",
    "MAX_STALENESS_AGE", "StalenessTracker", "apply_staleness_penalty",
]

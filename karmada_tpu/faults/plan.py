"""Deterministic fault injection: seeded chaos schedules, replayable bit-for-bit.

A `FaultPlan` is a seed plus a list of `FaultRule`s. Every member-facing I/O
site (the three process boundaries: `RemoteStore` HTTP, the estimator gRPC
fan-out, and member apply) asks the installed `FaultInjector` for a decision
before doing real work. Decisions are a PURE function of
(plan seed, rule index, boundary, target, per-site operation sequence number)
— never of wall clock or thread identity — so the same plan against the same
driver produces byte-identical fault schedules, and a chaos run can be
replayed exactly (the acceptance property tests/test_chaos.py pins by running
the sweep twice).

Installation is env-gated for daemon processes: set
`KARMADA_TPU_FAULT_PLAN` to a JSON document (or a path to one) and every
process that consults `active()` injects the same schedule. In-process tests
install a plan explicitly with `install()` / the `installed()` context
manager.

Rule semantics (all windows are counted in per-site OPERATIONS, not seconds —
the unit that replays deterministically):

  kind=error      ops in [after, heal_after) fail with probability `rate`
                  (deterministic splitmix coin per op); heal_after=0 = forever
  kind=partition  ops in [after, heal_after) ALL fail (rate ignored)
  kind=flap       alternating windows of `period` ops: the first window is
                  healthy, the second faulted, and so on (shifted by `after`)
  kind=latency    ops in [after, heal_after) sleep `latency` seconds with
                  probability `rate` (injected before the real call)

`target` matches the site's target string exactly, or "*" for any target on
that boundary. A site is (boundary, target); each keeps its own op counter.
"""
from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Optional

BOUNDARY_HTTP = "http"      # RemoteStore <-> control-plane apiserver
BOUNDARY_GRPC = "grpc"      # estimator fan-out, per member cluster
BOUNDARY_APPLY = "apply"    # execution controller / agent -> member apply
BOUNDARIES = (BOUNDARY_HTTP, BOUNDARY_GRPC, BOUNDARY_APPLY)

KINDS = ("error", "partition", "flap", "latency")

# Process-level fault vocabulary: whole-process events the soak harness fires
# BETWEEN traffic slices (boundary rules above fire per-op, these fire
# per-wave). The plan only *decides* (pure, seeded); the harness *executes*
# (kills the leader server, resizes the shard plane, valves a follower,
# blacks out an estimator) because only it holds the process handles.
PROCESS_KINDS = (
    "leader_kill",          # stop leader server group; seal-and-promote
    "shard_kill",           # kill one scheduler shard; map-resize handoff
    "partition",            # isolate a follower past the log ring (snapshot)
    "estimator_blackout",   # member estimators answer nothing for a window
)
ENV_FAULT_PLAN = "KARMADA_TPU_FAULT_PLAN"


class InjectedFault(RuntimeError):
    """A fault-plan decision, raised at the boundary it targets. Carries the
    gRPC-style status code chaos rules use (`UNAVAILABLE` by default,
    `DEADLINE_EXCEEDED` for latency-style kills) so the breaker/metric layer
    classifies injected faults exactly like real ones."""

    def __init__(self, boundary: str, target: str, code: str = "UNAVAILABLE"):
        super().__init__(f"injected fault [{boundary}/{target}] {code}")
        self.boundary = boundary
        self.target = target
        self.code = code


@dataclass(frozen=True)
class FaultRule:
    boundary: str
    target: str = "*"
    kind: str = "error"
    rate: float = 1.0          # per-op fault probability (error / latency)
    latency: float = 0.0       # seconds (kind=latency)
    period: int = 4            # ops per half-cycle (kind=flap)
    after: int = 0             # first faultable op index at this site
    heal_after: int = 0        # first healed op index; 0 = never heals
    code: str = "UNAVAILABLE"  # status code injected errors carry

    def validate(self) -> None:
        if self.boundary not in BOUNDARIES:
            # a typo'd boundary would install cleanly and inject NOTHING —
            # the silent-clean chaos run this plane must never produce
            raise ValueError(
                f"unknown fault boundary {self.boundary!r} "
                f"(want one of {sorted(BOUNDARIES)})"
            )
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.kind == "flap" and self.period <= 0:
            raise ValueError("flap rule needs period > 0")
        if self.kind == "latency" and self.latency <= 0:
            raise ValueError("latency rule needs latency > 0")
        if not 0.0 <= self.rate <= 1.0:
            raise ValueError(f"rate {self.rate} outside [0, 1]")


@dataclass
class FaultAction:
    """One site-op decision: at most one error and any accumulated latency."""

    error: Optional[str] = None  # status code when the op must fail
    latency: float = 0.0


@dataclass(frozen=True)
class ProcessFaultRule:
    """One whole-process fault candidate. `wave` pins the rule to exactly one
    fault wave (the unit that replays deterministically — the soak has no
    per-op counter for process lifecycles); wave=-1 makes the rule a
    candidate on EVERY wave, gated by the seeded `rate` coin."""

    kind: str
    target: str = "*"      # follower name / shard index / member — "*" lets
    #                        the harness pick (e.g. the max-applied follower)
    wave: int = -1         # fire on exactly this wave; -1 = every wave
    rate: float = 1.0      # per-wave firing probability (splitmix coin)

    def validate(self) -> None:
        if self.kind not in PROCESS_KINDS:
            raise ValueError(
                f"unknown process fault kind {self.kind!r} "
                f"(want one of {sorted(PROCESS_KINDS)})"
            )
        if self.wave < -1:
            raise ValueError(f"wave {self.wave} < -1")
        if not 0.0 <= self.rate <= 1.0:
            raise ValueError(f"rate {self.rate} outside [0, 1]")


@dataclass(frozen=True)
class ProcessEvent:
    """One fired process fault: what the harness must do this wave."""

    kind: str
    target: str
    wave: int


def _splitmix_unit(seed: int, rule_idx: int, site: str, n: int) -> float:
    """Deterministic uniform [0,1) for one (rule, site, op) — splitmix64 over
    a stable mix of the identifying tuple (no Python hash randomization)."""
    h = 0xCBF29CE484222325
    for b in site.encode():
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    x = (seed * 0x9E3779B97F4A7C15 + rule_idx * 0xBF58476D1CE4E5B9
         + h + n) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    x ^= x >> 31
    return (x >> 11) / float(1 << 53)


@dataclass
class FaultPlan:
    seed: int = 0
    rules: list[FaultRule] = field(default_factory=list)
    process_rules: list[ProcessFaultRule] = field(default_factory=list)

    def validate(self) -> None:
        for r in self.rules:
            r.validate()
        for p in self.process_rules:
            p.validate()

    # -- (de)serialization -------------------------------------------------

    @staticmethod
    def from_dict(d: dict) -> "FaultPlan":
        plan = FaultPlan(
            seed=int(d.get("seed", 0)),
            rules=[FaultRule(**r) for r in d.get("rules", [])],
            process_rules=[
                ProcessFaultRule(**r) for r in d.get("process_rules", [])
            ],
        )
        plan.validate()
        return plan

    @staticmethod
    def from_json(text: str) -> "FaultPlan":
        return FaultPlan.from_dict(json.loads(text))

    def to_json(self) -> str:
        from dataclasses import asdict

        doc = {"seed": self.seed, "rules": [asdict(r) for r in self.rules]}
        if self.process_rules:
            doc["process_rules"] = [asdict(r) for r in self.process_rules]
        return json.dumps(doc, sort_keys=True)

    # -- the pure decision function ---------------------------------------

    def decide(self, boundary: str, target: str, n: int) -> FaultAction:
        """Decision for op `n` at site (boundary, target) — pure, so the
        whole schedule can be previewed/replayed without an injector."""
        site = f"{boundary}/{target}"
        action = FaultAction()
        for i, r in enumerate(self.rules):
            if r.boundary != boundary:
                continue
            if r.target != "*" and r.target != target:
                continue
            if n < r.after or (r.heal_after and n >= r.heal_after):
                continue
            if r.kind == "partition":
                action.error = action.error or r.code
            elif r.kind == "flap":
                if ((n - r.after) // r.period) % 2 == 1:
                    action.error = action.error or r.code
            elif r.kind == "error":
                if _splitmix_unit(self.seed, i, site, n) < r.rate:
                    action.error = action.error or r.code
            elif r.kind == "latency":
                if _splitmix_unit(self.seed, i, site, n) < r.rate:
                    action.latency += r.latency
        return action

    def process_events(self, wave: int) -> list[ProcessEvent]:
        """Process faults that fire on fault wave `wave` — pure, like
        `decide()`, so a soak's whole process-fault schedule can be previewed
        without a harness. The splitmix coin keys on a "process/" site string,
        which no boundary rule can produce, so process and boundary streams
        never correlate even under the same seed."""
        fired = []
        for i, r in enumerate(self.process_rules):
            if r.wave != -1 and r.wave != wave:
                continue
            site = f"process/{r.kind}/{r.target}"
            if r.rate >= 1.0 or _splitmix_unit(self.seed, i, site, wave) < r.rate:
                fired.append(ProcessEvent(kind=r.kind, target=r.target, wave=wave))
        return fired

    def process_schedule(self, n_waves: int) -> bytes:
        """All process-fault firings over the first `n_waves` waves,
        serialized — the byte-identical-replay witness for the process
        vocabulary (mirrors `schedule()` for boundary rules)."""
        out = []
        for w in range(n_waves):
            for e in self.process_events(w):
                out.append(f"{w}:{e.kind}:{e.target}")
        return "\n".join(out).encode()

    def has_boundary(self, boundary: str) -> bool:
        """True when any rule can fire at `boundary` — call sites that
        reroute execution paths under chaos (e.g. the estimator sweep
        abandoning the fused fleet kernel for per-cluster legs) check this
        so an unrelated plan doesn't change their shape."""
        return any(r.boundary == boundary for r in self.rules)

    def schedule(self, boundary: str, target: str, n_ops: int) -> bytes:
        """The first `n_ops` decisions at one site, serialized — the
        byte-identical-replay witness (same seed + same plan ⇒ same bytes)."""
        out = []
        for n in range(n_ops):
            a = self.decide(boundary, target, n)
            out.append(f"{n}:{a.error or '-'}:{a.latency:g}")
        return "\n".join(out).encode()


class FaultInjector:
    """Installed plan + per-site op counters + the decision trace.

    `check()` is the call-site hook: it advances the site counter, applies
    latency (sleeps), and raises `InjectedFault` on an error decision.
    Thread-safe; counters only ever advance."""

    def __init__(self, plan: FaultPlan):
        plan.validate()
        self.plan = plan
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, str], int] = {}
        self.trace: list[tuple[str, str, int, str, float]] = []

    def decide(self, boundary: str, target: str) -> FaultAction:
        with self._lock:
            key = (boundary, target)
            n = self._counters.get(key, 0)
            self._counters[key] = n + 1
        action = self.plan.decide(boundary, target, n)
        if action.error or action.latency:
            from ..metrics import faults_injected

            faults_injected.inc(
                boundary=boundary,
                kind="error" if action.error else "latency",
            )
            with self._lock:
                self.trace.append(
                    (boundary, target, n, action.error or "", action.latency)
                )
        return action

    def check(self, boundary: str, target: str) -> None:
        action = self.decide(boundary, target)
        if action.latency:
            import time

            time.sleep(action.latency)
        if action.error:
            raise InjectedFault(boundary, target, action.error)

    def trace_bytes(self) -> bytes:
        """The recorded fault schedule, serialized for replay comparison."""
        with self._lock:
            rows = list(self.trace)
        return "\n".join(
            f"{b}/{t}:{n}:{e or '-'}:{lat:g}" for b, t, n, e, lat in rows
        ).encode()


# -- process-global installation (env-gated for daemons) -------------------

_active: Optional[FaultInjector] = None
_env_checked = False
_env_error: Optional[Exception] = None
# RLock: active()'s env-gated first call installs while already holding it
_lock = threading.RLock()


def install(plan: FaultPlan) -> FaultInjector:
    global _active, _env_checked
    with _lock:
        _active = FaultInjector(plan)
        _env_checked = True
        return _active


def reset() -> None:
    """Remove any installed injector AND forget the env check (tests)."""
    global _active, _env_checked, _env_error
    with _lock:
        _active = None
        _env_checked = False
        _env_error = None


def install_from_env() -> Optional[FaultInjector]:
    """Install from KARMADA_TPU_FAULT_PLAN (inline JSON, or a path to a JSON
    file). Returns None when the variable is unset. A malformed plan fails
    loudly — a chaos run silently running fault-free would be worse."""
    spec = os.environ.get(ENV_FAULT_PLAN, "")
    if not spec:
        return None
    text = spec
    if not spec.lstrip().startswith("{"):
        with open(spec, encoding="utf-8") as f:
            text = f.read()
    return install(FaultPlan.from_json(text))


def active() -> Optional[FaultInjector]:
    """The installed injector, if any. The first call per process also
    honors the env gate, so daemons need no explicit wiring beyond calling
    the boundary hooks. The check-and-install is atomic: exactly ONE
    injector is ever minted per process for an env plan — a second install
    would reset the per-site op counters and break bit-for-bit replay.

    A MALFORMED env plan fails persistently: the parse error re-raises on
    every call (not just the first, which a broad except at some boundary
    might swallow) — a broken chaos run must never quietly become a clean
    run that reports success."""
    global _env_checked, _env_error
    if _env_error is not None:
        raise _env_error
    if _active is None and not _env_checked:
        with _lock:
            if _env_error is not None:
                raise _env_error
            if _env_checked:
                return _active  # another thread won the race
            _env_checked = True
            if os.environ.get(ENV_FAULT_PLAN, ""):
                try:
                    return install_from_env()
                except Exception as e:
                    _env_error = e
                    raise
    return _active


def check(boundary: str, target: str) -> None:
    """Hook for the three boundaries: no-op without an installed plan."""
    inj = active()
    if inj is not None:
        inj.check(boundary, target)

"""Degraded-mode estimator staleness: per-cluster epochs + pure-array penalty.

When a member's circuit breaker is open, its estimator answers no fresh rows
— but the batched [B,C] solve must not stall, and discarding the column (the
-1 sentinel) would let the GeneralEstimator bound alone steer replicas onto a
possibly-dark cluster. Instead the last FRESH answers are kept per (cluster,
binding uid), a per-cluster staleness epoch counts the degraded sweeps since
that answer, and the stale values re-enter the matrix decayed:

    penalized = answer >> min(age, MAX_STALENESS_AGE)

i.e. the scheduler's trust in a stale answer halves every degraded sweep.
The transform is pure integer array math over the extra_avail matrix, so
everything that consumes extra_avail inherits it unchanged — the single-chip
and mesh kernels (sched/core.py), incremental replay (sched/incremental.py
digests the penalized row, so a staleness tick re-solves exactly the affected
rows and a stable stale row replays), and the vmapped simulation plane
(simulation/engine.py). The age cap bounds re-solve churn: after
MAX_STALENESS_AGE degraded sweeps the penalized row is stable (usually 0),
and replay re-engages.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

MAX_STALENESS_AGE = 8  # penalized values are stable past this many sweeps

UNAUTHENTIC = -1  # the estimator discard sentinel (client.UNAUTHENTIC_REPLICA)


def apply_staleness_penalty(values, age: int):
    """Decay estimator answers by staleness age: halve per epoch, sentinel
    (-1) rows pass through untouched. Works on numpy and jax arrays alike
    (shift + where are array-native), so callers may apply it host-side on
    the assembled matrix or inside a jitted program."""
    shift = min(int(age), MAX_STALENESS_AGE)
    if shift <= 0:
        return values
    return np.where(values >= 0, values >> shift, values) if isinstance(
        values, np.ndarray
    ) else _apply_jnp(values, shift)


def _apply_jnp(values, shift: int):
    import jax.numpy as jnp

    return jnp.where(values >= 0, values >> shift, values)


class StalenessTracker:
    """Last-known estimator answers per (cluster, binding uid) + per-cluster
    staleness epochs. Not thread-safe by itself — the estimator sweep that
    feeds it is already serialized per scheduler round.

    Snapshots store (uids tuple, i32 column) — the healthy-sweep hot path
    is one array copy per cluster, never a per-binding Python dict build
    (O(B·C) dict inserts per round would dwarf the array-only sweep).
    The uid→index map is built lazily, only on DEGRADED sweeps."""

    def __init__(self):
        # cluster -> (uids, i32[B] column); uids tuples are shared across
        # clusters of one sweep (the caller passes the same object)
        self._rows: dict[str, tuple] = {}
        self._age: dict[str, int] = {}
        self._index_cache: Optional[tuple] = None  # (uids, {uid: i})
        # chunked-round scope (pipelined scheduler, sched/pipeline.py): a
        # round of N chunk-shard sweeps must look like ONE sweep here —
        # fresh snapshots MERGE across the round's chunks (a replace would
        # keep only the last chunk's bindings) and each cluster's staleness
        # epoch advances once per ROUND, not once per chunk (else trust
        # decays chunk-count times faster and later chunks see a different
        # penalty than earlier ones, breaking serial/pipelined parity)
        self._round_active = False
        self._round_fresh: set = set()
        self._round_aged: set = set()

    def begin_round(self) -> None:
        self._round_active = True
        self._round_fresh.clear()
        self._round_aged.clear()

    def end_round(self) -> None:
        self._round_active = False
        self._round_fresh.clear()
        self._round_aged.clear()

    def age(self, cluster: str) -> int:
        return self._age.get(cluster, 0)

    def record_fresh(self, cluster: str, uids, column) -> None:
        """A successful sweep for `cluster`: snapshot its column (replacing
        the previous snapshot — deleted bindings fall out with their sweep)
        and reset the staleness epoch. Inside a chunked round, later chunks
        EXTEND the round's snapshot instead of replacing it."""
        if self._round_active and cluster in self._round_fresh:
            old_uids, old_col = self._rows[cluster]
            self._rows[cluster] = (
                tuple(old_uids) + tuple(uids),
                np.concatenate(
                    [old_col, np.array(column, np.int32, copy=True)]
                ),
            )
        else:
            self._rows[cluster] = (
                uids, np.array(column, np.int32, copy=True)
            )
            if self._round_active:
                self._round_fresh.add(cluster)
        self._age[cluster] = 0

    def _index_of(self, uids) -> dict:
        cached = self._index_cache
        if cached is not None and cached[0] is uids:
            return cached[1]
        index = {uid: i for i, uid in enumerate(uids) if uid}
        self._index_cache = (uids, index)
        return index

    def fill_stale(self, cluster: str, uids: Sequence[Optional[str]]):
        """One degraded sweep for `cluster`: bump its staleness epoch and
        return the penalized column for the CURRENT binding order (i32[B];
        bindings the cache never saw answer the -1 sentinel). Returns None
        when nothing was ever cached (the column stays all-sentinel).
        Inside a chunked round the epoch bumps once per ROUND — every chunk
        of the round sees the same decay."""
        if not (self._round_active and cluster in self._round_aged):
            self._age[cluster] = self._age.get(cluster, 0) + 1
            if self._round_active:
                self._round_aged.add(cluster)
        cached = self._rows.get(cluster)
        if cached is None:
            return None
        old_uids, old_col = cached
        age = self._age[cluster]
        if old_uids is uids or tuple(old_uids) == tuple(uids):
            col = old_col.copy()  # common case: binding set unchanged
        else:
            index = self._index_of(old_uids)
            col = np.fromiter(
                (old_col[index[uid]] if uid and uid in index
                 else UNAUTHENTIC for uid in uids),
                np.int32, count=len(uids),
            )
        return apply_staleness_penalty(col, age)

    def forget(self, cluster: str) -> None:
        self._rows.pop(cluster, None)
        self._age.pop(cluster, None)

"""Unified retry + circuit-breaker policy for every member-facing I/O path.

One place for the three fault-tolerance primitives the reference spreads over
client-go workqueue rate limiters, per-cluster gRPC connection management,
and taint-based failover:

  - `RetryPolicy`: exponential backoff with FULL jitter (delay is uniform in
    [0, min(cap, base·mult^attempt)] — the AWS-architecture-blog shape that
    de-synchronizes retry storms) under a total deadline budget.
  - `Backoff`: the stateful per-stream variant (replaces `RemoteStore`'s
    hand-rolled watch backoff).
  - `CircuitBreaker`: per-member closed → open → half-open probe machine.
    While open, callers fast-fail (the batched solve must never stall on a
    dark member); after `open_seconds` one probe is admitted, and its
    outcome closes or re-opens the breaker.

All time is injectable (`clock` returns monotonic seconds) and all jitter is
injectable (`rng` returns uniform [0,1)), so the state machines unit-test
with fake clocks and chaos runs stay deterministic.
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

# breaker states (gauge values: the wire encoding of karmada_breaker_state)
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"
_STATE_GAUGE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


@dataclass(frozen=True)
class RetryPolicy:
    """Declarative retry envelope: attempts × backoff under a deadline."""

    base_delay: float = 0.1
    max_delay: float = 5.0
    multiplier: float = 2.0
    max_attempts: int = 5
    deadline: float = 30.0  # total budget across attempts + sleeps

    def delay(self, attempt: int, u: Optional[float] = None) -> float:
        """Full-jitter delay for `attempt` (0-based): uniform in
        [0, min(max_delay, base·mult^attempt)]."""
        cap = min(self.max_delay, self.base_delay * self.multiplier ** attempt)
        if u is None:
            u = random.random()
        return u * cap

    def run(self, fn: Callable, retryable: Callable[[Exception], bool],
            sleep: Callable[[float], None] = time.sleep,
            clock: Callable[[], float] = time.monotonic,
            rng: Callable[[], float] = random.random):
        """Call `fn` until it succeeds, a non-retryable error escapes, the
        attempt budget is spent, or the next sleep would overrun the
        deadline. The last error re-raises."""
        t0 = clock()
        attempt = 0
        while True:
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 - classified below
                if not retryable(e) or attempt + 1 >= self.max_attempts:
                    raise
                d = self.delay(attempt, rng())
                if clock() - t0 + d > self.deadline:
                    raise
                sleep(d)
                attempt += 1


class Backoff:
    """Stateful exponential backoff with full jitter — the per-stream shape
    (watch reconnects): `next()` returns the sleep for the current failure
    streak and advances it; `reset()` on success."""

    def __init__(self, base: float = 0.5, cap: float = 30.0,
                 multiplier: float = 2.0,
                 rng: Callable[[], float] = random.random):
        self.base = base
        self.cap = cap
        self.multiplier = multiplier
        self._rng = rng
        self._current = base

    def next(self) -> float:
        d = self._current * self._rng()
        self._current = min(self._current * self.multiplier, self.cap)
        return d

    def peek_cap(self) -> float:
        """Upper bound of the next sleep (what a jitterless loop would use)."""
        return self._current

    def reset(self) -> None:
        self._current = self.base


class CircuitBreaker:
    """closed → open → half-open probe, per member.

    closed:    every call admitted; `failure_threshold` CONSECUTIVE failures
               trip to open.
    open:      `allow()` is False (fast-fail, no I/O) until `open_seconds`
               elapse, then the breaker moves to half-open.
    half-open: exactly `half_open_probes` in-flight probes admitted; a probe
               success closes the breaker, a probe failure re-opens it (and
               restarts the open window).
    """

    def __init__(self, name: str = "", failure_threshold: int = 3,
                 open_seconds: float = 5.0, half_open_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.failure_threshold = max(1, failure_threshold)
        self.open_seconds = open_seconds
        self.half_open_probes = max(1, half_open_probes)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probes_in_flight = 0
        self._publish(CLOSED)

    # -- state accessors ---------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    @property
    def is_open(self) -> bool:
        """True while calls should fast-fail (open and not yet probing)."""
        return self.state == OPEN

    # -- transitions -------------------------------------------------------

    def _publish(self, to: str) -> None:
        from ..metrics import breaker_state

        breaker_state.set(_STATE_GAUGE[to], member=self.name)

    def _transition(self, to: str) -> None:
        if self._state == to:
            return
        self._state = to
        from ..metrics import breaker_transitions

        breaker_transitions.inc(member=self.name, to=to)
        self._publish(to)

    def _maybe_half_open(self) -> None:
        if (self._state == OPEN
                and self._clock() - self._opened_at >= self.open_seconds):
            self._transition(HALF_OPEN)
            self._probes_in_flight = 0

    def allow(self) -> bool:
        """Admission check for one call. In half-open, admitting counts the
        call as a probe; its record_success/record_failure settles it."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                return False
            if self._probes_in_flight < self.half_open_probes:
                self._probes_in_flight += 1
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state in (HALF_OPEN, OPEN):
                self._transition(CLOSED)
            self._probes_in_flight = 0

    def record_failure(self) -> None:
        with self._lock:
            self._maybe_half_open()
            if self._state == HALF_OPEN:
                self._opened_at = self._clock()
                self._transition(OPEN)
                self._probes_in_flight = 0
                return
            self._failures += 1
            if self._state == CLOSED and self._failures >= self.failure_threshold:
                self._opened_at = self._clock()
                self._transition(OPEN)


class BreakerRegistry:
    """Per-member breakers with a shared configuration + clock. Created
    lazily on first use, so 'has a breaker' means 'this member has been
    called through a guarded path'."""

    def __init__(self, failure_threshold: int = 3, open_seconds: float = 5.0,
                 half_open_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = failure_threshold
        self.open_seconds = open_seconds
        self.half_open_probes = half_open_probes
        self.clock = clock
        self._lock = threading.Lock()
        self._breakers: dict[str, CircuitBreaker] = {}

    def for_member(self, name: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(name)
            if br is None:
                br = CircuitBreaker(
                    name=name,
                    failure_threshold=self.failure_threshold,
                    open_seconds=self.open_seconds,
                    half_open_probes=self.half_open_probes,
                    clock=self.clock,
                )
                self._breakers[name] = br
            return br

    def get(self, name: str) -> Optional[CircuitBreaker]:
        with self._lock:
            return self._breakers.get(name)

    def open_members(self) -> set[str]:
        """Members whose breaker currently fast-fails (OPEN — a half-open
        breaker is probing and no longer counts as dark)."""
        with self._lock:
            breakers = list(self._breakers.items())
        return {name for name, br in breakers if br.is_open}

    def any_open(self) -> bool:
        return bool(self.open_members())

"""In-memory versioned object store with a watch bus.

This is the control-plane storage/API layer (SURVEY L1 / D1): the reference
uses a stock kube-apiserver + etcd with level-triggered informers; we provide
the same contract — versioned objects, generation bumps on spec change, watch
events, finalizer-gated deletion — as an in-process store so every controller
can stay level-triggered and resumable (reference invariant: all state is CRDs,
device state is a rebuildable cache; SURVEY §5 checkpoint note).

Thread-safety and lock scope: a single RLock guards the maps, and the critical
section is kept to exactly the commit — validate, stamp, place, feed the
under-lock event sink. Input deepcopies happen before the lock, the
return/watcher copies and ALL watcher-bus dispatch happen after it drops
(including on the `apply` path, which used to notify re-entrantly under the
hold — the store side of the ABBA surface the queue side fixed first). Objects
are IMMUTABLE once committed: every mutation places a fresh copy, so readers
holding a committed reference (the watch cache retains them for lazy wire
encoding) never observe in-place changes.

Transactional batch writes (the etcd multi-op Txn analogue — PAPER.md L1:
write throughput comes from transactional commits, not raw fsync speed):
`create_batch` / `update_batch` / `apply_batch` admit, validate, and commit N
objects under ONE lock hold, minting contiguous resourceVersions and feeding
the event sink as one rv-ordered run; the batch then reaches persistence as a
single `watch_all_batch` delivery — one WAL group-commit unit, one fsync.
Semantics are all-or-nothing: any validation failure raises `BatchError` with
per-object typed results (conflict/not-found/admission/aborted) and commits
nothing, so a caller can distinguish re-send-the-rest from drop-this-one.
"""
from __future__ import annotations

import copy
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional

from ..analysis.lockorder import make_lock
from ..api.meta import ObjectMeta, new_uid, now
from ..api.unstructured import Unstructured
from ..metrics import store_lock_hold, store_lock_wait, txn_batch_size

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

WatchHandler = Callable[[str, Any], None]  # (event_type, obj)


class ConflictError(Exception):
    pass


class NotFoundError(KeyError):
    pass


class ReplicationGapError(ConflictError):
    """A replicated log entry does not start at the follower's next
    resourceVersion — the shipper must rewind to `expected_rv` (or fall
    back to a snapshot when the entry is already compacted out of its
    log)."""

    def __init__(self, message: str, expected_rv: int):
        super().__init__(message)
        self.expected_rv = expected_rv


@dataclass
class BatchOpResult:
    """Per-object disposition of a transactional batch write.

    reason: "" (committed) | "conflict" | "not-found" | "admission" |
    "skipped" (update_batch skip_missing) | "aborted" (this object was
    fine; a neighbor torched the batch)."""

    ok: bool
    obj: Any = None
    reason: str = ""
    error: str = ""

    @property
    def retryable(self) -> bool:
        """Worth re-sending as-is: the object conflicted with a racing
        writer or merely rode a batch a neighbor failed. Admission denials
        and not-found are terminal for this object."""
        return self.reason in ("conflict", "aborted")


class BatchError(Exception):
    """A transactional batch write failed validation: NOTHING was committed
    (all-or-nothing). `results` aligns 1:1 with the submitted objects — a
    conflict on one object leaves its neighbors marked `aborted` (retryable),
    so one bad object doesn't destroy the batch's retryable/terminal
    distinction."""

    def __init__(self, message: str, results: list[BatchOpResult]):
        super().__init__(message)
        self.results = results


def gvk_of(obj: Any) -> str:
    """Store key kind. Typed objects use their dataclass kind; unstructured
    use apiVersion+kind so e.g. apps/v1/Deployment is distinct."""
    if isinstance(obj, Unstructured):
        return f"{obj.api_version}/{obj.kind}"
    return obj.kind


@dataclass
class _Bucket:
    objects: dict[str, Any]
    watchers: list[tuple[WatchHandler, str]]  # (handler, namespace filter)


_REMOVED = object()  # batch-overlay tombstone (in-batch delete transition)


class Store:
    def __init__(self) -> None:
        # constructed through the lock-order seam: a plain RLock normally,
        # an instrumented one under KARMADA_TPU_LOCKCHECK=1 (the runtime
        # watchdog records the global acquisition-order graph and the
        # analysis tier-1 test fails on cycles — docs/ANALYSIS.md)
        self._lock = make_lock("store._lock", rlock=True)
        self._buckets: dict[str, _Bucket] = {}
        self._kinds_token = 0
        self._rv = 0
        self._all_watchers: list[Callable[[str, str, Any], None]] = []
        # event sinks run UNDER the mutation lock, at the point the rv is
        # assigned — unlike watchers (notified after the lock drops, so two
        # racing mutators may interleave), a sink observes the event log in
        # strict resourceVersion order. This is the feed for the revisioned
        # watch cache (store/watchcache.py); sinks must be fast, must never
        # call back into the store, and must never MUTATE the object — they
        # receive the committed (immutable-once-placed) object itself and
        # may retain the reference (the watch cache encodes it lazily,
        # outside this lock).
        self._event_sinks: list[Callable[[str, str, Any], None]] = []
        # batch watchers receive whole commit batches (single writes arrive
        # as one-element lists) OUTSIDE the lock — the persistence seam: a
        # transactional batch is delivered as ONE call so the WAL commits
        # it as one group-commit unit (one fsync)
        self._batch_watchers: list[
            Callable[[list[tuple[str, str, Any]]], None]
        ] = []
        # admission chain (op, kind, obj, old) -> obj; raises to deny —
        # the apiserver admission path (reference: pkg/webhook/* handlers)
        self._admission: Optional[Callable[[str, str, Any, Any], Any]] = None

    def set_admission(self, admit: Callable[[str, str, Any, Any], Any]) -> None:
        self._admission = admit

    def add_event_sink(self, sink: Callable[[str, str, Any], None], *,
                       prime: Optional[Callable[[str, Any], None]] = None) -> int:
        """Register an under-lock, rv-ordered event sink. The object passed
        is the committed stored object — immutable once placed — so a sink
        may retain the reference but must never mutate it (watchers get
        their own post-lock copy).

        `prime(kind, obj)` — when given — is called under the same lock hold
        for every object already stored, so a cache attaches with a snapshot
        index that is revision-consistent with the event feed (no mutation
        can land between the prime sweep and the first sinked event).
        Returns the store's current resourceVersion at attach time."""
        with self._lock:
            if prime is not None:
                for kind, b in self._buckets.items():
                    for o in b.objects.values():
                        prime(kind, copy.deepcopy(o))
            self._event_sinks.append(sink)
            return self._rv

    def remove_event_sink(self, sink: Callable[[str, str, Any], None]) -> None:
        with self._lock:
            if sink in self._event_sinks:
                self._event_sinks.remove(sink)

    def _sink(self, kind: str, event: str, obj: Any) -> None:
        """Feed event sinks; caller MUST hold self._lock."""
        for s in self._event_sinks:
            s(kind, event, obj)

    # -- helpers ----------------------------------------------------------

    def _bucket(self, kind: str) -> _Bucket:
        b = self._buckets.get(kind)
        if b is None:
            b = _Bucket(objects={}, watchers=[])
            self._buckets[kind] = b
            # kind registration bumps the token so kind-set caches (the
            # autoscaling template index) invalidate without re-listing
            self._kinds_token += 1
        return b

    @staticmethod
    def _key(meta: ObjectMeta) -> str:
        return meta.key()

    @staticmethod
    def _name_key(name: str, namespace: str) -> str:
        return ObjectMeta(name=name, namespace=namespace).key()

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    @contextmanager
    def _write_lock(self):
        """One measured hold of the store lock (the write paths). Lock-wait
        and lock-hold ride the karmada_store_lock_* histograms, observed
        AFTER release so the metrics mutex is never taken under the store
        lock. Re-entrant acquisitions (apply's inner commit) skip the
        metrics, so each write is measured exactly once."""
        lock = self._lock
        owned = getattr(lock, "_is_owned", None)
        if owned is not None and owned():
            with lock:
                yield
            return
        t0 = time.perf_counter()
        lock.acquire()
        t1 = time.perf_counter()
        try:
            yield
        finally:
            t2 = time.perf_counter()
            lock.release()
            store_lock_wait.observe(t1 - t0)
            store_lock_hold.observe(t2 - t1)

    def _peek_deletion_timestamp(self, kind: str, name: str, namespace: str):
        """Copy-free read of a stored object's deletionTimestamp (hot path:
        every update consults this for the removal-transition check)."""
        with self._lock:
            b = self._buckets.get(kind)
            if b is None:
                return None
            obj = b.objects.get(self._name_key(name, namespace))
            return None if obj is None else obj.metadata.deletion_timestamp

    @staticmethod
    def _spec_view(obj: Any) -> Any:
        """The part whose change bumps generation (k8s semantics: spec
        only). A comparison VIEW, not a copy — it is read for equality and
        dropped, and the old to_dict() round-trip deepcopied the whole
        manifest twice per update inside the lock hold."""
        if isinstance(obj, Unstructured):
            return obj.spec_view()
        spec = getattr(obj, "spec", None)
        return spec

    # -- admission wrappers (run OUTSIDE the lock on the direct paths) -----

    def _admit_create(self, obj: Any, kind: str) -> Any:
        if self._admission is not None:
            obj = self._admission("CREATE", kind, obj, None)
        return obj

    def _admit_update(self, obj: Any, kind: str) -> Any:
        if self._admission is not None:
            name, ns = obj.metadata.name, obj.metadata.namespace
            obj = self._admission("UPDATE", kind, obj, lambda: self.try_get(kind, name, ns))
            # an update that transitions into removal (deletionTimestamp set,
            # no finalizers left) IS a delete — run DELETE admission so
            # deletion protection cannot be bypassed via update()
            if not obj.metadata.finalizers and (
                obj.metadata.deletion_timestamp is not None
                or self._peek_deletion_timestamp(kind, name, ns) is not None
            ):
                self._admission("DELETE", kind, obj, None)
        return obj

    # -- commit primitives (caller holds the lock) -------------------------

    @staticmethod
    def _stamp_create(kind: str, stored: Any) -> None:
        m = stored.metadata
        if not m.uid:
            m.uid = new_uid(kind.split("/")[-1].lower())
        m.creation_timestamp = m.creation_timestamp or now()
        m.generation = 1

    @staticmethod
    def _stamp_update(stored: Any, existing: Any, check_rv: bool,
                      kind: str, key: str) -> tuple[str, bool]:
        """Stamp `stored` from its predecessor (uid, creation timestamp,
        generation bump on spec change, deletionTimestamp immutability);
        returns (event, removed) where removed means finalizer-gated
        removal. The caller mints the resourceVersion at commit."""
        if check_rv and stored.metadata.resource_version != existing.metadata.resource_version:
            raise ConflictError(
                f"{kind} {key}: rv {stored.metadata.resource_version} != "
                f"{existing.metadata.resource_version}"
            )
        m = stored.metadata
        m.uid = existing.metadata.uid
        m.creation_timestamp = existing.metadata.creation_timestamp
        m.generation = existing.metadata.generation
        # deletionTimestamp is immutable once set (k8s semantics): a stale
        # writer must not resurrect an object already marked for deletion.
        if existing.metadata.deletion_timestamp is not None:
            m.deletion_timestamp = existing.metadata.deletion_timestamp
        if Store._differs(Store._spec_view(existing), Store._spec_view(stored)):
            m.generation += 1
        if m.deletion_timestamp is not None and not m.finalizers:
            # removal gets a FRESH rv: a DELETED event must order after
            # every prior write of the object (WAL replay is rv-ordered)
            return DELETED, True
        return MODIFIED, False

    def _commit_create(self, kind: str, stored: Any) -> None:
        b = self._bucket(kind)
        key = self._key(stored.metadata)
        if key in b.objects:
            raise ConflictError(f"{kind} {key} already exists")
        self._stamp_create(kind, stored)
        stored.metadata.resource_version = self._next_rv()
        b.objects[key] = stored
        self._sink(kind, ADDED, stored)

    def _commit_update(self, kind: str, stored: Any, check_rv: bool) -> str:
        b = self._bucket(kind)
        key = self._key(stored.metadata)
        existing = b.objects.get(key)
        if existing is None:
            raise NotFoundError(f"{kind} {key}")
        event, removed = self._stamp_update(stored, existing, check_rv, kind, key)
        stored.metadata.resource_version = self._next_rv()
        if removed:
            del b.objects[key]
        else:
            b.objects[key] = stored
        self._sink(kind, event, stored)
        return event

    def _finish(self, kind: str, event: str, stored: Any) -> Any:
        """Post-commit tail, OUTSIDE the lock: the return/watcher copy and
        the persistence + watcher-bus dispatch. Subscribers may take their
        own locks — or call back into the store — without lock-order
        inversion, on every path including apply()."""
        out = copy.deepcopy(stored)
        self._dispatch([(kind, event, out)])
        return out

    # -- CRUD -------------------------------------------------------------

    def create(self, obj: Any) -> Any:
        kind = gvk_of(obj)
        obj = self._admit_create(obj, kind)
        stored = copy.deepcopy(obj)
        with self._write_lock():
            self._commit_create(kind, stored)
        return self._finish(kind, ADDED, stored)

    def get(self, kind: str, name: str, namespace: str = "") -> Any:
        with self._lock:
            b = self._buckets.get(kind)
            key = self._name_key(name, namespace)
            if b is None or key not in b.objects:
                raise NotFoundError(f"{kind} {key}")
            obj = b.objects[key]
        # committed objects are immutable once placed: copy outside the lock
        return copy.deepcopy(obj)

    def try_get(self, kind: str, name: str, namespace: str = "") -> Optional[Any]:
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def get_batch(self, kind: str,
                  keys: Iterable[tuple[str, str]]) -> list[Optional[Any]]:
        """One lock hold for N point reads: [(name, namespace), ...] ->
        [obj | None]. The deepcopies happen outside the lock."""
        with self._lock:
            b = self._buckets.get(kind)
            refs = [
                None if b is None
                else b.objects.get(self._name_key(n, ns))
                for n, ns in keys
            ]
        return [None if o is None else copy.deepcopy(o) for o in refs]

    def list(self, kind: str, namespace: str = "") -> list[Any]:
        with self._lock:
            b = self._buckets.get(kind)
            if b is None:
                return []
            objs = list(b.objects.values())
        if namespace:
            objs = [o for o in objs if o.metadata.namespace == namespace]
        return [copy.deepcopy(o) for o in objs]

    def kinds(self) -> list[str]:
        with self._lock:
            return list(self._buckets.keys())

    @property
    def kinds_token(self) -> int:
        """Monotonic counter bumped on every kind (bucket) registration.
        A cache keyed on kinds() content revalidates with one int compare
        instead of re-listing every kind per lookup."""
        with self._lock:
            return self._kinds_token

    def update(self, obj: Any, *, check_rv: bool = False) -> Any:
        """Update; bumps generation if the spec view changed. Finalizer-gated
        deletion: if deletionTimestamp set and no finalizers remain, the
        object is removed instead."""
        kind = gvk_of(obj)
        obj = self._admit_update(obj, kind)
        stored = copy.deepcopy(obj)
        with self._write_lock():
            event = self._commit_update(kind, stored, check_rv)
        return self._finish(kind, event, stored)

    def apply(self, obj: Any) -> Any:
        """create-or-update. The create-vs-update decision is made under
        the commit lock so concurrent apply() calls cannot race each other
        into ConflictError/NotFoundError — but the admission chain (user
        code: webhooks) and the input deepcopy run OUTSIDE the hold,
        against a one-peek existence guess, exactly like `_write_batch`'s
        apply path (lock-discipline rule: the critical section is
        validate+stamp+place+sink, nothing else). A racing writer that
        flips the guess re-runs the right chain under the lock — rare,
        never silently under-admitted. Watch handlers run AFTER the hold
        drops (they used to run re-entrantly under it on this path — the
        store half of the ABBA surface)."""
        kind = gvk_of(obj)
        key = self._key(obj.metadata)
        if self._admission is not None:
            with self._lock:
                guess_exists = key in self._bucket(kind).objects
            admitted = (self._admit_update(obj, kind) if guess_exists
                        else self._admit_create(obj, kind))
        else:
            guess_exists = None  # no chain: nothing depends on the guess
            admitted = obj
        stored = copy.deepcopy(admitted)
        with self._write_lock():
            exists = key in self._bucket(kind).objects
            if self._admission is not None and exists != guess_exists:
                # the existence race flipped create<->update after the
                # pre-lock admission: re-run the right chain here (under
                # the lock — baselined, like _write_batch's twin)
                admitted = (self._admit_update(obj, kind) if exists
                            else self._admit_create(obj, kind))
                stored = copy.deepcopy(admitted)
            if exists:
                event = self._commit_update(kind, stored, False)
            else:
                self._commit_create(kind, stored)
                event = ADDED
        return self._finish(kind, event, stored)

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        """Marks deletionTimestamp; removes immediately when no finalizers."""
        if self._admission is not None:
            target = self.try_get(kind, name, namespace)
            if target is not None:
                self._admission("DELETE", kind, target, None)
        with self._write_lock():
            b = self._buckets.get(kind)
            key = self._name_key(name, namespace)
            if b is None or key not in b.objects:
                return
            # copy-on-write: committed objects are immutable once placed
            # (the watch cache retains references for lazy encoding) — the
            # marked copy REPLACES the stored object, never mutates it
            stored = copy.deepcopy(b.objects[key])
            m = stored.metadata
            if m.deletion_timestamp is None:
                m.deletion_timestamp = now()
            if m.finalizers:
                m.resource_version = self._next_rv()
                b.objects[key] = stored
                event = MODIFIED
            else:
                del b.objects[key]
                m.resource_version = self._next_rv()  # see _stamp_update
                event = DELETED
            self._sink(kind, event, stored)
        self._finish(kind, event, stored)

    @staticmethod
    def _differs(a: Any, b: Any) -> bool:
        if a is None and b is None:
            return False
        try:
            return a != b
        except Exception:
            return True

    # -- transactional batch writes ---------------------------------------

    def create_batch(self, objs: Iterable[Any]) -> list[Any]:
        """N creates admitted, validated, and committed under ONE lock hold
        with contiguous resourceVersions; the event sink sees one rv-ordered
        run and persistence one group-commit unit. All-or-nothing: any
        conflict/denial raises BatchError (typed per-object results) and
        commits nothing."""
        return self._write_batch([("create", o) for o in objs])

    def apply_batch(self, objs: Iterable[Any]) -> list[Any]:
        """Batched create-or-update; per-object semantics identical to N
        sequential apply() calls (same stamps, same events, contiguous rvs)
        at one lock hold and one WAL fsync."""
        return self._write_batch([("apply", o) for o in objs])

    def update_batch(self, objs: Iterable[Any], *, check_rv: bool = False,
                     skip_missing: bool = False,
                     skip_stale: bool = False) -> list[Optional[Any]]:
        """Batched update. `skip_missing=True` records a vanished object as
        a skipped slot (None in the result) instead of failing the batch —
        the patch-coalescing caller's tolerance for a delete racing its
        read-prepare-commit window. `skip_stale=True` (implies rv
        checking) does the same for an rv mismatch: a slot whose object
        was rewritten since the caller's read SKIPS instead of committing
        a stale full-object snapshot over the newer write — batching
        widens the read→commit window from per-object to per-cohort, and
        this is what keeps that window from ever clobbering a concurrent
        writer (the skipped slot's own change event re-converges the
        caller). NOTE for retry loops: with plain check_rv (no
        skip_stale), a replayed batch whose first attempt committed
        answers `conflict` for its own writes."""
        return self._write_batch(
            [("update", o) for o in objs],
            check_rv=check_rv or skip_stale, skip_missing=skip_missing,
            skip_stale=skip_stale,
        )

    @staticmethod
    def _abort_batch(results: list[BatchOpResult]) -> None:
        """All-or-nothing failure: objects that validated fine become
        `aborted` (retryable — they only rode a torched batch); raises."""
        for r in results:
            if r.ok:
                r.ok = False
                r.reason = "aborted"
                r.error = "batch aborted: nothing committed"
        bad = next((r for r in results if r.reason not in ("aborted", "skipped")),
                   None)
        raise BatchError(
            "batch write failed (nothing committed): "
            + (bad.error if bad is not None else "unknown"),
            results,
        )

    def _write_batch(self, ops: list[tuple[str, Any]], *,
                     check_rv: bool = False,
                     skip_missing: bool = False,
                     skip_stale: bool = False) -> list[Optional[Any]]:
        if not ops:
            return []
        from ..webhook.admission import AdmissionDenied  # optional layer

        n = len(ops)
        results = [BatchOpResult(ok=True) for _ in range(n)]
        failed = False

        # phase 1 — NO lock held across it: admission chains + input
        # deepcopies. The create-vs-update admission choice for "apply"
        # rides ONE existence-peek lock hold (skipped entirely without an
        # admission chain — phase 2 resolves the real op either way) and
        # is re-checked under the commit lock (a racing writer flipping it
        # re-runs the right chain there).
        guesses: dict[int, str] = {}
        if self._admission is not None and any(op == "apply" for op, _ in ops):
            with self._lock:
                for i, (op, obj) in enumerate(ops):
                    if op != "apply":
                        continue
                    b = self._buckets.get(gvk_of(obj))
                    guesses[i] = (
                        "update" if b is not None
                        and self._key(obj.metadata) in b.objects
                        else "create"
                    )
        prepped: list[Optional[tuple[str, str, str, Any, Any]]] = [None] * n
        for i, (op, obj) in enumerate(ops):
            kind = gvk_of(obj)
            eff = guesses.get(i, "create") if op == "apply" else op
            try:
                admitted = (self._admit_update(obj, kind) if eff == "update"
                            else self._admit_create(obj, kind))
            except AdmissionDenied as e:
                results[i] = BatchOpResult(False, reason="admission",
                                           error=str(e))
                failed = True
                continue
            prepped[i] = (op, eff, kind, copy.deepcopy(admitted), obj)
        if failed:
            self._abort_batch(results)

        # phase 2 — ONE lock hold: validate every op against an overlay of
        # the batch's own effects (in-batch create→update sequences behave
        # exactly like the sequential calls), then commit with contiguous
        # rvs, feeding the event sink in rv order. The buckets are not
        # touched until the whole batch validated.
        staged: list[Optional[tuple[str, str, Any, str, bool]]] = [None] * n
        events: list[tuple[int, str, str, Any]] = []
        with self._write_lock():
            overlay: dict[tuple[str, str], Any] = {}
            for i in range(n):
                op, eff_guess, kind, stored, raw = prepped[i]
                key = self._key(stored.metadata)
                okey = (kind, key)
                if okey in overlay:
                    existing = overlay[okey]
                    if existing is _REMOVED:
                        existing = None
                else:
                    b = self._buckets.get(kind)
                    existing = None if b is None else b.objects.get(key)
                eff = op
                if op == "apply":
                    eff = "update" if existing is not None else "create"
                    if eff != eff_guess and self._admission is not None:
                        # the existence race flipped create<->update after
                        # phase-1 admission: re-run the right chain (under
                        # the lock — rare, never silently under-admitted)
                        try:
                            admitted = (
                                self._admit_update(raw, kind) if eff == "update"
                                else self._admit_create(raw, kind)
                            )
                        except AdmissionDenied as e:
                            results[i] = BatchOpResult(
                                False, reason="admission", error=str(e))
                            failed = True
                            continue
                        stored = copy.deepcopy(admitted)
                if eff == "create":
                    if existing is not None:
                        results[i] = BatchOpResult(
                            False, reason="conflict",
                            error=f"{kind} {key} already exists")
                        failed = True
                        continue
                    self._stamp_create(kind, stored)
                    staged[i] = (kind, key, stored, ADDED, False)
                    overlay[okey] = stored
                else:
                    if existing is None:
                        if skip_missing:
                            results[i] = BatchOpResult(
                                False, reason="skipped",
                                error=f"{kind} {key} not found")
                            continue
                        results[i] = BatchOpResult(
                            False, reason="not-found",
                            error=f"{kind} {key}")
                        failed = True
                        continue
                    try:
                        event, removed = self._stamp_update(
                            stored, existing, check_rv, kind, key)
                    except ConflictError as e:
                        if skip_stale:
                            results[i] = BatchOpResult(
                                False, reason="skipped", error=str(e))
                            continue
                        results[i] = BatchOpResult(
                            False, reason="conflict", error=str(e))
                        failed = True
                        continue
                    staged[i] = (kind, key, stored, event, removed)
                    overlay[okey] = _REMOVED if removed else stored
            if failed:
                self._abort_batch(results)  # raises; lock releases
            for i in range(n):
                st = staged[i]
                if st is None:
                    continue
                kind, key, stored, event, removed = st
                stored.metadata.resource_version = self._next_rv()
                b = self._bucket(kind)
                if removed:
                    b.objects.pop(key, None)
                else:
                    b.objects[key] = stored
                self._sink(kind, event, stored)
                events.append((i, kind, event, stored))
        txn_batch_size.observe(float(len(events)))

        # phase 3 — outside the lock: watcher/return copies + dispatch (the
        # whole batch reaches persistence as ONE watch_all_batch call)
        outs: list[Optional[Any]] = [None] * n
        dispatch: list[tuple[str, str, Any]] = []
        for i, kind, event, stored in events:
            out = copy.deepcopy(stored)
            outs[i] = out
            results[i].obj = out
            dispatch.append((kind, event, out))
        self._dispatch(dispatch)
        return outs

    # -- replication (store/replication.py) --------------------------------

    @property
    def current_rv(self) -> int:
        with self._lock:
            return self._rv

    def apply_replicated(self, records: list[tuple[str, str, Any]]) -> int:
        """Follower-side commit of one leader log entry: `records` is a
        list of (kind, event, obj) whose resourceVersions were minted BY
        THE LEADER and must continue this store's sequence exactly
        (leader commits are globally contiguous, so a follower applying
        every entry in order holds the leader's byte-exact state at every
        applied rv — same watch-cache event stream, same snapshot pages).

        One lock hold for the whole entry, rv continuity validated BEFORE
        anything is applied (no partial entries), events fed to the
        under-lock sink with their ORIGINAL type and rv so the follower's
        revisioned watch cache is indistinguishable from the leader's.
        The post-lock dispatch reaches persistence as one batch — one WAL
        group-commit fsync per entry, mirroring the leader — so when this
        returns the entry is durable and the follower may ack it."""
        if not records:
            return self.current_rv
        with self._write_lock():
            base = self._rv
            for i, (kind, event, obj) in enumerate(records):
                rv = obj.metadata.resource_version
                if rv != base + 1 + i:
                    raise ReplicationGapError(
                        f"replication gap: record {i} of entry carries rv "
                        f"{rv}, follower expects {base + 1 + i}",
                        base + 1,
                    )
            for kind, event, obj in records:
                self._rv = obj.metadata.resource_version
                b = self._bucket(kind)
                key = self._key(obj.metadata)
                if event == DELETED:
                    b.objects.pop(key, None)
                else:
                    # decoded fresh off the wire: committed as-is, and the
                    # immutable-once-placed contract holds (a later entry
                    # REPLACES it, never mutates)
                    b.objects[key] = obj
                self._sink(kind, event, obj)
            tip = self._rv
        self._dispatch([
            (kind, event, copy.deepcopy(obj)) for kind, event, obj in records
        ])
        return tip

    def snapshot_state(self) -> tuple[int, list[tuple[str, Any]]]:
        """Revision-consistent full dump for replication catch-up: one
        lock hold pins (rv, [(kind, obj), ...]); the deepcopies happen
        outside it. The counterpart of load_snapshot() on the follower."""
        with self._lock:
            rv = self._rv
            refs = [
                (kind, o)
                for kind, b in self._buckets.items()
                for o in b.objects.values()
            ]
        return rv, [(kind, copy.deepcopy(o)) for kind, o in refs]

    def load_snapshot(self, rv: int, objects: Iterable[Any]) -> int:
        """Replication catch-up: replace the whole state with a leader
        snapshot pinned at `rv` and adopt that rv exactly, so subsequent
        log entries (rv+1, ...) continue the sequence. Only moves FORWARD
        (a follower needs a snapshot because it is behind). Event sinks
        may detach for the swap (the server detaches and re-attaches its
        watch cache around this call — re-attach primes a revision-
        consistent index); sinks that STAY attached receive the
        transition under the lock as DELETED-for-vanished + ADDED-for-all
        in rv order (how a follower's search ingest survives a catch-up
        snapshot), and the watcher bus and persistence get the same
        events post-lock, so a follower's WAL replays to the snapshot
        state."""
        objs = sorted(objects, key=lambda o: o.metadata.resource_version)
        dispatch: list[tuple[str, str, Any]] = []
        with self._write_lock():
            if rv < self._rv:
                raise ConflictError(
                    f"snapshot at rv {rv} is behind this store's rv "
                    f"{self._rv}"
                )
            old = {
                (kind, key): o
                for kind, b in self._buckets.items()
                for key, o in b.objects.items()
            }
            for b in self._buckets.values():
                # keep the buckets themselves: their watcher lists are
                # live subscriptions that must survive the state swap
                b.objects = {}
            seen: set[tuple[str, str]] = set()
            for obj in objs:
                kind = gvk_of(obj)
                key = self._key(obj.metadata)
                self._bucket(kind).objects[key] = obj
                seen.add((kind, key))
            self._rv = rv
            for (kind, key), o in old.items():
                if (kind, key) not in seen:
                    dispatch.append((kind, DELETED, o))
                    self._sink(kind, DELETED, o)
            for obj in objs:
                self._sink(gvk_of(obj), ADDED, obj)
        dispatch += [(gvk_of(o), ADDED, o) for o in objs]
        self._dispatch([
            (kind, event, copy.deepcopy(o)) for kind, event, o in dispatch
        ])
        return len(objs)

    # -- restore (persistence) --------------------------------------------

    def restore(self, objects: Iterable[Any]) -> int:
        """Load persisted objects verbatim — uid/resourceVersion/generation
        kept, admission NOT re-run (the reference's apiserver does not
        re-admit etcd content on restart). Watchers are notified ADDED so
        already-subscribed level-triggered controllers converge, exactly as
        an informer relist would deliver the initial state."""
        # input deepcopies BEFORE the lock (lock-discipline): restore runs
        # at boot, but a replication snapshot can land mid-flight and the
        # hold must stay validate+stamp+place+sink there too
        incoming = [(gvk_of(o), copy.deepcopy(o)) for o in objects]
        loaded: list[tuple[str, Any]] = []
        with self._lock:
            for kind, stored in incoming:
                b = self._bucket(kind)
                b.objects[self._key(stored.metadata)] = stored
                self._rv = max(self._rv, stored.metadata.resource_version)
                # restored rvs arrive in file order, not rv order — the
                # watch cache treats a non-monotonic rv as a compaction
                # point (no since-resume across a restore), so feeding them
                # here keeps its snapshot index complete without games
                self._sink(kind, ADDED, stored)
                loaded.append((kind, stored))
        self._dispatch([
            (kind, ADDED, copy.deepcopy(stored)) for kind, stored in loaded
        ])
        return len(loaded)

    # -- watch ------------------------------------------------------------

    def watch(self, kind: str, handler: WatchHandler, *, replay: bool = True,
              namespace: str = "") -> None:
        """Subscribe; with replay=True existing objects are delivered as ADDED
        first (informer 'list+watch' semantics). A non-empty `namespace`
        scopes delivery — the reference agent's informers are scoped to its
        execution namespace the same way (agent.go:248-433)."""
        with self._lock:
            self._bucket(kind).watchers.append((handler, namespace))
            refs = [
                o for o in self._buckets[kind].objects.values()
                if not namespace or o.metadata.namespace == namespace
            ]
        # committed objects are immutable once placed: refs under the
        # lock, replay copies outside it (lock-discipline)
        if replay:
            for o in refs:
                handler(ADDED, copy.deepcopy(o))

    def unwatch(self, kind: str, handler: WatchHandler) -> None:
        """Drop a kind subscription (a disconnected watch stream must not
        keep filling a dead queue)."""
        with self._lock:
            b = self._buckets.get(kind)
            if b is not None:
                # equality, not identity: bound-method handlers compare ==
                # across separate attribute accesses but are never `is`
                b.watchers = [
                    (h, ns) for h, ns in b.watchers if h != handler
                ]

    def unwatch_all(self, handler: Callable[[str, str, Any], None]) -> None:
        with self._lock:
            if handler in self._all_watchers:
                self._all_watchers.remove(handler)

    def watch_all(self, handler: Callable[[str, str, Any], None], *, replay: bool = True) -> None:
        """Subscribe to every kind: handler(kind, event, obj). Used by the
        detector's dynamic-informer sweep (detector.go:112)."""
        with self._lock:
            self._all_watchers.append(handler)
            refs = [
                (kind, o)
                for kind, b in self._buckets.items()
                for o in b.objects.values()
            ]
        # immutable-once-placed: copy outside the hold (lock-discipline)
        if replay:
            for kind, o in refs:
                handler(kind, ADDED, copy.deepcopy(o))

    def watch_all_batch(
        self, handler: Callable[[list[tuple[str, str, Any]]], None]
    ) -> None:
        """Subscribe to commit batches: handler(events) with `events` a list
        of (kind, event, obj) in commit (resourceVersion) order. Single
        writes arrive as one-element batches; a transactional batch write
        arrives as ONE call — the seam the WAL's group commit turns into a
        single fsync. Runs outside the store lock, like the watcher bus."""
        with self._lock:
            self._batch_watchers.append(handler)

    def unwatch_all_batch(
        self, handler: Callable[[list[tuple[str, str, Any]]], None]
    ) -> None:
        with self._lock:
            if handler in self._batch_watchers:
                self._batch_watchers.remove(handler)

    def _dispatch(self, events: list[tuple[str, str, Any]]) -> None:
        """Deliver committed events to subscribers — always OUTSIDE the
        store lock. Batch watchers (persistence, replication) get the
        whole rv-ordered list first, so a mutator returns only after its
        records are durable; the kind/all watcher bus then fans out per
        event. Per-key ordering across RACING writers remains the sink's
        contract (under-lock sequencing), not the bus's.

        A batch watcher that RAISES (WAL write failure, replication
        quorum timeout) surfaces its error to the mutator — but the
        events are already committed to the store, so the per-event bus
        fan-out still runs first (finally): level-triggered subscribers
        must converge on committed state even when its durability or
        replication promise failed."""
        if not events:
            return
        with self._lock:
            batch_watchers = list(self._batch_watchers)
        try:
            for bw in batch_watchers:
                bw(events)
        finally:
            for kind, event, obj in events:
                self._notify(kind, event, obj)

    def _notify(self, kind: str, event: str, obj: Any) -> None:
        """Watcher-bus fan-out for one event; never called with the store
        lock held (see _dispatch)."""
        with self._lock:
            watchers = list(self._buckets[kind].watchers)
            all_watchers = list(self._all_watchers)
        ns = obj.metadata.namespace
        for w, want_ns in watchers:
            if not want_ns or ns == want_ns:
                w(event, obj)
        for w in all_watchers:
            w(kind, event, obj)

"""In-memory versioned object store with a watch bus.

This is the control-plane storage/API layer (SURVEY L1 / D1): the reference
uses a stock kube-apiserver + etcd with level-triggered informers; we provide
the same contract — versioned objects, generation bumps on spec change, watch
events, finalizer-gated deletion — as an in-process store so every controller
can stay level-triggered and resumable (reference invariant: all state is CRDs,
device state is a rebuildable cache; SURVEY §5 checkpoint note).

Thread-safety: a single RLock guards all maps; watch delivery is synchronous
(callbacks run under the caller, outside the lock) feeding controller queues.
"""
from __future__ import annotations

import copy
import threading
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional

from ..api.meta import ObjectMeta, new_uid, now
from ..api.unstructured import Unstructured

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

WatchHandler = Callable[[str, Any], None]  # (event_type, obj)


class ConflictError(Exception):
    pass


class NotFoundError(KeyError):
    pass


def gvk_of(obj: Any) -> str:
    """Store key kind. Typed objects use their dataclass kind; unstructured
    use apiVersion+kind so e.g. apps/v1/Deployment is distinct."""
    if isinstance(obj, Unstructured):
        return f"{obj.api_version}/{obj.kind}"
    return obj.kind


@dataclass
class _Bucket:
    objects: dict[str, Any]
    watchers: list[tuple[WatchHandler, str]]  # (handler, namespace filter)


class Store:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._buckets: dict[str, _Bucket] = {}
        self._rv = 0
        self._all_watchers: list[Callable[[str, str, Any], None]] = []
        # event sinks run UNDER the mutation lock, at the point the rv is
        # assigned — unlike watchers (notified after the lock drops, so two
        # racing mutators may interleave), a sink observes the event log in
        # strict resourceVersion order. This is the feed for the revisioned
        # watch cache (store/watchcache.py); sinks must be fast and must
        # never call back into the store.
        self._event_sinks: list[Callable[[str, str, Any], None]] = []
        # admission chain (op, kind, obj, old) -> obj; raises to deny —
        # the apiserver admission path (reference: pkg/webhook/* handlers)
        self._admission: Optional[Callable[[str, str, Any, Any], Any]] = None

    def set_admission(self, admit: Callable[[str, str, Any, Any], Any]) -> None:
        self._admission = admit

    def add_event_sink(self, sink: Callable[[str, str, Any], None], *,
                       prime: Optional[Callable[[str, Any], None]] = None) -> int:
        """Register an under-lock, rv-ordered event sink. The object passed
        is the same post-mutation copy watchers receive; sinks needing to
        retain it beyond the call must take their own copy (the watch cache
        retains only the wire encoding).

        `prime(kind, obj)` — when given — is called under the same lock hold
        for every object already stored, so a cache attaches with a snapshot
        index that is revision-consistent with the event feed (no mutation
        can land between the prime sweep and the first sinked event).
        Returns the store's current resourceVersion at attach time."""
        with self._lock:
            if prime is not None:
                for kind, b in self._buckets.items():
                    for o in b.objects.values():
                        prime(kind, copy.deepcopy(o))
            self._event_sinks.append(sink)
            return self._rv

    def remove_event_sink(self, sink: Callable[[str, str, Any], None]) -> None:
        with self._lock:
            if sink in self._event_sinks:
                self._event_sinks.remove(sink)

    def _sink(self, kind: str, event: str, obj: Any) -> None:
        """Feed event sinks; caller MUST hold self._lock."""
        for s in self._event_sinks:
            s(kind, event, obj)

    # -- helpers ----------------------------------------------------------

    def _bucket(self, kind: str) -> _Bucket:
        b = self._buckets.get(kind)
        if b is None:
            b = _Bucket(objects={}, watchers=[])
            self._buckets[kind] = b
        return b

    @staticmethod
    def _key(meta: ObjectMeta) -> str:
        return meta.key()

    @staticmethod
    def _name_key(name: str, namespace: str) -> str:
        return ObjectMeta(name=name, namespace=namespace).key()

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def _peek_deletion_timestamp(self, kind: str, name: str, namespace: str):
        """Copy-free read of a stored object's deletionTimestamp (hot path:
        every update consults this for the removal-transition check)."""
        with self._lock:
            b = self._buckets.get(kind)
            if b is None:
                return None
            obj = b.objects.get(self._name_key(name, namespace))
            return None if obj is None else obj.metadata.deletion_timestamp

    @staticmethod
    def _spec_view(obj: Any) -> Any:
        """The part whose change bumps generation (k8s semantics: spec only)."""
        if isinstance(obj, Unstructured):
            d = obj.to_dict()
            d.pop("status", None)
            d.pop("metadata", None)
            return d
        spec = getattr(obj, "spec", None)
        return spec

    # -- CRUD -------------------------------------------------------------

    def create(self, obj: Any) -> Any:
        kind = gvk_of(obj)
        if self._admission is not None:
            obj = self._admission("CREATE", kind, obj, None)
        with self._lock:
            b = self._bucket(kind)
            key = self._key(obj.metadata)
            if key in b.objects:
                raise ConflictError(f"{kind} {key} already exists")
            stored = copy.deepcopy(obj)
            m = stored.metadata
            if not m.uid:
                m.uid = new_uid(kind.split("/")[-1].lower())
            m.creation_timestamp = m.creation_timestamp or now()
            m.resource_version = self._next_rv()
            m.generation = 1
            b.objects[key] = stored
            out = copy.deepcopy(stored)
            self._sink(kind, ADDED, out)
        self._notify(kind, ADDED, out)
        return out

    def get(self, kind: str, name: str, namespace: str = "") -> Any:
        with self._lock:
            b = self._buckets.get(kind)
            key = self._name_key(name, namespace)
            if b is None or key not in b.objects:
                raise NotFoundError(f"{kind} {key}")
            return copy.deepcopy(b.objects[key])

    def try_get(self, kind: str, name: str, namespace: str = "") -> Optional[Any]:
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: str = "") -> list[Any]:
        with self._lock:
            b = self._buckets.get(kind)
            if b is None:
                return []
            objs = b.objects.values()
            if namespace:
                objs = [o for o in objs if o.metadata.namespace == namespace]
            return [copy.deepcopy(o) for o in objs]

    def kinds(self) -> list[str]:
        with self._lock:
            return list(self._buckets.keys())

    def update(self, obj: Any, *, check_rv: bool = False) -> Any:
        """Update; bumps generation if the spec view changed. Finalizer-gated
        deletion: if deletionTimestamp set and no finalizers remain, the
        object is removed instead."""
        kind = gvk_of(obj)
        if self._admission is not None:
            name, ns = obj.metadata.name, obj.metadata.namespace
            obj = self._admission("UPDATE", kind, obj, lambda: self.try_get(kind, name, ns))
            # an update that transitions into removal (deletionTimestamp set,
            # no finalizers left) IS a delete — run DELETE admission so
            # deletion protection cannot be bypassed via update()
            if not obj.metadata.finalizers and (
                obj.metadata.deletion_timestamp is not None
                or self._peek_deletion_timestamp(kind, name, ns) is not None
            ):
                self._admission("DELETE", kind, obj, None)
        with self._lock:
            b = self._bucket(kind)
            key = self._key(obj.metadata)
            existing = b.objects.get(key)
            if existing is None:
                raise NotFoundError(f"{kind} {key}")
            if check_rv and obj.metadata.resource_version != existing.metadata.resource_version:
                raise ConflictError(
                    f"{kind} {key}: rv {obj.metadata.resource_version} != {existing.metadata.resource_version}"
                )
            stored = copy.deepcopy(obj)
            m = stored.metadata
            m.uid = existing.metadata.uid
            m.creation_timestamp = existing.metadata.creation_timestamp
            m.generation = existing.metadata.generation
            # deletionTimestamp is immutable once set (k8s semantics): a stale
            # writer must not resurrect an object already marked for deletion.
            if existing.metadata.deletion_timestamp is not None:
                m.deletion_timestamp = existing.metadata.deletion_timestamp
            if self._differs(self._spec_view(existing), self._spec_view(stored)):
                m.generation += 1
            if m.deletion_timestamp is not None and not m.finalizers:
                del b.objects[key]
                # removal gets a FRESH rv: a DELETED event must order after
                # every prior write of the object (WAL replay is rv-ordered)
                m.resource_version = self._next_rv()
                out = copy.deepcopy(stored)
                deleted = True
            else:
                m.resource_version = self._next_rv()
                b.objects[key] = stored
                out = copy.deepcopy(stored)
                deleted = False
            self._sink(kind, DELETED if deleted else MODIFIED, out)
        self._notify(kind, DELETED if deleted else MODIFIED, out)
        return out

    def apply(self, obj: Any) -> Any:
        """create-or-update. The existence check and the inner create/update
        run under one reentrant-lock hold so concurrent apply() calls cannot
        race each other into ConflictError/NotFoundError. Watch handlers must
        stay enqueue-only (they may run with the lock held on this path)."""
        kind = gvk_of(obj)
        key = self._key(obj.metadata)
        with self._lock:
            exists = key in self._bucket(kind).objects
            return self.update(obj) if exists else self.create(obj)

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        """Marks deletionTimestamp; removes immediately when no finalizers."""
        if self._admission is not None:
            target = self.try_get(kind, name, namespace)
            if target is not None:
                self._admission("DELETE", kind, target, None)
        with self._lock:
            b = self._buckets.get(kind)
            key = self._name_key(name, namespace)
            if b is None or key not in b.objects:
                return
            obj = b.objects[key]
            if obj.metadata.deletion_timestamp is None:
                obj.metadata.deletion_timestamp = now()
            if obj.metadata.finalizers:
                obj.metadata.resource_version = self._next_rv()
                out = copy.deepcopy(obj)
                deleted = False
            else:
                del b.objects[key]
                obj.metadata.resource_version = self._next_rv()  # see update()
                out = copy.deepcopy(obj)
                deleted = True
            self._sink(kind, DELETED if deleted else MODIFIED, out)
        self._notify(kind, DELETED if deleted else MODIFIED, out)

    @staticmethod
    def _differs(a: Any, b: Any) -> bool:
        if a is None and b is None:
            return False
        try:
            return a != b
        except Exception:
            return True

    # -- restore (persistence) --------------------------------------------

    def restore(self, objects: Iterable[Any]) -> int:
        """Load persisted objects verbatim — uid/resourceVersion/generation
        kept, admission NOT re-run (the reference's apiserver does not
        re-admit etcd content on restart). Watchers are notified ADDED so
        already-subscribed level-triggered controllers converge, exactly as
        an informer relist would deliver the initial state."""
        loaded = []
        with self._lock:
            for obj in objects:
                kind = gvk_of(obj)
                b = self._bucket(kind)
                stored = copy.deepcopy(obj)
                b.objects[self._key(stored.metadata)] = stored
                self._rv = max(self._rv, stored.metadata.resource_version)
                out = copy.deepcopy(stored)
                # restored rvs arrive in file order, not rv order — the
                # watch cache treats a non-monotonic rv as a compaction
                # point (no since-resume across a restore), so feeding them
                # here keeps its snapshot index complete without games
                self._sink(kind, ADDED, out)
                loaded.append((kind, out))
        for kind, obj in loaded:
            self._notify(kind, ADDED, obj)
        return len(loaded)

    # -- watch ------------------------------------------------------------

    def watch(self, kind: str, handler: WatchHandler, *, replay: bool = True,
              namespace: str = "") -> None:
        """Subscribe; with replay=True existing objects are delivered as ADDED
        first (informer 'list+watch' semantics). A non-empty `namespace`
        scopes delivery — the reference agent's informers are scoped to its
        execution namespace the same way (agent.go:248-433)."""
        with self._lock:
            self._bucket(kind).watchers.append((handler, namespace))
            snapshot = [
                copy.deepcopy(o)
                for o in self._buckets[kind].objects.values()
                if not namespace or o.metadata.namespace == namespace
            ]
        if replay:
            for o in snapshot:
                handler(ADDED, o)

    def unwatch(self, kind: str, handler: WatchHandler) -> None:
        """Drop a kind subscription (a disconnected watch stream must not
        keep filling a dead queue)."""
        with self._lock:
            b = self._buckets.get(kind)
            if b is not None:
                # equality, not identity: bound-method handlers compare ==
                # across separate attribute accesses but are never `is`
                b.watchers = [
                    (h, ns) for h, ns in b.watchers if h != handler
                ]

    def unwatch_all(self, handler: Callable[[str, str, Any], None]) -> None:
        with self._lock:
            if handler in self._all_watchers:
                self._all_watchers.remove(handler)

    def watch_all(self, handler: Callable[[str, str, Any], None], *, replay: bool = True) -> None:
        """Subscribe to every kind: handler(kind, event, obj). Used by the
        detector's dynamic-informer sweep (detector.go:112)."""
        with self._lock:
            self._all_watchers.append(handler)
            snapshot = [
                (kind, copy.deepcopy(o))
                for kind, b in self._buckets.items()
                for o in b.objects.values()
            ]
        if replay:
            for kind, o in snapshot:
                handler(kind, ADDED, o)

    def _notify(self, kind: str, event: str, obj: Any) -> None:
        with self._lock:
            watchers = list(self._buckets[kind].watchers)
            all_watchers = list(self._all_watchers)
        ns = obj.metadata.namespace
        for w, want_ns in watchers:
            if not want_ns or ns == want_ns:
                w(event, obj)
        for w in all_watchers:
            w(kind, event, obj)

"""Durable control-plane state: snapshot + write-ahead log over the store.

The reference's L1 persists in etcd; every controller is level-triggered
and resumes from informer cache (SURVEY §5 checkpoint note). This module
is that durability for the TPU build's store: every watch event appends a
codec-encoded JSON line to `wal.jsonl`; a periodic (or explicit) snapshot
rotates the WAL aside, rewrites `snapshot.jsonl` atomically, then drops
the rotated WAL; `load()` replays whatever files survive into the store
via `Store.restore`, which notifies subscribers as ADDED — so a daemon
started with `--data-dir` converges to its pre-restart state the way
controllers converge after an informer relist.

Crash-safety without ordering games: replay applies a record only when its
resourceVersion is >= the highest seen for that object key (store RVs are
monotonic), so snapshot + rotated WAL + live WAL merge correctly no matter
which rename a crash interrupted, and a torn tail line just ends that
file's replay.

Group commit (docs/PERF.md "Control-plane read path"): concurrent events
coalesce into ONE buffered write + fsync. The first appender to find no
commit in flight becomes the batch leader; appenders arriving while it is
on the disk ride its batch (or the next one) and merely wait for their
record's sequence to commit. Durability contract: when `_on_event`
returns, the record IS on disk (fsync'd) — under W concurrent writers the
write path pays ~1 fsync per batch instead of per record, which is what
keeps write p99 flat while thousands of watch clients hammer the same
plane. Transactional batch writes (Store.apply_batch and friends) arrive
through the store's batch seam as ONE enqueue, so a single writer's
N-object transaction is also one fsync — group commit alone only coalesced
across threads. `fsync=False` keeps the pre-group-commit flush-only
behavior (process-crash-safe, not power-loss-safe) for tests and
benchmarks.

Device state needs no persistence at all: the fleet arrays are a pure
cache rebuilt from the Cluster objects this file restores. Member-cluster
SIMULATIONS are not persisted — they stand in for real clusters, which
survive a control-plane restart on their own (push members re-join via
flags/CLI; pull agents re-register and their works re-apply).
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Optional

from ..server import codec
from .store import DELETED, Store

SNAPSHOT_FILE = "snapshot.jsonl"
WAL_FILE = "wal.jsonl"
WAL_ROTATED = "wal.1.jsonl"


class StorePersistence:
    def __init__(self, store: Store, data_dir: str, *,
                 snapshot_every: int = 5000, fsync: bool = True):
        self.store = store
        self.data_dir = data_dir
        self.snapshot_every = snapshot_every
        self.fsync = fsync
        os.makedirs(data_dir, exist_ok=True)
        # guards pending-batch state + the WAL handle pointer — never call
        # into the store while holding it (watch handlers can run with the
        # store lock held). Disk I/O happens OUTSIDE it, under _io_lock,
        # so appenders can queue behind an in-flight fsync.
        self._lock = threading.Lock()
        self._commit_cv = threading.Condition(self._lock)
        self._io_lock = threading.Lock()  # serializes write/fsync + rotation
        self._pending: list[str] = []
        self._seq = 0            # sequence of the newest enqueued record
        self._committed_seq = 0  # sequence through which disk is current
        self._committing = False
        # last failed batch: (through-seq, exception) — riders whose record
        # was in it must raise too, not report durability that didn't happen
        self._commit_error: Optional[tuple[int, BaseException]] = None
        self._wal: Optional[Any] = None
        self._wal_len = 0
        self._attached = False

    # -- restore ----------------------------------------------------------

    def load(self) -> int:
        """Replay snapshot + rotated WAL + WAL into the store. Call after
        the consuming controllers subscribed (they receive the state as
        ADDED events, like an informer's initial list) and before
        attach().

        Torn-tail hardening: a truncated/corrupt FINAL record (crash or
        SIGKILL mid-append — routine once replication replays partial
        logs) is logged loudly and the live WAL is TRUNCATED back to the
        last whole record, so the next attach() appends at a clean record
        boundary instead of gluing new records onto a torn line. A
        corrupt record in the MIDDLE of a file (bit rot, interrupted
        rotation merge) is logged and skipped — it must not silently drop
        every record after it, as the old break-on-first-error did."""
        latest: dict[tuple, tuple[int, Any]] = {}  # key -> (rv, obj|None)
        for name in (SNAPSHOT_FILE, WAL_ROTATED, WAL_FILE):
            path = self._path(name)
            if not os.path.exists(path):
                continue
            # only the live WAL is repaired in place: it is the one file
            # attach() will append to (the snapshot/rotated files are
            # read-only history, rewritten wholesale by snapshot())
            self._replay_file(path, latest, repair=(name == WAL_FILE))
        return self.store.restore(
            obj for _, obj in latest.values() if obj is not None
        )

    def _replay_file(self, path: str, latest: dict, repair: bool) -> None:
        """Streamed replay with byte-offset bookkeeping: one line in
        memory at a time (a WAL can be hundreds of MB between snapshots),
        a one-line lookahead distinguishing the FINAL record (torn-tail
        candidate) from a corrupt mid-file one."""
        import logging

        log = logging.getLogger(__name__)
        pos = 0
        good_end = 0  # byte offset just past the last whole record
        # a final record that parses but lost its trailing newline (the
        # crash tore exactly the separator): keep it, but repair must
        # restore the newline or the next append glues onto it
        needs_newline = False
        f = open(path, "rb")
        try:
            raw = f.readline()
            while raw:
                nxt = f.readline()
                is_last = not nxt
                next_pos = pos + len(raw)
                line = raw.strip()
                if not line:
                    pos = good_end = next_pos
                    raw = nxt
                    continue
                try:
                    rec = json.loads(line.decode())
                    if not isinstance(rec, dict):
                        # `123` or `"x"` is valid JSON but not a record —
                        # treat exactly like an unparseable line
                        raise ValueError("non-object WAL record")
                except (UnicodeDecodeError, json.JSONDecodeError,
                        ValueError):
                    if is_last:
                        # torn tail: the crash interrupted the final
                        # append. The record was never group-commit-acked
                        # as a whole line, so dropping it loses nothing
                        # durably promised.
                        log.warning(
                            "WAL %s: torn final record (%d trailing "
                            "bytes); truncating to the last whole record "
                            "at offset %d",
                            path, next_pos - good_end, good_end,
                        )
                        if repair:
                            f.close()
                            with open(path, "rb+") as rf:
                                rf.truncate(good_end)
                        return
                    log.warning(
                        "WAL %s: corrupt mid-file record at offset %d "
                        "(%d bytes); skipping it and continuing the "
                        "replay", path, pos, len(line),
                    )
                    pos = next_pos
                    raw = nxt
                    continue
                pos = good_end = next_pos
                needs_newline = not raw.endswith(b"\n")
                raw = nxt
                self._apply_record(rec, latest, path, log)
        finally:
            if not f.closed:
                f.close()
        if repair and needs_newline:
            log.warning(
                "WAL %s: final record lost its newline separator; "
                "restoring it so the next append starts a fresh line",
                path,
            )
            with open(path, "ab") as af:
                af.write(b"\n")

    @staticmethod
    def _apply_record(rec: dict, latest: dict, path: str, log) -> None:
        try:
            obj = codec.decode(rec["obj"])
        except Exception as e:  # noqa: BLE001 - one bad record
            # must not drop the rest of the file (a decode failure is
            # schema drift/corruption, not a tail)
            log.warning(
                "skipping undecodable %s record in %s: %s",
                rec.get("kind"), path, e,
            )
            return
        key = (rec["kind"], obj.metadata.namespace, obj.metadata.name)
        rv = obj.metadata.resource_version
        if key in latest and rv < latest[key][0]:
            return  # older than what another file delivered
        latest[key] = (rv, None if rec["event"] == DELETED else obj)

    # -- capture ----------------------------------------------------------

    def attach(self) -> None:
        """Subscribe to the store and append every event to the WAL. The
        subscription rides the BATCH seam (`Store.watch_all_batch`): a
        transactional batch write is delivered as one call, so its records
        enter the group commit as one unit — one buffered write + fsync for
        the whole batch, even from a single writer thread (the per-event
        bus would pay one leader election and fsync per record there)."""
        if self._attached:
            return
        self._attached = True
        with self._lock:
            self._open_wal()
        self.store.watch_all_batch(self._on_events)

    def _on_event(self, kind: str, event: str, obj: Any) -> None:
        """Single-record append (kept for callers/tests that feed events
        directly); equivalent to a one-element batch."""
        self._on_events([(kind, event, obj)])

    def _on_events(self, records: list) -> None:
        """Group commit: enqueue the records, then either lead a batch to
        disk or wait for the leader whose batch includes them. Returns only
        once every record is durably written (fsync'd when self.fsync)."""
        if not records:
            return
        # codec work outside every lock: appenders encode concurrently
        lines = [
            json.dumps({"kind": k, "event": ev, "obj": codec.encode(o)})
            for k, ev, o in records
        ]
        lead = False
        need_snapshot = False
        with self._commit_cv:
            if self._wal is None:
                return
            self._pending.extend(lines)
            self._seq += len(lines)
            my_seq = self._seq
            while self._committed_seq < my_seq:
                if not self._committing:
                    self._committing = True
                    lead = True
                    break  # this thread leads the next batch
                self._commit_cv.wait()
                if self._wal is None:
                    return  # closed mid-wait
            if lead:
                batch = self._pending
                self._pending = []
                batch_hi = self._seq
            else:
                # a rider of a FAILED batch must raise like its leader did
                # (the durability contract is per record, not per leader) —
                # any rider with my_seq <= the failed batch's high seq had
                # its record captured in that batch
                err = self._commit_error
                if err is not None and my_seq <= err[0]:
                    raise OSError(
                        f"WAL group commit failed: {err[1]}") from err[1]
            # followers return without re-checking the snapshot threshold:
            # the batch leader triggers it, so a batch crossing the line
            # causes ONE snapshot, not one per rider
        if lead:
            committed = False
            failure: Optional[BaseException] = None
            try:
                committed = self._commit_batch(batch)
            except BaseException as e:
                failure = e
                raise
            finally:
                # on a failed commit (disk full, EIO) the leadership and
                # the sequence MUST still advance — otherwise every later
                # write parks forever on _commit_cv. The error surfaces to
                # the leader's mutator AND to every rider of this batch.
                with self._commit_cv:
                    self._committed_seq = batch_hi
                    self._committing = False
                    if failure is not None:
                        self._commit_error = (batch_hi, failure)
                    if committed:
                        self._wal_len += len(batch)
                    need_snapshot = self._wal_len >= self.snapshot_every
                    self._commit_cv.notify_all()
        if need_snapshot:
            self.snapshot()

    def _commit_batch(self, batch: list[str]) -> bool:
        """One buffered write + flush (+ fsync) for the whole batch."""
        from ..metrics import wal_fsync_batch_size

        with self._io_lock:
            wal = self._wal
            if wal is None or not batch:
                return False
            wal.write("".join(l + "\n" for l in batch))
            wal.flush()
            if self.fsync:
                os.fsync(wal.fileno())
        wal_fsync_batch_size.observe(len(batch))
        return True

    def snapshot(self) -> int:
        """Rotate the WAL aside, write the full store state atomically,
        then drop the rotated WAL. Any crash point leaves a recoverable
        combination (load() is rv-ordered, not file-ordered).

        Correctness of the rotation point: a WAL line is written only
        AFTER its mutation committed to the store, so every line in the
        rotated WAL is reflected in the state listed below; lines arriving
        after the rotation land in the fresh WAL."""
        wal1 = self._path(WAL_ROTATED)
        # _io_lock first: an in-flight group-commit batch must finish its
        # write+fsync before the handle under it is rotated away
        with self._io_lock, self._lock:
            if self._wal is not None:
                self._wal.close()
            wal = self._path(WAL_FILE)
            if os.path.exists(wal):
                if os.path.exists(wal1):
                    # previous snapshot crashed mid-flight: merge, keeping
                    # chronological order within the rotated file
                    with open(wal1, "a") as dst, open(wal) as src:
                        dst.write(src.read())
                    os.remove(wal)
                else:
                    os.replace(wal, wal1)
            self._open_wal(truncate=True)

        records = []
        for kind in self.store.kinds():
            for obj in self.store.list(kind):
                records.append(json.dumps({
                    "kind": kind, "event": "ADDED", "obj": codec.encode(obj),
                }))
        tmp = self._path(SNAPSHOT_FILE + ".tmp")
        with open(tmp, "w") as f:
            f.write("\n".join(records) + ("\n" if records else ""))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path(SNAPSHOT_FILE))
        if os.path.exists(wal1):
            os.remove(wal1)
        return len(records)

    def close(self) -> None:
        self.store.unwatch_all_batch(self._on_events)
        with self._commit_cv:
            # wait out an in-flight batch leader: its captured batch is no
            # longer in _pending, so closing under it would silently drop
            # records whose mutators were promised durability (the leader
            # would find the handle gone and write nothing). Leadership is
            # only ever taken under this condition, so once _committing
            # reads False HERE no new batch can start before we finish.
            while self._committing:
                self._commit_cv.wait(0.1)
            if self._wal is not None:
                if self._pending:
                    # records enqueued but never led ride out in one final
                    # batch
                    self._wal.write(
                        "".join(l + "\n" for l in self._pending))
                    self._pending = []
                self._wal.flush()
                if self.fsync:
                    # the durability contract holds through shutdown: the
                    # final batch is on disk before close() returns
                    os.fsync(self._wal.fileno())
                self._wal.close()
                self._wal = None
            self._committed_seq = self._seq
            self._commit_cv.notify_all()
        self._attached = False

    # -- helpers ----------------------------------------------------------

    def _path(self, name: str) -> str:
        return os.path.join(self.data_dir, name)

    def _open_wal(self, truncate: bool = False) -> None:
        mode = "w" if truncate else "a"
        self._wal = open(self._path(WAL_FILE), mode)
        self._wal_len = 0 if truncate else self._count_lines(self._path(WAL_FILE))

    @staticmethod
    def _count_lines(path: str) -> int:
        try:
            with open(path) as f:
                return sum(1 for _ in f)
        except OSError:
            return 0

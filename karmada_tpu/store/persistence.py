"""Durable control-plane state: snapshot + write-ahead log over the store.

The reference's L1 persists in etcd; every controller is level-triggered
and resumes from informer cache (SURVEY §5 checkpoint note). This module
is that durability for the TPU build's store: every watch event appends a
codec-encoded JSON line to `wal.jsonl`; a periodic (or explicit) snapshot
rotates the WAL aside, rewrites `snapshot.jsonl` atomically, then drops
the rotated WAL; `load()` replays whatever files survive into the store
via `Store.restore`, which notifies subscribers as ADDED — so a daemon
started with `--data-dir` converges to its pre-restart state the way
controllers converge after an informer relist.

Crash-safety without ordering games: replay applies a record only when its
resourceVersion is >= the highest seen for that object key (store RVs are
monotonic), so snapshot + rotated WAL + live WAL merge correctly no matter
which rename a crash interrupted, and a torn tail line just ends that
file's replay.

Device state needs no persistence at all: the fleet arrays are a pure
cache rebuilt from the Cluster objects this file restores. Member-cluster
SIMULATIONS are not persisted — they stand in for real clusters, which
survive a control-plane restart on their own (push members re-join via
flags/CLI; pull agents re-register and their works re-apply).
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Optional

from ..server import codec
from .store import DELETED, Store

SNAPSHOT_FILE = "snapshot.jsonl"
WAL_FILE = "wal.jsonl"
WAL_ROTATED = "wal.1.jsonl"


class StorePersistence:
    def __init__(self, store: Store, data_dir: str, *,
                 snapshot_every: int = 5000):
        self.store = store
        self.data_dir = data_dir
        self.snapshot_every = snapshot_every
        os.makedirs(data_dir, exist_ok=True)
        # guards ONLY the WAL file handle — never call into the store while
        # holding it (watch handlers can run with the store lock held)
        self._lock = threading.Lock()
        self._wal: Optional[Any] = None
        self._wal_len = 0
        self._attached = False

    # -- restore ----------------------------------------------------------

    def load(self) -> int:
        """Replay snapshot + rotated WAL + WAL into the store. Call after
        the consuming controllers subscribed (they receive the state as
        ADDED events, like an informer's initial list) and before
        attach()."""
        latest: dict[tuple, tuple[int, Any]] = {}  # key -> (rv, obj|None)
        for name in (SNAPSHOT_FILE, WAL_ROTATED, WAL_FILE):
            path = self._path(name)
            if not os.path.exists(path):
                continue
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        break  # torn tail write (crash mid-append)
                    try:
                        obj = codec.decode(rec["obj"])
                    except Exception as e:  # noqa: BLE001 - one bad record
                        # must not drop the rest of the file (a decode
                        # failure is schema drift/corruption, not a tail)
                        import logging

                        logging.getLogger(__name__).warning(
                            "skipping undecodable %s record in %s: %s",
                            rec.get("kind"), path, e,
                        )
                        continue
                    key = (rec["kind"], obj.metadata.namespace,
                           obj.metadata.name)
                    rv = obj.metadata.resource_version
                    if key in latest and rv < latest[key][0]:
                        continue  # older than what another file delivered
                    latest[key] = (rv, None if rec["event"] == DELETED else obj)
        return self.store.restore(
            obj for _, obj in latest.values() if obj is not None
        )

    # -- capture ----------------------------------------------------------

    def attach(self) -> None:
        """Subscribe to the store and append every event to the WAL."""
        if self._attached:
            return
        self._attached = True
        with self._lock:
            self._open_wal()
        self.store.watch_all(self._on_event, replay=False)

    def _on_event(self, kind: str, event: str, obj: Any) -> None:
        line = json.dumps({
            "kind": kind, "event": event, "obj": codec.encode(obj),
        })
        with self._lock:
            if self._wal is None:
                return
            self._wal.write(line + "\n")
            self._wal.flush()
            self._wal_len += 1
            need_snapshot = self._wal_len >= self.snapshot_every
        if need_snapshot:
            self.snapshot()

    def snapshot(self) -> int:
        """Rotate the WAL aside, write the full store state atomically,
        then drop the rotated WAL. Any crash point leaves a recoverable
        combination (load() is rv-ordered, not file-ordered).

        Correctness of the rotation point: a WAL line is written only
        AFTER its mutation committed to the store, so every line in the
        rotated WAL is reflected in the state listed below; lines arriving
        after the rotation land in the fresh WAL."""
        wal1 = self._path(WAL_ROTATED)
        with self._lock:
            if self._wal is not None:
                self._wal.close()
            wal = self._path(WAL_FILE)
            if os.path.exists(wal):
                if os.path.exists(wal1):
                    # previous snapshot crashed mid-flight: merge, keeping
                    # chronological order within the rotated file
                    with open(wal1, "a") as dst, open(wal) as src:
                        dst.write(src.read())
                    os.remove(wal)
                else:
                    os.replace(wal, wal1)
            self._open_wal(truncate=True)

        records = []
        for kind in self.store.kinds():
            for obj in self.store.list(kind):
                records.append(json.dumps({
                    "kind": kind, "event": "ADDED", "obj": codec.encode(obj),
                }))
        tmp = self._path(SNAPSHOT_FILE + ".tmp")
        with open(tmp, "w") as f:
            f.write("\n".join(records) + ("\n" if records else ""))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path(SNAPSHOT_FILE))
        if os.path.exists(wal1):
            os.remove(wal1)
        return len(records)

    def close(self) -> None:
        self.store.unwatch_all(self._on_event)
        with self._lock:
            if self._wal is not None:
                self._wal.close()
                self._wal = None
        self._attached = False

    # -- helpers ----------------------------------------------------------

    def _path(self, name: str) -> str:
        return os.path.join(self.data_dir, name)

    def _open_wal(self, truncate: bool = False) -> None:
        mode = "w" if truncate else "a"
        self._wal = open(self._path(WAL_FILE), mode)
        self._wal_len = 0 if truncate else self._count_lines(self._path(WAL_FILE))

    @staticmethod
    def _count_lines(path: str) -> int:
        try:
            with open(path) as f:
                return sum(1 for _ in f)
        except OSError:
            return 0

"""Replicated control-plane store: fenced log shipping, quorum writes,
and follower reads.

The reference control plane gets durability AND read capacity from etcd's
replicated Raft log (PAPER.md L1); until now our whole store lived in one
flock'd `--data-dir` WAL and docs/HA.md hot-standby was availability, not
capacity. This module turns the single-node store into a leader/follower
group built entirely on primitives earlier rounds shipped:

- **The log entry already exists.** Transactional batches (PR-9) commit
  with contiguous resourceVersions through ONE `Store.watch_all_batch`
  delivery — the same unit the WAL group-commits with one fsync. The
  leader's `ReplicationManager` subscribes to that seam and every
  delivery becomes one rv-contiguous log entry, shipped over the existing
  HTTP plane (`POST /replication/append`).

- **Followers are rv-exact.** `Store.apply_replicated` commits an entry
  under one lock hold, preserving the leader's rvs and ORIGINAL event
  types through the under-lock event sink — so a follower's revisioned
  watch cache (PR-8) and snapshot-pinned paginated lists are byte-exact
  with the leader's at every applied rv. Follower reads (`GET /objects`,
  `GET /watch?since=`) carry the same consistency contract the leader
  serves, and a `min_rv=` read barrier waits out replication lag for
  read-your-writes callers.

- **Fencing, not consensus.** Appends are fenced by the `coordination/`
  lease token, exactly like stale client writes: every acquisition mints
  a strictly larger token, followers track the highest token they have
  accepted, and a deposed leader's stale appends bounce with 409. The
  lease itself is a store object and REPLICATES, so the token counter's
  monotonicity survives failover: a promoted follower's local acquire
  mints old_token+1 against its replicated copy.

- **Quorum rides the batch.** In `--replication=quorum` mode a write (or
  whole transactional batch) returns once `quorum` followers have
  applied AND fsync'd its entry — the ack piggybacks on the group-commit
  unit, so quorum costs one round-trip per BATCH, not per object. The
  async mode ships the same entries in the background with a bounded-lag
  backpressure gate.

- **Failover is seal-and-promote.** Because every follower's state is a
  contiguous PREFIX of the leader's log, the follower with the highest
  applied rv contains every entry ANY follower acked — promoting it
  (`seal_and_promote`) loses zero quorum-acked writes for any quorum
  >= 1. The promoted follower seals its log at its applied rv, acquires
  the lease locally (fresh fencing token), and ships to the remaining
  peers; lagging peers catch up through the same append stream, falling
  back to `POST /replication/snapshot` + rv offset when their next entry
  has been compacted out of the in-memory log ring.
"""
from __future__ import annotations

import bisect
import json
import logging
import threading
import time
from typing import Any, Iterable, Optional
from urllib.error import HTTPError
from urllib.parse import urlparse
from urllib.request import Request, urlopen

from ..metrics import (
    replica_lag,
    replication_appends,
    replication_quorum_latency,
)
from ..server import codec
from .store import ConflictError, ReplicationGapError, Store

log = logging.getLogger(__name__)

# the store-replication election: one lease fences the whole append stream
REPLICATION_LEASE = "karmada-store"

# in-memory log ring (catch-up window): entries older than this fall back
# to the snapshot path, like a watch client lagging past ring compaction
DEFAULT_LOG_ENTRIES = 4096
# quorum mode: how long a write waits for its acks before failing loudly
DEFAULT_ACK_TIMEOUT = 15.0
# async mode backpressure: writers stall briefly once the BEST follower is
# this many rvs behind (bounded lag, not unbounded divergence)
DEFAULT_MAX_ASYNC_LAG = 16384
# entries shipped per append round-trip (group shipping: a backlog drains
# in few requests, mirroring WAL group commit)
APPEND_MAX_ENTRIES = 64


class ReplicationError(RuntimeError):
    """Replication-plane failure (transport, protocol, or deposition)."""


class QuorumTimeoutError(ReplicationError):
    """The write committed (and fsync'd) locally but its quorum of
    follower acks did not arrive in time — durable here, NOT
    quorum-acked; the caller must treat it as failed."""


class StaleAppendError(ConflictError):
    """An append/snapshot carried a fencing token older than one this
    follower has already accepted — the sender was deposed (HTTP 409)."""


class LogEntry:
    """One rv-contiguous run of committed events — exactly one
    `watch_all_batch` delivery, wire-encoded once at append."""

    __slots__ = ("start_rv", "end_rv", "records")

    def __init__(self, records: list[dict]):
        self.records = records
        self.start_rv = records[0]["rv"]
        self.end_rv = records[-1]["rv"]

    def to_wire(self) -> dict:
        return {"start_rv": self.start_rv, "end_rv": self.end_rv,
                "records": self.records}


def encode_events(events: list[tuple[str, str, Any]]) -> list[dict]:
    return [
        {"kind": kind, "event": event,
         "rv": obj.metadata.resource_version, "obj": codec.encode(obj)}
        for kind, event, obj in events
    ]


def decode_records(records: list[dict]) -> list[tuple[str, str, Any]]:
    out = []
    for rec in records:
        obj = codec.decode(rec["obj"])
        out.append((rec["kind"], rec["event"], obj))
    return out


class ReplicaClient:
    """Leader-side HTTP transport to one follower's replication routes.
    Rides the same fault-plan boundary as every other HTTP client
    (faults.BOUNDARY_HTTP), so seeded chaos plans exercise the shipping
    retry/backoff path like any transport blip."""

    def __init__(self, url: str, timeout: float = 30.0,
                 token: Optional[str] = None, cafile: Optional[str] = None):
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.token = token
        self._ssl_ctx = None
        if self.url.startswith("https"):
            import ssl

            self._ssl_ctx = ssl.create_default_context(cafile=cafile)
        self._fault_target = urlparse(self.url).netloc or "replica"
        # negotiated body codec (server/wirecodec.py): append/snapshot
        # bodies upgrade to the zlib-framed binary message once a follower
        # response carries the advertise header — replication batches are
        # many near-identical JSON records, the codec's best case. A
        # body-rejection error on a binary append (wirecodec.body_rejected)
        # downgrades stickily (mixed-version fleet mid-rollout).
        self._wire_seen = False
        self._wire_down = False

    def _call(self, path: str, body: dict) -> dict:
        from .. import faults
        from ..server import wirecodec

        try:
            faults.check(faults.BOUNDARY_HTTP, self._fault_target)
        except faults.InjectedFault as e:
            raise ReplicationError(f"replica unreachable: {e}") from None
        sent_bin = self._wire_seen and not self._wire_down
        if sent_bin:
            headers = {"Content-Type": wirecodec.CONTENT_TYPE_BIN}
            data = wirecodec.pack_message(body)
        else:
            headers = {"Content-Type": "application/json"}
            data = json.dumps(body).encode()
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        req = Request(self.url + path, data=data,
                      method="POST", headers=headers)
        try:
            with urlopen(req, timeout=self.timeout,
                         context=self._ssl_ctx) as resp:
                if resp.headers.get(wirecodec.HEADER_WIRE):
                    self._wire_seen = True
                return json.loads(resp.read().decode() or "{}")
        except HTTPError as e:
            try:
                payload = json.loads(e.read().decode())
            except Exception:  # noqa: BLE001
                payload = {}
            msg = payload.get("error", str(e))
            if sent_bin and wirecodec.body_rejected(e.code, msg):
                self._wire_down = True
                return self._call(path, body)
            if e.code == 409:
                if payload.get("stale_token"):
                    raise StaleAppendError(msg) from None
                if "expected_rv" in payload:
                    raise ReplicationGapError(
                        msg, int(payload["expected_rv"])) from None
                raise ConflictError(msg) from None
            raise ReplicationError(f"HTTP {e.code}: {msg}") from None
        except OSError as e:
            raise ReplicationError(f"replica unreachable: {e}") from None

    def append(self, body: dict) -> dict:
        return self._call("/replication/append", body)

    def snapshot(self, body: dict) -> dict:
        return self._call("/replication/snapshot", body)


class _Peer:
    __slots__ = ("url", "client", "acked_rv", "thread", "last_error",
                 "snapshots", "appends", "diverged")

    def __init__(self, url: str, client: ReplicaClient):
        self.url = url
        self.client = client
        self.acked_rv = -1  # unknown: first contact probes with an append
        self.thread: Optional[threading.Thread] = None
        self.last_error = ""
        self.snapshots = 0
        self.appends = 0
        # the follower's store moved AHEAD of this leader's log (it
        # minted local rvs — a fork). Appending on top would silently
        # corrupt it, so the peer is quarantined until an operator
        # resets it (restart as --follower / wipe its data dir).
        self.diverged = False


class ReplicationManager:
    """Leader role: tail the store's commit stream, ship rv-contiguous
    log entries to followers, and (in quorum mode) hold each write until
    enough followers fsync'd its entry.

    Attach AFTER persistence: `Store._dispatch` calls batch watchers in
    subscription order, so the local WAL fsync completes before the
    quorum wait begins — a quorum-acked write is durable on leader AND
    `quorum` followers."""

    def __init__(self, store: Store, peer_urls: Iterable[str], *,
                 mode: str = "async", quorum: int = 1, token: int = 0,
                 identity: str = "leader", advertise_url: str = "",
                 lease_name: str = REPLICATION_LEASE,
                 ack_timeout: float = DEFAULT_ACK_TIMEOUT,
                 max_entries: int = DEFAULT_LOG_ENTRIES,
                 max_async_lag: int = DEFAULT_MAX_ASYNC_LAG,
                 auth_token: Optional[str] = None,
                 cafile: Optional[str] = None,
                 client_timeout: float = 30.0):
        if mode not in ("async", "quorum"):
            raise ValueError(f"replication mode {mode!r}: async|quorum")
        self.store = store
        self.mode = mode
        self.quorum = max(int(quorum), 1)
        self.token = token
        self.identity = identity
        self.advertise_url = advertise_url
        self.lease_name = lease_name
        self.ack_timeout = ack_timeout
        self.max_entries = max(int(max_entries), 8)
        self.max_async_lag = max_async_lag
        self._cond = threading.Condition()
        self._entries: list[LogEntry] = []  # sorted by start_rv
        self._floor = 0   # entries <= floor are not in the ring
        self._tip = 0     # highest committed rv seen
        self._stop = threading.Event()
        self._attached = False
        self.deposed = False
        self.deposed_reason = ""
        self.peers = [
            _Peer(u, ReplicaClient(u, timeout=client_timeout,
                                   token=auth_token, cafile=cafile))
            for u in peer_urls
        ]
        if self.mode == "quorum" and self.quorum > len(self.peers):
            raise ValueError(
                f"quorum {self.quorum} > {len(self.peers)} followers")

    # -- lifecycle ---------------------------------------------------------

    def attach(self) -> None:
        if self._attached:
            return
        self._attached = True
        with self._cond:
            self._floor = self._tip = self.store.current_rv
        self.store.watch_all_batch(self._on_batch)
        for p in self.peers:
            p.thread = threading.Thread(
                target=self._peer_loop, args=(p,),
                name=f"repl-{urlparse(p.url).netloc}", daemon=True,
            )
            p.thread.start()

    def close(self) -> None:
        if not self._attached:
            return
        self._attached = False
        self.store.unwatch_all_batch(self._on_batch)
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        for p in self.peers:
            if p.thread is not None:
                p.thread.join(timeout=5.0)
            replica_lag.remove(peer=p.url)

    def depose(self, reason: str) -> None:
        """A newer fencing token exists somewhere: stop shipping, fail
        any quorum waiters. The daemon's elector observes its own renew
        Conflict independently; this keeps the two signals consistent."""
        if self.deposed:
            return
        self.deposed = True
        self.deposed_reason = reason
        log.warning("replication leader %s deposed: %s",
                    self.identity, reason)
        with self._cond:
            self._cond.notify_all()

    def revive(self, token: int) -> None:
        """The elector re-won the lease (e.g. a GC pause cost one renewal
        with no successor taking over): resume shipping with the fresh
        token. Without this a deposed-then-re-elected leader would fail
        every write forever — depose() lets the peer threads exit, so
        revival must restart them. Entries committed while deposed are
        still in the log (insertion precedes the deposed check), so the
        resumed shippers drain the backlog; if a real successor DOES
        exist out there, its higher token re-deposes us on first contact."""
        with self._cond:
            self.token = max(self.token, token)
            was_deposed = self.deposed
            self.deposed = False
            self.deposed_reason = ""
            self._cond.notify_all()
        if not was_deposed:
            return
        if not self._attached:
            # the manager was CLOSED when a higher claim took over; a
            # legitimate re-election (that leader died, the lease came
            # back to us with a fresh token) re-attaches from scratch
            self._stop = threading.Event()
            self.attach()
            return
        log.warning("replication leader %s revived (token %d)",
                    self.identity, token)
        # peer loops PARK while deposed (they never exit on deposition,
        # so there is no alive-but-exiting race to lose a shipper to);
        # restarting here is defensive, for a loop killed by something
        # unexpected
        for p in self.peers:
            if (p.thread is None or not p.thread.is_alive()) \
                    and not p.diverged:
                p.thread = threading.Thread(
                    target=self._peer_loop, args=(p,),
                    name=f"repl-{urlparse(p.url).netloc}", daemon=True,
                )
                p.thread.start()

    # -- the commit-stream tail (runs in mutator threads) ------------------

    def _on_batch(self, events: list[tuple[str, str, Any]]) -> None:
        if not events or self._stop.is_set():
            return
        end_rv = events[-1][2].metadata.resource_version
        if end_rv <= self._floor:
            return  # pre-attach commit whose dispatch raced attach()
        entry = LogEntry(encode_events(events))
        with self._cond:
            # racing mutators dispatch out of commit order — insert
            # sorted; peers only ship contiguous prefixes, so a hole
            # (a batch still in flight between commit and dispatch)
            # parks the shippers until its entry arrives
            bisect.insort(self._entries, entry, key=lambda e: e.start_rv)
            self._tip = max(self._tip, entry.end_rv)
            if len(self._entries) > self.max_entries:
                drop = len(self._entries) - self.max_entries
                self._floor = self._entries[drop - 1].end_rv
                del self._entries[:drop]
            self._cond.notify_all()
        if self.deposed:
            raise ReplicationError(
                f"replication leader deposed ({self.deposed_reason}); "
                f"write at rv {end_rv} is fenced out")
        if self.mode == "quorum":
            self._await_quorum(entry.end_rv)
        elif self.max_async_lag:
            self._bound_async_lag(entry.end_rv)

    def _acks_through(self, rv: int) -> int:
        return sum(1 for p in self.peers if p.acked_rv >= rv)

    def _await_quorum(self, rv: int) -> None:
        t0 = time.perf_counter()
        deadline = t0 + self.ack_timeout
        with self._cond:
            while self._acks_through(rv) < self.quorum:
                if self.deposed:
                    raise ReplicationError(
                        f"replication leader deposed "
                        f"({self.deposed_reason}) awaiting quorum for rv "
                        f"{rv}")
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    raise QuorumTimeoutError(
                        f"rv {rv}: {self._acks_through(rv)}/{self.quorum} "
                        f"follower acks after {self.ack_timeout}s (write "
                        f"is durable locally but NOT quorum-acked)")
                self._cond.wait(min(remaining, 0.25))
        replication_quorum_latency.observe(time.perf_counter() - t0)

    def _bound_async_lag(self, rv: int) -> None:
        """Backpressure, not durability: stall the writer briefly while
        even the most caught-up HEALTHY follower is > max_async_lag rvs
        behind. Peers in a failure state (unreachable, never probed,
        diverged) are exempt — a single dead follower must not tax every
        async write with the full wait (availability is the async mode's
        whole point); it catches up through the snapshot path when it
        returns."""
        deadline = time.perf_counter() + 1.0
        with self._cond:
            while True:
                healthy = [
                    p.acked_rv for p in self.peers
                    if p.acked_rv >= 0 and not p.last_error
                    and not p.diverged
                ]
                if not healthy:
                    return  # nobody shippable to wait for
                if rv - max(healthy) <= self.max_async_lag:
                    return
                remaining = deadline - time.perf_counter()
                if remaining <= 0 or self.deposed:
                    return
                self._cond.wait(min(remaining, 0.1))

    # -- per-peer shipping loops -------------------------------------------

    def _next_for(self, peer: _Peer) -> Optional[list[LogEntry]]:
        """Caller holds self._cond. Returns the next contiguous batch of
        entries for this peer, [] when it is caught up / waiting on a
        dispatch hole, or None when the peer needs a snapshot (its next
        entry fell off the ring, or it has never synced)."""
        want = peer.acked_rv + 1
        if peer.acked_rv >= self._tip:
            return []  # caught up (acked_rv < 0 never reaches here: the
            # peer loop PROBES an unknown peer before calling this)
        if want <= self._floor:
            return None  # lagged past ring compaction: snapshot
        idx = bisect.bisect_left(self._entries, want,
                                 key=lambda e: e.start_rv)
        batch: list[LogEntry] = []
        expect = want
        for e in self._entries[idx:]:
            if e.start_rv != expect:
                break  # hole: a racing dispatch hasn't landed yet
            batch.append(e)
            expect = e.end_rv + 1
            if len(batch) >= APPEND_MAX_ENTRIES:
                break
        return batch

    # a peer waiting on a log HOLE (an entry committed but whose dispatch
    # never reached the log — e.g. the persistence batch-watcher raised
    # before replication's ran) must not park forever: after this long
    # with work visibly pending, fall back to a snapshot, which carries
    # the committed state whether or not its entry ever landed
    HOLE_TIMEOUT_S = 2.0

    def _peer_loop(self, peer: _Peer) -> None:
        from ..faults.policy import Backoff

        bo = Backoff(base=0.2, cap=5.0)
        stalled_since: Optional[float] = None
        while not self._stop.is_set():
            if peer.diverged:
                return  # quarantined: nothing safe to ship
            if self.deposed:
                # PARK, don't exit: revive() clearing the flag resumes
                # shipping with no thread restart — an exiting thread
                # could otherwise read as alive during revive's check and
                # leave its peer without a shipping loop forever
                with self._cond:
                    self._cond.wait(0.5)
                continue
            if peer.acked_rv < 0:
                # first contact: PROBE with an empty (still token-fenced)
                # append instead of assuming a snapshot — an already-
                # in-sync follower (leader restart, promotion) answers
                # with its applied rv and costs nothing; snapshots are
                # reserved for peers genuinely past the ring
                try:
                    self._probe(peer)
                    bo.reset()
                except StaleAppendError as e:
                    replication_appends.inc(outcome="stale_token")
                    self.depose(str(e))
                except ReplicationGapError as e:
                    # a follower demanding a re-sync (forked-then-demoted
                    # promotion) answers even the empty probe with a gap
                    replication_appends.inc(outcome="gap")
                    with self._cond:
                        peer.acked_rv = max(e.expected_rv - 1, 0)
                except Exception as e:  # noqa: BLE001 - transport/5xx
                    replication_appends.inc(outcome="transport")
                    peer.last_error = f"{type(e).__name__}: {e}"
                    self._stop.wait(bo.next())
                continue
            with self._cond:
                batch = self._next_for(peer)
                if batch == []:
                    lag = max(self._tip - max(peer.acked_rv, 0), 0)
                    replica_lag.set(lag, peer=peer.url)
                    if lag > 0:
                        # entries exist past acked but the CONTIGUOUS next
                        # one is missing — normally a sub-millisecond
                        # commit->dispatch race, but a dropped dispatch
                        # would park us forever; bound the wait
                        now = time.monotonic()
                        if stalled_since is None:
                            stalled_since = now
                        elif now - stalled_since > self.HOLE_TIMEOUT_S:
                            stalled_since = None
                            batch = None  # snapshot past the hole
                    else:
                        stalled_since = None
                    if batch == []:
                        self._cond.wait(0.5)
                        continue
                else:
                    stalled_since = None
            try:
                if batch is None:
                    self._send_snapshot(peer)
                else:
                    self._send_entries(peer, batch)
                bo.reset()
            except StaleAppendError as e:
                replication_appends.inc(outcome="stale_token")
                self.depose(str(e))
                continue  # park (above) until revived or closed
            except ReplicationGapError as e:
                replication_appends.inc(outcome="gap")
                with self._cond:
                    if e.expected_rv > self._tip + 1:
                        # the follower is AHEAD of everything we ever
                        # committed: it minted local rvs (forked store).
                        # Shipping entries on top would silently corrupt
                        # it — quarantine loudly instead.
                        peer.diverged = True
                        peer.last_error = (
                            f"diverged: follower expects rv "
                            f"{e.expected_rv}, leader tip {self._tip} — "
                            f"quarantined (reset the follower)")
                        log.error("replication peer %s %s",
                                  peer.url, peer.last_error)
                        replication_appends.inc(outcome="diverged")
                        return
                    # rewind to what the follower actually has; if that
                    # fell off the ring the next iteration snapshots
                    peer.acked_rv = e.expected_rv - 1
            except Exception as e:  # noqa: BLE001 - transport/5xx
                replication_appends.inc(outcome="transport")
                peer.last_error = f"{type(e).__name__}: {e}"
                self._stop.wait(bo.next())

    def _base_body(self) -> dict:
        return {"token": self.token, "leader": self.identity,
                "leader_url": self.advertise_url,
                "lease": self.lease_name}

    def _probe(self, peer: _Peer) -> None:
        """Empty append: learns the follower's applied rv (and asserts
        the token fence) without shipping state. A follower AHEAD of
        everything this leader ever committed forked (it minted local
        rvs) — quarantine it exactly like the gap path would."""
        body = self._base_body()
        body["entries"] = []
        applied = int(peer.client.append(body).get("applied_rv", 0))
        with self._cond:
            if applied > self._tip:
                peer.diverged = True
                peer.last_error = (
                    f"diverged: follower at rv {applied}, leader tip "
                    f"{self._tip} — quarantined (reset the follower)")
                log.error("replication peer %s %s",
                          peer.url, peer.last_error)
                replication_appends.inc(outcome="diverged")
                return
            peer.acked_rv = max(peer.acked_rv, applied)
            replica_lag.set(max(self._tip - peer.acked_rv, 0), peer=peer.url)
            self._cond.notify_all()

    def _send_entries(self, peer: _Peer, batch: list[LogEntry]) -> None:
        body = self._base_body()
        body["entries"] = [e.to_wire() for e in batch]
        resp = peer.client.append(body)
        applied = int(resp.get("applied_rv", batch[-1].end_rv))
        peer.appends += 1
        peer.last_error = ""
        replication_appends.inc(outcome="ok")
        with self._cond:
            peer.acked_rv = max(peer.acked_rv, applied)
            replica_lag.set(max(self._tip - peer.acked_rv, 0), peer=peer.url)
            self._cond.notify_all()

    def _send_snapshot(self, peer: _Peer) -> None:
        rv, items = self.store.snapshot_state()
        body = self._base_body()
        body["rv"] = rv
        body["objs"] = [codec.encode(o) for _, o in items]
        peer.client.snapshot(body)
        peer.snapshots += 1
        peer.last_error = ""
        replication_appends.inc(outcome="snapshot")
        with self._cond:
            peer.acked_rv = max(peer.acked_rv, rv)
            replica_lag.set(max(self._tip - peer.acked_rv, 0), peer=peer.url)
            self._cond.notify_all()

    # -- status ------------------------------------------------------------

    def acked_quorum_rv(self) -> int:
        """Highest rv with >= quorum follower acks (the seal point a
        promoted follower is guaranteed to reach or exceed). The cond's
        default lock is an RLock, so status() may call this under it."""
        with self._cond:
            acked = sorted((p.acked_rv for p in self.peers), reverse=True)
            if len(acked) < self.quorum:
                return 0
            return max(acked[self.quorum - 1], 0)

    def fleet_acked_rv(self) -> int:
        """Highest rv EVERY follower has acked (min over peers): a search
        query pinned at or below this rv is servable by any replica with
        the identical answer — the freshness floor GET /search reports as
        `replicated_rv` (docs/SEARCH.md). 0 with no peers."""
        with self._cond:
            if not self.peers:
                return 0
            return max(min(p.acked_rv for p in self.peers), 0)

    def status(self) -> dict:
        with self._cond:
            return {
                "role": "leader" if not self.deposed else "deposed",
                "mode": self.mode,
                "quorum": self.quorum,
                "token": self.token,
                "identity": self.identity,
                "applied_rv": self._tip,
                "quorum_acked_rv": self.acked_quorum_rv(),
                "peers": [
                    {"url": p.url, "acked_rv": max(p.acked_rv, 0),
                     "lag_rvs": max(self._tip - max(p.acked_rv, 0), 0),
                     "snapshots": p.snapshots, "appends": p.appends,
                     "diverged": p.diverged,
                     "last_error": p.last_error}
                    for p in self.peers
                ],
            }


class FollowerState:
    """Follower role bookkeeping on a serving plane: the highest fencing
    token accepted (monotonic — the append fence), who the leader is (the
    redirect target for rejected writes), and the seal switch promotion
    flips."""

    def __init__(self, store: Store):
        self.store = store
        self.max_token = 0
        self.leader_id = ""
        self.leader_url = ""
        self.sealed = False
        self.sealed_rv = 0
        # a demoted promotion minted a local lease rv the new leader's
        # log does not contain: entries must not glue onto the fork —
        # answer gaps until a snapshot re-syncs the whole state
        self.force_snapshot = False
        self.last_append_at = 0.0
        self._lock = threading.Lock()

    @property
    def active(self) -> bool:
        return not self.sealed and self.max_token > 0

    def _fence(self, token: int, leader: str, leader_url: str) -> None:
        """Caller holds self._lock. Claim fencing — the (token, leader)
        pair totally ordered, the same rule that 409s a deposed client's
        stale store writes; the identity tiebreak resolves two
        equal-token leaders (concurrent promotions against independent
        lease copies) to exactly one accepted stream."""
        if self.sealed:
            raise StaleAppendError(
                f"follower sealed at rv {self.sealed_rv} (promoted); "
                f"append from {leader!r} rejected")
        if (token, leader) < (self.max_token, self.leader_id):
            raise StaleAppendError(
                f"stale replication claim ({token}, {leader!r}) "
                f"(current ({self.max_token}, {self.leader_id!r}))")
        self.max_token = token
        self.leader_id = leader
        self.leader_url = leader_url or self.leader_url
        self.last_append_at = time.monotonic()

    def apply_entries(self, token: int, leader: str, leader_url: str,
                      entries: list[dict]) -> int:
        """Apply one append request. The entries it carries are
        rv-contiguous end to end, so the whole request commits as ONE
        `apply_replicated` call — one store lock hold and one WAL
        group-commit fsync per round-trip, however many leader-side
        batches the shipper coalesced into it (the follower-side mirror
        of the leader's group commit; a per-entry fsync would gate the
        follower's apply rate at the disk instead of the wire)."""
        with self._lock:
            self._fence(token, leader, leader_url)
            if self.force_snapshot:
                raise ReplicationGapError(
                    "re-sync required (this plane's demoted promotion "
                    "forked the log); send a snapshot", 1)
            records: list = []
            for wire in entries:
                records.extend(decode_records(wire.get("records", [])))
            return self.store.apply_replicated(records)

    def apply_snapshot(self, token: int, leader: str, leader_url: str,
                       rv: int, objs: list, *, swap=None) -> int:
        """`swap` wraps the store.load_snapshot call so the server can
        detach/re-attach its watch cache around the state swap."""
        with self._lock:
            self._fence(token, leader, leader_url)
            objects = [codec.decode(o) for o in objs]
            if swap is not None:
                swap(rv, objects)
            else:
                self.store.load_snapshot(rv, objects)
            self.force_snapshot = False  # re-synced: entries resume
            return self.store.current_rv

    def seal(self) -> int:
        """Promotion step 1: stop accepting appends (any late append from
        the dead leader 409s) and pin the rv the new leader serves from.
        Every applied entry is a contiguous prefix of the old leader's
        log, so sealing at the applied rv keeps every quorum-acked write
        this follower ever acknowledged."""
        with self._lock:
            self.sealed = True
            self.sealed_rv = self.store.current_rv
            return self.sealed_rv

    def unseal(self, resync: bool = False) -> None:
        """Roll a seal back: promotion failed (lost the election), or a
        higher-claim leader's appends re-fenced this plane — it returns
        to ordinary follower service. Without this a sealed-but-not-
        promoted plane would accept client writes (it no longer reads as
        a follower) while 409ing the legitimate leader's appends.

        `resync=True` when the plane actually PROMOTED before being
        outranked: its local lease acquire minted an rv the winner's log
        does not contain, so subsequent entries must not apply until a
        snapshot replaces the forked state."""
        with self._lock:
            was_sealed = self.sealed
            self.sealed = False
            self.sealed_rv = 0
            if resync and was_sealed:
                self.force_snapshot = True

    def status(self) -> dict:
        with self._lock:
            return {
                "role": "follower" if self.active else (
                    "promoted" if self.sealed else "candidate"),
                "applied_rv": self.store.current_rv,
                "token": self.max_token,
                "leader": self.leader_id,
                "leader_url": self.leader_url,
                "sealed_rv": self.sealed_rv if self.sealed else None,
            }


def seal_and_promote(server, peer_urls: Iterable[str], *, identity: str,
                     coordinator=None, lease_name: str = REPLICATION_LEASE,
                     lease_duration: float = 10.0, mode: str = "async",
                     quorum: int = 1, auth_token: Optional[str] = None,
                     cafile: Optional[str] = None,
                     **manager_kwargs) -> ReplicationManager:
    """Failover: promote a follower `ControlPlaneServer` to leader.

    1. Seal its follower log at the applied rv (late appends 409).
    2. Acquire the replication lease against its OWN store — the lease is
       a replicated object, so the counter continues and the acquisition
       mints a fencing token strictly above the dead leader's (this local
       write is also the new leader's first minted rv).
    3. Start a ReplicationManager shipping to the surviving peers; they
       re-fence on the higher token and catch up from the append stream
       (or a snapshot when they lag past the ring).

    Promotion should target the follower with the HIGHEST applied rv
    (`karmadactl replication status` / GET /replication/status): follower
    state is a contiguous log prefix, so the max-rv follower contains
    every entry any quorum ever acked — zero quorum-acked writes lost.

    A FAILED promotion (lost the election — e.g. two operators promoting
    concurrently) rolls the seal back: the loser returns to follower
    service and accepts the winner's appends instead of 409ing them
    while taking client writes.
    """
    server.seal_follower()
    try:
        token = 0
        if coordinator is None:
            coordinator = getattr(server.cp, "coordinator", None)
        if coordinator is not None:
            lease, acquired = coordinator.acquire(
                lease_name, identity, lease_duration)
            if not acquired:
                raise ReplicationError(
                    f"promotion lost the {lease_name} election to "
                    f"{lease.spec.holder_identity!r}")
            token = lease.spec.fencing_token
        mgr = ReplicationManager(
            server.cp.store, peer_urls, mode=mode, quorum=quorum,
            token=token, identity=identity, advertise_url=server.url,
            lease_name=lease_name, auth_token=auth_token, cafile=cafile,
            **manager_kwargs,
        )
        server.promote(mgr)
        return mgr
    except BaseException:
        server.unseal_follower()
        raise


class ReplicaControlPlane:
    """The minimal cp surface a FOLLOWER plane serves with: store only, no
    controllers, no members — a follower must mint no local rvs (any local
    write would fork the leader's contiguous sequence), so it runs
    read-only until promoted. Promotion hands the store to a real leader
    role; the coordinator exists so the promotion path can acquire the
    replicated lease locally."""

    def __init__(self, store: Optional[Store] = None, clock=None,
                 search: bool = False):
        from ..coordination.lease import LeaseCoordinator

        self.store = store if store is not None else Store()
        self.members: dict = {}
        self.coordinator = LeaseCoordinator(self.store, clock)
        self.search_index = None
        self.search_ingestor = None
        if search:
            # follower-served search (docs/SEARCH.md): replicated
            # ClusterObjectSummary objects arrive through apply_replicated
            # with the leader's original rvs and event types, so the same
            # event-sink ingest builds a byte-identical columnar index here
            # and GET /search answers from this replica match the leader's
            # at any rv both have reached
            from ..search import ColumnarIndex, SearchIngestor

            self.search_index = ColumnarIndex()
            self.search_ingestor = SearchIngestor(self.store, self.search_index)

    def search(self, params: dict, *, at_rv=None, trace_id: str = ""):
        """Same surface as ControlPlane.search, served from this replica's
        own index. Raises LookupError when search was not enabled."""
        if self.search_index is None:
            raise LookupError("search plane not enabled on this replica")
        from ..search import compile_query, run_query

        return run_query(self.search_index, compile_query(params),
                         at_rv=at_rv, trace_id=trace_id)

    def close(self) -> None:
        if self.search_ingestor is not None:
            self.search_ingestor.close()

    def settle(self, max_steps: int = 0) -> int:
        return 0

    def tick(self, seconds: float = 0.0) -> int:
        return 0

"""Revisioned watch cache: the server-side read-scaling layer over the store.

The reference control plane gets its read throughput from etcd revisions
plus the kube-apiserver watch cache (PAPER.md L1/L6): every mutation is
stamped with a monotonic revision, the apiserver keeps a bounded in-memory
log of recent events plus a revision-consistent object index, and serves

- resumable watches — a client reconnecting with `since=<rv>` receives
  only the delta, not a full relist, as long as the ring still holds it;
- consistent paginated lists — `limit=`/`continue=` pages pinned to one
  snapshot revision, so a list crawled across many requests never shows
  dupes or skips from writes that landed mid-crawl.

This module is that analogue for the TPU build's store. `WatchCache`
attaches to a `Store` through the under-lock event-sink seam
(`Store.add_event_sink`), which delivers mutations in strict
resourceVersion order — unlike the watcher bus, whose callbacks run after
the lock drops and may interleave under concurrent writers. Each event is
wire-encoded ONCE (`server/codec.py`) — lazily, on the first serving read,
so the store's lock hold pays only the ring append while every watch
client still writes the same cached bytes: fan-out cost per client is a
filter check plus a socket write, not an encode.

Consistency model:
- the ring holds the last `capacity` events in rv order; `events_since(rv)`
  is exact while `rv >= compacted_rv`, else the caller must fall back to
  snapshot + replay (exactly the reference's "too old resource version");
- the object index is updated in the same critical section as the ring
  append, so `snapshot()` at rv R reflects precisely the first R events;
- a non-monotonic rv (a persistence `restore()` replaying files in
  file order) resets the ring and moves the compaction point forward —
  no since-resume across a restore, snapshots stay correct.

Thread-safety: one condition variable guards ring + index + pinned pages;
`wait()` lets serving threads block for the next event without polling.
"""
from __future__ import annotations

import bisect
import itertools
import json
import threading
import time
from typing import Any, Optional

from ..analysis.lockorder import make_lock
from ..server import codec, wirecodec
from .store import ADDED, DELETED, Store

DEFAULT_CAPACITY = 8192
# pinned list snapshots: a crawler must finish its pages inside the TTL
# (refreshed per page fetch); beyond MAX_PINNED the oldest pin is dropped
DEFAULT_PAGE_TTL = 60.0
MAX_PINNED_PAGES = 64


class ContinueExpired(Exception):
    """The continue token's pinned snapshot is gone (TTL or pressure);
    the client must restart the list from the beginning (HTTP 410)."""


class CacheEvent:
    """One revisioned event, wire-encoded once, shared by ring and index.

    The encode is LAZY: the event is appended under the store lock (the
    sink runs in the mutation's critical section so the ring sees strict
    rv order), but the codec work happens on the first serving thread that
    reads `enc`/`line()` — the store commits immutable objects, so
    retaining the reference and encoding it outside the lock is safe, and
    the write path's lock hold stays free of codec cost. Two racing
    builders produce identical values — benign."""

    __slots__ = ("rv", "kind", "event", "namespace", "name", "obj", "_enc",
                 "_line", "_added_line", "_frame", "_added_frame",
                 "_base_rv", "_base_src", "_delta_frame")

    def __init__(self, rv: int, kind: str, event: str, namespace: str,
                 name: str, obj: Any = None, enc: Any = None):
        self.rv = rv
        self.kind = kind
        self.event = event
        self.namespace = namespace
        self.name = name
        self.obj = obj
        self._enc = enc
        self._line: Optional[bytes] = None
        self._added_line: Optional[bytes] = None
        # dual encoding (binary wire codec): the full binary frame, the
        # ADDED-frame variant, and the delta frame against the PREVIOUS
        # object for this key. `_base_src` holds a reference to the prior
        # object (or its encoding) — never the prior CacheEvent, so no
        # predecessor chain is retained: one extra object per ring slot
        # at most, freed with the slot on compaction or delta build.
        self._frame: Optional[bytes] = None
        self._added_frame: Optional[bytes] = None
        self._base_rv: int = 0
        self._base_src: Any = None
        self._delta_frame: Optional[bytes] = None

    @property
    def enc(self) -> Any:
        """Wire encoding, built once on first read (never under the store
        lock); the retained object reference drops once encoded. Two
        racing first-readers are safe under the GIL: the object reference
        is snapshotted BEFORE encoding, and a reader that finds it already
        dropped re-reads the published encoding (the writer publishes
        `_enc` before clearing `obj`, so a None obj implies `_enc` is
        set — encoding the dropped None would cache a corrupt obj:null
        wire line forever)."""
        e = self._enc
        if e is None:
            obj = self.obj
            if obj is None:
                return self._enc  # racer published between our two reads
            e = codec.encode(obj)
            self._enc = e
            self.obj = None  # footprint: keep bytes OR object, not both
        return e

    def matches(self, kind: str, namespace: str) -> bool:
        if kind != "*" and self.kind != kind:
            return False
        return not namespace or self.namespace == namespace

    def line(self) -> bytes:
        """The JSON wire line for this event (built once, served to every
        client). Two racing builders produce identical bytes — benign."""
        line = self._line
        if line is None:
            line = (json.dumps({
                "kind": self.kind, "event": self.event, "rv": self.rv,
                "obj": self.enc,
            }) + "\n").encode()
            self._line = line
        return line

    def added_line(self) -> bytes:
        """The same object as an ADDED line — what a snapshot replay sends
        (informer initial-list semantics), whatever the live event was."""
        if self.event == ADDED:
            return self.line()
        line = self._added_line
        if line is None:
            line = (json.dumps({
                "kind": self.kind, "event": ADDED, "rv": self.rv,
                "obj": self.enc,
            }) + "\n").encode()
            self._added_line = line
        return line

    # -- binary wire frames (server/wirecodec.py), built once like line()

    def frame(self) -> bytes:
        """Full binary event frame — same JSON object as line(), framed."""
        f = self._frame
        if f is None:
            f = wirecodec.event_frame(self.kind, self.event, self.rv,
                                      self.enc)
            self._frame = f
        return f

    def added_frame(self) -> bytes:
        """frame() with the event rewritten to ADDED — snapshot replay."""
        if self.event == ADDED:
            return self.frame()
        f = self._added_frame
        if f is None:
            f = wirecodec.event_frame(self.kind, ADDED, self.rv, self.enc)
            self._added_frame = f
        return f

    def delta_frame(self) -> Optional[bytes]:
        """Delta frame against this key's previous object, or None when no
        base exists (ADDED/DELETED, or a delta would not be smaller than
        the full frame). Built once; the base reference drops after the
        build either way. Racing builders produce identical bytes."""
        f = self._delta_frame
        if f is not None:
            return f if f else None  # b"" caches "not worth it"
        base_rv = self._base_rv
        if not base_rv or self.event in (ADDED, DELETED):
            return None
        src = self._base_src
        if src is None:
            return None
        base_enc = codec.encode(src)  # idempotent on already-encoded dicts
        patch = wirecodec.diff(base_enc, self.enc)
        f = wirecodec.delta_frame(self.kind, self.event, self.rv,
                                  self.namespace, self.name, base_rv, patch)
        if len(f) >= len(self.frame()):
            f = b""
        self._delta_frame = f
        self._base_src = None  # the base served its purpose
        return f if f else None


class WatchCache:
    def __init__(self, store: Store, capacity: int = DEFAULT_CAPACITY,
                 page_ttl: float = DEFAULT_PAGE_TTL):
        self._store = store
        self.capacity = max(int(capacity), 1)
        self.page_ttl = page_ttl
        # lock-order watchdog seam (KARMADA_TPU_LOCKCHECK=1): the cache
        # lock is acquired under the store hold via the event sink — the
        # watchdog proves that edge never reverses (docs/ANALYSIS.md)
        self._cond = threading.Condition(make_lock("watchcache._cond"))
        self._events: list[CacheEvent] = []
        # kind -> (namespace, name) -> latest CacheEvent (current state)
        self._index: dict[str, dict[tuple[str, str], CacheEvent]] = {}
        self._rv = 0
        self._compacted_rv = 0  # since-resume exact iff since >= this
        # pinned list snapshots: id -> [expires, rv, items]
        self._pages: dict[int, list] = {}
        self._page_ids = itertools.count(1)
        self._attached = False
        # wakeup fan-out beyond the condition variable: the event loop
        # (server/eventloop.py) blocks in selectors.select(), not in
        # wait() — each hook runs inside _on_event (store lock held) and
        # must be non-blocking (the loop's is one os.write to a self-pipe)
        self._notify_hooks: list = []

    # -- lifecycle --------------------------------------------------------

    def attach(self) -> None:
        """Prime the index from current store state and subscribe to the
        event-sink seam, atomically with respect to mutations.

        Safe to call again after detach() — the replication snapshot path
        swaps the store's whole state with the cache detached, then
        re-attaches: the stale ring and index are dropped FIRST (a
        since-resume across the swap must fall back to snapshot replay,
        and index entries for objects that vanished during the swap must
        not survive), and prime rebuilds a revision-consistent index
        under the same lock hold that gates new events."""
        if self._attached:
            return
        self._attached = True
        with self._cond:
            self._events.clear()
            self._index.clear()
        rv = self._store.add_event_sink(self._on_event, prime=self._prime)
        with self._cond:
            self._rv = max(self._rv, rv)
            self._compacted_rv = self._rv

    def detach(self) -> None:
        if not self._attached:
            return
        self._attached = False
        self._store.remove_event_sink(self._on_event)

    # -- feed (runs under the store lock) ---------------------------------

    def _prime(self, kind: str, obj: Any) -> None:
        ev = self._make_event(kind, ADDED, obj)
        with self._cond:
            self._apply_index(ev)
            self._rv = max(self._rv, ev.rv)

    @staticmethod
    def _make_event(kind: str, event: str, obj: Any) -> CacheEvent:
        # no encode here: _on_event runs under the store lock, and the
        # committed object is immutable — CacheEvent encodes lazily on the
        # first serving read instead (write-path lock-scope shrink)
        m = obj.metadata
        return CacheEvent(m.resource_version, kind, event, m.namespace,
                          m.name, obj=obj)

    def _on_event(self, kind: str, event: str, obj: Any) -> None:
        ev = self._make_event(kind, event, obj)
        with self._cond:
            if ev.rv <= self._rv:
                # non-monotonic: a restore() replaying persisted files in
                # file order. Keep the index correct, forbid since-resume
                # across the discontinuity — and mint a FRESH store
                # revision for it (we run under the store lock, so
                # _next_rv is safe): a pre-restore cursor numerically
                # equal to the post-restore tip must not alias a client
                # that already resynced and holds the restored state.
                self._apply_index(ev)
                self._events.clear()
                self._compacted_rv = self._rv = self._store._next_rv()
            else:
                self._rv = ev.rv
                self._apply_index(ev)
                self._events.append(ev)
                if len(self._events) > self.capacity:
                    drop = len(self._events) - self.capacity
                    self._compacted_rv = self._events[drop - 1].rv
                    del self._events[:drop]
            self._cond.notify_all()
        for hook in self._notify_hooks:
            hook()

    def add_notify(self, hook) -> None:
        """Register a non-blocking wakeup hook, called after every ring
        append (outside the cache lock, still under the store lock)."""
        self._notify_hooks.append(hook)

    def remove_notify(self, hook) -> None:
        try:
            self._notify_hooks.remove(hook)
        except ValueError:
            pass

    def _apply_index(self, ev: CacheEvent) -> None:
        by_key = self._index.setdefault(ev.kind, {})
        key = (ev.namespace, ev.name)
        if ev.event == DELETED:
            by_key.pop(key, None)
        else:
            prev = by_key.get(key)
            if prev is not None and ev.event != ADDED:
                # delta base: the key's previous OBJECT (or its encoding
                # if already built) — exactly the state an rv-contiguous
                # client holds for this key when ev arrives. Never the
                # CacheEvent itself: that would chain predecessors
                # indefinitely when no binary client forces delta builds.
                ev._base_rv = prev.rv
                ev._base_src = (prev._enc if prev._enc is not None
                                else prev.obj)
            by_key[key] = ev

    # -- read side --------------------------------------------------------

    @property
    def current_rv(self) -> int:
        with self._cond:
            return self._rv

    @property
    def compacted_rv(self) -> int:
        """Cursors at or past this rv resume exactly; older ones must
        snapshot+replay (the event loop checks before each pump)."""
        with self._cond:
            return self._compacted_rv

    def events_since(self, rv: int, kind: str = "*", namespace: str = "",
                     limit: int = 0) -> tuple[list[CacheEvent], int, bool]:
        """Events with resourceVersion > rv matching the filter, in order.

        Returns (events, cursor, ok): `cursor` is the rv the caller should
        resume from next (past filtered-out events too, so an idle filter
        never rescans the ring); ok=False means the ring has compacted past
        `rv` — the caller must snapshot+replay instead."""
        with self._cond:
            if rv < self._compacted_rv:
                return [], rv, False
            events = self._events
            lo = self._idx_after(rv)
            out: list[CacheEvent] = []
            cursor = rv
            for ev in events[lo:]:
                cursor = ev.rv
                if ev.matches(kind, namespace):
                    out.append(ev)
                    if limit and len(out) >= limit:
                        break
            return out, cursor, True

    def wait(self, rv: int, timeout: float) -> bool:
        """Block until an event past `rv` exists (True) or timeout."""
        with self._cond:
            if self._rv > rv:
                return True
            self._cond.wait(timeout)
            return self._rv > rv

    def lag(self, rv: int) -> int:
        """How many ring events a cursor at `rv` still has to consume —
        the per-client backlog the lag gauge exports."""
        with self._cond:
            return len(self._events) - self._idx_after(rv)

    def _idx_after(self, rv: int) -> int:
        """Index of the first ring event with .rv > rv (rv-sorted ring);
        caller must hold self._cond."""
        return bisect.bisect_right(self._events, rv, key=lambda e: e.rv)

    def snapshot(self, kind: str = "*", namespace: str = ""
                 ) -> tuple[int, list[CacheEvent]]:
        """Revision-consistent current state matching the filter, sorted by
        (kind, namespace, name) — the replay source for watch fallback."""
        with self._cond:
            rv = self._rv
            items = self._collect(kind, namespace)
        return rv, items

    def _collect(self, kind: str, namespace: str) -> list[CacheEvent]:
        """Caller must hold self._cond."""
        kinds = self._index.keys() if kind == "*" else (kind,)
        out: list[CacheEvent] = []
        for k in kinds:
            by_key = self._index.get(k)
            if not by_key:
                continue
            for ev in by_key.values():
                if not namespace or ev.namespace == namespace:
                    out.append(ev)
        out.sort(key=lambda e: (e.kind, e.namespace, e.name))
        return out

    # -- paginated, revision-consistent lists -----------------------------

    def list_page(self, kind: str, namespace: str, limit: int,
                  token: Optional[str] = None
                  ) -> tuple[int, list[Any], str]:
        """One page of encoded objects. First call (token=None) pins a
        snapshot at the current rv; the returned continue token fetches
        later pages FROM THAT SNAPSHOT, so concurrent writes can neither
        duplicate nor skip items across pages. Returns (rv, items, token);
        an empty token means the list is complete."""
        limit = max(int(limit), 1)
        now = time.monotonic()
        with self._cond:
            self._prune_pages(now)
            if token:
                try:
                    pid_s, off_s = token.split(":", 1)
                    pid, off = int(pid_s), int(off_s)
                except ValueError:
                    raise ContinueExpired(
                        f"malformed continue token {token!r}") from None
                if pid <= 0 or off < 0:
                    # a negative offset would slice from the END of the pin
                    # and silently duplicate items across pages
                    raise ContinueExpired(
                        f"malformed continue token {token!r}")
                page = self._pages.get(pid)
                if page is None:
                    raise ContinueExpired(
                        "continue token expired; restart the list")
                page[0] = now + self.page_ttl  # crawl in progress: refresh
                _, rv, items = page
            else:
                rv = self._rv
                items = self._collect(kind, namespace)
                off = 0
                pid = 0
                if len(items) > limit:
                    pid = next(self._page_ids)
                    self._pages[pid] = [now + self.page_ttl, rv, items]
            chunk = items[off:off + limit]
            end = off + limit
            next_token = f"{pid}:{end}" if end < len(items) else ""
            if not next_token and token:
                self._pages.pop(pid, None)  # crawl done: unpin eagerly
            return rv, [it.enc for it in chunk], next_token

    def _prune_pages(self, now: float) -> None:
        expired = [pid for pid, p in self._pages.items() if p[0] <= now]
        for pid in expired:
            del self._pages[pid]
        while len(self._pages) > MAX_PINNED_PAGES:
            oldest = min(self._pages, key=lambda pid: self._pages[pid][0])
            del self._pages[oldest]

"""Write coalescing over the Store surface (docs/PERF.md "Write path at
fleet scale").

Every control-plane writer that used to loop per object — the binding
controller materializing one Work per target cluster, agents reporting
status for each drained Work, the scheduler patching a micro-batch of
decisions — shares these two helpers instead of growing its own copy of
the batching logic:

- `apply_all(store, objs)`: one-shot coalescing. Rides the store's
  transactional `apply_batch` when present (one lock hold / one request
  per chunk, one WAL fsync), degrading to per-object `apply` on a
  `BatchError` so one bad object costs itself — exactly the pre-batch
  loop's failure semantics — and falling back entirely for stores without
  the batch surface.

- `WriteCoalescer`: a buffered create-or-update writer for trickle
  producers (agent status reports). `apply()` enqueues; a background
  flusher commits the buffer as ONE batch after `flush_delay` seconds (the
  knob: trade a small latency floor for N-fold fewer round-trips), or
  sooner when `max_batch` accumulates. Writes to the same object key
  coalesce last-write-wins while buffered — a work whose status flapped
  twice within the window costs one write. Intended for level-triggered,
  idempotent status writes: a flush that fails is logged and dropped,
  because the next reconcile re-writes the same state.
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Optional

from ..analysis.lockorder import make_lock
from ..metrics import writes_coalesced
from .store import BatchError, gvk_of

log = logging.getLogger(__name__)

DEFAULT_CHUNK = 256


def _obj_key(obj: Any) -> tuple[str, str, str]:
    return (gvk_of(obj), obj.metadata.namespace, obj.metadata.name)


def apply_all(store, objs, *, path: str = "coalesced",
              chunk: int = DEFAULT_CHUNK) -> list:
    """Create-or-update every object, coalesced into batch calls when the
    store supports them. Returns the committed objects in input order.

    Failure semantics match the per-object loop: on a BatchError (one
    object failed validation — the batch committed nothing) the chunk
    degrades to per-object apply, so the healthy objects land and the bad
    one raises exactly where the old loop would have raised."""
    objs = list(objs)
    if not objs:
        return []
    batch = getattr(store, "apply_batch", None)
    if batch is None:
        return [store.apply(o) for o in objs]
    out: list = []
    step = max(1, chunk)
    for s in range(0, len(objs), step):
        ch = objs[s:s + step]
        if len(ch) == 1:
            out.append(store.apply(ch[0]))
            continue
        try:
            out.extend(batch(ch))
            writes_coalesced.inc(len(ch), path=path)
        except BatchError:
            out.extend(store.apply(o) for o in ch)
    return out


def update_all(store, objs, *, path: str = "coalesced",
               skip_missing: bool = False, skip_stale: bool = False,
               chunk: int = DEFAULT_CHUNK) -> list:
    """Update every object, coalesced into batch calls when the store
    supports them; the shared home for the update-batch-or-fallback shape
    (the scheduler's patch and observed-generation flushes both ride it).
    Returns the per-object committed objects — None marks a slot the batch
    SKIPPED (vanished object under skip_missing, or a newer concurrent
    write under skip_stale); callers must treat those as not-written.

    The per-object fallback (no batch surface) preserves the old write
    semantics exactly: blind update, NotFound raising unless
    skip_missing."""
    from .store import NotFoundError

    objs = list(objs)
    if not objs:
        return []
    batch = getattr(store, "update_batch", None)
    out: list = []
    if batch is None:
        for o in objs:
            try:
                out.append(store.update(o))
            except NotFoundError:
                if not skip_missing:
                    raise
                out.append(None)
        return out
    step = max(1, chunk)
    for s in range(0, len(objs), step):
        ch = objs[s:s + step]
        out.extend(batch(ch, skip_missing=skip_missing,
                         skip_stale=skip_stale))
        writes_coalesced.inc(len(ch), path=path)
    return out


class WriteCoalescer:
    """Buffered apply() writer with a flush-delay knob (see module doc).

    flush_delay <= 0 disables buffering entirely: apply() writes through
    synchronously — the zero-config default for in-process callers, so
    only deployments that opt in (remote agents) pay the latency floor."""

    def __init__(self, store, *, flush_delay: float = 0.005,
                 max_batch: int = DEFAULT_CHUNK,
                 path: str = "coalesced") -> None:
        self._store = store
        self.flush_delay = flush_delay
        self.max_batch = max(1, max_batch)
        self.path = path
        # lock-order watchdog seam (KARMADA_TPU_LOCKCHECK=1): flush()
        # commits to the store AFTER dropping this lock — the watchdog
        # proves the coalescer/store order never inverts
        self._cv = threading.Condition(make_lock("coalescer._cv"))
        self._buf: dict[tuple[str, str, str], Any] = {}
        self._closed = False
        self._closed_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- producer side -----------------------------------------------------

    def apply(self, obj: Any) -> Optional[Any]:
        """Enqueue a create-or-update. Returns the committed object when
        writing through (flush_delay <= 0), else None — buffered writes
        commit on the flusher thread within flush_delay."""
        if self.flush_delay <= 0:
            return self._store.apply(obj)
        with self._cv:
            if self._closed:
                raise RuntimeError("WriteCoalescer is closed")
            self._buf[_obj_key(obj)] = obj
            full = len(self._buf) >= self.max_batch
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._flusher, name=f"coalescer-{self.path}",
                    daemon=True,
                )
                self._thread.start()
            self._cv.notify_all()
        if full:
            self.flush()
        return None

    def flush(self) -> int:
        """Commit the buffered writes NOW, on the caller's thread; returns
        how many objects were written. Unlike the background flusher, a
        flush() failure RAISES — explicit flush points (end of an agent
        step) want to see the error."""
        with self._cv:
            batch = list(self._buf.values())
            self._buf.clear()
        if batch:
            apply_all(self._store, batch, path=self.path)
        return len(batch)

    def pending(self) -> int:
        with self._cv:
            return len(self._buf)

    def close(self) -> None:
        """Flush the tail and stop the flusher."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._closed_evt.set()  # interrupt a mid-delay flusher sleep
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self.flush()

    # -- flusher thread ----------------------------------------------------

    def _flusher(self) -> None:
        while True:
            with self._cv:
                while not self._buf and not self._closed:
                    self._cv.wait()
                if self._closed:
                    return  # close() flushes the tail itself
            # let the trickle coalesce: more writes land while we sleep
            # (the flush-delay knob); a full buffer flushed synchronously
            # by apply() just leaves nothing for us to do. close() cuts
            # the sleep short so shutdown never waits out the delay.
            self._closed_evt.wait(self.flush_delay)
            with self._cv:
                batch = list(self._buf.values())
                self._buf.clear()
            if not batch:
                continue
            try:
                apply_all(self._store, batch, path=self.path)
            except Exception:  # noqa: BLE001 - status writes are
                # level-triggered and idempotent: the next reconcile
                # re-writes the same state, so log loudly and keep serving
                log.exception(
                    "coalesced flush of %d writes failed (path=%s); "
                    "dropped — the next reconcile re-writes them",
                    len(batch), self.path,
                )

"""Object-model metadata shared by every API type.

Behavior modeled on how the reference uses Kubernetes object metadata
(labels/annotations as the idempotence keys, generation vs observedGeneration,
finalizers + deletionTimestamp for teardown) — e.g.
pkg/scheduler/scheduler.go:400-441 keys scheduling decisions off
annotations/generation. Not a port of apimachinery: just enough metadata for a
level-triggered, versioned object store.
"""
from __future__ import annotations

import copy
import dataclasses
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Optional

# Resource quantities. Canonical units: "cpu" in cores (float), "memory" in
# bytes, everything else raw counts. The reference uses resource.Quantity;
# floats are sufficient for the scheduling math (the division algorithms all
# operate on integer replica counts, not quantities).
Resources = dict[str, float]

CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
EPHEMERAL_STORAGE = "ephemeral-storage"

_uid_counter = itertools.count(1)


def new_uid(prefix: str = "uid") -> str:
    return f"{prefix}-{next(_uid_counter)}"


def now() -> float:
    return time.time()


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    uid: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    finalizers: list[str] = field(default_factory=list)
    owner_references: list[OwnerReference] = field(default_factory=list)
    resource_version: int = 0
    generation: int = 0
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None

    def key(self) -> str:
        return f"{self.namespace}/{self.name}" if self.namespace else self.name


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""


@dataclass
class Condition:
    """Mirrors metav1.Condition semantics (status True/False/Unknown)."""

    type: str = ""
    status: str = "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


def set_condition(conditions: list[Condition], cond: Condition) -> bool:
    """Upsert by type; only bumps transition time when status flips.

    Returns True when anything changed (reference: meta.SetStatusCondition use
    throughout pkg/scheduler/scheduler.go:913-961).
    """
    for i, existing in enumerate(conditions):
        if existing.type == cond.type:
            if (
                existing.status == cond.status
                and existing.reason == cond.reason
                and existing.message == cond.message
            ):
                return False
            if existing.status == cond.status:
                cond.last_transition_time = existing.last_transition_time
            elif not cond.last_transition_time:
                cond.last_transition_time = now()
            conditions[i] = cond
            return True
    if not cond.last_transition_time:
        cond.last_transition_time = now()
    conditions.append(cond)
    return True


def get_condition(conditions: list[Condition], ctype: str) -> Optional[Condition]:
    for c in conditions:
        if c.type == ctype:
            return c
    return None


def deepcopy_obj(obj: Any) -> Any:
    return copy.deepcopy(obj)


@dataclass
class LabelSelector:
    """matchLabels + matchExpressions (In/NotIn/Exists/DoesNotExist).

    Reference: metav1.LabelSelector as consumed by
    pkg/scheduler/framework/plugins/clusteraffinity/cluster_affinity.go:51-80.
    """

    match_labels: dict[str, str] = field(default_factory=dict)
    match_expressions: list[LabelSelectorRequirement] = field(default_factory=list)

    def matches(self, labels: dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for req in self.match_expressions:
            if not req.matches(labels):
                return False
        return True

    def is_empty(self) -> bool:
        return not self.match_labels and not self.match_expressions


@dataclass
class LabelSelectorRequirement:
    key: str = ""
    operator: str = "In"  # In | NotIn | Exists | DoesNotExist
    values: list[str] = field(default_factory=list)

    def matches(self, labels: dict[str, str]) -> bool:
        present = self.key in labels
        if self.operator == "Exists":
            return present
        if self.operator == "DoesNotExist":
            return not present
        if self.operator == "In":
            return present and labels[self.key] in self.values
        if self.operator == "NotIn":
            return not present or labels[self.key] not in self.values
        raise ValueError(f"unknown label selector operator {self.operator!r}")


def add_resources(a: Resources, b: Resources) -> Resources:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0.0) + v
    return out


def sub_resources(a: Resources, b: Resources) -> Resources:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0.0) - v
    return out


def dataclass_replace(obj, **kw):
    return dataclasses.replace(obj, **kw)

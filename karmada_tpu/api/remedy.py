"""Remedy API: cluster-condition-triggered remedy actions.

Parity with pkg/apis/remedy/v1alpha1: a Remedy selects clusters (by names or
all) and lists decisionMatches on cluster conditions; when a match fires, the
remedy's actions land in cluster.status.remedyActions
(pkg/controllers/remediation/remedy_controller.go:51).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .meta import ObjectMeta

KIND_REMEDY = "Remedy"

# ConditionType addressable by decision matches (remedy API)
SERVICE_DOMAIN_NAME_RESOLUTION_READY = "ServiceDomainNameResolutionReady"

# RemedyAction
ACTION_TRAFFIC_CONTROL = "TrafficControl"


@dataclass
class ClusterConditionRequirement:
    condition_type: str = ""
    operator: str = "Equal"  # Equal | NotEqual
    condition_status: str = ""  # "True" | "False" | "Unknown"


@dataclass
class DecisionMatch:
    cluster_condition_match: Optional[ClusterConditionRequirement] = None


@dataclass
class RemedyClusterAffinity:
    cluster_names: list[str] = field(default_factory=list)


@dataclass
class RemedySpec:
    cluster_affinity: Optional[RemedyClusterAffinity] = None  # None = all clusters
    decision_matches: list[DecisionMatch] = field(default_factory=list)  # empty = always
    actions: list[str] = field(default_factory=list)


@dataclass
class Remedy:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: RemedySpec = field(default_factory=RemedySpec)
    kind: str = KIND_REMEDY

    @property
    def name(self) -> str:
        return self.metadata.name

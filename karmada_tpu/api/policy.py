"""Policy API: PropagationPolicy / ClusterPropagationPolicy, Placement,
OverridePolicy.

Behavior parity with pkg/apis/policy/v1alpha1/propagation_types.go and
override_types.go: resource selectors (priority name>label), placement with
cluster affinity (+ordered affinity terms), tolerations, spread constraints
(min/max groups over provider/region/zone/cluster, types.go:466-504), replica
scheduling (Duplicated | Divided × Weighted/Aggregated × static/dynamic
weights, :543-631), failover behavior (:304-408), and override rules.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from .meta import LabelSelector, ObjectMeta

KIND_PROPAGATION_POLICY = "PropagationPolicy"
KIND_CLUSTER_PROPAGATION_POLICY = "ClusterPropagationPolicy"
KIND_OVERRIDE_POLICY = "OverridePolicy"
KIND_CLUSTER_OVERRIDE_POLICY = "ClusterOverridePolicy"

# ReplicaSchedulingType (propagation_types.go:543-550)
REPLICA_SCHEDULING_DUPLICATED = "Duplicated"
REPLICA_SCHEDULING_DIVIDED = "Divided"

# ReplicaDivisionPreference
DIVISION_PREFERENCE_AGGREGATED = "Aggregated"
DIVISION_PREFERENCE_WEIGHTED = "Weighted"

# DynamicWeightFactor (propagation_types.go:616-631)
DYNAMIC_WEIGHT_AVAILABLE_REPLICAS = "AvailableReplicas"

# SpreadFieldValue (propagation_types.go:466-504)
SPREAD_BY_FIELD_CLUSTER = "cluster"
SPREAD_BY_FIELD_REGION = "region"
SPREAD_BY_FIELD_ZONE = "zone"
SPREAD_BY_FIELD_PROVIDER = "provider"

# Failover PurgeMode
PURGE_MODE_IMMEDIATELY = "Immediately"
PURGE_MODE_GRACIOUSLY = "Graciously"
PURGE_MODE_NEVER = "Never"

# ConflictResolution
CONFLICT_OVERWRITE = "Overwrite"
CONFLICT_ABORT = "Abort"

DEFAULT_SCHEDULER_NAME = "default-scheduler"

# Scheduling preemption policy (kube PreemptionPolicy vocabulary, distinct
# from spec.preemption which governs POLICY-claim preemption in the
# detector): "" defaults to Never; PreemptLowerPriority lets an
# unschedulable binding evict placed replicas of strictly-lower-priority
# bindings (sched/preemption.py).
PREEMPT_NEVER = "Never"
PREEMPT_LOWER_PRIORITY = "PreemptLowerPriority"
VALID_SCHEDULER_PREEMPTION = ("", PREEMPT_NEVER, PREEMPT_LOWER_PRIORITY)

# schedule_priority bounds enforced at admission (webhook/handlers.py) —
# mirrors kube's PriorityClass value range so priorities stay well inside
# i32 for the tiered device solve
SCHEDULE_PRIORITY_BOUND = 1_000_000_000


@dataclass
class ResourceSelector:
    """propagation_types.go ResourceSelector: apiVersion+kind required,
    name > labelSelector precedence is enforced by the detector."""

    api_version: str = ""
    kind: str = ""
    namespace: str = ""
    name: str = ""
    label_selector: Optional[LabelSelector] = None


@dataclass
class ClusterAffinity:
    label_selector: Optional[LabelSelector] = None
    field_selector: Optional[FieldSelector] = None
    cluster_names: list[str] = field(default_factory=list)
    exclude: list[str] = field(default_factory=list)

    def is_empty(self) -> bool:
        return (
            self.label_selector is None
            and self.field_selector is None
            and not self.cluster_names
            and not self.exclude
        )


@dataclass
class ClusterAffinityTerm:
    """Ordered failover terms (propagation_types.go OrderedClusterAffinity);
    the scheduler retries terms in order
    (pkg/scheduler/scheduler.go:562-625)."""

    affinity_name: str = ""
    affinity: ClusterAffinity = field(default_factory=ClusterAffinity)


@dataclass
class FieldSelector:
    """Only provider/region/zone fields are addressable (cluster API)."""

    match_expressions: list[FieldSelectorRequirement] = field(default_factory=list)


@dataclass
class FieldSelectorRequirement:
    key: str = ""  # provider | region | zone
    operator: str = "In"  # In | NotIn
    values: list[str] = field(default_factory=list)


@dataclass
class Toleration:
    """Mirrors corev1.Toleration semantics as used by the TaintToleration
    filter (plugins/tainttoleration/taint_toleration.go:52)."""

    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty matches all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if not self.key:
            # empty key with Exists tolerates everything
            return self.operator == "Exists"
        if self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


@dataclass
class SpreadConstraint:
    spread_by_field: str = ""  # cluster|region|zone|provider
    spread_by_label: str = ""
    min_groups: int = 1
    max_groups: int = 0  # 0 = unconstrained


@dataclass
class StaticClusterWeight:
    target_cluster: ClusterAffinity = field(default_factory=ClusterAffinity)
    weight: int = 1


@dataclass
class ClusterPreferences:
    static_weight_list: list[StaticClusterWeight] = field(default_factory=list)
    dynamic_weight: str = ""  # "" | AvailableReplicas


@dataclass
class ReplicaSchedulingStrategy:
    replica_scheduling_type: str = REPLICA_SCHEDULING_DUPLICATED
    replica_division_preference: str = ""  # Aggregated | Weighted
    weight_preference: Optional[ClusterPreferences] = None


@dataclass
class Placement:
    cluster_affinity: Optional[ClusterAffinity] = None
    cluster_affinities: list[ClusterAffinityTerm] = field(default_factory=list)
    cluster_tolerations: list[Toleration] = field(default_factory=list)
    spread_constraints: list[SpreadConstraint] = field(default_factory=list)
    replica_scheduling: Optional[ReplicaSchedulingStrategy] = None

    def replica_scheduling_type(self) -> str:
        if self.replica_scheduling is None:
            return REPLICA_SCHEDULING_DUPLICATED
        return self.replica_scheduling.replica_scheduling_type


@dataclass
class ApplicationFailoverBehavior:
    decision_conditions_toleration_seconds: int = 300
    purge_mode: str = PURGE_MODE_GRACIOUSLY
    grace_period_seconds: Optional[int] = None
    state_preservation: Optional[StatePreservation] = None


@dataclass
class StatePreservation:
    rules: list[StatePreservationRule] = field(default_factory=list)


@dataclass
class StatePreservationRule:
    alias_label_name: str = ""
    json_path: str = ""


@dataclass
class FailoverBehavior:
    application: Optional[ApplicationFailoverBehavior] = None


@dataclass
class Suspension:
    dispatching: bool = False
    scheduling: bool = False


@dataclass
class PropagationSpec:
    resource_selectors: list[ResourceSelector] = field(default_factory=list)
    placement: Placement = field(default_factory=Placement)
    propagate_deps: bool = False
    priority: int = 0
    scheduler_priority: Optional[int] = None
    preemption: str = "Never"  # Never | Always (policy-claim preemption)
    # scheduling preemption: may this policy's bindings evict placed
    # replicas of strictly-lower-priority bindings when they place short?
    scheduler_preemption: str = ""  # "" | Never | PreemptLowerPriority
    # gang scheduling: bindings sharing gang_name co-admit as an
    # all-or-nothing cohort of gang_size members (sched/preemption.py);
    # template labels gang.karmada.io/{name,size} override per workload
    gang_name: str = ""
    gang_size: int = 0
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    failover: Optional[FailoverBehavior] = None
    suspension: Optional[Suspension] = None
    conflict_resolution: str = CONFLICT_ABORT
    activation_preference: str = ""  # "" | Lazy


@dataclass
class PropagationPolicy:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PropagationSpec = field(default_factory=PropagationSpec)
    kind: str = KIND_PROPAGATION_POLICY

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class ClusterPropagationPolicy:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PropagationSpec = field(default_factory=PropagationSpec)
    kind: str = KIND_CLUSTER_PROPAGATION_POLICY

    @property
    def name(self) -> str:
        return self.metadata.name


# ---------------------------------------------------------------------------
# Override policy (pkg/apis/policy/v1alpha1/override_types.go)
# ---------------------------------------------------------------------------


@dataclass
class ImageOverrider:
    component: str = ""  # Registry | Repository | Tag
    operator: str = "replace"  # add | remove | replace
    value: str = ""
    predicate_path: Optional[str] = None


@dataclass
class CommandArgsOverrider:
    container_name: str = ""
    operator: str = "add"  # add | remove
    value: list[str] = field(default_factory=list)


@dataclass
class LabelAnnotationOverrider:
    operator: str = "add"  # add | remove | replace
    value: dict[str, str] = field(default_factory=dict)


@dataclass
class PlaintextOverrider:
    path: str = ""  # JSON pointer
    operator: str = "add"  # add | remove | replace
    value: Any = None


@dataclass
class FieldPatchOperation:
    """JSONPatchOperation / YAMLPatchOperation (override_types.go:288-325):
    one add/remove/replace at an RFC 6901 subPath inside the embedded
    document."""

    sub_path: str = ""
    operator: str = "add"  # add | remove | replace
    value: Any = None


@dataclass
class FieldOverrider:
    """Modify a STRING field holding an embedded JSON or YAML document
    (e.g. a ConfigMap data value) with patch operations
    (override_types.go:266-286). Either `json` or `yaml` per instance."""

    field_path: str = ""  # RFC 6901 pointer to the string field
    json: list[FieldPatchOperation] = field(default_factory=list)
    yaml: list[FieldPatchOperation] = field(default_factory=list)


@dataclass
class Overriders:
    plaintext: list[PlaintextOverrider] = field(default_factory=list)
    image_overrider: list[ImageOverrider] = field(default_factory=list)
    command_overrider: list[CommandArgsOverrider] = field(default_factory=list)
    args_overrider: list[CommandArgsOverrider] = field(default_factory=list)
    labels_overrider: list[LabelAnnotationOverrider] = field(default_factory=list)
    annotations_overrider: list[LabelAnnotationOverrider] = field(default_factory=list)
    field_overrider: list[FieldOverrider] = field(default_factory=list)


@dataclass
class RuleWithCluster:
    target_cluster: Optional[ClusterAffinity] = None
    overriders: Overriders = field(default_factory=Overriders)


@dataclass
class OverrideSpec:
    resource_selectors: list[ResourceSelector] = field(default_factory=list)
    override_rules: list[RuleWithCluster] = field(default_factory=list)


@dataclass
class OverridePolicy:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: OverrideSpec = field(default_factory=OverrideSpec)
    kind: str = KIND_OVERRIDE_POLICY

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class ClusterOverridePolicy:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: OverrideSpec = field(default_factory=OverrideSpec)
    kind: str = KIND_CLUSTER_OVERRIDE_POLICY

    @property
    def name(self) -> str:
        return self.metadata.name

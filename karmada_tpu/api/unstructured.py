"""Unstructured workload objects (templates) stored as plain dict manifests.

The reference detector watches *all* API resources as
unstructured.Unstructured (pkg/detector/detector.go:112); we mirror that with
a thin wrapper over a dict manifest that exposes ObjectMeta accessors so it can
live in the same store as typed objects.
"""
from __future__ import annotations

import copy
from typing import Any, Optional

from .meta import ObjectMeta


def copy_json_tree(x: Any) -> Any:
    """Deep-copy a JSON-shaped tree several times faster than copy.deepcopy
    (no memo bookkeeping, no reduce protocol) — manifests are copied on
    every store write, so this is control-plane write-path time. Non-JSON
    leaves (rare: objects placed via set()) fall back to copy.deepcopy for
    fidelity. Aliased sub-trees are duplicated rather than shared, which
    only strengthens isolation for store semantics; cycles are the
    caller's bug (json.dumps would reject the manifest anyway)."""
    t = x.__class__
    if t is dict:
        return {k: copy_json_tree(v) for k, v in x.items()}
    if t is list:
        return [copy_json_tree(v) for v in x]
    if t is str or t is int or t is float or t is bool or x is None:
        return x
    return copy.deepcopy(x)


class Unstructured:
    """Dict-backed object: {'apiVersion','kind','metadata',...}."""

    def __init__(self, manifest: dict):
        manifest.setdefault("metadata", {})
        self._m = manifest
        self._load_meta()

    def _load_meta(self) -> None:
        """(Re)build the typed metadata view from the backing dict."""
        md = self._m["metadata"]
        self.metadata = ObjectMeta(
            name=md.get("name", ""),
            namespace=md.get("namespace", ""),
            uid=md.get("uid", ""),
            labels=md.setdefault("labels", {}),
            annotations=md.setdefault("annotations", {}),
            finalizers=md.setdefault("finalizers", []),
            resource_version=md.get("resourceVersion", 0),
            generation=md.get("generation", 0),
            creation_timestamp=md.get("creationTimestamp", 0.0),
            deletion_timestamp=md.get("deletionTimestamp"),
        )

    # Keep the wrapper and the dict view coherent when the store mutates meta.
    def sync_meta(self) -> None:
        md = self._m["metadata"]
        md["name"] = self.metadata.name
        md["namespace"] = self.metadata.namespace
        md["uid"] = self.metadata.uid
        md["labels"] = self.metadata.labels
        md["annotations"] = self.metadata.annotations
        md["finalizers"] = self.metadata.finalizers
        md["resourceVersion"] = self.metadata.resource_version
        md["generation"] = self.metadata.generation
        md["creationTimestamp"] = self.metadata.creation_timestamp
        if self.metadata.deletion_timestamp is not None:
            md["deletionTimestamp"] = self.metadata.deletion_timestamp

    @property
    def kind(self) -> str:
        return self._m.get("kind", "")

    @property
    def api_version(self) -> str:
        return self._m.get("apiVersion", "")

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def to_dict(self) -> dict:
        self.sync_meta()
        return copy_json_tree(self._m)

    def spec_view(self) -> dict:
        """The manifest minus status/metadata WITHOUT copying — the store's
        generation-diff compares two of these for equality only. Read-only:
        the values alias the live manifest; callers must not mutate or
        retain them. (to_dict() here deepcopied the whole manifest twice
        per update, inside the store's critical section.)"""
        return {
            k: v for k, v in self._m.items()
            if k not in ("status", "metadata")
        }

    def merge_patch(self, patch: dict) -> None:
        """RFC 7386 merge-patch applied in place: null deletes, dicts
        recurse (kubectl patch --type=merge semantics)."""

        def merge(dst: dict, src: dict) -> None:
            for k, v in src.items():
                if v is None:
                    dst.pop(k, None)
                elif isinstance(v, dict) and isinstance(dst.get(k), dict):
                    merge(dst[k], v)
                else:
                    dst[k] = v

        merge(self._m, patch)
        self._m.setdefault("metadata", {})
        # re-derive the typed view: without this, sync_meta would write the
        # PRE-patch metadata back over any metadata fields the patch touched
        self._load_meta()

    def get(self, *path: str, default: Any = None) -> Any:
        cur: Any = self._m
        for p in path:
            if not isinstance(cur, dict) or p not in cur:
                return default
            cur = cur[p]
        return cur

    def set(self, *path_and_value: Any) -> None:
        *path, value = path_and_value
        cur = self._m
        for p in path[:-1]:
            cur = cur.setdefault(p, {})
        cur[path[-1]] = value

    @property
    def spec(self) -> dict:
        return self._m.setdefault("spec", {})

    @property
    def status(self) -> dict:
        return self._m.setdefault("status", {})

    @status.setter
    def status(self, v: dict) -> None:
        self._m["status"] = v

    def __deepcopy__(self, memo):
        self.sync_meta()
        return Unstructured(copy_json_tree(self._m))

    def __repr__(self) -> str:
        return f"Unstructured({self.api_version}/{self.kind} {self.metadata.key()})"

"""Autoscaling API (reference: pkg/apis/autoscaling/v1alpha1 — FederatedHPA +
CronFederatedHPA CRDs consumed by pkg/controllers/{federatedhpa,cronfederatedhpa}).

FederatedHPA scales a workload template across the whole federation on
aggregated member-cluster pod metrics; CronFederatedHPA scales a FederatedHPA
(its min/max) or a workload (its replicas) on cron schedules.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .meta import ObjectMeta

KIND_FEDERATED_HPA = "FederatedHPA"
KIND_CRON_FEDERATED_HPA = "CronFederatedHPA"


@dataclass
class ScaleTargetRef:
    api_version: str = "apps/v1"
    kind: str = ""
    name: str = ""


@dataclass
class ResourceMetricSource:
    """metrics: resource type with target average utilization percentage
    (autoscaling/v2 ResourceMetricSource as used by FederatedHPA)."""

    name: str = "cpu"
    target_average_utilization: int = 80  # percent of request


@dataclass
class FederatedHPASpec:
    scale_target_ref: ScaleTargetRef = field(default_factory=ScaleTargetRef)
    min_replicas: Optional[int] = 1
    max_replicas: int = 1
    metrics: list[ResourceMetricSource] = field(default_factory=list)


@dataclass
class FederatedHPAStatus:
    current_replicas: int = 0
    desired_replicas: int = 0
    current_average_utilization: Optional[int] = None
    last_scale_time: Optional[float] = None


@dataclass
class FederatedHPA:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: FederatedHPASpec = field(default_factory=FederatedHPASpec)
    status: FederatedHPAStatus = field(default_factory=FederatedHPAStatus)
    kind: str = KIND_FEDERATED_HPA

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class CronFederatedHPARule:
    name: str = ""
    schedule: str = ""  # 5-field cron
    target_replicas: Optional[int] = None  # when scaling a workload
    target_min_replicas: Optional[int] = None  # when scaling a FederatedHPA
    target_max_replicas: Optional[int] = None
    suspend: bool = False


@dataclass
class CronFederatedHPASpec:
    scale_target_ref: ScaleTargetRef = field(default_factory=ScaleTargetRef)
    rules: list[CronFederatedHPARule] = field(default_factory=list)


@dataclass
class ExecutionHistory:
    rule_name: str = ""
    next_execution_time: Optional[float] = None
    last_execution_time: Optional[float] = None
    last_result: str = ""  # Succeed | Failed
    message: str = ""


@dataclass
class CronFederatedHPAStatus:
    execution_histories: list[ExecutionHistory] = field(default_factory=list)


@dataclass
class CronFederatedHPA:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CronFederatedHPASpec = field(default_factory=CronFederatedHPASpec)
    status: CronFederatedHPAStatus = field(default_factory=CronFederatedHPAStatus)
    kind: str = KIND_CRON_FEDERATED_HPA

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

"""Autoscaling API (reference: pkg/apis/autoscaling/v1alpha1 — FederatedHPA +
CronFederatedHPA CRDs consumed by pkg/controllers/{federatedhpa,cronfederatedhpa}).

FederatedHPA scales a workload template across the whole federation on
aggregated member-cluster pod metrics; CronFederatedHPA scales a FederatedHPA
(its min/max) or a workload (its replicas) on cron schedules.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .meta import ObjectMeta

KIND_FEDERATED_HPA = "FederatedHPA"
KIND_CRON_FEDERATED_HPA = "CronFederatedHPA"
KIND_WORKLOAD_METRICS_REPORT = "WorkloadMetricsReport"


@dataclass
class ScaleTargetRef:
    api_version: str = "apps/v1"
    kind: str = ""
    name: str = ""


@dataclass
class ResourceMetricSource:
    """metrics: resource type with target average utilization percentage
    (autoscaling/v2 ResourceMetricSource as used by FederatedHPA)."""

    name: str = "cpu"
    target_average_utilization: int = 80  # percent of request


@dataclass
class HPABehavior:
    """Per-direction stabilization windows (autoscaling/v2
    HPAScalingRules.stabilizationWindowSeconds, kube defaults: scale-up 0,
    scale-down 300). The elasticity daemon applies them as the hysteresis
    half of its vectorized step: scale-up is damped to the MIN
    recommendation over the up window, scale-down to the MAX over the down
    window — a metric flapping inside the window produces zero scale
    events."""

    scale_up_stabilization_seconds: float = 0.0
    scale_down_stabilization_seconds: float = 300.0


@dataclass
class FederatedHPASpec:
    scale_target_ref: ScaleTargetRef = field(default_factory=ScaleTargetRef)
    min_replicas: Optional[int] = 1
    max_replicas: int = 1
    metrics: list[ResourceMetricSource] = field(default_factory=list)
    behavior: HPABehavior = field(default_factory=HPABehavior)
    # HPAScaleToZero analogue: allows minReplicas 0 — the workload scales
    # to zero when its utilization drops to zero and resurrects (through
    # ordinary scheduler admission) when the demand signal returns
    scale_to_zero: bool = False


@dataclass
class FederatedHPAStatus:
    current_replicas: int = 0
    desired_replicas: int = 0
    current_average_utilization: Optional[int] = None
    # which metric the observed percent belongs to (the last RESOLVED
    # metric — without this, a multi-metric printer would attribute the
    # one stored number to the wrong metric)
    current_metric: str = ""
    last_scale_time: Optional[float] = None


@dataclass
class FederatedHPA:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: FederatedHPASpec = field(default_factory=FederatedHPASpec)
    status: FederatedHPAStatus = field(default_factory=FederatedHPAStatus)
    kind: str = KIND_FEDERATED_HPA

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class CronFederatedHPARule:
    name: str = ""
    schedule: str = ""  # 5-field cron
    target_replicas: Optional[int] = None  # when scaling a workload
    target_min_replicas: Optional[int] = None  # when scaling a FederatedHPA
    target_max_replicas: Optional[int] = None
    suspend: bool = False


@dataclass
class CronFederatedHPASpec:
    scale_target_ref: ScaleTargetRef = field(default_factory=ScaleTargetRef)
    rules: list[CronFederatedHPARule] = field(default_factory=list)


@dataclass
class ExecutionHistory:
    rule_name: str = ""
    next_execution_time: Optional[float] = None
    last_execution_time: Optional[float] = None
    last_result: str = ""  # Succeed | Failed
    message: str = ""


@dataclass
class CronFederatedHPAStatus:
    execution_histories: list[ExecutionHistory] = field(default_factory=list)


@dataclass
class CronFederatedHPA:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CronFederatedHPASpec = field(default_factory=CronFederatedHPASpec)
    status: CronFederatedHPAStatus = field(default_factory=CronFederatedHPAStatus)
    kind: str = KIND_CRON_FEDERATED_HPA

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class WorkloadMetricsRow:
    """One workload's metrics in one member cluster, as reported by that
    cluster's status stream: ready pod count + average PER-POD usage. A
    workload at zero ready pods carries its raw demand signal instead
    (queue depth / external traffic — the scale-from-zero trigger; with no
    pods there are no pod metrics to report)."""

    kind: str = ""
    namespace: str = ""
    name: str = ""
    ready_pods: int = 0
    usage: dict[str, float] = field(default_factory=dict)  # per ready pod
    demand: dict[str, float] = field(default_factory=dict)  # at 0 ready


@dataclass
class WorkloadMetricsReport:
    """Per-cluster workload utilization report (cluster-scoped, named after
    the member): the feed the elasticity daemon's aggregator folds into its
    [W, C] usage/capacity matrix. Pull agents publish it on their heartbeat
    through the coalesced agent-status write path; the control plane
    collects it for push members. Level-triggered and last-write-wins: a
    report wholly REPLACES the cluster's previous rows."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    cluster: str = ""
    rows: list[WorkloadMetricsRow] = field(default_factory=list)
    reported_at: float = 0.0
    kind: str = KIND_WORKLOAD_METRICS_REPORT

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

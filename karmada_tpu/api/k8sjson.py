"""Parsers for the reference's own JSON wire shapes → our typed API.

A stock Go karmada component marshals its CRD structs with k8s JSON tags
(camelCase, quantity strings, RFC3339 times). The scheduler sidecar shim
accepts exactly those bytes, so the Go side needs no translation layer:
`json.Marshal(spec)` of a `workv1alpha2.ResourceBindingSpec` (
binding_types.go) or a `clusterv1alpha1.Cluster` (types.go) is a valid
request body. Unknown fields are ignored (k8s clients are forward-
compatible the same way).
"""
from __future__ import annotations

from datetime import datetime
from typing import Any, Optional

from ..interpreter.interpreter import _parse_quantity
from . import policy as pol
from .cluster import (
    APIEnablement,
    Cluster,
    ClusterSpec,
    ClusterStatus,
    NodeSummary,
    ResourceSummary,
    Taint,
)
from .meta import (
    Condition,
    LabelSelector,
    LabelSelectorRequirement,
    ObjectMeta,
)
from .work import (
    BindingSpec,
    NodeClaim,
    ObjectReference,
    ReplicaRequirements,
    TargetCluster,
)


def rfc3339_to_epoch(v: Any) -> Optional[float]:
    if v in (None, ""):
        return None
    if isinstance(v, (int, float)):
        return float(v)
    # metav1.Time marshals as RFC3339 (Z or numeric offset, optional
    # fractional seconds) — exactly what fromisoformat accepts
    try:
        return datetime.fromisoformat(str(v).replace("Z", "+00:00")).timestamp()
    except ValueError:
        return None


def resources_from_json(d: Optional[dict]) -> dict[str, float]:
    return {k: _parse_quantity(v) for k, v in (d or {}).items()}


def _label_selector(d: Optional[dict]) -> Optional[LabelSelector]:
    if not d:
        return None
    return LabelSelector(
        match_labels=dict(d.get("matchLabels") or {}),
        match_expressions=[
            LabelSelectorRequirement(
                key=e.get("key", ""),
                operator=e.get("operator", "In"),
                values=list(e.get("values") or []),
            )
            for e in (d.get("matchExpressions") or [])
        ],
    )


def _field_selector(d: Optional[dict]) -> Optional[pol.FieldSelector]:
    if not d:
        return None
    return pol.FieldSelector(
        match_expressions=[
            pol.FieldSelectorRequirement(
                key=e.get("key", ""),
                operator=e.get("operator", "In"),
                values=list(e.get("values") or []),
            )
            for e in (d.get("matchExpressions") or [])
        ]
    )


def cluster_affinity_from_json(d: Optional[dict]) -> Optional[pol.ClusterAffinity]:
    if d is None:
        return None
    return pol.ClusterAffinity(
        label_selector=_label_selector(d.get("labelSelector")),
        field_selector=_field_selector(d.get("fieldSelector")),
        cluster_names=list(d.get("clusterNames") or []),
        exclude=list(d.get("exclude") or []),
    )


def _toleration(d: dict) -> pol.Toleration:
    return pol.Toleration(
        key=d.get("key", ""),
        operator=d.get("operator", "Equal"),
        value=d.get("value", ""),
        effect=d.get("effect", ""),
        toleration_seconds=d.get("tolerationSeconds"),
    )


def placement_from_json(d: Optional[dict]) -> Optional[pol.Placement]:
    """propagation_types.go Placement (JSON tags) → Placement."""
    if d is None:
        return None
    rs = d.get("replicaScheduling")
    strategy = None
    if rs is not None:
        wp = rs.get("weightPreference")
        prefs = None
        if wp is not None:
            prefs = pol.ClusterPreferences(
                static_weight_list=[
                    pol.StaticClusterWeight(
                        target_cluster=cluster_affinity_from_json(
                            w.get("targetCluster")
                        ) or pol.ClusterAffinity(),
                        weight=int(w.get("weight", 1)),
                    )
                    for w in (wp.get("staticWeightList") or [])
                ],
                dynamic_weight=wp.get("dynamicWeight", ""),
            )
        strategy = pol.ReplicaSchedulingStrategy(
            replica_scheduling_type=rs.get(
                "replicaSchedulingType", pol.REPLICA_SCHEDULING_DUPLICATED
            ),
            replica_division_preference=rs.get("replicaDivisionPreference", ""),
            weight_preference=prefs,
        )
    return pol.Placement(
        cluster_affinity=cluster_affinity_from_json(d.get("clusterAffinity")),
        cluster_affinities=[
            pol.ClusterAffinityTerm(
                affinity_name=t.get("affinityName", ""),
                affinity=cluster_affinity_from_json(t) or pol.ClusterAffinity(),
            )
            for t in (d.get("clusterAffinities") or [])
        ],
        cluster_tolerations=[
            _toleration(t) for t in (d.get("clusterTolerations") or [])
        ],
        spread_constraints=[
            pol.SpreadConstraint(
                spread_by_field=s.get("spreadByField", ""),
                spread_by_label=s.get("spreadByLabel", ""),
                min_groups=int(s.get("minGroups") or 1),
                max_groups=int(s.get("maxGroups") or 0),
            )
            for s in (d.get("spreadConstraints") or [])
        ],
        replica_scheduling=strategy,
    )


def replica_requirements_from_json(d: Optional[dict]) -> Optional[ReplicaRequirements]:
    if d is None:
        return None
    nc = d.get("nodeClaim")
    claim = None
    if nc is not None:
        claim = NodeClaim(
            node_selector=dict(nc.get("nodeSelector") or {}),
            tolerations=list(nc.get("tolerations") or []),
            hard_node_affinity=nc.get("hardNodeAffinity"),
        )
    return ReplicaRequirements(
        node_claim=claim,
        resource_request=resources_from_json(d.get("resourceRequest")),
        namespace=d.get("namespace", ""),
        priority_class_name=d.get("priorityClassName", ""),
    )


def binding_spec_from_json(d: dict) -> BindingSpec:
    """workv1alpha2.ResourceBindingSpec JSON → BindingSpec (the scheduler's
    slice of it: resource identity, replicas+requirements, placement,
    previous clusters, reschedule trigger)."""
    res = d.get("resource") or {}
    return BindingSpec(
        resource=ObjectReference(
            api_version=res.get("apiVersion", ""),
            kind=res.get("kind", ""),
            namespace=res.get("namespace", ""),
            name=res.get("name", ""),
            uid=res.get("uid", ""),
        ),
        replicas=int(d.get("replicas") or 0),
        replica_requirements=replica_requirements_from_json(
            d.get("replicaRequirements")
        ),
        placement=placement_from_json(d.get("placement")),
        clusters=[
            TargetCluster(name=c.get("name", ""), replicas=int(c.get("replicas") or 0))
            for c in (d.get("clusters") or [])
        ],
        scheduler_name=d.get("schedulerName", ""),
        reschedule_triggered_at=rfc3339_to_epoch(d.get("rescheduleTriggeredAt")),
    )


def cluster_from_json(d: dict) -> Cluster:
    """clusterv1alpha1.Cluster JSON → Cluster (the scheduler's slice:
    identity/topology, taints, Ready condition, resource summary, API
    enablements)."""
    meta = d.get("metadata") or {}
    spec = d.get("spec") or {}
    status = d.get("status") or {}
    summary = status.get("resourceSummary") or {}
    nodes = status.get("nodeSummary") or {}
    return Cluster(
        metadata=ObjectMeta(
            name=meta.get("name", ""),
            labels=dict(meta.get("labels") or {}),
        ),
        spec=ClusterSpec(
            sync_mode=spec.get("syncMode", "Push"),
            provider=spec.get("provider", ""),
            region=spec.get("region", ""),
            zone=spec.get("zone", ""),
            taints=[
                Taint(
                    key=t.get("key", ""),
                    value=t.get("value", ""),
                    effect=t.get("effect", ""),
                    time_added=rfc3339_to_epoch(t.get("timeAdded")),
                )
                for t in (spec.get("taints") or [])
            ],
        ),
        status=ClusterStatus(
            kubernetes_version=status.get("kubernetesVersion", ""),
            api_enablements=[
                APIEnablement(
                    group_version=e.get("groupVersion", ""),
                    resources=[
                        r.get("kind", "") for r in (e.get("resources") or [])
                    ],
                )
                for e in (status.get("apiEnablements") or [])
            ],
            conditions=[
                Condition(
                    type=c.get("type", ""),
                    status=c.get("status", ""),
                    reason=c.get("reason", ""),
                    message=c.get("message", ""),
                )
                for c in (status.get("conditions") or [])
            ],
            node_summary=NodeSummary(
                total_num=int(nodes.get("totalNum") or 0),
                ready_num=int(nodes.get("readyNum") or 0),
            ),
            resource_summary=ResourceSummary(
                allocatable=resources_from_json(summary.get("allocatable")),
                allocating=resources_from_json(summary.get("allocating")),
                allocated=resources_from_json(summary.get("allocated")),
            ),
        ),
    )


def target_clusters_to_json(clusters: list[TargetCluster]) -> list[dict]:
    """→ workv1alpha2.TargetCluster JSON (the ScheduleResult payload)."""
    return [
        {"name": tc.name, **({"replicas": tc.replicas} if tc.replicas else {})}
        for tc in clusters
    ]

"""Parsers for the reference's own JSON wire shapes → our typed API.

A stock Go karmada component marshals its CRD structs with k8s JSON tags
(camelCase, quantity strings, RFC3339 times). The scheduler sidecar shim
accepts exactly those bytes, so the Go side needs no translation layer:
`json.Marshal(spec)` of a `workv1alpha2.ResourceBindingSpec` (
binding_types.go) or a `clusterv1alpha1.Cluster` (types.go) is a valid
request body. Unknown fields are ignored (k8s clients are forward-
compatible the same way).
"""
from __future__ import annotations

from datetime import datetime
from typing import Any, Optional

from ..interpreter.interpreter import _parse_quantity
from . import policy as pol
from .cluster import (
    APIEnablement,
    Cluster,
    ClusterSpec,
    ClusterStatus,
    NodeSummary,
    ResourceSummary,
    Taint,
)
from .meta import (
    Condition,
    LabelSelector,
    LabelSelectorRequirement,
    ObjectMeta,
)
from .work import (
    BindingSpec,
    NodeClaim,
    ObjectReference,
    ReplicaRequirements,
    TargetCluster,
)


def rfc3339_to_epoch(v: Any) -> Optional[float]:
    if v in (None, ""):
        return None
    if isinstance(v, (int, float)):
        return float(v)
    # metav1.Time marshals as RFC3339 (Z or numeric offset, optional
    # fractional seconds) — exactly what fromisoformat accepts
    try:
        return datetime.fromisoformat(str(v).replace("Z", "+00:00")).timestamp()
    except ValueError:
        return None


def resources_from_json(d: Optional[dict]) -> dict[str, float]:
    return {k: _parse_quantity(v) for k, v in (d or {}).items()}


def _label_selector(d: Optional[dict]) -> Optional[LabelSelector]:
    if not d:
        return None
    return LabelSelector(
        match_labels=dict(d.get("matchLabels") or {}),
        match_expressions=[
            LabelSelectorRequirement(
                key=e.get("key", ""),
                operator=e.get("operator", "In"),
                values=list(e.get("values") or []),
            )
            for e in (d.get("matchExpressions") or [])
        ],
    )


def _field_selector(d: Optional[dict]) -> Optional[pol.FieldSelector]:
    if not d:
        return None
    return pol.FieldSelector(
        match_expressions=[
            pol.FieldSelectorRequirement(
                key=e.get("key", ""),
                operator=e.get("operator", "In"),
                values=list(e.get("values") or []),
            )
            for e in (d.get("matchExpressions") or [])
        ]
    )


def cluster_affinity_from_json(d: Optional[dict]) -> Optional[pol.ClusterAffinity]:
    if d is None:
        return None
    return pol.ClusterAffinity(
        label_selector=_label_selector(d.get("labelSelector")),
        field_selector=_field_selector(d.get("fieldSelector")),
        cluster_names=list(d.get("clusterNames") or []),
        exclude=list(d.get("exclude") or []),
    )


def _toleration(d: dict) -> pol.Toleration:
    return pol.Toleration(
        key=d.get("key", ""),
        operator=d.get("operator", "Equal"),
        value=d.get("value", ""),
        effect=d.get("effect", ""),
        toleration_seconds=d.get("tolerationSeconds"),
    )


def placement_from_json(d: Optional[dict]) -> Optional[pol.Placement]:
    """propagation_types.go Placement (JSON tags) → Placement."""
    if d is None:
        return None
    rs = d.get("replicaScheduling")
    strategy = None
    if rs is not None:
        wp = rs.get("weightPreference")
        prefs = None
        if wp is not None:
            prefs = pol.ClusterPreferences(
                static_weight_list=[
                    pol.StaticClusterWeight(
                        target_cluster=cluster_affinity_from_json(
                            w.get("targetCluster")
                        ) or pol.ClusterAffinity(),
                        weight=int(w.get("weight", 1)),
                    )
                    for w in (wp.get("staticWeightList") or [])
                ],
                dynamic_weight=wp.get("dynamicWeight", ""),
            )
        strategy = pol.ReplicaSchedulingStrategy(
            replica_scheduling_type=rs.get(
                "replicaSchedulingType", pol.REPLICA_SCHEDULING_DUPLICATED
            ),
            replica_division_preference=rs.get("replicaDivisionPreference", ""),
            weight_preference=prefs,
        )
    return pol.Placement(
        cluster_affinity=cluster_affinity_from_json(d.get("clusterAffinity")),
        cluster_affinities=[
            pol.ClusterAffinityTerm(
                affinity_name=t.get("affinityName", ""),
                affinity=cluster_affinity_from_json(t) or pol.ClusterAffinity(),
            )
            for t in (d.get("clusterAffinities") or [])
        ],
        cluster_tolerations=[
            _toleration(t) for t in (d.get("clusterTolerations") or [])
        ],
        spread_constraints=[
            pol.SpreadConstraint(
                spread_by_field=s.get("spreadByField", ""),
                spread_by_label=s.get("spreadByLabel", ""),
                min_groups=int(s.get("minGroups") or 1),
                max_groups=int(s.get("maxGroups") or 0),
            )
            for s in (d.get("spreadConstraints") or [])
        ],
        replica_scheduling=strategy,
    )


def replica_requirements_from_json(d: Optional[dict]) -> Optional[ReplicaRequirements]:
    if d is None:
        return None
    nc = d.get("nodeClaim")
    claim = None
    if nc is not None:
        claim = NodeClaim(
            node_selector=dict(nc.get("nodeSelector") or {}),
            tolerations=list(nc.get("tolerations") or []),
            hard_node_affinity=nc.get("hardNodeAffinity"),
        )
    return ReplicaRequirements(
        node_claim=claim,
        resource_request=resources_from_json(d.get("resourceRequest")),
        namespace=d.get("namespace", ""),
        priority_class_name=d.get("priorityClassName", ""),
    )


def binding_spec_from_json(d: dict) -> BindingSpec:
    """workv1alpha2.ResourceBindingSpec JSON → BindingSpec (the scheduler's
    slice of it: resource identity, replicas+requirements, placement,
    previous clusters, reschedule trigger)."""
    res = d.get("resource") or {}
    return BindingSpec(
        resource=ObjectReference(
            api_version=res.get("apiVersion", ""),
            kind=res.get("kind", ""),
            namespace=res.get("namespace", ""),
            name=res.get("name", ""),
            uid=res.get("uid", ""),
        ),
        replicas=int(d.get("replicas") or 0),
        replica_requirements=replica_requirements_from_json(
            d.get("replicaRequirements")
        ),
        placement=placement_from_json(d.get("placement")),
        clusters=[
            TargetCluster(name=c.get("name", ""), replicas=int(c.get("replicas") or 0))
            for c in (d.get("clusters") or [])
        ],
        scheduler_name=d.get("schedulerName", ""),
        reschedule_triggered_at=rfc3339_to_epoch(d.get("rescheduleTriggeredAt")),
    )


def cluster_from_json(d: dict) -> Cluster:
    """clusterv1alpha1.Cluster JSON → Cluster (the scheduler's slice:
    identity/topology, taints, Ready condition, resource summary, API
    enablements)."""
    meta = d.get("metadata") or {}
    spec = d.get("spec") or {}
    status = d.get("status") or {}
    summary = status.get("resourceSummary") or {}
    nodes = status.get("nodeSummary") or {}
    return Cluster(
        metadata=ObjectMeta(
            name=meta.get("name", ""),
            labels=dict(meta.get("labels") or {}),
        ),
        spec=ClusterSpec(
            sync_mode=spec.get("syncMode", "Push"),
            provider=spec.get("provider", ""),
            region=spec.get("region", ""),
            zone=spec.get("zone", ""),
            taints=[
                Taint(
                    key=t.get("key", ""),
                    value=t.get("value", ""),
                    effect=t.get("effect", ""),
                    time_added=rfc3339_to_epoch(t.get("timeAdded")),
                )
                for t in (spec.get("taints") or [])
            ],
        ),
        status=ClusterStatus(
            kubernetes_version=status.get("kubernetesVersion", ""),
            api_enablements=[
                APIEnablement(
                    group_version=e.get("groupVersion", ""),
                    resources=[
                        r.get("kind", "") for r in (e.get("resources") or [])
                    ],
                )
                for e in (status.get("apiEnablements") or [])
            ],
            conditions=[
                Condition(
                    type=c.get("type", ""),
                    status=c.get("status", ""),
                    reason=c.get("reason", ""),
                    message=c.get("message", ""),
                )
                for c in (status.get("conditions") or [])
            ],
            node_summary=NodeSummary(
                total_num=int(nodes.get("totalNum") or 0),
                ready_num=int(nodes.get("readyNum") or 0),
            ),
            resource_summary=ResourceSummary(
                allocatable=resources_from_json(summary.get("allocatable")),
                allocating=resources_from_json(summary.get("allocating")),
                allocated=resources_from_json(summary.get("allocated")),
            ),
        ),
    )


def target_clusters_to_json(clusters: list[TargetCluster]) -> list[dict]:
    """→ workv1alpha2.TargetCluster JSON (the ScheduleResult payload)."""
    return [
        {"name": tc.name, **({"replicas": tc.replicas} if tc.replicas else {})}
        for tc in clusters
    ]


# -- typed → reference JSON (the marshal direction a Go component's own
# json.Marshal produces; mirrors of the parsers above, omitempty-style) ------


def epoch_to_rfc3339(v: Optional[float]) -> Optional[str]:
    if v is None:
        return None
    from datetime import timezone

    return (
        datetime.fromtimestamp(float(v), tz=timezone.utc)
        .isoformat()
        .replace("+00:00", "Z")
    )


def resources_to_json(d: Optional[dict]) -> dict:
    """→ corev1.ResourceList quantity strings ('2', '0.25')."""
    out = {}
    for k, v in (d or {}).items():
        out[k] = str(int(v)) if float(v) == int(v) else repr(float(v))
    return out


def _label_selector_to_json(s: Optional[LabelSelector]) -> Optional[dict]:
    if s is None:
        return None
    out: dict = {}
    if s.match_labels:
        out["matchLabels"] = dict(s.match_labels)
    if s.match_expressions:
        out["matchExpressions"] = [
            {"key": e.key, "operator": e.operator,
             **({"values": list(e.values)} if e.values else {})}
            for e in s.match_expressions
        ]
    # an empty selector parses back as None; omit it so marshal∘parse∘marshal
    # is a fixpoint (it selects everything either way)
    return out or None


def _field_selector_to_json(s) -> Optional[dict]:
    if s is None:
        return None
    return {
        "matchExpressions": [
            {"key": e.key, "operator": e.operator,
             **({"values": list(e.values)} if e.values else {})}
            for e in s.match_expressions
        ]
    }


def cluster_affinity_to_json(a: Optional[pol.ClusterAffinity]) -> Optional[dict]:
    if a is None:
        return None
    out: dict = {}
    sel = _label_selector_to_json(a.label_selector)
    if sel is not None:
        out["labelSelector"] = sel
    fsel = _field_selector_to_json(a.field_selector)
    if fsel is not None:
        out["fieldSelector"] = fsel
    if a.cluster_names:
        out["clusterNames"] = list(a.cluster_names)
    if a.exclude:
        out["exclude"] = list(a.exclude)
    return out


def _toleration_to_json(t: pol.Toleration) -> dict:
    out: dict = {}
    if t.key:
        out["key"] = t.key
    # the parser defaults a missing operator to Equal; normalize here so
    # the marshal is a fixpoint under parse∘marshal
    out["operator"] = t.operator or "Equal"
    if t.value:
        out["value"] = t.value
    if t.effect:
        out["effect"] = t.effect
    if t.toleration_seconds is not None:
        out["tolerationSeconds"] = t.toleration_seconds
    return out


def placement_to_json(p: Optional[pol.Placement]) -> Optional[dict]:
    if p is None:
        return None
    out: dict = {}
    aff = cluster_affinity_to_json(p.cluster_affinity)
    if aff is not None:
        out["clusterAffinity"] = aff
    if p.cluster_affinities:
        out["clusterAffinities"] = [
            {"affinityName": t.affinity_name,
             **(cluster_affinity_to_json(t.affinity) or {})}
            for t in p.cluster_affinities
        ]
    if p.cluster_tolerations:
        out["clusterTolerations"] = [
            _toleration_to_json(t) for t in p.cluster_tolerations
        ]
    if p.spread_constraints:
        out["spreadConstraints"] = [
            {
                **({"spreadByField": s.spread_by_field}
                   if s.spread_by_field else {}),
                **({"spreadByLabel": s.spread_by_label}
                   if s.spread_by_label else {}),
                "minGroups": s.min_groups or 1,
                **({"maxGroups": s.max_groups} if s.max_groups else {}),
            }
            for s in p.spread_constraints
        ]
    rs = p.replica_scheduling
    if rs is not None:
        rsj: dict = {"replicaSchedulingType": rs.replica_scheduling_type}
        if rs.replica_division_preference:
            rsj["replicaDivisionPreference"] = rs.replica_division_preference
        wp = rs.weight_preference
        if wp is not None:
            wpj: dict = {}
            if wp.static_weight_list:
                wpj["staticWeightList"] = [
                    {
                        "targetCluster": cluster_affinity_to_json(
                            w.target_cluster
                        ) or {},
                        "weight": w.weight,
                    }
                    for w in wp.static_weight_list
                ]
            if wp.dynamic_weight:
                wpj["dynamicWeight"] = wp.dynamic_weight
            rsj["weightPreference"] = wpj
        out["replicaScheduling"] = rsj
    return out


def replica_requirements_to_json(r: Optional[ReplicaRequirements]) -> Optional[dict]:
    if r is None:
        return None
    out: dict = {}
    if r.node_claim is not None:
        nc: dict = {}
        if r.node_claim.node_selector:
            nc["nodeSelector"] = dict(r.node_claim.node_selector)
        if r.node_claim.tolerations:
            nc["tolerations"] = list(r.node_claim.tolerations)
        if r.node_claim.hard_node_affinity is not None:
            nc["hardNodeAffinity"] = r.node_claim.hard_node_affinity
        out["nodeClaim"] = nc
    if r.resource_request:
        out["resourceRequest"] = resources_to_json(r.resource_request)
    if r.namespace:
        out["namespace"] = r.namespace
    if r.priority_class_name:
        out["priorityClassName"] = r.priority_class_name
    return out


def binding_spec_to_json(s: BindingSpec) -> dict:
    """BindingSpec → workv1alpha2.ResourceBindingSpec JSON (the scheduler's
    slice; inverse of binding_spec_from_json)."""
    out: dict = {
        "resource": {
            **({"apiVersion": s.resource.api_version}
               if s.resource.api_version else {}),
            **({"kind": s.resource.kind} if s.resource.kind else {}),
            **({"namespace": s.resource.namespace}
               if s.resource.namespace else {}),
            **({"name": s.resource.name} if s.resource.name else {}),
            **({"uid": s.resource.uid} if s.resource.uid else {}),
        },
    }
    if s.replicas:
        out["replicas"] = s.replicas
    rr = replica_requirements_to_json(s.replica_requirements)
    if rr is not None:
        out["replicaRequirements"] = rr
    pj = placement_to_json(s.placement)
    if pj is not None:
        out["placement"] = pj
    if s.clusters:
        out["clusters"] = target_clusters_to_json(s.clusters)
    if s.scheduler_name:
        out["schedulerName"] = s.scheduler_name
    if s.reschedule_triggered_at is not None:
        out["rescheduleTriggeredAt"] = epoch_to_rfc3339(s.reschedule_triggered_at)
    return out


def cluster_to_json(c: Cluster) -> dict:
    """Cluster → clusterv1alpha1.Cluster JSON (the scheduler's slice;
    inverse of cluster_from_json)."""
    out: dict = {
        "metadata": {
            "name": c.metadata.name,
            **({"labels": dict(c.metadata.labels)}
               if c.metadata.labels else {}),
        },
        "spec": {
            "syncMode": c.spec.sync_mode,
            **({"provider": c.spec.provider} if c.spec.provider else {}),
            **({"region": c.spec.region} if c.spec.region else {}),
            **({"zone": c.spec.zone} if c.spec.zone else {}),
        },
    }
    if c.spec.taints:
        out["spec"]["taints"] = [
            {
                **({"key": t.key} if t.key else {}),
                **({"value": t.value} if t.value else {}),
                **({"effect": t.effect} if t.effect else {}),
                **({"timeAdded": epoch_to_rfc3339(t.time_added)}
                   if t.time_added is not None else {}),
            }
            for t in c.spec.taints
        ]
    status: dict = {}
    if c.status.kubernetes_version:
        status["kubernetesVersion"] = c.status.kubernetes_version
    if c.status.api_enablements:
        status["apiEnablements"] = [
            {"groupVersion": e.group_version,
             "resources": [{"kind": k} for k in e.resources]}
            for e in c.status.api_enablements
        ]
    if c.status.conditions:
        status["conditions"] = [
            {
                "type": cond.type, "status": cond.status,
                **({"reason": cond.reason} if cond.reason else {}),
                **({"message": cond.message} if cond.message else {}),
            }
            for cond in c.status.conditions
        ]
    ns = c.status.node_summary
    if ns is not None and (ns.total_num or ns.ready_num):
        status["nodeSummary"] = {"totalNum": ns.total_num,
                                 "readyNum": ns.ready_num}
    rs = c.status.resource_summary
    if rs is not None and (rs.allocatable or rs.allocating or rs.allocated):
        status["resourceSummary"] = {
            **({"allocatable": resources_to_json(rs.allocatable)}
               if rs.allocatable else {}),
            **({"allocating": resources_to_json(rs.allocating)}
               if rs.allocating else {}),
            **({"allocated": resources_to_json(rs.allocated)}
               if rs.allocated else {}),
        }
    if status:
        out["status"] = status
    return out

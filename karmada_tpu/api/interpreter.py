"""Resource-interpreter customization APIs (reference:
pkg/apis/config/v1alpha1 — ResourceInterpreterCustomization with per-operation
Lua scripts, and ResourceInterpreterWebhookConfiguration pointing at external
interpreter endpoints).

The script dialect here is a sandboxed Python-expression subset (the TPU-native
stand-in for the reference's gopher-lua sandbox, luavm/lua.go); the operation
names and call contracts mirror interpreter.go:39-68.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .meta import ObjectMeta

KIND_RESOURCE_INTERPRETER_CUSTOMIZATION = "ResourceInterpreterCustomization"
KIND_RESOURCE_INTERPRETER_WEBHOOK_CONFIGURATION = "ResourceInterpreterWebhookConfiguration"


@dataclass
class CustomizationTarget:
    api_version: str = ""
    kind: str = ""


@dataclass
class ScriptRule:
    script: str = ""


@dataclass
class Customizations:
    """One optional script per interpreter operation (config/v1alpha1
    CustomizationRules: GetReplicas/ReviseReplica/Retain/AggregateStatus/
    ReflectStatus/InterpretHealth/GetDependencies)."""

    replica_resource: Optional[ScriptRule] = None       # GetReplicas
    replica_revision: Optional[ScriptRule] = None       # ReviseReplica
    retention: Optional[ScriptRule] = None              # Retain
    status_aggregation: Optional[ScriptRule] = None     # AggregateStatus
    status_reflection: Optional[ScriptRule] = None      # ReflectStatus
    health_interpretation: Optional[ScriptRule] = None  # InterpretHealth
    dependency_interpretation: Optional[ScriptRule] = None  # GetDependencies


@dataclass
class ResourceInterpreterCustomizationSpec:
    target: CustomizationTarget = field(default_factory=CustomizationTarget)
    customizations: Customizations = field(default_factory=Customizations)


@dataclass
class ResourceInterpreterCustomization:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceInterpreterCustomizationSpec = field(
        default_factory=ResourceInterpreterCustomizationSpec
    )
    kind: str = KIND_RESOURCE_INTERPRETER_CUSTOMIZATION

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class InterpreterRule:
    """Which (apiVersion, kind, operations) a webhook serves."""

    api_versions: list[str] = field(default_factory=list)
    kinds: list[str] = field(default_factory=list)
    operations: list[str] = field(default_factory=list)  # e.g. InterpretReplica


@dataclass
class InterpreterWebhook:
    name: str = ""
    # in-process endpoint name in the HookRegistry, or a real http(s):// URL
    # of an interpreter hook server (examples/customresourceinterpreter)
    url: str = ""
    # PEM CA bundle for https:// hooks (clientConfig.caBundle)
    ca_bundle: str = ""
    rules: list[InterpreterRule] = field(default_factory=list)
    timeout_seconds: int = 10


@dataclass
class ResourceInterpreterWebhookConfiguration:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    webhooks: list[InterpreterWebhook] = field(default_factory=list)
    kind: str = KIND_RESOURCE_INTERPRETER_WEBHOOK_CONFIGURATION

    @property
    def name(self) -> str:
        return self.metadata.name

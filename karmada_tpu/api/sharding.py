"""Sharded-scheduler-plane kinds (docs/SCHEDULING.md "Sharded plane").

Two in-store objects back the shard subsystem (sched/shards/):

- `SchedulerShard` — one per shard slot, the status surface `karmadactl
  get shards` renders: current leader identity + lease token, queue depth,
  owned-binding count, last-solve time and handoff state. Published by the
  shard's leader from its idle loop; purely observational (the shard MAP is
  deterministic — rendezvous hash — so no assignment state lives here).
- `ShardGangProposal` — the cross-shard gang commit protocol's unit. A gang
  whose members hash to different shards cannot commit through one shard's
  local all-or-nothing `_patch_gang`; instead each member shard solves its
  own members and publishes their prepared placements as proposal ENTRIES
  (solved rv + targets + joint-feasibility verdict), and the gang's
  deterministic COORDINATOR shard (shardmap.shard_of_gang) assembles
  entries until the cohort is complete, then commits every member in ONE
  rv-checked `update_batch` — any member moving past its solved rv vetoes
  the whole gang (PR-13 semantics across shards). The coordinator stamps
  `status.outcome`; member shards react to that event (re-admit on abort,
  settle on commit) and the coordinator deletes the proposal afterwards.

Both kinds live in the `karmada-system` namespace, like the election
leases they complement.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from .meta import ObjectMeta

KIND_SCHEDULER_SHARD = "SchedulerShard"
KIND_SHARD_GANG_PROPOSAL = "ShardGangProposal"

# shard objects and gang proposals deploy next to the election leases
SHARD_NAMESPACE = "karmada-system"


def shard_lease_name(index: int) -> str:
    """Election lease for shard slot `index` — each slot elects its own
    streaming leader, independently of its siblings."""
    return f"karmada-sched-shard-{index}"


def shard_object_name(index: int) -> str:
    return f"scheduler-shard-{index}"


def gang_proposal_name(gang_ns: str, gang_name: str, shard: int) -> str:
    """One proposal object per (gang, member shard): entry writes never
    contend across shards — each shard owns its own proposal object and
    only the coordinator reads them all."""
    ns = gang_ns or "default"
    return f"gang-{ns}-{gang_name}-s{shard}"


@dataclass
class ShardStatus:
    leader: str = ""  # holder identity of the shard's lease ("" = no leader)
    fencing_token: int = 0
    epoch: int = 0  # admission epochs consumed by this shard's leader
    queue_depth: int = 0
    bindings: int = 0  # bindings the shard map currently assigns to the slot
    last_solve_time: float = 0.0
    # "" steady-state; "draining" while a resize moves keys off the slot,
    # "absorbing" while re-admitting a moved-in keyspace
    handoff: str = ""
    shards_total: int = 0


@dataclass
class SchedulerShard:
    kind: str = KIND_SCHEDULER_SHARD
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    status: ShardStatus = field(default_factory=ShardStatus)

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class GangMemberEntry:
    """One solved member inside a shard's proposal: everything the
    coordinator needs to re-prepare and commit the placement without
    re-solving — plus the rv fence that makes the commit honest."""

    key: str = ""  # namespace/name
    uid: str = ""
    solved_rv: int = 0  # the member's resource_version at solve time
    # (cluster, replicas) pairs — the ScheduleDecision targets flattened
    targets: list = field(default_factory=list)
    affinity_name: str = ""
    error: str = ""  # non-empty = this member solved infeasible
    feasible: bool = True  # _gang_full verdict (full replica placement)


@dataclass
class GangProposalSpec:
    gang_name: str = ""
    gang_ns: str = ""
    gang_size: int = 0
    shard: int = -1  # the member shard that published this proposal
    coordinator: int = -1  # shard_of_gang at publish time
    entries: list = field(default_factory=list)  # list[GangMemberEntry]
    created_at: float = 0.0  # coordinator-side expiry clock


@dataclass
class GangProposalStatus:
    # "" = pending assembly; terminal: committed | aborted | rejected |
    # timeout. Member shards key their disposition off this field's event.
    outcome: str = ""
    message: str = ""


@dataclass
class ShardGangProposal:
    kind: str = KIND_SHARD_GANG_PROPOSAL
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: GangProposalSpec = field(default_factory=GangProposalSpec)
    status: GangProposalStatus = field(default_factory=GangProposalStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

"""Apps API: WorkloadRebalancer.

Parity with pkg/apis/apps/v1alpha1/workloadrebalancer_types.go: a list of
workload references whose bindings should be freshly rescheduled; per-workload
observed result in status; optional TTL-after-finished cleanup.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .meta import ObjectMeta

KIND_WORKLOAD_REBALANCER = "WorkloadRebalancer"

REBALANCE_SUCCESSFUL = "Successful"
REBALANCE_FAILED = "Failed"

REASON_REFERENCED_BINDING_NOT_FOUND = "ReferencedBindingNotFound"


@dataclass
class RebalancerObjectReference:
    api_version: str = ""
    kind: str = ""
    namespace: str = ""
    name: str = ""

    def key(self) -> str:
        return f"{self.api_version}/{self.kind}/{self.namespace}/{self.name}"


REASON_NO_IMPROVING_MOVE = "RepackNoImprovingMove"
REASON_REPACK_TRIGGERED = "RepackTriggered"


@dataclass
class WorkloadRebalancerSpec:
    workloads: list[RebalancerObjectReference] = field(default_factory=list)
    ttl_seconds_after_finished: Optional[int] = None
    # periodic re-pack mode (sched/preemption.py's background consumer):
    # when set, the rebalancer never one-shots — every interval it re-runs
    # placement for its workloads through the counterfactual solve and
    # triggers a reschedule ONLY for improving moves (a placement that
    # lands strictly more replicas than the current one). finish_time and
    # the TTL never fire in this mode.
    repack_every_seconds: Optional[int] = None


@dataclass
class ObservedWorkload:
    workload: RebalancerObjectReference = field(default_factory=RebalancerObjectReference)
    result: str = ""  # "" (pending) | Successful | Failed
    reason: str = ""


@dataclass
class WorkloadRebalancerStatus:
    observed_workloads: list[ObservedWorkload] = field(default_factory=list)
    observed_generation: int = 0
    finish_time: Optional[float] = None
    last_repack_time: Optional[float] = None  # repack mode bookkeeping


@dataclass
class WorkloadRebalancer:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: WorkloadRebalancerSpec = field(default_factory=WorkloadRebalancerSpec)
    status: WorkloadRebalancerStatus = field(default_factory=WorkloadRebalancerStatus)
    kind: str = KIND_WORKLOAD_REBALANCER

    @property
    def name(self) -> str:
        return self.metadata.name

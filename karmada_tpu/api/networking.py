"""Networking API (reference: pkg/apis/networking/v1alpha1 — MultiClusterService
and MultiClusterIngress; plus the upstream MCS API ServiceExport/ServiceImport
consumed by pkg/controllers/mcs/).

MultiClusterService exposes a Service across clusters: provider clusters run
the backing pods, consumer clusters receive the derived service + imported
EndpointSlices (pkg/controllers/multiclusterservice/).
"""
from __future__ import annotations

from dataclasses import dataclass, field

from .meta import ObjectMeta

KIND_MULTI_CLUSTER_SERVICE = "MultiClusterService"
KIND_MULTI_CLUSTER_INGRESS = "MultiClusterIngress"
KIND_SERVICE_EXPORT = "ServiceExport"
KIND_SERVICE_IMPORT = "ServiceImport"

EXPOSURE_TYPE_CROSS_CLUSTER = "CrossCluster"
EXPOSURE_TYPE_LOAD_BALANCER = "LoadBalancer"

# label stamped on imported EndpointSlices (reference:
# discovery.karmada.io labels on collected slices)
ENDPOINT_SLICE_SOURCE_CLUSTER_LABEL = "endpointslice.karmada.io/source-cluster"
ENDPOINT_SLICE_SERVICE_LABEL = "kubernetes.io/service-name"
DERIVED_SERVICE_PREFIX = "derived-"


@dataclass
class ExposurePort:
    name: str = ""
    port: int = 0
    protocol: str = "TCP"


@dataclass
class ExposureRange:
    cluster_names: list[str] = field(default_factory=list)


@dataclass
class MultiClusterServiceSpec:
    types: list[str] = field(default_factory=lambda: [EXPOSURE_TYPE_CROSS_CLUSTER])
    ports: list[ExposurePort] = field(default_factory=list)
    provider_clusters: list[str] = field(default_factory=list)  # empty = all
    consumer_clusters: list[str] = field(default_factory=list)  # empty = all


@dataclass
class MultiClusterServiceStatus:
    conditions: list = field(default_factory=list)


@dataclass
class MultiClusterService:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: MultiClusterServiceSpec = field(default_factory=MultiClusterServiceSpec)
    status: MultiClusterServiceStatus = field(default_factory=MultiClusterServiceStatus)
    kind: str = KIND_MULTI_CLUSTER_SERVICE

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class ServiceExport:
    """MCS API: marks a Service (same ns/name) for export from the clusters
    it is propagated to (pkg/controllers/mcs/service_export_controller.go)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    kind: str = KIND_SERVICE_EXPORT

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class ServiceImportSpec:
    type: str = "ClusterSetIP"
    ports: list[ExposurePort] = field(default_factory=list)


@dataclass
class ServiceImport:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceImportSpec = field(default_factory=ServiceImportSpec)
    kind: str = KIND_SERVICE_IMPORT

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class IngressBackend:
    service_name: str = ""
    service_port: int = 80


@dataclass
class IngressRule:
    host: str = ""
    path: str = "/"
    backend: IngressBackend = field(default_factory=IngressBackend)


@dataclass
class MultiClusterIngressSpec:
    rules: list[IngressRule] = field(default_factory=list)


@dataclass
class MultiClusterIngress:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: MultiClusterIngressSpec = field(default_factory=MultiClusterIngressSpec)
    kind: str = KIND_MULTI_CLUSTER_INGRESS

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

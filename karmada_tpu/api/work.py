"""Work API: ResourceBinding / ClusterResourceBinding / Work.

Behavior parity with pkg/apis/work/v1alpha2/binding_types.go (ResourceBinding:
target clusters, replica requirements, graceful eviction tasks :241-311,
reschedule trigger, suspension) and pkg/apis/work/v1alpha1/work_types.go (Work:
manifests + per-manifest reflected status).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from .meta import ObjectMeta, Resources
from .policy import Placement, PURGE_MODE_GRACIOUSLY

KIND_RESOURCE_BINDING = "ResourceBinding"
KIND_CLUSTER_RESOURCE_BINDING = "ClusterResourceBinding"
KIND_WORK = "Work"

# Well-known labels/annotations (mirror pkg/apis/work/v1alpha2/well_known_*.go)
RESOURCE_BINDING_PERMANENT_ID_LABEL = "resourcebinding.karmada.io/permanent-id"
# Work -> owning-ResourceBinding back-references (stamped by the
# binding controller, read by status aggregation and the trace
# collector — one definition so the wire-visible names cannot drift)
WORK_BINDING_NAMESPACE_LABEL = "resourcebinding.karmada.io/namespace"
WORK_BINDING_NAME_LABEL = "resourcebinding.karmada.io/name"
POLICY_PLACEMENT_ANNOTATION = "policy.karmada.io/applied-placement"
WORK_NAMESPACE_PREFIX = "karmada-es-"

# Binding condition types (binding_types.go)
CONDITION_SCHEDULED = "Scheduled"
CONDITION_FULLY_APPLIED = "FullyApplied"

# Scheduled condition reasons (scheduler.go:913-961)
REASON_BINDING_SCHEDULED = "BindingScheduled"
REASON_SCHEDULE_FAILED = "BindingFailedScheduling"
REASON_UNSCHEDULABLE = "Unschedulable"
# workload-class scheduling (sched/preemption.py)
REASON_GANG_TIMEOUT = "GangTimeout"
REASON_GANG_UNSCHEDULABLE = "GangUnschedulable"

# graceful-eviction task reason/producer stamped by the preemption plane
EVICTION_REASON_PREEMPTED = "Preempted"
EVICTION_PRODUCER_PREEMPTION = "karmada-scheduler-preemption"

# template labels the detector lifts into the binding's gang/priority
# fields (they override the claiming policy's spec so several templates
# under one policy can form one gang)
GANG_NAME_LABEL = "gang.karmada.io/name"
GANG_SIZE_LABEL = "gang.karmada.io/size"
SCHEDULE_PRIORITY_LABEL = "scheduling.karmada.io/priority"

# Work condition types
WORK_CONDITION_APPLIED = "Applied"
WORK_CONDITION_AVAILABLE = "Available"
WORK_CONDITION_DISPATCHING = "Dispatching"


def work_namespace_for_cluster(cluster: str) -> str:
    """Per-cluster execution namespace (reference: names.GenerateExecutionSpaceName)."""
    return WORK_NAMESPACE_PREFIX + cluster


def cluster_of_work_namespace(ns: str) -> str:
    if not ns.startswith(WORK_NAMESPACE_PREFIX):
        raise ValueError(f"{ns} is not an execution namespace")
    return ns[len(WORK_NAMESPACE_PREFIX) :]


@dataclass
class ObjectReference:
    api_version: str = ""
    kind: str = ""
    namespace: str = ""
    name: str = ""
    uid: str = ""
    resource_version: int = 0

    def key(self) -> str:
        return f"{self.api_version}/{self.kind}/{self.namespace}/{self.name}"


@dataclass
class NodeClaim:
    node_selector: dict[str, str] = field(default_factory=dict)
    tolerations: list[Any] = field(default_factory=list)
    hard_node_affinity: Optional[Any] = None


@dataclass
class ReplicaRequirements:
    """binding_types.go ReplicaRequirements; resourceRequest feeds the
    estimators (pb/generated.proto ReplicaRequirements :91-110)."""

    node_claim: Optional[NodeClaim] = None
    resource_request: Resources = field(default_factory=dict)
    namespace: str = ""
    priority_class_name: str = ""


@dataclass
class TargetCluster:
    name: str = ""
    replicas: int = 0


@dataclass
class BindingSnapshot:
    """Requirements snapshot used by attached (dependency) bindings."""

    resource: ObjectReference = field(default_factory=ObjectReference)
    clusters: list[TargetCluster] = field(default_factory=list)


@dataclass
class GracefulEvictionTask:
    """binding_types.go:241-311."""

    from_cluster: str = ""
    replicas: Optional[int] = None
    reason: str = ""
    message: str = ""
    producer: str = ""
    grace_period_seconds: Optional[int] = None
    suppress_deletion: Optional[bool] = None
    creation_timestamp: Optional[float] = None  # None = not yet stamped
    purge_mode: str = PURGE_MODE_GRACIOUSLY
    preserved_label_state: dict[str, str] = field(default_factory=dict)
    cluster_before_failover: list[str] = field(default_factory=list)


@dataclass
class BindingSuspension:
    dispatching: bool = False
    scheduling: bool = False
    dispatching_on_clusters: list[str] = field(default_factory=list)


@dataclass
class BindingSpec:
    resource: ObjectReference = field(default_factory=ObjectReference)
    propagate_deps: bool = False
    replicas: int = 0
    replica_requirements: Optional[ReplicaRequirements] = None
    clusters: list[TargetCluster] = field(default_factory=list)
    placement: Optional[Placement] = None
    scheduler_name: str = ""
    schedule_priority: Optional[int] = None
    # scheduling preemption + gang membership (workload-class scheduling,
    # sched/preemption.py): plumbed from the claiming policy / template
    # labels by the detector, validated by the admission webhook
    preemption_policy: str = ""  # "" | Never | PreemptLowerPriority
    gang_name: str = ""
    gang_size: int = 0
    reschedule_triggered_at: Optional[float] = None
    graceful_eviction_tasks: list[GracefulEvictionTask] = field(default_factory=list)
    required_by: list[BindingSnapshot] = field(default_factory=list)
    suspension: Optional[BindingSuspension] = None
    conflict_resolution: str = ""
    failover: Optional[Any] = None  # policy.FailoverBehavior snapshot

    def target_cluster_names(self) -> list[str]:
        return [tc.name for tc in self.clusters]

    def assigned_replicas(self) -> int:
        return sum(tc.replicas for tc in self.clusters)

    def scheduling_suspended(self) -> bool:
        return self.suspension is not None and self.suspension.scheduling


@dataclass
class AggregatedStatusItem:
    cluster_name: str = ""
    status: Optional[dict] = None
    applied: bool = False
    applied_message: str = ""
    health: str = "Unknown"  # Healthy | Unhealthy | Unknown


@dataclass
class BindingStatus:
    scheduler_observed_generation: int = 0
    scheduler_observed_affinity_name: str = ""
    last_scheduled_time: Optional[float] = None
    conditions: list = field(default_factory=list)
    aggregated_status: list[AggregatedStatusItem] = field(default_factory=list)


@dataclass
class ResourceBinding:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: BindingSpec = field(default_factory=BindingSpec)
    status: BindingStatus = field(default_factory=BindingStatus)
    kind: str = KIND_RESOURCE_BINDING

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class ClusterResourceBinding:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: BindingSpec = field(default_factory=BindingSpec)
    status: BindingStatus = field(default_factory=BindingStatus)
    kind: str = KIND_CLUSTER_RESOURCE_BINDING

    @property
    def name(self) -> str:
        return self.metadata.name


# ---------------------------------------------------------------------------
# Work
# ---------------------------------------------------------------------------


@dataclass
class ManifestStatus:
    identifier: ObjectReference = field(default_factory=ObjectReference)
    status: Optional[dict] = None
    health: str = "Unknown"


@dataclass
class WorkSpec:
    workload_manifests: list[dict] = field(default_factory=list)
    suspend_dispatching: bool = False
    preserve_resources_on_deletion: bool = False


@dataclass
class WorkStatus:
    conditions: list = field(default_factory=list)
    manifest_statuses: list[ManifestStatus] = field(default_factory=list)


@dataclass
class Work:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: WorkSpec = field(default_factory=WorkSpec)
    status: WorkStatus = field(default_factory=WorkStatus)
    kind: str = KIND_WORK

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

"""Search & quota APIs.

- ResourceRegistry (reference: pkg/apis/search/v1alpha1): which resources to
  cache from which clusters, with an optional backend store (OpenSearch).
- FederatedResourceQuota (reference: pkg/apis/policy/v1alpha1/federatedresourcequota_types.go):
  federation-wide hard limits with per-cluster static assignments.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .meta import ObjectMeta
from .policy import ClusterAffinity

KIND_RESOURCE_REGISTRY = "ResourceRegistry"
KIND_FEDERATED_RESOURCE_QUOTA = "FederatedResourceQuota"
KIND_CLUSTER_OBJECT_SUMMARY = "ClusterObjectSummary"


@dataclass
class ObjectSummaryRow:
    """One member object as the search plane ingests it: the selector
    surface (labels, flattened scalar fields) pre-extracted next to the
    full manifest the query plane materializes."""

    namespace: str = ""
    name: str = ""
    uid: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    fields: dict[str, str] = field(default_factory=dict)
    manifest: dict = field(default_factory=dict)


@dataclass
class ClusterObjectSummary:
    """Per-(cluster, gvk) object summary published by a member's agent on
    its heartbeat, riding the coalesced agent-status write path — the
    search plane's remote ingest feed (docs/SEARCH.md). Level-triggered
    and last-write-wins: a summary wholly REPLACES the (cluster, gvk)
    slice of the columnar index, so the plane-side fold needs no diff
    protocol and an empty `rows` retracts the slice. Named
    `{cluster}.{kind}` (cluster-scoped, like WorkloadMetricsReport)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    cluster: str = ""
    api_version: str = ""
    object_kind: str = ""  # the summarized Kind; `kind` is this object's
    rows: list[ObjectSummaryRow] = field(default_factory=list)
    reported_at: float = 0.0
    kind: str = KIND_CLUSTER_OBJECT_SUMMARY

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def gvk(self) -> str:
        return f"{self.api_version}/{self.object_kind}"


def summary_name(cluster: str, api_version: str, kind: str) -> str:
    """Deterministic ClusterObjectSummary object name: one per
    (cluster, gvk), so heartbeats upsert in place."""
    return f"{cluster}.{api_version.replace('/', '-')}.{kind.lower()}"


@dataclass
class SearchResourceSelector:
    api_version: str = ""
    kind: str = ""


@dataclass
class BackendStoreConfig:
    """backendStore.openSearch equivalent; None = in-memory cache only."""

    type: str = "memory"  # memory | opensearch
    addresses: list[str] = field(default_factory=list)
    # auto-flush the bulk queue once it holds this many operations
    # (0 = only the end-of-sweep flush)
    flush_threshold: int = 0


@dataclass
class ResourceRegistrySpec:
    target_cluster: ClusterAffinity = field(default_factory=ClusterAffinity)
    resource_selectors: list[SearchResourceSelector] = field(default_factory=list)
    backend_store: Optional[BackendStoreConfig] = None


@dataclass
class ResourceRegistry:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceRegistrySpec = field(default_factory=ResourceRegistrySpec)
    kind: str = KIND_RESOURCE_REGISTRY

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class StaticClusterAssignment:
    cluster_name: str = ""
    hard: dict[str, float] = field(default_factory=dict)


@dataclass
class FederatedResourceQuotaSpec:
    overall: dict[str, float] = field(default_factory=dict)
    static_assignments: list[StaticClusterAssignment] = field(default_factory=list)


@dataclass
class ClusterQuotaStatus:
    cluster_name: str = ""
    hard: dict[str, float] = field(default_factory=dict)
    used: dict[str, float] = field(default_factory=dict)


@dataclass
class FederatedResourceQuotaStatus:
    overall: dict[str, float] = field(default_factory=dict)
    overall_used: dict[str, float] = field(default_factory=dict)
    aggregated_status: list[ClusterQuotaStatus] = field(default_factory=list)


@dataclass
class FederatedResourceQuota:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: FederatedResourceQuotaSpec = field(default_factory=FederatedResourceQuotaSpec)
    status: FederatedResourceQuotaStatus = field(default_factory=FederatedResourceQuotaStatus)
    kind: str = KIND_FEDERATED_RESOURCE_QUOTA

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

"""Search & quota APIs.

- ResourceRegistry (reference: pkg/apis/search/v1alpha1): which resources to
  cache from which clusters, with an optional backend store (OpenSearch).
- FederatedResourceQuota (reference: pkg/apis/policy/v1alpha1/federatedresourcequota_types.go):
  federation-wide hard limits with per-cluster static assignments.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .meta import ObjectMeta
from .policy import ClusterAffinity

KIND_RESOURCE_REGISTRY = "ResourceRegistry"
KIND_FEDERATED_RESOURCE_QUOTA = "FederatedResourceQuota"


@dataclass
class SearchResourceSelector:
    api_version: str = ""
    kind: str = ""


@dataclass
class BackendStoreConfig:
    """backendStore.openSearch equivalent; None = in-memory cache only."""

    type: str = "memory"  # memory | opensearch
    addresses: list[str] = field(default_factory=list)


@dataclass
class ResourceRegistrySpec:
    target_cluster: ClusterAffinity = field(default_factory=ClusterAffinity)
    resource_selectors: list[SearchResourceSelector] = field(default_factory=list)
    backend_store: Optional[BackendStoreConfig] = None


@dataclass
class ResourceRegistry:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceRegistrySpec = field(default_factory=ResourceRegistrySpec)
    kind: str = KIND_RESOURCE_REGISTRY

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class StaticClusterAssignment:
    cluster_name: str = ""
    hard: dict[str, float] = field(default_factory=dict)


@dataclass
class FederatedResourceQuotaSpec:
    overall: dict[str, float] = field(default_factory=dict)
    static_assignments: list[StaticClusterAssignment] = field(default_factory=list)


@dataclass
class ClusterQuotaStatus:
    cluster_name: str = ""
    hard: dict[str, float] = field(default_factory=dict)
    used: dict[str, float] = field(default_factory=dict)


@dataclass
class FederatedResourceQuotaStatus:
    overall: dict[str, float] = field(default_factory=dict)
    overall_used: dict[str, float] = field(default_factory=dict)
    aggregated_status: list[ClusterQuotaStatus] = field(default_factory=list)


@dataclass
class FederatedResourceQuota:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: FederatedResourceQuotaSpec = field(default_factory=FederatedResourceQuotaSpec)
    status: FederatedResourceQuotaStatus = field(default_factory=FederatedResourceQuotaStatus)
    kind: str = KIND_FEDERATED_RESOURCE_QUOTA

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

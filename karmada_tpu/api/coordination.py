"""coordination.k8s.io-shaped leader-election Lease.

The reference runs every binary behind client-go leader election over a
`coordination.k8s.io/Lease` (cmd/scheduler/app/scheduler.go:33-34,188,
cmd/controller-manager/app/controllermanager.go:154-155). `LeaderLease` is
that resource for the TPU build's daemon topology, distinct from the
cluster-heartbeat `Lease` (agent/agent.py): one per elected ROLE
(karmada-scheduler, karmada-descheduler, karmada-agent-<cluster>,
karmada-controller-manager), not per member cluster.

Beyond the k8s shape it carries a monotonic **fencing token**, minted on
every leadership acquisition (not on renewals): a write stamped with an
older token than the lease's current one comes from a deposed leader and
must be rejected (coordination/lease.py `check_fence`). Tokens only ever
increase for a given lease name — release clears the holder but keeps the
counter, so monotonicity survives clean handovers and restarts (the lease
rides the store's WAL like every other object).
"""
from __future__ import annotations

from dataclasses import dataclass, field

from .meta import ObjectMeta

KIND_LEADER_LEASE = "LeaderLease"

# the reference deploys its election leases in the karmada-system namespace
LEADER_LEASE_NAMESPACE = "karmada-system"

# client-go defaults are 15s/10s/2s (LeaseDuration/RenewDeadline/RetryPeriod);
# we keep the same envelope with renew at duration/3
DEFAULT_LEASE_DURATION = 15.0

# well-known lease names for the daemon roles
LEASE_SCHEDULER = "karmada-scheduler"
LEASE_DESCHEDULER = "karmada-descheduler"
LEASE_CONTROLLER_MANAGER = "karmada-controller-manager"


def agent_lease_name(cluster: str) -> str:
    """Election lease for the pull agent serving `cluster` — exactly one
    agent process may heartbeat/apply for a given member identity."""
    return f"karmada-agent-{cluster}"


@dataclass
class LeaderLeaseSpec:
    holder_identity: str = ""  # "" = released / never held
    lease_duration_seconds: float = DEFAULT_LEASE_DURATION
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_transitions: int = 0  # holder changes (k8s leaseTransitions)
    fencing_token: int = 0  # monotonic; bumped on every acquisition


@dataclass
class LeaderLease:
    kind: str = KIND_LEADER_LEASE
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LeaderLeaseSpec = field(default_factory=LeaderLeaseSpec)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def expired(self, now: float) -> bool:
        return (
            not self.spec.holder_identity
            or now - self.spec.renew_time > self.spec.lease_duration_seconds
        )

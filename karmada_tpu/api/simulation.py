"""What-if simulation API: SimulationRequest / SimulationReport.

The reference has no counterfactual surface at all — its only fault
injection is deleting kind clusters in e2e, and the descheduler/rebalancer
act blind. These resources expose the TPU build's batched [S,B,C] solve
(simulation/engine.py) as an API: POST /simulate evaluates a
SimulationRequest's scenarios against the live fleet in one vmapped device
launch and answers with a SimulationReport; the last N reports persist in
the store so an operator can review a preflight decision after the fact
(`karmadactl get simulationreports`).

Scenario kinds:
  Drain          remove `cluster` from the candidate fleet (placements are
                 bit-identical to actually deleting the cluster and
                 cold-solving — the tie stream is index-remapped)
  Loss           mark `cluster` NotReady (stays in the fleet, infeasible)
  Taint          add a NoSchedule/NoExecute taint to `cluster`
  CapacityDelta  shift `cluster`'s allocatable by ±`resources`
  BindingSurge   inject `surge_count` synthetic dynamic-divided bindings
  Composite      apply `steps` together as ONE counterfactual (the quota
                 preflight caps several clusters at once this way)
"""
from __future__ import annotations

from dataclasses import dataclass, field

from .meta import ObjectMeta
from .work import TargetCluster

KIND_SIMULATION_REQUEST = "SimulationRequest"
KIND_SIMULATION_REPORT = "SimulationReport"

SCENARIO_BASELINE = "Baseline"
SCENARIO_DRAIN = "Drain"
SCENARIO_LOSS = "Loss"
SCENARIO_TAINT = "Taint"
SCENARIO_CAPACITY = "CapacityDelta"
SCENARIO_SURGE = "BindingSurge"
SCENARIO_COMPOSITE = "Composite"
# preemption preview (sched/preemption.py): what would placing `binding`
# evict? Answered by the SAME planner the live scheduler runs, against the
# same snapshot — the previewed victim set is identical to what a real
# admission would cut, and nothing mutates
SCENARIO_PREEMPT = "Preemption"

SCENARIO_KINDS = (
    SCENARIO_BASELINE, SCENARIO_DRAIN, SCENARIO_LOSS, SCENARIO_TAINT,
    SCENARIO_CAPACITY, SCENARIO_SURGE, SCENARIO_COMPOSITE, SCENARIO_PREEMPT,
)


@dataclass
class Scenario:
    """One counterfactual. Flat on purpose (codec-friendly): each kind reads
    only its own fields; Composite nests sub-steps under `steps`."""

    kind: str = SCENARIO_BASELINE
    name: str = ""  # display label; label() derives one when empty
    cluster: str = ""  # Drain / Loss / Taint / CapacityDelta target
    # Taint
    taint_key: str = ""
    taint_value: str = ""
    taint_effect: str = "NoSchedule"
    # CapacityDelta: ± per resource, allocatable units (cpu cores, bytes)
    resources: dict[str, float] = field(default_factory=dict)
    # BindingSurge: synthetic dynamic-divided bindings over the whole fleet
    surge_count: int = 0
    surge_replicas: int = 1
    surge_request: dict[str, float] = field(default_factory=dict)
    # Preemption: namespace/name of the (typically pending) preemptor
    # binding whose victim set the preview computes
    binding: str = ""
    # Composite
    steps: list["Scenario"] = field(default_factory=list)

    def label(self) -> str:
        if self.name:
            return self.name
        if self.kind == SCENARIO_PREEMPT:
            return f"preempt({self.binding})"
        if self.kind == SCENARIO_COMPOSITE:
            inner = ",".join(s.label() for s in self.steps[:3])
            more = "" if len(self.steps) <= 3 else f"+{len(self.steps) - 3}"
            return f"composite({inner}{more})"
        if self.kind == SCENARIO_SURGE:
            return f"surge({self.surge_count}x{self.surge_replicas})"
        if self.kind == SCENARIO_CAPACITY:
            delta = ",".join(
                f"{r}{v:+g}" for r, v in sorted(self.resources.items())
            )
            return f"capacity({self.cluster}:{delta})"
        if self.kind == SCENARIO_TAINT:
            return f"taint({self.cluster}:{self.taint_key})"
        return f"{self.kind.lower()}({self.cluster})" if self.cluster else self.kind.lower()


@dataclass
class SimulationRequestSpec:
    scenarios: list[Scenario] = field(default_factory=list)
    namespace: str = ""  # restrict to one namespace's bindings ("" = all)
    diff_limit: int = 8  # max per-scenario BindingDiff entries in the report


@dataclass
class SimulationRequest:
    kind: str = KIND_SIMULATION_REQUEST
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: SimulationRequestSpec = field(default_factory=SimulationRequestSpec)


@dataclass
class BindingDiff:
    """One displaced binding: placements before (baseline solve) and after
    (the scenario's counterfactual solve); error set when the row went
    unplaceable under the scenario."""

    binding: str = ""  # namespace/name key
    before: list[TargetCluster] = field(default_factory=list)
    after: list[TargetCluster] = field(default_factory=list)
    error: str = ""


@dataclass
class PreemptionVictim:
    """One previewed victim replica reduction (Preemption scenarios)."""

    binding: str = ""  # namespace/name
    cluster: str = ""
    replicas: int = 0
    priority: int = 0


@dataclass
class ScenarioReport:
    scenario: Scenario = field(default_factory=Scenario)
    displaced: int = 0  # bindings whose placement changed vs baseline
    unplaceable: int = 0  # bindings with no feasible/schedulable placement
    injected: int = 0  # surge rows evaluated under this scenario
    overcommitted: list[str] = field(default_factory=list)  # cluster names
    diffs: list[BindingDiff] = field(default_factory=list)  # first diff_limit
    # Preemption scenarios: who pays for placing the previewed binding —
    # identical to the live planner's victim set (shared plan code)
    victims: list[PreemptionVictim] = field(default_factory=list)


@dataclass
class SimulationReport:
    kind: str = KIND_SIMULATION_REPORT
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    scenarios: list[ScenarioReport] = field(default_factory=list)
    bindings: int = 0
    clusters: int = 0
    baseline_unplaceable: int = 0
    batched_solves: int = 0  # vmapped [S,B,C] launches this report cost
    fallback_solves: int = 0  # per-scenario exact re-solves (spread rows etc.)

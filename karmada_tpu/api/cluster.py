"""Cluster API: member-cluster registration, taints, capacity summaries.

Behavior parity with the reference cluster API
(pkg/apis/cluster/v1alpha1/types.go): SyncMode push/pull, taints with
NoSchedule/NoExecute/PreferNoSchedule effects, Status.ResourceSummary
(allocatable/allocating/allocated) that powers the GeneralEstimator
(pkg/estimator/client/general.go:96-114), APIEnablements consumed by the
APIEnablement filter (plugins/apienablement/api_enablement.go:52), and the
grade-based cluster resource models (types.go:207-252).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .meta import Condition, ObjectMeta, Resources

KIND_CLUSTER = "Cluster"

# Sync modes (types.go SyncMode)
SYNC_MODE_PUSH = "Push"
SYNC_MODE_PULL = "Pull"

# Taint effects
EFFECT_NO_SCHEDULE = "NoSchedule"
EFFECT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
EFFECT_NO_EXECUTE = "NoExecute"

# Condition types
CLUSTER_CONDITION_READY = "Ready"

# Well-known taint keys the cluster controller applies on condition changes
# (reference: pkg/controllers/cluster/cluster_controller.go taint constants).
TAINT_CLUSTER_NOT_READY = "cluster.karmada.io/not-ready"
TAINT_CLUSTER_UNREACHABLE = "cluster.karmada.io/unreachable"


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = EFFECT_NO_SCHEDULE
    time_added: Optional[float] = None


@dataclass
class ResourceModelRange:
    name: str = ""  # resource name, e.g. "cpu"
    min: float = 0.0
    max: float = float("inf")


@dataclass
class ResourceModel:
    """One grade of the node-histogram resource model (types.go:207-252)."""

    grade: int = 0
    ranges: list[ResourceModelRange] = field(default_factory=list)


@dataclass
class AllocatableModeling:
    grade: int = 0
    count: int = 0


@dataclass
class ResourceSummary:
    """allocatable − allocated − allocating is the GeneralEstimator's input
    (pkg/estimator/client/general.go:96-114)."""

    allocatable: Resources = field(default_factory=dict)
    allocating: Resources = field(default_factory=dict)
    allocated: Resources = field(default_factory=dict)
    allocatable_modelings: list[AllocatableModeling] = field(default_factory=list)

    def available(self) -> Resources:
        out: Resources = {}
        for k, v in self.allocatable.items():
            out[k] = v - self.allocated.get(k, 0.0) - self.allocating.get(k, 0.0)
        return out


@dataclass
class NodeSummary:
    total_num: int = 0
    ready_num: int = 0


@dataclass
class APIEnablement:
    group_version: str = ""
    resources: list[str] = field(default_factory=list)  # Kind names


# the API surface every simulated member advertises (status collector's
# APIEnablements probe; consumed by the APIEnablement filter plugin)
DEFAULT_API_ENABLEMENTS = [
    APIEnablement(group_version="apps/v1", resources=["Deployment", "StatefulSet"]),
    APIEnablement(group_version="v1", resources=["ConfigMap", "Secret", "Service"]),
    APIEnablement(group_version="batch/v1", resources=["Job"]),
]


@dataclass
class ClusterSpec:
    sync_mode: str = SYNC_MODE_PUSH
    api_endpoint: str = ""
    provider: str = ""
    region: str = ""
    zone: str = ""
    zones: list[str] = field(default_factory=list)
    taints: list[Taint] = field(default_factory=list)
    resource_models: list[ResourceModel] = field(default_factory=list)


@dataclass
class ClusterStatus:
    kubernetes_version: str = ""
    api_enablements: list[APIEnablement] = field(default_factory=list)
    conditions: list[Condition] = field(default_factory=list)
    node_summary: Optional[NodeSummary] = None
    resource_summary: Optional[ResourceSummary] = None
    remedy_actions: list[str] = field(default_factory=list)


@dataclass
class Cluster:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ClusterSpec = field(default_factory=ClusterSpec)
    status: ClusterStatus = field(default_factory=ClusterStatus)
    kind: str = KIND_CLUSTER

    @property
    def name(self) -> str:
        return self.metadata.name


def cluster_ready(cluster: Cluster) -> bool:
    for c in cluster.status.conditions:
        if c.type == CLUSTER_CONDITION_READY:
            return c.status == "True"
    return False


def cluster_api_enabled(cluster: Cluster, api_version: str, kind: str) -> bool:
    """APIEnablement filter predicate (api_enablement.go:52).

    Empty enablement list counts as 'unknown' and the reference treats missing
    enablement as filter failure only when the list is populated and lacks the
    GVK; an empty status means the collector has not run, which the reference
    also rejects (helper.IsAPIEnabled returns false)."""
    for en in cluster.status.api_enablements:
        if en.group_version == api_version and kind in en.resources:
            return True
    return False

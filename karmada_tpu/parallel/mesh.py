"""Multi-chip sharding of the batched scheduling solve.

The reference scales by sharding *work items* over goroutines (SURVEY §5
long-context note: no batched path exists). Here the scheduling problem itself
is sharded over a 2D `jax.sharding.Mesh`:

  axis "bindings" — data-parallel over the dirty-binding batch rows (the DP
    axis of this domain: rows are independent end-to-end);
  axis "clusters" — model-parallel over the fleet columns (the TP-like axis:
    filter masks, locality score and the GeneralEstimator math
    [general.go:96-114] are elementwise over (B,C) and run on local cluster
    shards; the replica-division solve needs full rows — each row is a
    sort/prefix-sum over ALL clusters, binding.go:112-144 — so the per-cluster
    partials ride one `all_gather` over ICI before assignment).

This keeps the HBM-resident working set per chip at B/mesh_b × C/mesh_c for
the quadratic phase, which is what lets 10k bindings × 5k clusters (BASELINE
north star) exceed a single chip.

Everything here compiles under `jit` on N virtual CPU devices too
(xla_force_host_platform_device_count) — see __graft_entry__.dryrun_multichip.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..models.batch import AGGREGATED, BindingBatch, DUPLICATED, DYNAMIC_WEIGHT, STATIC_WEIGHT
from ..models.fleet import FleetArrays
from ..ops import assign as assign_ops
from ..ops import filters as filter_ops

AXIS_BINDINGS = "bindings"
AXIS_CLUSTERS = "clusters"


def factor_mesh(n_devices: int) -> tuple[int, int]:
    """Split n devices into (bindings, clusters) axis sizes, as square as
    possible with bindings >= clusters (binding rows are the cheaper axis to
    widen: no collective crosses it)."""
    best = (n_devices, 1)
    f = 1
    while f * f <= n_devices:
        if n_devices % f == 0:
            best = (n_devices // f, f)
        f += 1
    return best


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    b, c = factor_mesh(len(devices))
    return Mesh(np.array(devices).reshape(b, c), (AXIS_BINDINGS, AXIS_CLUSTERS))


# in_specs in the exact positional order of sched.core._schedule_kernel
_FLEET_SPECS = (
    P(AXIS_CLUSTERS),        # alive
    P(AXIS_CLUSTERS, None),  # capacity
    P(AXIS_CLUSTERS),        # has_summary
    P(AXIS_CLUSTERS, None),  # taint_key
    P(AXIS_CLUSTERS, None),  # taint_value
    P(AXIS_CLUSTERS, None),  # taint_effect
    P(AXIS_CLUSTERS, None),  # api_ok
)
_BATCH_SPECS = (
    P(AXIS_BINDINGS),        # replicas
    P(AXIS_BINDINGS, None),  # request
    P(AXIS_BINDINGS),        # unknown_request
    P(AXIS_BINDINGS),        # gvk
    P(AXIS_BINDINGS),        # strategy
    P(AXIS_BINDINGS),        # fresh
    P(AXIS_BINDINGS, None),  # tol_key
    P(AXIS_BINDINGS, None),  # tol_value
    P(AXIS_BINDINGS, None),  # tol_effect
    P(AXIS_BINDINGS, None),  # tol_op
    P(AXIS_BINDINGS, AXIS_CLUSTERS),  # affinity_ok
    P(AXIS_BINDINGS, AXIS_CLUSTERS),  # eviction_ok
    P(AXIS_BINDINGS, AXIS_CLUSTERS),  # static_weight
    P(AXIS_BINDINGS, AXIS_CLUSTERS),  # prev_member
    P(AXIS_BINDINGS, AXIS_CLUSTERS),  # prev_replicas
    P(AXIS_BINDINGS, AXIS_CLUSTERS),  # tie
    P(AXIS_BINDINGS, AXIS_CLUSTERS),  # extra_avail
)
_OUT_SPECS = (
    P(AXIS_BINDINGS, None),  # feasible
    P(AXIS_BINDINGS, None),  # score
    P(AXIS_BINDINGS, None),  # result
    P(AXIS_BINDINGS),        # unschedulable
    P(AXIS_BINDINGS),        # available_sum
    P(AXIS_BINDINGS, None),  # avail
)


def _sharded_body(
    alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
    replicas, request, unknown_request, gvk, strategy, fresh,
    tol_key, tol_value, tol_effect, tol_op,
    affinity_ok, eviction_ok, static_weight, prev_member, prev_replicas, tie,
    extra_avail,
):
    # ---- local phase: elementwise over (B_local, C_local) ----
    taint_mask = filter_ops.taint_toleration_mask(
        taint_key, taint_value, taint_effect, tol_key, tol_value, tol_effect, tol_op
    )
    api_mask = filter_ops.api_enablement_mask(api_ok, gvk)
    feasible_l = filter_ops.feasible_mask(
        alive, api_mask, taint_mask, jnp.ones_like(affinity_ok), affinity_ok, eviction_ok
    )
    score_l = filter_ops.locality_score(prev_member)
    avail_l = assign_ops.general_estimate(capacity, has_summary, request, replicas)
    avail_l = jnp.where(unknown_request[:, None], 0, avail_l)
    avail_l = jnp.where(extra_avail >= 0, jnp.minimum(avail_l, extra_avail), avail_l)

    # ---- gather the cluster shards: the division solve is a per-row
    # sort/cumsum over the FULL fleet (binding.go:112-144). One tiled
    # all_gather over ICI reconstructs the global rows. ----
    def gcols(x):
        return jax.lax.all_gather(x, AXIS_CLUSTERS, axis=1, tiled=True)

    feasible = gcols(feasible_l)
    score = gcols(score_l)
    avail = gcols(avail_l)
    static_w = gcols(static_weight)
    prev_m = gcols(prev_member)
    prev_r = gcols(prev_replicas)
    tie_g = gcols(tie)

    dup = assign_ops.duplicated_assign(feasible, replicas)
    static = assign_ops.static_weight_assign(feasible, static_w, prev_r, tie_g, replicas)
    dyn = assign_ops.dynamic_assign(
        feasible, avail, prev_r, tie_g, replicas, fresh, strategy == AGGREGATED
    )

    result = jnp.zeros_like(dup)
    result = jnp.where((strategy == DUPLICATED)[:, None], dup, result)
    result = jnp.where((strategy == STATIC_WEIGHT)[:, None], static, result)
    is_dyn = (strategy == DYNAMIC_WEIGHT) | (strategy == AGGREGATED)
    result = jnp.where(is_dyn[:, None], dyn.result, result)
    unschedulable = is_dyn & dyn.unschedulable
    return feasible, score, result, unschedulable, dyn.available_sum, avail


def build_sharded_kernel(mesh: Mesh):
    """jit(shard_map(schedule kernel)) over the given mesh. Same positional
    signature and outputs as sched.core._schedule_kernel; inputs may be plain
    numpy arrays (jit shards them per in_specs)."""
    fn = jax.shard_map(
        _sharded_body,
        mesh=mesh,
        in_specs=_FLEET_SPECS + _BATCH_SPECS,
        out_specs=_OUT_SPECS,
        check_vma=False,
    )
    return jax.jit(fn)


def _pad_axis(a: np.ndarray, axis: int, to: int, fill=0) -> np.ndarray:
    cur = a.shape[axis]
    if cur >= to:
        return a
    width = [(0, 0)] * a.ndim
    width[axis] = (0, to - cur)
    return np.pad(a, width, constant_values=fill)


def _round_up(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


class MeshScheduleKernel:
    """Host wrapper: pads fleet/batch axes to mesh-divisible sizes (padded
    clusters are dead — alive=False ⇒ infeasible; padded bindings are
    NON_WORKLOAD rows) and trims outputs back."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self.kernel = build_sharded_kernel(mesh)
        self.mesh_b = mesh.shape[AXIS_BINDINGS]
        self.mesh_c = mesh.shape[AXIS_CLUSTERS]

    def __call__(self, fleet: FleetArrays, batch: BindingBatch, extra_avail=None):
        B = len(batch.replicas)
        C = fleet.alive.shape[0]
        Bp = _round_up(max(B, self.mesh_b), self.mesh_b)
        Cp = _round_up(max(C, self.mesh_c), self.mesh_c)
        if extra_avail is None:
            extra_avail = np.full((B, C), -1, np.int32)

        def fb(a):  # fleet array: pad cluster axis 0
            return _pad_axis(a, 0, Cp)

        def bb(a):  # batch array: pad binding axis 0
            return _pad_axis(a, 0, Bp)

        def bc(a):  # [B,C] matrix: pad both
            return _pad_axis(_pad_axis(a, 0, Bp), 1, Cp)

        out = self.kernel(
            fb(fleet.alive), fb(fleet.capacity), fb(fleet.has_summary),
            fb(fleet.taint_key), fb(fleet.taint_value), fb(fleet.taint_effect),
            fb(fleet.api_ok),
            bb(batch.replicas), bb(batch.request), bb(batch.unknown_request),
            bb(batch.gvk), bb(batch.strategy), bb(batch.fresh),
            bb(batch.tol_key), bb(batch.tol_value), bb(batch.tol_effect),
            bb(batch.tol_op),
            bc(batch.affinity_ok), bc(batch.eviction_ok), bc(batch.static_weight),
            bc(batch.prev_member), bc(batch.prev_replicas), bc(batch.tie),
            _pad_axis(_pad_axis(extra_avail, 0, Bp), 1, Cp, fill=-1),
        )
        feasible, score, result, unsched, avail_sum, avail = (np.asarray(x) for x in out)
        return (
            feasible[:B, :C],
            score[:B, :C],
            result[:B, :C],
            unsched[:B],
            avail_sum[:B],
            avail[:B, :C],
        )

"""Multi-chip sharding of the batched scheduling solve.

The reference scales by sharding *work items* over goroutines (SURVEY §5
long-context note: no batched path exists). Here the scheduling problem itself
is sharded over a 2D `jax.sharding.Mesh`:

  axis "bindings" — data-parallel over the dirty-binding batch rows (the DP
    axis of this domain: rows are independent end-to-end);
  axis "clusters" — model-parallel over the fleet columns (the TP-like axis:
    filter masks, locality score and the GeneralEstimator math
    [general.go:96-114] are elementwise over (B,C) and run on local cluster
    shards; the replica-division solve needs full rows — each row is a
    sort/prefix-sum over ALL clusters, binding.go:112-144 — so the per-cluster
    partials ride one `all_gather` over ICI before assignment).

Transfer discipline matches the single-chip path (sched/core.py): the host
ships the FACTORED batch — policy tables [P,C]/[W,C] column-sharded, per-row
indices row-sharded, sparse prev/eviction entries, a tie seed — and each
device decompresses its (B_local, C_local) tile on device. Host→device per
round is O(B·K + P·C), never O(B·C); device→host is the compact top-K
outputs. (Round-1 fed dense host-materialized [B,C] tensors here, which
recreated exactly the transfer wall the factored encoding removes.)

Everything compiles under `jit` on N virtual CPU devices too
(xla_force_host_platform_device_count) — see __graft_entry__.dryrun_multichip.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.batch import BindingBatch
from ..models.fleet import FleetArrays

AXIS_BINDINGS = "bindings"
AXIS_CLUSTERS = "clusters"


def factor_mesh(n_devices: int) -> tuple[int, int]:
    """Split n devices into (bindings, clusters) axis sizes, as square as
    possible with bindings >= clusters (binding rows are the cheaper axis to
    widen: no collective crosses it)."""
    best = (n_devices, 1)
    f = 1
    while f * f <= n_devices:
        if n_devices % f == 0:
            best = (n_devices // f, f)
        f += 1
    return best


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    b, c = factor_mesh(len(devices))
    return Mesh(np.array(devices).reshape(b, c), (AXIS_BINDINGS, AXIS_CLUSTERS))


def initialize_multihost(coordinator: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Join this process to a multi-host JAX cluster (the distributed
    communication backend — the reference scales its control plane by adding
    scheduler replicas behind leader election; the TPU-native equivalent is
    one SPMD program spanning hosts, with XLA emitting the cross-host
    collectives over DCN). Safe to call on single-host: it no-ops when no
    coordinator is configured."""
    if coordinator is None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_hierarchical_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """Multi-host mesh with DCN/ICI-aware axis assignment (the scaling-book
    recipe: put the axis with the cheapest communication across the slowest
    link). The BINDINGS axis carries no collective at all in this solve —
    rows are independent end-to-end — so it spans HOSTS (DCN); the CLUSTERS
    axis carries the per-round all_gather, so it stays within each host's
    local devices (ICI). On a single host this degenerates to
    (1 x local-device factorization) of make_mesh."""
    devices = list(devices if devices is not None else jax.devices())
    by_process: dict[int, list] = {}
    for d in devices:
        by_process.setdefault(getattr(d, "process_index", 0), []).append(d)
    n_hosts = len(by_process)
    per_host = min(len(v) for v in by_process.values())
    dropped = sum(len(v) - per_host for v in by_process.values())
    if dropped:
        import warnings

        warnings.warn(
            f"make_hierarchical_mesh: hosts have unequal device counts; "
            f"dropping {dropped} device(s) to keep the mesh rectangular",
            stacklevel=2,
        )
    grid = np.array(
        [v[:per_host] for _, v in sorted(by_process.items())]
    )  # [hosts, local]
    # widen bindings within the host too when local devices outnumber the
    # useful cluster shards (keeps shard shapes square-ish)
    lb, lc = factor_mesh(per_host)
    grid = grid.reshape(n_hosts * lb, lc)
    return Mesh(grid, (AXIS_BINDINGS, AXIS_CLUSTERS))


# in_specs in the exact positional order of sched.core._schedule_kernel_compact
_FLEET_SPECS = (
    P(AXIS_CLUSTERS),        # alive
    P(AXIS_CLUSTERS, None),  # capacity
    P(AXIS_CLUSTERS),        # has_summary
    P(AXIS_CLUSTERS, None),  # taint_key
    P(AXIS_CLUSTERS, None),  # taint_value
    P(AXIS_CLUSTERS, None),  # taint_effect
    P(AXIS_CLUSTERS, None),  # api_ok
)
_BATCH_SPECS = (
    P(AXIS_BINDINGS),        # replicas
    P(AXIS_BINDINGS),        # unknown_request
    P(AXIS_BINDINGS),        # gvk
    P(AXIS_BINDINGS),        # strategy
    P(AXIS_BINDINGS),        # fresh
    P(None, None, None),     # tol_tables [T,4,K] (replicated policy table)
    P(AXIS_BINDINGS),        # tol_idx
    P(None, AXIS_CLUSTERS),  # aff_masks   [P,C] policy table, column-sharded
    P(AXIS_BINDINGS),        # aff_idx
    P(None, AXIS_CLUSTERS),  # weight_tables [W,C]
    P(AXIS_BINDINGS),        # weight_idx
    P(AXIS_BINDINGS, None),  # prev_idx (global column ids)
    P(AXIS_BINDINGS, None),  # prev_rep
    P(AXIS_BINDINGS, None),  # evict_idx
    P(AXIS_BINDINGS),        # seeds
    P(None, None),           # req_unique (replicated policy table)
    P(AXIS_BINDINGS),        # req_idx
)
_OUT_SPECS = (
    P(AXIS_BINDINGS, None),  # feasible (full rows, replicated over clusters axis)
    P(AXIS_BINDINGS, None),  # score
    P(AXIS_BINDINGS, None),  # result
    P(AXIS_BINDINGS),        # unschedulable
    P(AXIS_BINDINGS),        # available_sum
    P(AXIS_BINDINGS, None),  # avail
    P(AXIS_BINDINGS),        # feas_count
    P(AXIS_BINDINGS),        # nnz
    P(AXIS_BINDINGS, None),  # top_idx
    P(AXIS_BINDINGS, None),  # top_val
)


def _sharded_body(topk: int, plugin_bits: int, has_terms: bool):
    def body(
        alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
        replicas, unknown_request, gvk, strategy, fresh,
        tol_tables, tol_idx,
        aff_masks, aff_idx, weight_tables, weight_idx,
        prev_idx, prev_rep, evict_idx, seeds,
        req_unique, req_idx,
        extra_avail, extra_mask, extra_score,
    ):
        # shares the single-chip kernel's phases (sched/core.py): decompress →
        # filter/estimate on the local tile → all_gather → assignment tail
        from ..sched.core import (
            assignment_tail,
            compact_outputs,
            decompress_batch,
            filter_estimate_phase,
        )

        C_l = alive.shape[0]
        c0 = jax.lax.axis_index(AXIS_CLUSTERS).astype(jnp.int32) * C_l

        affinity_ok, static_weight, prev_member, prev_replicas, eviction_ok, tie = (
            decompress_batch(
                aff_masks, aff_idx, weight_tables, weight_idx,
                prev_idx, prev_rep, evict_idx, seeds, C_l, col_offset=c0,
            )
        )
        tol = tol_tables[tol_idx]  # [B_l,4,K] on-device gather
        feasible_l, score_l, avail_l = filter_estimate_phase(
            alive, capacity, has_summary, taint_key, taint_value, taint_effect,
            api_ok,
            replicas, None, unknown_request, gvk,
            tol[:, 0], tol[:, 1], tol[:, 2], tol[:, 3],
            affinity_ok, eviction_ok, prev_member,
            req_unique=req_unique, req_idx=req_idx,
            plugin_bits=plugin_bits,
        )

        # ---- gather the cluster shards: the division solve is a per-row
        # sort/cumsum over the FULL fleet (binding.go:112-144). One tiled
        # all_gather over ICI reconstructs the global rows. ----
        def gcols(x):
            return jax.lax.all_gather(x, AXIS_CLUSTERS, axis=1, tiled=True)

        feasible = gcols(feasible_l)
        score = gcols(score_l)
        avail = gcols(avail_l)
        static_w = gcols(static_weight)
        prev_r = gcols(prev_replicas)
        tie_g = gcols(tie)

        if has_terms:
            # out-of-tree plugin terms are host-computed full rows
            # (row-sharded): masks only shrink feasibility and scores only
            # add, so applying them post-gather is equivalent to the
            # single-chip in-phase application
            feasible = feasible & jnp.broadcast_to(extra_mask, feasible.shape)
            score = score + jnp.broadcast_to(extra_score, score.shape)

        # registered-estimator min-merge (row-sharded dense [B_l, C] or the
        # replicated [1,1] no-estimator sentinel)
        extra = jnp.broadcast_to(extra_avail, avail.shape)
        avail = jnp.where(extra >= 0, jnp.minimum(avail, extra), avail)

        result, unschedulable, avail_sum = assignment_tail(
            feasible, strategy, static_w, avail, prev_r, tie_g, replicas, fresh
        )
        feas_count, nnz, top_idx, top_val = compact_outputs(feasible, result, topk)
        return (
            feasible, score, result, unschedulable, avail_sum, avail,
            feas_count, nnz, top_idx, top_val,
        )

    return body


def _pad_axis(a: np.ndarray, axis: int, to: int, fill=0) -> np.ndarray:
    cur = a.shape[axis]
    if cur >= to:
        return a
    width = [(0, 0)] * a.ndim
    width[axis] = (0, to - cur)
    return np.pad(a, width, constant_values=fill)


def _round_up(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


class MeshScheduleKernel:
    """Drop-in replacement for ArrayScheduler.run_kernel over a device mesh.

    Holds the fleet column-sharded and device-resident across rounds (same
    persistent-snapshot discipline as the single-chip path); each call ships
    only the factored batch and returns the compact 10-output tuple of
    sched.core._schedule_kernel_compact (dense tensors stay on device until
    the host decode actually fetches them).

    Padded clusters are dead (alive=False ⇒ infeasible); padded binding rows
    are NON_WORKLOAD rows the decode never reads."""

    def __init__(self, mesh: Mesh, fleet: Optional[FleetArrays] = None):
        self.mesh = mesh
        self.mesh_b = mesh.shape[AXIS_BINDINGS]
        self.mesh_c = mesh.shape[AXIS_CLUSTERS]
        from ..sched.core import TOPK_TARGETS

        self._topk = TOPK_TARGETS
        self._kernels: dict[int, object] = {}
        self._fleet_dev = None
        self.n_clusters = 0
        if fleet is not None:
            self.set_fleet(fleet)

    def _kernel(self, topk: int, dense_extra: bool, plugin_bits: int,
                has_terms: bool):
        key = (topk, dense_extra, plugin_bits, has_terms)
        fn = self._kernels.get(key)
        if fn is None:
            extra_spec = P(AXIS_BINDINGS, None) if dense_extra else P(None, None)
            term_spec = P(AXIS_BINDINGS, None) if has_terms else P(None, None)
            fn = jax.jit(
                jax.shard_map(
                    _sharded_body(topk, plugin_bits, has_terms),
                    mesh=self.mesh,
                    in_specs=_FLEET_SPECS + _BATCH_SPECS
                    + (extra_spec, term_spec, term_spec),
                    out_specs=_OUT_SPECS,
                    check_vma=False,
                )
            )
            self._kernels[key] = fn
        return fn

    def set_fleet(self, fleet: FleetArrays) -> None:
        """Pad the cluster axis to a mesh-divisible size and place the fleet
        sharded on device once (re-placed only on cluster-set change)."""
        C = fleet.alive.shape[0]
        self.n_clusters = C
        self.padded_clusters = _round_up(max(C, self.mesh_c), self.mesh_c)

        def fb(a, spec):
            return jax.device_put(
                _pad_axis(a, 0, self.padded_clusters),
                NamedSharding(self.mesh, spec),
            )

        self._fleet_dev = (
            fb(fleet.alive, P(AXIS_CLUSTERS)),
            fb(fleet.capacity, P(AXIS_CLUSTERS, None)),
            fb(fleet.has_summary, P(AXIS_CLUSTERS)),
            fb(fleet.taint_key, P(AXIS_CLUSTERS, None)),
            fb(fleet.taint_value, P(AXIS_CLUSTERS, None)),
            fb(fleet.taint_effect, P(AXIS_CLUSTERS, None)),
            fb(fleet.api_ok, P(AXIS_CLUSTERS, None)),
        )

    _NO_EXTRA = np.full((1, 1), -1, np.int32)
    _NO_MASK = np.ones((1, 1), bool)
    _NO_SCORE = np.zeros((1, 1), np.int32)

    def __call__(self, batch: BindingBatch, extra_avail=None,
                 extra_mask=None, extra_score=None,
                 plugin_bits: Optional[int] = None):
        from ..sched import plugins as plugin_mod

        if plugin_bits is None:
            plugin_bits = plugin_mod.ALL_PLUGIN_BITS
        if self._fleet_dev is None:
            raise RuntimeError("set_fleet() before scheduling")
        B = len(batch.replicas)
        Bp = _round_up(max(B, self.mesh_b), self.mesh_b)
        Cp = self.padded_clusters

        def bb(a):  # [B,...] row-sharded arrays: pad binding axis
            return _pad_axis(a, 0, Bp)

        def tbl(a):  # policy tables: pad the cluster axis
            return _pad_axis(a, 1, Cp)

        # the encoder always factors requests (BindingBatch.request is a
        # view over req_unique/req_idx now, so there is no dense fallback)
        if batch.req_unique is None or batch.req_idx is None:
            raise ValueError(
                "BindingBatch lacks req_unique/req_idx — encode batches via "
                "BatchEncoder.encode()"
            )
        req_unique, req_idx = batch.req_unique, batch.req_idx
        if extra_avail is None or extra_avail.shape == (1, 1):
            extra, dense_extra = self._NO_EXTRA, False
        else:
            # registered-estimator answers are per-row: ship them row-sharded
            extra = _pad_axis(_pad_axis(extra_avail, 0, Bp, fill=-1), 1, Cp, fill=-1)
            dense_extra = True
        has_terms = (
            extra_mask is not None and extra_mask.shape != (1, 1)
        ) or (extra_score is not None and extra_score.shape != (1, 1))
        if has_terms:
            mask = (
                np.ones((B, self.n_clusters), bool)
                if extra_mask is None or extra_mask.shape == (1, 1)
                else np.asarray(extra_mask, bool)
            )
            score = (
                np.zeros((B, self.n_clusters), np.int32)
                if extra_score is None or extra_score.shape == (1, 1)
                else np.asarray(extra_score, np.int32)
            )
            mask = _pad_axis(_pad_axis(mask, 0, Bp, fill=True), 1, Cp, fill=True)
            score = _pad_axis(_pad_axis(score, 0, Bp), 1, Cp)
        else:
            mask, score = self._NO_MASK, self._NO_SCORE
        return self._kernel(min(Cp, self._topk), dense_extra, plugin_bits,
                            has_terms)(
            *self._fleet_dev,
            bb(batch.replicas), bb(batch.unknown_request),
            bb(batch.gvk), bb(batch.strategy), bb(batch.fresh),
            batch.tol_tables, bb(batch.tol_idx),
            tbl(batch.aff_masks), bb(batch.aff_idx),
            tbl(batch.weight_tables), bb(batch.weight_idx),
            # padded rows carry the global drop sentinel, not column 0
            _pad_axis(batch.prev_idx, 0, Bp, fill=Cp),
            bb(batch.prev_rep),
            _pad_axis(batch.evict_idx, 0, Bp, fill=Cp),
            bb(batch.seeds),
            req_unique,
            bb(req_idx),
            extra,
            mask,
            score,
        )

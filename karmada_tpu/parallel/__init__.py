"""Multi-chip sharding of the scheduling solve over a jax.sharding.Mesh."""
from .mesh import (
    AXIS_BINDINGS,
    AXIS_CLUSTERS,
    MeshScheduleKernel,
    factor_mesh,
    make_mesh,
)

__all__ = [
    "AXIS_BINDINGS",
    "AXIS_CLUSTERS",
    "MeshScheduleKernel",
    "factor_mesh",
    "make_mesh",
]

from .modeling import (
    DEFAULT_RESOURCE_MODELS,
    GradeHistogram,
    ModelBasedEstimator,
    default_resource_models,
    max_replicas_from_models,
    model_estimates_batch,
)

__all__ = [
    "DEFAULT_RESOURCE_MODELS",
    "GradeHistogram",
    "ModelBasedEstimator",
    "default_resource_models",
    "max_replicas_from_models",
    "model_estimates_batch",
]

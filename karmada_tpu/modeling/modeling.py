"""Cluster resource modeling (EST6): node histogram over configurable grades.

Reference behavior (pkg/modeling/modeling.go:33-240, defaults
pkg/apis/cluster/mutation/mutation.go:84-205, estimator path
pkg/estimator/client/general.go:116-249):
- every node is classified into a *grade* — per resource, the last grade whose
  range-min is <= the node's available amount; the node's grade is the MIN over
  its resources (getIndex, modeling.go:112-121);
- `Cluster.status.resourceSummary.allocatableModelings[g].count` histograms the
  fleet's nodes;
- the model-based MaxAvailableReplicas: find the minimum *compliant* grade
  (per resource, first grade with min >= request, maxed over resources —
  general.go:199-233); every node at grade >= that contributes
  `min_over_resources(floor(grade_min / request))` replicas, floored at 1 for
  the first suitable grade (general.go:127-154).

Instead of the reference's red-black-tree per grade, the histogram is a dense
[G] count vector and classification is a vectorized searchsorted over the grade
boundaries — O(N log G) for N nodes with plain numpy, trivially battachable.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np

from ..api.cluster import AllocatableModeling, ResourceModel, ResourceModelRange

GB = 1.0  # memory unit across the framework is GB-as-float

_DEFAULT_CPU_MINS = [0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0]
_DEFAULT_MEM_MINS = [0.0, 4.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0, 1024.0]


def default_resource_models() -> list[ResourceModel]:
    """The 9 default grades (mutation.go:84-205): cpu 0/1/2/4/8/16/32/64/128,
    memory 0/4/16/32/64/128/256/512/1024 GB; last grade max = +inf."""
    models: list[ResourceModel] = []
    n = len(_DEFAULT_CPU_MINS)
    for g in range(n):
        cpu_max = _DEFAULT_CPU_MINS[g + 1] if g + 1 < n else math.inf
        mem_max = _DEFAULT_MEM_MINS[g + 1] if g + 1 < n else math.inf
        models.append(
            ResourceModel(
                grade=g,
                ranges=[
                    ResourceModelRange(name="cpu", min=_DEFAULT_CPU_MINS[g], max=cpu_max),
                    ResourceModelRange(name="memory", min=_DEFAULT_MEM_MINS[g], max=mem_max),
                ],
            )
        )
    return models


DEFAULT_RESOURCE_MODELS = default_resource_models()


def _mins_by_resource(models: list[ResourceModel]) -> dict[str, np.ndarray]:
    """resource name -> [G] array of grade range-mins (convertToResourceModelsMinMap)."""
    out: dict[str, list[float]] = {}
    for m in sorted(models, key=lambda m: m.grade):
        for r in m.ranges:
            out.setdefault(r.name, []).append(r.min)
    return {k: np.asarray(v, dtype=np.float64) for k, v in out.items()}


class GradeHistogram:
    """Histogram of a cluster's nodes over model grades (the modeling.go
    ResourceSummary, minus the per-grade node trees — counts are enough for
    the estimator math)."""

    def __init__(self, models: Optional[list[ResourceModel]] = None):
        self.models = sorted(models or default_resource_models(), key=lambda m: m.grade)
        self.mins = _mins_by_resource(self.models)
        self.counts = np.zeros(len(self.models), dtype=np.int64)

    def classify(self, node_resources: dict[str, float]) -> int:
        """Grade of one node = min over resources of last grade whose min <=
        value (getIndex/searchLastLessElement, modeling.go:112-140)."""
        grade = len(self.models) - 1
        for name, mins in self.mins.items():
            v = node_resources.get(name, 0.0)
            # searchsorted(side='right')-1 == last index with mins[i] <= v
            idx = int(np.searchsorted(mins, v, side="right")) - 1
            grade = min(grade, max(idx, 0))
        return grade

    def add_nodes(self, nodes: list[dict[str, float]]) -> None:
        """Vectorized bulk classification (AddToResourceSummary over a fleet)."""
        if not nodes:
            return
        g = np.full(len(nodes), len(self.models) - 1, dtype=np.int64)
        for name, mins in self.mins.items():
            vals = np.asarray([n.get(name, 0.0) for n in nodes], dtype=np.float64)
            idx = np.searchsorted(mins, vals, side="right") - 1
            g = np.minimum(g, np.maximum(idx, 0))
        self.counts += np.bincount(g, minlength=len(self.models))

    def to_allocatable_modelings(self) -> list[AllocatableModeling]:
        return [
            AllocatableModeling(grade=m.grade, count=int(c))
            for m, c in zip(self.models, self.counts)
        ]


def max_replicas_from_models(
    models: list[ResourceModel],
    counts: list[int],
    request: dict[str, float],
) -> int:
    """Model-based MaxAvailableReplicas for one cluster
    (getMaximumReplicasBasedOnResourceModels, general.go:198-233)."""
    mins = _mins_by_resource(models)
    G = len(models)
    min_compliant = 0
    for name, req in request.items():
        if req <= 0:
            continue
        arr = mins.get(name)
        if arr is None:
            # resource model inapplicable for this resource (general.go:208-210)
            return -1
        # first grade with min >= request (minimumModelIndex)
        ge = np.nonzero(arr >= req)[0]
        if len(ge) == 0:
            return 0
        min_compliant = max(min_compliant, int(ge[0]))

    total = 0
    for g in range(min_compliant, G):
        c = counts[g] if g < len(counts) else 0
        if c == 0:
            continue
        per_node = math.inf
        for name, req in request.items():
            if req <= 0:
                continue
            per_node = min(per_node, mins[name][g] // req)
        if per_node == 0:
            per_node = 1  # first suitable grade can host one pod (general.go:149-152)
        total += int(c) * int(per_node)
    return total


def model_estimates_batch(
    models: list[ResourceModel],
    counts_matrix: np.ndarray,  # [C, G] per-cluster grade counts
    requests: np.ndarray,  # [B, R] requests over a fixed resource axis
    resource_names: list[str],
) -> np.ndarray:
    """Batched [B, C] model-based estimates — the whole fleet × all dirty
    bindings in one shot (the TPU-shaped equivalent of per-cluster loops).

    Uses the same grade math as max_replicas_from_models, vectorized:
      per_grade[b, g]  = min over resources floor(grade_min[g, r]/req[b, r])
      suitable[b, g]   = all resources' grade_min >= req  AND  g >= compliant
      answer[b, c]     = Σ_g suitable[b, g] * counts[c, g] * max(per_grade, 1 if ==0)
    """
    mins = _mins_by_resource(models)
    G = len(models)
    B = requests.shape[0]
    grade_min = np.zeros((G, len(resource_names)))
    have = np.zeros(len(resource_names), dtype=bool)
    for i, name in enumerate(resource_names):
        if name in mins:
            grade_min[:, i] = mins[name]
            have[i] = True

    req = np.asarray(requests, dtype=np.float64)  # [B, R]
    active = req > 0  # resources that constrain
    # a requested resource missing from the model: the model is inapplicable
    # for that binding (general.go:208-210 errors → summary fallback) — mark
    # with the -1 sentinel so the min-merge discards these answers
    inapplicable = (active & ~have[None, :]).any(axis=1)  # [B]

    with np.errstate(divide="ignore", invalid="ignore"):
        ratio = np.floor(grade_min[None, :, :] / req[:, None, :])  # [B, G, R]
    ratio = np.where(active[:, None, :], ratio, np.inf)
    per_grade = ratio.min(axis=2)  # [B, G]
    per_grade = np.where(np.isinf(per_grade), 0.0, per_grade)

    compliant = grade_min[None, :, :] >= req[:, None, :]  # grade min can host the pod
    suitable = np.where(active[:, None, :], compliant, True).all(axis=2)  # [B, G]
    # min-compliant grade: first suitable; all grades >= it contribute
    first = np.where(suitable.any(axis=1), suitable.argmax(axis=1), G)  # [B]
    grades = np.arange(G)
    contributing = grades[None, :] >= first[:, None]  # [B, G]
    per_node = np.where(contributing, np.maximum(per_grade, 1.0), 0.0)  # [B, G]

    answers = per_node @ counts_matrix.T.astype(np.float64)  # [B, C]
    answers[inapplicable] = -1.0
    return answers.astype(np.int64)


UNAUTHENTIC_REPLICA = -1  # estimator/client/interface.go:27-30 sentinel


class ModelBasedEstimator:
    """ReplicaEstimator backed by the cluster resource models
    (general.go:75-86: when CustomizedClusterResourceModeling is enabled and a
    cluster reports allocatableModelings, the model math bounds its answer;
    clusters without modelings answer the UnauthenticReplica sentinel so the
    min-merge discards this column for them — the summary path in the device
    kernel remains their estimate, mirroring the reference's fallback).

    Batched: clusters sharing a model definition are answered with one
    [B, C_group] matrix product (model_estimates_batch)."""

    def __init__(self, store, gates=None):
        self.store = store
        self.gates = gates

    def _enabled(self) -> bool:
        from ..features import CUSTOMIZED_CLUSTER_RESOURCE_MODELING

        return self.gates is None or self.gates.enabled(CUSTOMIZED_CLUSTER_RESOURCE_MODELING)

    def max_available_replicas_rows(self, clusters, requirements_list):
        C = len(clusters)
        B = len(requirements_list)
        out = np.full((B, C), UNAUTHENTIC_REPLICA, dtype=np.int64)
        if not self._enabled():
            return out.tolist()

        # collect model groups: model-signature -> (models, [cluster col], [counts])
        groups: dict = {}
        for c, name in enumerate(clusters):
            cluster = self.store.try_get("Cluster", name)
            if cluster is None or not cluster.spec.resource_models:
                continue
            modelings = (
                cluster.status.resource_summary.allocatable_modelings
                if cluster.status.resource_summary is not None
                else []
            )
            if not modelings:
                continue
            sig = tuple(
                (m.grade, tuple((r.name, r.min, r.max) for r in m.ranges))
                for m in cluster.spec.resource_models
            )
            models, cols, counts = groups.setdefault(sig, (cluster.spec.resource_models, [], []))
            cols.append(c)
            by_grade = {am.grade: am.count for am in modelings}
            counts.append([by_grade.get(m.grade, 0) for m in models])

        if not groups:
            return out.tolist()

        resource_names = sorted(
            {k for req in requirements_list if req is not None for k in req.resource_request}
        )
        if not resource_names:
            return out.tolist()
        requests = np.zeros((B, len(resource_names)))
        no_request = np.zeros(B, dtype=bool)
        for b, req in enumerate(requirements_list):
            if req is None or not req.resource_request:
                no_request[b] = True
                continue
            for i, name in enumerate(resource_names):
                requests[b, i] = req.resource_request.get(name, 0.0)

        for models, cols, counts in groups.values():
            answers = model_estimates_batch(
                models, np.asarray(counts, dtype=np.int64), requests, resource_names
            )  # [B, len(cols)]
            for j, c in enumerate(cols):
                out[:, c] = answers[:, j]
        # rows with no resource request: no model constraint (general.go:69-71)
        out[no_request, :] = UNAUTHENTIC_REPLICA
        return out.tolist()

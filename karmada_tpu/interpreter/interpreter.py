"""Resource interpreter: per-kind understanding of workload objects.

Parity with pkg/resourceinterpreter/interpreter.go:39-68 — operations:
GetReplicas, ReviseReplica, Retain, AggregateStatus, GetDependencies,
ReflectStatus, InterpretHealth — with default native interpreters for common
kinds (default/native/*.go) and a registry for customized interpreters (the
Lua/webhook tiers of the reference map to plain-Python customizations here;
declarative configs can be layered on this registry).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..api.meta import Resources
from ..api.unstructured import Unstructured
from ..api.work import AggregatedStatusItem, NodeClaim, ReplicaRequirements

HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"
UNKNOWN = "Unknown"

# the federated-generation protocol annotation (workv1alpha2
# ResourceTemplateGenerationAnnotationKey): members report which template
# revision they run; aggregations gate observedGeneration on it
RESOURCE_TEMPLATE_GENERATION_ANNOTATION = "resourcetemplate.karmada.io/generation"


@dataclass
class KindInterpreter:
    """Hooks for one GVK; any hook may be None → fall back to defaults."""

    get_replicas: Optional[Callable[[Unstructured], tuple[int, Optional[ReplicaRequirements]]]] = None
    revise_replica: Optional[Callable[[Unstructured, int], Unstructured]] = None
    retain: Optional[Callable[[Unstructured, Unstructured], Unstructured]] = None
    aggregate_status: Optional[Callable[[Unstructured, list[AggregatedStatusItem]], Unstructured]] = None
    get_dependencies: Optional[Callable[[Unstructured], list[dict]]] = None
    reflect_status: Optional[Callable[[Unstructured], Optional[dict]]] = None
    interpret_health: Optional[Callable[[Unstructured], str]] = None


def _pod_template_requirements(pod_spec: dict, namespace: str) -> ReplicaRequirements:
    request: Resources = {}
    for container in pod_spec.get("containers", []):
        for k, v in container.get("resources", {}).get("requests", {}).items():
            request[k] = request.get(k, 0.0) + _parse_quantity(v)
    node_claim = None
    if pod_spec.get("nodeSelector") or pod_spec.get("tolerations"):
        node_claim = NodeClaim(
            node_selector=dict(pod_spec.get("nodeSelector", {})),
            tolerations=list(pod_spec.get("tolerations", [])),
        )
    return ReplicaRequirements(
        node_claim=node_claim,
        resource_request=request,
        namespace=namespace,
        priority_class_name=pod_spec.get("priorityClassName", ""),
    )


def _parse_quantity(v: Any) -> float:
    """Kubernetes quantity strings → canonical floats (cpu cores / bytes)."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    try:
        return float(s)
    except ValueError:
        pass
    if s.endswith("m"):
        return float(s[:-1]) / 1000.0
    suffixes = {
        "Ki": 1024.0,
        "Mi": 1024.0**2,
        "Gi": 1024.0**3,
        "Ti": 1024.0**4,
        "Pi": 1024.0**5,
        "k": 1e3,
        "M": 1e6,
        "G": 1e9,
        "T": 1e12,
    }
    for suf, mult in suffixes.items():
        if s.endswith(suf):
            return float(s[: -len(suf)]) * mult
    raise ValueError(f"unparseable quantity {v!r}")


# Default native interpreters live in interpreter/native.py (default/native/*.go equivalents, the full kind matrix).


class ResourceInterpreter:
    """Facade (interpreter.go:39-68). Custom interpreters override defaults
    per GVK; generic fallbacks keep unknown kinds propagatable."""

    def __init__(self) -> None:
        # Tier priority (interpreter.go: customized webhook > customized
        # declarative > thirdparty configs > default native). Interpreters
        # registered through the public register() API live in their own tier:
        # the declarative manager rebuilds _declarative wholesale on every
        # customization change and must not drop manual registrations.
        self._webhook: dict[str, KindInterpreter] = {}
        self._registered: dict[str, KindInterpreter] = {}
        self._declarative: dict[str, KindInterpreter] = {}
        self._thirdparty: dict[str, KindInterpreter] = {}
        from .native import default_native_tier

        self._native: dict[str, KindInterpreter] = default_native_tier()

    @staticmethod
    def _gvk(obj: Unstructured) -> str:
        return f"{obj.api_version}/{obj.kind}"

    def register(self, gvk: str, interpreter: KindInterpreter) -> None:
        """Manually-registered customized interpreter (survives declarative
        reconciles; takes priority over declarative scripts)."""
        self._registered[gvk] = interpreter

    def set_declarative_tier(self, tier: dict[str, KindInterpreter]) -> None:
        """Replace the declarative-customization tier wholesale (the manager
        rebuilds it from the live customization objects)."""
        self._declarative = tier

    def set_webhook_tier(self, tier: dict[str, KindInterpreter]) -> None:
        self._webhook = tier

    def load_thirdparty(self) -> None:
        """Load the shipped thirdparty configs (default/thirdparty/)."""
        from .thirdparty import load_thirdparty_tier

        self._thirdparty = load_thirdparty_tier()

    def _hook(self, obj: Unstructured, name: str):
        gvk = self._gvk(obj)
        for tier in (
            self._webhook,
            self._registered,
            self._declarative,
            self._thirdparty,
            self._native,
        ):
            ki = tier.get(gvk)
            if ki is not None and getattr(ki, name) is not None:
                return getattr(ki, name)
        return None

    # -- operations -------------------------------------------------------

    def get_replicas(self, obj: Unstructured) -> tuple[int, Optional[ReplicaRequirements]]:
        hook = self._hook(obj, "get_replicas")
        if hook:
            return hook(obj)
        return 0, None  # non-workload

    def revise_replica(self, obj: Unstructured, replicas: int) -> Unstructured:
        hook = self._hook(obj, "revise_replica")
        if hook:
            return hook(obj, replicas)
        if obj.get("spec", "replicas") is not None:
            obj.set("spec", "replicas", replicas)
        return obj

    def retain(self, desired: Unstructured, observed: Unstructured) -> Unstructured:
        hook = self._hook(desired, "retain")
        if hook:
            return hook(desired, observed)
        return desired

    def aggregate_status(
        self, template: Unstructured, items: list[AggregatedStatusItem]
    ) -> Unstructured:
        hook = self._hook(template, "aggregate_status")
        if hook:
            return hook(template, items)
        return template

    def get_dependencies(self, obj: Unstructured) -> list[dict]:
        hook = self._hook(obj, "get_dependencies")
        if hook:
            return hook(obj)
        return []

    def reflect_status(self, obj: Unstructured) -> Optional[dict]:
        hook = self._hook(obj, "reflect_status")
        if hook:
            return hook(obj)
        status = obj.get("status")
        return dict(status) if isinstance(status, dict) else None

    def interpret_health(self, obj: Unstructured) -> str:
        hook = self._hook(obj, "interpret_health")
        if hook:
            return hook(obj)
        return UNKNOWN

"""Shipped thirdparty resource customizations (I3).

The reference ships 16 customization sets as Lua executed in its sandboxed VM
(`pkg/resourceinterpreter/default/thirdparty/resourcecustomizations/*/*/
customizations.yaml`). Here the same per-kind behaviors are native Python
hooks — the scripts share a handful of shapes (sum-counters aggregate with
the observed-generation count, cluster-prefixed condition merge, last-wins
scalars, Ready-condition health), factored below as combinators.

Kind inventory (matching the reference library kind-for-kind):
  apps.kruise.io/v1alpha1  AdvancedCronJob, BroadcastJob, CloneSet, DaemonSet
  apps.kruise.io/v1beta1   StatefulSet
  argoproj.io/v1alpha1     Workflow
  flink.apache.org/v1beta1 FlinkDeployment
  helm.toolkit.fluxcd.io/v2beta1      HelmRelease
  kustomize.toolkit.fluxcd.io/v1      Kustomization
  source.toolkit.fluxcd.io/v1         GitRepository
  source.toolkit.fluxcd.io/v1beta2    Bucket, HelmChart, HelmRepository,
                                      OCIRepository
  kyverno.io/v1            ClusterPolicy, Policy
(plus argoproj.io/v1alpha1 Rollout, an extra not in the reference set)

Behavior citations in the builders refer to the corresponding
customizations.yaml; the resource-template generation handling mirrors the
reference's `resourcetemplate.karmada.io/generation` protocol.
"""
from __future__ import annotations

import math
from typing import Callable, Optional, Sequence

from ..api.unstructured import Unstructured
from ..api.work import AggregatedStatusItem, NodeClaim, ReplicaRequirements
from .interpreter import (
    HEALTHY,
    KindInterpreter,
    RESOURCE_TEMPLATE_GENERATION_ANNOTATION,
    UNHEALTHY,
    _parse_quantity,
    _pod_template_requirements,
)


# ---------------------------------------------------------------------------
# combinators (the shapes shared across the reference's Lua scripts)
# ---------------------------------------------------------------------------


def _statuses(items: Sequence[AggregatedStatusItem]) -> list[dict]:
    return [it.status or {} for it in items]


def _sum_field(items: Sequence[AggregatedStatusItem], field: str) -> int:
    total = 0
    for st in _statuses(items):
        v = st.get(field)
        if v is not None:
            total += v
    return total


def _last_wins(items, field, default=None, nonempty: bool = False):
    """Accumulator shape `if st.X ~= nil [and ~= ''] then acc = st.X end`."""
    acc = default
    for st in _statuses(items):
        v = st.get(field)
        if v is None:
            continue
        if nonempty and v == "":
            continue
        acc = v
    return acc


def _merge_conditions(items: Sequence[AggregatedStatusItem]) -> list[dict]:
    """Cluster-prefixed condition merge: each member condition's message is
    prefixed `{cluster}={message}`; conditions agreeing on (type, status,
    reason) merge by comma-joining their messages (the shape in every FluxCD
    / Kyverno statusAggregation script)."""
    merged: list[dict] = []
    for it in items:
        st = it.status or {}
        for cond in st.get("conditions") or []:
            c = dict(cond)
            c["message"] = f"{it.cluster_name}={c.get('message', '')}"
            for have in merged:
                if (
                    have.get("type") == c.get("type")
                    and have.get("status") == c.get("status")
                    and have.get("reason") == c.get("reason")
                ):
                    have["message"] = f"{have['message']}, {c['message']}"
                    break
            else:
                merged.append(c)
    return merged


def _aggregate_observed_generation(template: Unstructured,
                                   items: Sequence[AggregatedStatusItem]) -> int:
    """The observed-generation count: the aggregated observedGeneration
    advances to the template generation only when EVERY member reports
    (a) resourceTemplateGeneration == template generation and (b) its own
    status caught up (generation == observedGeneration) — otherwise the
    previous aggregated value is kept."""
    generation = template.metadata.generation or 0
    prev = template.get("status", "observedGeneration", default=0) or 0
    caught_up = 0
    for st in _statuses(items):
        rtg = st.get("resourceTemplateGeneration") or 0
        member_gen = st.get("generation") or 0
        member_obs = st.get("observedGeneration") or 0
        if rtg == generation and member_gen == member_obs:
            caught_up += 1
    return generation if caught_up == len(items) else prev


def _reflect_with_generation(obj: Unstructured, fields: Sequence[str]) -> dict:
    """statusReflection shape: copy the named PRESENT status fields, report
    the member generation, and lift the resource-template generation from
    the `resourcetemplate.karmada.io/generation` annotation when numeric."""
    status = {}
    observed = obj.get("status") or {}
    for f in fields:
        if f in observed:
            status[f] = observed[f]
    status["generation"] = obj.metadata.generation
    rtg = obj.metadata.annotations.get(RESOURCE_TEMPLATE_GENERATION_ANNOTATION)
    if rtg is not None:
        try:
            status["resourceTemplateGeneration"] = int(float(rtg))
        except (TypeError, ValueError):
            pass
    return status


def _ready_condition_health(*reasons: str) -> Callable[[Unstructured], str]:
    """healthInterpretation shape shared by every FluxCD kind: healthy iff
    some condition is (Ready, True) with one of the given reasons."""

    def health(obj: Unstructured) -> str:
        for cond in obj.get("status", "conditions", default=[]) or []:
            if (
                cond.get("type") == "Ready"
                and cond.get("status") == "True"
                and cond.get("reason") in reasons
            ):
                return HEALTHY
        return UNHEALTHY

    return health


def _spec_replicas_hooks(template_path=("spec", "template")):
    """(get_replicas, revise_replica) for Deployment-shaped CRDs: replicas
    at spec.replicas, requirements from the pod template."""

    def get_replicas(obj: Unstructured):
        replicas = int(obj.get("spec", "replicas", default=1) or 0)
        tpl = obj.get(*template_path, default={}) or {}
        pod_spec = tpl.get("spec", {}) or {}
        return replicas, _pod_template_requirements(pod_spec, obj.namespace)

    def revise(obj: Unstructured, n: int) -> Unstructured:
        obj.set("spec", "replicas", n)
        return obj

    return get_replicas, revise


def _pod_spec_dependencies(pod_spec: dict, namespace: str) -> list[dict]:
    """kube.getPodDependencies equivalent (luavm/kube.go:104-132 →
    helper.GetDependenciesFromPodTemplate): ConfigMaps/Secrets/PVCs/
    ServiceAccount referenced by a pod spec."""
    cms: dict[str, bool] = {}
    secrets: dict[str, bool] = {}
    pvcs: dict[str, bool] = {}
    sas: dict[str, bool] = {}
    for vol in pod_spec.get("volumes") or []:
        cm = vol.get("configMap", {}).get("name")
        if cm:
            cms[cm] = True
        sec = vol.get("secret", {}).get("secretName")
        if sec:
            secrets[sec] = True
        pvc = vol.get("persistentVolumeClaim", {}).get("claimName")
        if pvc:
            pvcs[pvc] = True
        for src in (vol.get("projected") or {}).get("sources") or []:
            n = src.get("configMap", {}).get("name")
            if n:
                cms[n] = True
            n = src.get("secret", {}).get("name")
            if n:
                secrets[n] = True
    for container in (
        list(pod_spec.get("containers") or [])
        + list(pod_spec.get("initContainers") or [])
    ):
        for env in container.get("env") or []:
            src = env.get("valueFrom") or {}
            n = src.get("configMapKeyRef", {}).get("name")
            if n:
                cms[n] = True
            n = src.get("secretKeyRef", {}).get("name")
            if n:
                secrets[n] = True
        for envfrom in container.get("envFrom") or []:
            n = envfrom.get("configMapRef", {}).get("name")
            if n:
                cms[n] = True
            n = envfrom.get("secretRef", {}).get("name")
            if n:
                secrets[n] = True
    for ref in pod_spec.get("imagePullSecrets") or []:
        if ref.get("name"):
            secrets[ref["name"]] = True
    sa = pod_spec.get("serviceAccountName")
    if sa and sa != "default":
        sas[sa] = True
    return _refs(namespace, ConfigMap=cms, Secret=secrets,
                 ServiceAccount=sas, PersistentVolumeClaim=pvcs)


def _refs(namespace: str, **by_kind: dict) -> list[dict]:
    out = []
    for kind, names in by_kind.items():
        for name in names:
            out.append({
                "apiVersion": "v1", "kind": kind,
                "namespace": namespace, "name": name,
            })
    return out


def _pod_template_dependencies(template_path=("spec", "template")):
    def deps(obj: Unstructured) -> list[dict]:
        tpl = obj.get(*template_path, default={}) or {}
        return _pod_spec_dependencies(tpl.get("spec", {}) or {}, obj.namespace)

    return deps


def _retain_suspend(desired: Unstructured, observed: Unstructured) -> Unstructured:
    """Retention shape shared by the FluxCD kinds: member controllers may
    suspend a resource in place; keep that."""
    suspend = observed.get("spec", "suspend")
    if suspend is not None:
        desired.set("spec", "suspend", suspend)
    return desired


def _counter_aggregate(
    sum_fields: Sequence[str],
    last_fields: Sequence[str] = (),
    last_default="",
    init_zero: Sequence[str] = (),
    init_extra: Optional[dict] = None,
):
    """The Kruise workload statusAggregation shape (CloneSet/StatefulSet/
    DaemonSet): numeric member counters sum; revision-ish scalars last-wins
    (skipping empties); observedGeneration advances via the caught-up count;
    an empty member set resets the counters and stamps observedGeneration =
    generation."""

    def aggregate(template: Unstructured,
                  items: list[AggregatedStatusItem]) -> Unstructured:
        status = template.get("status") or {}
        template.set("status", status)
        if not items:
            status["observedGeneration"] = template.metadata.generation or 0
            for f in init_zero or sum_fields:
                status[f] = 0
            for k, v in (init_extra or {}).items():
                status[k] = v
            return template
        status["observedGeneration"] = _aggregate_observed_generation(
            template, items
        )
        for f in sum_fields:
            status[f] = _sum_field(items, f)
        for f in last_fields:
            status[f] = _last_wins(items, f, default=last_default, nonempty=True)
        return template

    return aggregate


def _generation_gated_workload_health(
    updated_field: str, available_field: str, desired_field: Optional[str] = None
):
    """Kruise workload healthInterpretation shape: healthy iff the status
    caught up with the template generation, every desired replica is
    updated, and every updated replica is available."""

    def health(obj: Unstructured) -> str:
        st = obj.get("status") or {}
        if (st.get("observedGeneration") or 0) != obj.metadata.generation:
            return UNHEALTHY
        updated = st.get(updated_field) or 0
        if desired_field is None:
            spec_replicas = obj.get("spec", "replicas")
            if spec_replicas is not None and updated < spec_replicas:
                return UNHEALTHY
        else:
            if updated < (st.get(desired_field) or 0):
                return UNHEALTHY
        if (st.get(available_field) or 0) < updated:
            return UNHEALTHY
        return HEALTHY

    return health


def _reflector(fields: Sequence[str]):
    return lambda obj: _reflect_with_generation(obj, fields)


# ---------------------------------------------------------------------------
# Kruise workloads
# ---------------------------------------------------------------------------


def _cloneset() -> KindInterpreter:
    """apps.kruise.io/v1alpha1 CloneSet customizations.yaml."""
    get_replicas, revise = _spec_replicas_hooks()
    return KindInterpreter(
        get_replicas=get_replicas,
        revise_replica=revise,
        aggregate_status=_counter_aggregate(
            sum_fields=(
                "replicas", "updatedReplicas", "readyReplicas",
                "availableReplicas", "updatedReadyReplicas",
                "expectedUpdatedReplicas",
            ),
            last_fields=("updateRevision", "currentRevision", "labelSelector"),
        ),
        reflect_status=_reflector((
            "replicas", "updatedReplicas", "readyReplicas",
            "availableReplicas", "updatedReadyReplicas",
            "expectedUpdatedReplicas", "updateRevision", "currentRevision",
            "observedGeneration", "labelSelector",
        )),
        interpret_health=_generation_gated_workload_health(
            "updatedReplicas", "availableReplicas"
        ),
        get_dependencies=_pod_template_dependencies(),
    )


def _kruise_statefulset() -> KindInterpreter:
    """apps.kruise.io/v1beta1 StatefulSet customizations.yaml."""
    get_replicas, revise = _spec_replicas_hooks()
    return KindInterpreter(
        get_replicas=get_replicas,
        revise_replica=revise,
        aggregate_status=_counter_aggregate(
            sum_fields=(
                "replicas", "readyReplicas", "currentReplicas",
                "updatedReplicas", "availableReplicas", "updatedReadyReplicas",
            ),
            last_fields=("updateRevision", "currentRevision"),
            init_extra={"updateRevision": "", "currentRevision": ""},
        ),
        reflect_status=_reflector((
            "replicas", "readyReplicas", "currentReplicas", "updatedReplicas",
            "availableReplicas", "updateRevision", "currentRevision",
            "updatedReadyReplicas", "observedGeneration",
        )),
        interpret_health=_generation_gated_workload_health(
            "updatedReplicas", "availableReplicas"
        ),
        get_dependencies=_pod_template_dependencies(),
    )


def _kruise_daemonset() -> KindInterpreter:
    """apps.kruise.io/v1alpha1 DaemonSet customizations.yaml (no replica
    hooks — daemons size themselves per member)."""
    return KindInterpreter(
        aggregate_status=_counter_aggregate(
            sum_fields=(
                "currentNumberScheduled", "numberMisscheduled",
                "desiredNumberScheduled", "numberReady",
                "updatedNumberScheduled", "numberAvailable",
                "numberUnavailable",
            ),
            last_fields=("daemonSetHash",),
            last_default=0,  # the script's accumulator seed in BOTH branches
            init_extra={"daemonSetHash": 0},
        ),
        reflect_status=_reflector((
            "observedGeneration", "currentNumberScheduled",
            "numberMisscheduled", "desiredNumberScheduled", "numberReady",
            "updatedNumberScheduled", "numberAvailable", "numberUnavailable",
            "daemonSetHash",
        )),
        interpret_health=_generation_gated_workload_health(
            "updatedNumberScheduled", "numberAvailable",
            desired_field="desiredNumberScheduled",
        ),
        get_dependencies=_pod_template_dependencies(),
    )


def _advanced_cronjob() -> KindInterpreter:
    """apps.kruise.io/v1alpha1 AdvancedCronJob customizations.yaml."""

    def aggregate(template: Unstructured, items) -> Unstructured:
        if not items:
            return template
        status = template.get("status") or {}
        template.set("status", status)
        active: list = []
        last_type = ""
        last_schedule = {}
        for st in _statuses(items):
            active.extend(st.get("active") or [])
            if st.get("type") is not None:
                last_type = st["type"]
            if st.get("lastScheduleTime") is not None:
                last_schedule = st["lastScheduleTime"]
        status["active"] = active
        status["type"] = last_type
        status["lastScheduleTime"] = last_schedule
        return template

    def deps(obj: Unstructured) -> list[dict]:
        tpl = obj.get("spec", "template", default={}) or {}
        inner = (
            tpl.get("jobTemplate")
            or tpl.get("broadcastJobTemplate")
            or {}
        )
        pod_tpl = (inner.get("spec") or {}).get("template") or {}
        return _pod_spec_dependencies(pod_tpl.get("spec", {}) or {}, obj.namespace)

    return KindInterpreter(aggregate_status=aggregate, get_dependencies=deps)


def _broadcast_job() -> KindInterpreter:
    """apps.kruise.io/v1alpha1 BroadcastJob customizations.yaml."""

    def get_replicas(obj: Unstructured):
        replicas = int(obj.get("spec", "parallelism", default=1) or 1)
        tpl = obj.get("spec", "template", default={}) or {}
        return replicas, _pod_template_requirements(
            tpl.get("spec", {}) or {}, obj.namespace
        )

    def revise(obj: Unstructured, n: int) -> Unstructured:
        obj.set("spec", "parallelism", n)
        return obj

    def health(obj: Unstructured) -> str:
        st = obj.get("status") or {}
        if (st.get("desired") or 0) == 0 or (st.get("failed") or 0) != 0:
            return UNHEALTHY
        if (st.get("succeeded") or 0) == 0 and (st.get("active") or 0) == 0:
            return UNHEALTHY
        return HEALTHY

    def aggregate(template: Unstructured, items) -> Unstructured:
        if not items:
            return template
        status = template.get("status") or {}
        template.set("status", status)
        active = succeeded = failed = desired = 0
        phase = ""
        successful_jobs = 0
        job_failed: list[str] = []
        # NOTE: `cond_type` persists across members, mirroring the script's
        # accumulator (a member without Complete/Failed conditions inherits
        # the previous member's verdict)
        cond_type = ""
        for it in items:
            st = it.status or {}
            active += st.get("active") or 0
            succeeded += st.get("succeeded") or 0
            failed += st.get("failed") or 0
            desired += st.get("desired") or 0
            if st.get("phase") is not None:
                phase = st["phase"]
            for cond in st.get("conditions") or []:
                if cond.get("type") in ("Complete", "Failed") and (
                    cond.get("status") == "True"
                ):
                    cond_type = cond["type"]
                    break
            if cond_type == "Complete":
                successful_jobs += 1
            if cond_type == "Failed":
                job_failed.append(it.cluster_name)
        conditions = []
        if job_failed:
            conditions.append({
                "type": "Failed", "status": "True", "reason": "JobFailed",
                "message": (
                    "Job executed failed in member clusters: "
                    + ", ".join(job_failed)
                ),
            })
        if successful_jobs == len(items) and successful_jobs > 0:
            conditions.append({
                "type": "Completed", "status": "True", "reason": "Completed",
                "message": "Job completed",
            })
        status["active"] = active
        status["succeeded"] = succeeded
        status["failed"] = failed
        status["desired"] = desired
        status["phase"] = phase
        status["conditions"] = conditions
        return template

    def retain(desired: Unstructured, observed: Unstructured) -> Unstructured:
        labels = observed.get("spec", "template", "metadata", "labels")
        if labels is not None:
            desired.set("spec", "template", "metadata", "labels", labels)
        return desired

    return KindInterpreter(
        get_replicas=get_replicas,
        revise_replica=revise,
        interpret_health=health,
        aggregate_status=aggregate,
        retain=retain,
        reflect_status=_reflector((
            "conditions", "startTime", "completionTime", "active",
            "succeeded", "failed", "desired", "phase",
        )),
        get_dependencies=_pod_template_dependencies(),
    )


# ---------------------------------------------------------------------------
# Argo Workflow
# ---------------------------------------------------------------------------


def _argo_workflow() -> KindInterpreter:
    """argoproj.io/v1alpha1 Workflow customizations.yaml."""

    def get_replicas(obj: Unstructured):
        replicas = int(obj.get("spec", "parallelism", default=1) or 1)
        # the Workflow spec carries scheduling fields at the top level; the
        # script builds a pseudo pod template from them
        pseudo_spec = {
            "nodeSelector": obj.get("spec", "nodeSelector", default={}) or {},
            "tolerations": obj.get("spec", "tolerations", default=[]) or [],
        }
        return replicas, _pod_template_requirements(pseudo_spec, obj.namespace)

    def revise(obj: Unstructured, n: int) -> Unstructured:
        obj.set("spec", "parallelism", n)
        return obj

    def health(obj: Unstructured) -> str:
        st = obj.get("status")
        if not st:
            return UNHEALTHY
        phase = st.get("phase")
        # 'Error' is a real terminal Argo phase alongside 'Failed'; the
        # script's `status.failed == 'Error'` accumulator check is kept too
        if phase in (None, "", "Failed", "Error") or st.get("failed") == "Error":
            return UNHEALTHY
        return HEALTHY

    def retain(desired: Unstructured, observed: Unstructured) -> Unstructured:
        suspend = observed.get("spec", "suspend")
        if suspend is not None:
            desired.set("spec", "suspend", suspend)
        st = observed.get("status")
        if st is not None:
            desired.set("status", st)
        return desired

    def deps(obj: Unstructured) -> list[dict]:
        spec = obj.get("spec") or {}
        ns = obj.namespace
        cms: dict[str, bool] = {}
        secrets: dict[str, bool] = {}
        sas: dict[str, bool] = {}
        pvcs: dict[str, bool] = {}
        executor_sa = (spec.get("executor") or {}).get("serviceAccountName")
        if executor_sa:
            sas[executor_sa] = True
        for claim in spec.get("volumeClaimTemplates") or []:
            n = (claim.get("metadata") or {}).get("name")
            if n:
                pvcs[n] = True
        for vol in spec.get("volumes") or []:
            n = vol.get("configMap", {}).get("name")
            if n:
                cms[n] = True
            for src in (vol.get("projected") or {}).get("sources") or []:
                n = src.get("configMap", {}).get("name")
                if n:
                    cms[n] = True
                n = src.get("secret", {}).get("name")
                if n:
                    secrets[n] = True
            for holder, key in (
                ("azureFile", "secretName"),
                ("secret", "name"),  # the script checks .name, like argo's
            ):
                n = vol.get(holder, {}).get(key)
                if n:
                    secrets[n] = True
            for holder in (
                "cephfs", "cinder", "flexVolume", "rbd", "scaleIO",
                "iscsi", "storageos",
            ):
                n = vol.get(holder, {}).get("secretRef", {}).get("name")
                if n:
                    secrets[n] = True
            n = vol.get("csi", {}).get("nodePublishSecretRef", {}).get("name")
            if n:
                secrets[n] = True
            n = vol.get("persistentVolumeClaim", {}).get("claimName")
            if n:
                pvcs[n] = True
        for ref in spec.get("imagePullSecrets") or []:
            if ref.get("name"):
                secrets[ref["name"]] = True
        sa = spec.get("serviceAccountName")
        if sa and sa != "default":
            sas[sa] = True
        return _refs(ns, ConfigMap=cms, Secret=secrets,
                     ServiceAccount=sas, PersistentVolumeClaim=pvcs)

    return KindInterpreter(
        get_replicas=get_replicas,
        revise_replica=revise,
        interpret_health=health,
        retain=retain,
        get_dependencies=deps,
    )


# ---------------------------------------------------------------------------
# Flink
# ---------------------------------------------------------------------------

_FLINK_EPHEMERAL = ("CREATED", "INITIALIZING", "RECONCILING")


def _flink_deployment() -> KindInterpreter:
    """flink.apache.org/v1beta1 FlinkDeployment customizations.yaml."""

    def health(obj: Unstructured) -> str:
        st = obj.get("status") or {}
        state = (st.get("jobStatus") or {}).get("state")
        if state is not None:
            if state not in _FLINK_EPHEMERAL:
                # terminal/running/short-lived states are all healthy
                return HEALTHY
            # ephemeral states are healthy only with a published error
            ok = (
                st.get("error") is not None
                or st.get("jobManagerDeploymentStatus") == "ERROR"
            )
            return HEALTHY if ok else UNHEALTHY
        return HEALTHY if st.get("error") is not None else UNHEALTHY

    def get_replicas(obj: Unstructured):
        spec = obj.get("spec") or {}
        jm = spec.get("jobManager") or {}
        tm = spec.get("taskManager") or {}
        jm_replicas = jm.get("replicas") or 1
        tm_replicas = tm.get("replicas")
        if not tm_replicas:
            parallelism = (spec.get("job") or {}).get("parallelism")
            slots = (spec.get("flinkConfiguration") or {}).get(
                "taskmanager.numberOfTaskSlots"
            )
            if not parallelism or not slots:
                tm_replicas = 1
            else:
                tm_replicas = math.ceil(float(parallelism) / float(slots))
        replicas = int(jm_replicas) + int(tm_replicas)
        # one podTemplate per deployment isn't expressible yet: take the max
        # of the jobManager/taskManager resource as the requirement
        jm_res = jm.get("resource") or {}
        tm_res = tm.get("resource") or {}
        request = {
            "cpu": max(
                float(tm_res.get("cpu") or 0.0), float(jm_res.get("cpu") or 0.0)
            ),
            "memory": max(
                _parse_quantity(jm_res.get("memory") or 0),
                _parse_quantity(tm_res.get("memory") or 0),
            ),
        }
        node_claim = None
        priority_class = ""
        pod_tpl_spec = (spec.get("podTemplate") or {}).get("spec") or {}
        if pod_tpl_spec:
            node_claim = NodeClaim(
                node_selector=dict(pod_tpl_spec.get("nodeSelector") or {}),
                tolerations=list(pod_tpl_spec.get("tolerations") or []),
            )
            priority_class = pod_tpl_spec.get("priorityClassName") or ""
        return replicas, ReplicaRequirements(
            node_claim=node_claim,
            resource_request=request,
            namespace=obj.namespace,
            priority_class_name=priority_class,
        )

    _fields = (
        "clusterInfo", "error", "jobManagerDeploymentStatus", "jobStatus",
        "lifecycleState", "observedGeneration", "reconciliationStatus",
        "taskManager",
    )

    def aggregate(template: Unstructured, items) -> Unstructured:
        if not items:
            return template
        status = template.get("status") or {}
        template.set("status", status)
        for f in _fields:
            status[f] = _last_wins(items, f)
        return template

    return KindInterpreter(
        get_replicas=get_replicas,
        interpret_health=health,
        aggregate_status=aggregate,
        reflect_status=lambda obj: {
            f: (obj.get("status") or {}).get(f) for f in _fields
        } if obj.get("status") else {},
    )


# ---------------------------------------------------------------------------
# Kyverno
# ---------------------------------------------------------------------------


def _kyverno_policy() -> KindInterpreter:
    """kyverno.io/v1 ClusterPolicy + Policy customizations.yaml (identical
    scripts for both kinds)."""

    def health(obj: Unstructured) -> str:
        st = obj.get("status") or {}
        if st.get("ready") is not None:
            return HEALTHY if st["ready"] else UNHEALTHY
        for cond in st.get("conditions") or []:
            if (
                cond.get("type") == "Ready"
                and cond.get("status") == "True"
                and cond.get("reason") == "Succeeded"
            ):
                return HEALTHY
        return UNHEALTHY

    def aggregate(template: Unstructured, items) -> Unstructured:
        if not items:
            return template
        status: dict = {"conditions": []}
        template.set("status", status)
        rulecount = {"validate": 0, "generate": 0, "mutate": 0, "verifyimages": 0}
        for st in _statuses(items):
            if st.get("autogen") is not None:
                status["autogen"] = st["autogen"]
            if st.get("ready") is not None:
                status["ready"] = st["ready"]
            rc = st.get("rulecount")
            if rc is not None:
                for k in rulecount:
                    rulecount[k] += rc.get(k) or 0
        status["rulecount"] = rulecount
        status["conditions"] = _merge_conditions(items)
        return template

    return KindInterpreter(
        interpret_health=health,
        aggregate_status=aggregate,
        reflect_status=_reflector(("ready", "conditions", "autogen", "rulecount")),
    )


# ---------------------------------------------------------------------------
# FluxCD
# ---------------------------------------------------------------------------


def _flux_aggregate(
    last_nonempty: Sequence[str] = (),
    last_any: Sequence[str] = (),
    guarded_sums: Sequence[str] = (),
    init: Optional[dict] = None,
):
    """The FluxCD statusAggregation shape: accumulators seed from the
    TEMPLATE's current status (so the values survive when no member reports
    them), revisions last-win skipping empties, conditions merge with
    cluster-prefixed messages, and the observed generation advances via the
    caught-up count. `guarded_sums` only accumulate when the template
    already carries the field (HelmRelease failures counters)."""

    def aggregate(template: Unstructured, items) -> Unstructured:
        status = template.get("status") or {}
        template.set("status", status)
        if not items:
            status["observedGeneration"] = template.metadata.generation or 0
            for k, v in (init or {}).items():
                status[k] = v() if callable(v) else v
            status["conditions"] = []
            return template
        og = _aggregate_observed_generation(template, items)
        for f in last_nonempty:
            status[f] = _last_wins(
                items, f, default=status.get(f), nonempty=True
            )
        for f in last_any:
            status[f] = _last_wins(items, f, default=status.get(f))
        for f in guarded_sums:
            if status.get(f) is not None:
                status[f] = status[f] + _sum_field(items, f)
        status["conditions"] = _merge_conditions(items)
        status["observedGeneration"] = og
        return template

    return aggregate


def _helm_release() -> KindInterpreter:
    """helm.toolkit.fluxcd.io/v2beta1 HelmRelease customizations.yaml."""

    def deps(obj: Unstructured) -> list[dict]:
        spec = obj.get("spec") or {}
        secrets: dict[str, bool] = {}
        sas: dict[str, bool] = {}
        cms: dict[str, bool] = {}
        for vf in spec.get("valuesFrom") or []:
            if vf.get("kind") == "Secret" and vf.get("name"):
                secrets[vf["name"]] = True
            if vf.get("kind") == "ConfigMap" and vf.get("name"):
                cms[vf["name"]] = True
        verify_ref = (
            ((spec.get("chart") or {}).get("spec") or {}).get("verify") or {}
        ).get("secretRef") or {}
        if verify_ref.get("name"):
            secrets[verify_ref["name"]] = True
        kc_ref = (spec.get("kubeConfig") or {}).get("secretRef") or {}
        if kc_ref.get("name"):
            secrets[kc_ref["name"]] = True
        sa = spec.get("serviceAccountName")
        if sa:
            sas[sa] = True
        return _refs(obj.namespace, Secret=secrets, ServiceAccount=sas,
                     ConfigMap=cms)

    return KindInterpreter(
        interpret_health=_ready_condition_health("ReconciliationSucceeded"),
        aggregate_status=_flux_aggregate(
            last_nonempty=(
                "lastAttemptedRevision", "lastAppliedRevision",
                "lastAttemptedValuesChecksum", "helmChart",
            ),
            last_any=("lastReleaseRevision",),
            guarded_sums=("failures", "upgradeFailures", "installFailures"),
            init={
                "lastAttemptedRevision": "", "lastAppliedRevision": "",
                "lastAttemptedValuesChecksum": "", "helmChart": "",
                "lastReleaseRevision": "", "failures": 0,
                "upgradeFailures": 0, "installFailures": 0,
            },
        ),
        retain=_retain_suspend,
        reflect_status=_reflector((
            "conditions", "observedGeneration", "lastAttemptedRevision",
            "lastAppliedRevision", "lastAttemptedValuesChecksum", "helmChart",
            "lastReleaseRevision", "failures", "upgradeFailures",
            "installFailures",
        )),
        get_dependencies=deps,
    )


def _kustomization() -> KindInterpreter:
    """kustomize.toolkit.fluxcd.io/v1 Kustomization customizations.yaml."""

    def deps(obj: Unstructured) -> list[dict]:
        spec = obj.get("spec") or {}
        secrets: dict[str, bool] = {}
        sas: dict[str, bool] = {}
        dec_ref = (spec.get("decryption") or {}).get("secretRef") or {}
        if dec_ref.get("name"):
            secrets[dec_ref["name"]] = True
        kc_ref = (spec.get("kubeConfig") or {}).get("secretRef") or {}
        if kc_ref.get("name"):
            secrets[kc_ref["name"]] = True
        sa = spec.get("serviceAccountName")
        if sa:
            sas[sa] = True
        return _refs(obj.namespace, Secret=secrets, ServiceAccount=sas)

    return KindInterpreter(
        interpret_health=_ready_condition_health("ReconciliationSucceeded"),
        aggregate_status=_flux_aggregate(
            last_nonempty=("lastAttemptedRevision", "lastAppliedRevision"),
            init={"lastAttemptedRevision": "", "lastAppliedRevision": ""},
        ),
        retain=_retain_suspend,
        reflect_status=_reflector((
            "conditions", "lastAppliedRevision", "lastAttemptedRevision",
            "observedGeneration",
        )),
        get_dependencies=deps,
    )


def _flux_source(
    reflect_fields: Sequence[str],
    health_reasons: Sequence[str] = ("Succeeded",),
    with_url: bool = False,
    secret_paths: Sequence[Sequence[str]] = (("secretRef",),),
):
    """The source.toolkit.fluxcd.io shape (GitRepository/Bucket/HelmChart/
    HelmRepository/OCIRepository): artifact last-wins, optional url,
    merged conditions, Ready-condition health, suspend retention, and
    secretRef-flavored dependencies."""

    def deps(obj: Unstructured) -> list[dict]:
        spec = obj.get("spec") or {}
        secrets: dict[str, bool] = {}
        for path in secret_paths:
            node = spec
            for p in path:
                node = (node or {}).get(p) or {}
            name = node.get("name")
            if name:
                secrets[name] = True
        return _refs(obj.namespace, Secret=secrets)

    init: dict = {"artifact": dict}
    last_nonempty: tuple = ()
    if with_url:
        init["url"] = ""
        last_nonempty = ("url",)

    return KindInterpreter(
        interpret_health=_ready_condition_health(*health_reasons),
        aggregate_status=_flux_aggregate(
            last_nonempty=last_nonempty,
            last_any=("artifact",),
            init=init,
        ),
        retain=_retain_suspend,
        reflect_status=_reflector(reflect_fields),
        get_dependencies=deps,
    )


def _helm_chart() -> KindInterpreter:
    """source.toolkit.fluxcd.io/v1beta2 HelmChart customizations.yaml —
    the source shape plus chart-name/source-revision scalars and the
    ChartPullSucceeded health reason."""
    ki = _flux_source(
        reflect_fields=(
            "artifact", "conditions", "observedChartName",
            "observedGeneration", "observedSourceArtifactRevision", "url",
        ),
        health_reasons=("Succeeded", "ChartPullSucceeded"),
        with_url=True,
        secret_paths=(("verify", "secretRef"),),
    )
    ki.aggregate_status = _flux_aggregate(
        last_nonempty=(
            "url", "observedChartName", "observedSourceArtifactRevision",
        ),
        last_any=("artifact",),
        init={
            "artifact": dict, "url": "", "observedChartName": "",
            "observedSourceArtifactRevision": "",
        },
    )
    return ki


# ---------------------------------------------------------------------------
# Argo Rollout (extra: not in the reference library, kept from round 2)
# ---------------------------------------------------------------------------


def _argo_rollout() -> KindInterpreter:
    get_replicas, revise = _spec_replicas_hooks()

    def health(obj: Unstructured) -> str:
        st = obj.get("status") or {}
        if st.get("phase") == "Healthy":
            return HEALTHY
        ready = st.get("readyReplicas") or 0
        want = obj.get("spec", "replicas", default=1) or 0
        return HEALTHY if ready >= want else UNHEALTHY

    return KindInterpreter(
        get_replicas=get_replicas,
        revise_replica=revise,
        interpret_health=health,
    )


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

THIRDPARTY_CUSTOMIZATIONS: dict[str, Callable[[], KindInterpreter]] = {
    "apps.kruise.io/v1alpha1/AdvancedCronJob": _advanced_cronjob,
    "apps.kruise.io/v1alpha1/BroadcastJob": _broadcast_job,
    "apps.kruise.io/v1alpha1/CloneSet": _cloneset,
    "apps.kruise.io/v1alpha1/DaemonSet": _kruise_daemonset,
    "apps.kruise.io/v1beta1/StatefulSet": _kruise_statefulset,
    "argoproj.io/v1alpha1/Workflow": _argo_workflow,
    "flink.apache.org/v1beta1/FlinkDeployment": _flink_deployment,
    "helm.toolkit.fluxcd.io/v2beta1/HelmRelease": _helm_release,
    "kustomize.toolkit.fluxcd.io/v1/Kustomization": _kustomization,
    "kyverno.io/v1/ClusterPolicy": _kyverno_policy,
    "kyverno.io/v1/Policy": _kyverno_policy,
    "source.toolkit.fluxcd.io/v1/GitRepository": lambda: _flux_source(
        reflect_fields=(
            "conditions", "artifact", "observedGeneration", "observedIgnore",
            "observedRecurseSubmodules",
        ),
        secret_paths=(("secretRef",), ("verify", "secretRef")),
    ),
    "source.toolkit.fluxcd.io/v1beta2/Bucket": lambda: _flux_source(
        reflect_fields=(
            "conditions", "artifact", "observedIgnore", "observedGeneration",
            "url",
        ),
        with_url=True,
    ),
    "source.toolkit.fluxcd.io/v1beta2/HelmChart": _helm_chart,
    "source.toolkit.fluxcd.io/v1beta2/HelmRepository": lambda: _flux_source(
        reflect_fields=(
            "artifact", "conditions", "observedGeneration", "url",
        ),
        with_url=True,
    ),
    "source.toolkit.fluxcd.io/v1beta2/OCIRepository": lambda: _flux_source(
        reflect_fields=(
            "artifact", "conditions", "url", "observedGeneration",
            "observedIgnore", "observedLayerSelector",
        ),
        with_url=True,
        secret_paths=(
            ("secretRef",), ("verify", "secretRef"), ("certSecretRef",),
        ),
    ),
    "argoproj.io/v1alpha1/Rollout": _argo_rollout,
}


def load_thirdparty_tier() -> dict[str, KindInterpreter]:
    return {gvk: build() for gvk, build in THIRDPARTY_CUSTOMIZATIONS.items()}

"""HTTPS interpreter webhooks: the I5 tier over a real socket.

The reference's customized-webhook interpreter POSTs a
`ResourceInterpreterContext` (pkg/apis/config/v1alpha1/
interpretercontext_types.go) to an HTTPS hook server and applies the
response's JSONPatch / rawStatus / healthy answer
(customized/webhook/customized.go:122,279-310); a runnable hook server
ships in examples/customresourceinterpreter. This module is both sides of
that contract for the TPU build:

- `InterpreterHookServer`: hosts any dict-level handler (the HookRegistry
  duck: get_replicas/revise_replica/retain/aggregate_status/reflect_status/
  interpret_health/get_dependencies) behind the wire protocol, over TLS
  with certs from auth/pki.py.
- `HttpHookClient`: the HookRegistry-compatible client — what the
  WebhookInterpreterManager binds when a
  ResourceInterpreterWebhookConfiguration names an https:// URL. Applies
  returned JSONPatches exactly like the reference's applyPatch.

Patches are RFC 6902 add/replace/remove, produced server-side by diffing
the handler's mutated object against the request object — so hook authors
write plain "return the new object" logic and the wire stays
reference-shaped.
"""
from __future__ import annotations

import json
import ssl
import tempfile
from typing import Any, Optional
from urllib.request import Request, urlopen

from ..server.httpbase import BackgroundHTTPServer, QuietHandler, read_json, send_json

API_VERSION = "config.karmada.io/v1alpha1"
KIND_CONTEXT = "ResourceInterpreterContext"


# -- RFC 6902 subset: diff + apply ------------------------------------------


def _escape(seg: str) -> str:
    return seg.replace("~", "~0").replace("/", "~1")


def _unescape(seg: str) -> str:
    return seg.replace("~1", "/").replace("~0", "~")


def json_patch_diff(old: Any, new: Any, path: str = "") -> list[dict]:
    """Minimal add/replace/remove patch turning `old` into `new`."""
    if type(old) is not type(new):
        return [{"op": "replace", "path": path or "/", "value": new}]
    if isinstance(old, dict):
        ops: list[dict] = []
        for k in old:
            p = f"{path}/{_escape(str(k))}"
            if k not in new:
                ops.append({"op": "remove", "path": p})
            else:
                ops.extend(json_patch_diff(old[k], new[k], p))
        for k in new:
            if k not in old:
                ops.append({"op": "add", "path": f"{path}/{_escape(str(k))}",
                            "value": new[k]})
        return ops
    if isinstance(old, list):
        if old != new:
            return [{"op": "replace", "path": path or "/", "value": new}]
        return []
    if old != new:
        return [{"op": "replace", "path": path or "/", "value": new}]
    return []


def json_patch_apply(obj: Any, patch: list[dict]) -> Any:
    """Apply an add/replace/remove patch (the subset the server emits and
    the reference's JSONPatch mode accepts)."""
    import copy

    out = copy.deepcopy(obj)
    for op in patch:
        path = op["path"]
        if path in ("", "/"):
            out = copy.deepcopy(op.get("value"))
            continue
        segs = [_unescape(s) for s in path.lstrip("/").split("/")]
        parent = out
        for s in segs[:-1]:
            parent = parent[int(s)] if isinstance(parent, list) else parent[s]
        last = segs[-1]
        kind = op["op"]
        if isinstance(parent, list):
            idx = len(parent) if last == "-" else int(last)
            if kind == "add":
                parent.insert(idx, op["value"])
            elif kind == "replace":
                parent[idx] = op["value"]
            elif kind == "remove":
                del parent[idx]
        else:
            if kind in ("add", "replace"):
                parent[last] = op["value"]
            elif kind == "remove":
                parent.pop(last, None)
    return out


# -- server -----------------------------------------------------------------


class InterpreterHookServer:
    """Runnable hook server (examples/customresourceinterpreter equivalent):
    wraps one dict-level handler behind the ResourceInterpreterContext wire,
    optionally TLS-terminated with an auth/pki.py-issued certificate."""

    def __init__(self, handler: Any, host: str = "127.0.0.1", port: int = 0,
                 pki=None, hostname: str = "localhost"):
        self.handler = handler
        self._pki = pki
        self._hostname = hostname
        ssl_ctx = None
        if pki is not None:
            cert = pki.sign(hostname, dns_names=(hostname, host))
            ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            with tempfile.NamedTemporaryFile(suffix=".pem") as cf, \
                    tempfile.NamedTemporaryFile(suffix=".pem") as kf:
                cf.write(cert.cert_pem)
                cf.flush()
                kf.write(cert.key_pem)
                kf.flush()
                ssl_ctx.load_cert_chain(cf.name, kf.name)
        self._server = BackgroundHTTPServer(host, port, ssl_context=ssl_ctx)

    def start(self) -> int:
        hook = self

        class Handler(QuietHandler):
            def do_POST(self):
                try:
                    ctx = read_json(self)
                    response = hook._serve(ctx.get("request") or {})
                except Exception as e:  # noqa: BLE001 - wire boundary
                    response = {
                        "uid": "", "successful": False,
                        "status": {"code": 500,
                                   "message": f"{type(e).__name__}: {e}"},
                    }
                send_json(self, 200, {
                    "apiVersion": API_VERSION, "kind": KIND_CONTEXT,
                    "response": response,
                })

        return self._server.bind(Handler, "interp-hook")

    @property
    def url(self) -> str:
        scheme = "https" if self._pki is not None else "http"
        host = self._hostname if self._pki else self._server.host
        return f"{scheme}://{host}:{self._server.port}/interpret"

    def stop(self) -> None:
        self._server.stop()

    # -- operation dispatch ----------------------------------------------

    def _serve(self, req: dict) -> dict:
        op = req.get("operation", "")
        uid = req.get("uid", "")
        obj = (req.get("object") or {})
        out: dict = {"uid": uid, "successful": True}
        h = self.handler
        if op == "InterpretReplica":
            n, requirements = h.get_replicas(obj)
            out["replicas"] = int(n)
            if requirements:
                out["replicaRequirements"] = requirements
        elif op == "ReviseReplica":
            new = h.revise_replica(obj, int(req.get("replicas") or 0))
            out["patch"] = json_patch_diff(obj, new)
            out["patchType"] = "JSONPatch"
        elif op == "Retain":
            # desired comes as `object`, member-observed as `observedObject`
            new = h.retain(obj, req.get("observedObject") or {})
            out["patch"] = json_patch_diff(obj, new)
            out["patchType"] = "JSONPatch"
        elif op == "AggregateStatus":
            new = h.aggregate_status(obj, req.get("aggregatedStatus") or [])
            out["patch"] = json_patch_diff(obj, new)
            out["patchType"] = "JSONPatch"
        elif op == "InterpretStatus":
            out["rawStatus"] = h.reflect_status(obj) or {}
        elif op == "InterpretHealth":
            out["healthy"] = bool(h.interpret_health(obj))
        elif op == "InterpretDependency":
            out["dependencies"] = list(h.get_dependencies(obj) or [])
        else:
            out["successful"] = False
            out["status"] = {"code": 400,
                             "message": f"unsupported operation {op!r}"}
        return out


# -- client -----------------------------------------------------------------


class HttpHookClient:
    """HookRegistry-compatible handler that crosses the socket: each duck
    method POSTs one ResourceInterpreterContext and decodes the response,
    applying JSONPatches the way customized.go's applyPatch does."""

    def __init__(self, url: str, ca_pem: Optional[bytes] = None,
                 timeout: float = 10.0):
        self.url = url
        self.timeout = timeout
        if url.startswith("https"):
            self._ssl = ssl.create_default_context()
            if ca_pem:
                self._ssl.load_verify_locations(cadata=ca_pem.decode())
        else:
            self._ssl = None

    def _call(self, operation: str, obj: dict, **extra) -> dict:
        req = {"uid": "hook", "operation": operation, "object": obj,
               "name": (obj.get("metadata") or {}).get("name", ""),
               "namespace": (obj.get("metadata") or {}).get("namespace", ""),
               **extra}
        body = json.dumps({
            "apiVersion": API_VERSION, "kind": KIND_CONTEXT, "request": req,
        }).encode()
        http_req = Request(self.url, data=body,
                           headers={"Content-Type": "application/json"})
        with urlopen(http_req, timeout=self.timeout, context=self._ssl) as r:
            ctx = json.loads(r.read().decode())
        resp = ctx.get("response") or {}
        if not resp.get("successful", False):
            msg = ((resp.get("status") or {}).get("message")
                   or "interpreter webhook failed")
            raise RuntimeError(f"{self.url}: {msg}")
        return resp

    def _patched(self, resp: dict, obj: dict) -> dict:
        patch = resp.get("patch")
        if not patch:
            return obj
        if resp.get("patchType") not in (None, "", "JSONPatch"):
            raise RuntimeError(
                f"patch type {resp.get('patchType')!r} is not supported"
            )
        return json_patch_apply(obj, patch)

    # the HookRegistry duck ----------------------------------------------

    def get_replicas(self, obj: dict):
        resp = self._call("InterpretReplica", obj)
        req = resp.get("replicaRequirements") or None
        return int(resp.get("replicas") or 0), (
            (req or {}).get("resourceRequest") if req else None
        )

    def revise_replica(self, obj: dict, replicas: int) -> dict:
        resp = self._call("ReviseReplica", obj, replicas=int(replicas))
        return self._patched(resp, obj)

    def retain(self, desired: dict, observed: dict) -> dict:
        resp = self._call("Retain", desired, observedObject=observed)
        return self._patched(resp, desired)

    def aggregate_status(self, obj: dict, items: list) -> dict:
        resp = self._call("AggregateStatus", obj, aggregatedStatus=items)
        return self._patched(resp, obj)

    def reflect_status(self, obj: dict):
        return self._call("InterpretStatus", obj).get("rawStatus")

    def interpret_health(self, obj: dict) -> bool:
        return bool(self._call("InterpretHealth", obj).get("healthy"))

    def get_dependencies(self, obj: dict) -> list:
        return list(self._call("InterpretDependency", obj).get("dependencies") or [])

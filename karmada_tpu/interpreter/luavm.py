"""Sandboxed Lua-subset interpreter for ResourceInterpreterCustomization.

The reference executes customization scripts as Lua in a gopher-lua sandbox
(pkg/resourceinterpreter/customized/declarative/luavm/lua.go:59-129) with a
`kube` helper library (kube.go: accuratePodRequirements, getPodDependencies,
getResourceQuantity). This module implements the Lua subset those scripts
use — enough that an existing Karmada user's Lua customizations (and the
reference's own shipped library) run unmodified:

  - functions, locals, assignment, multiple return values
  - if/elseif/else, while, numeric `for i = a, b [, step]`,
    generic `for k, v in pairs(t)`, break
  - tables (array + map duality, 1-based, `#` length, nil-assignment
    deletes), constructors `{}` / `{a = 1}` / `{x, y}`
  - operators: and/or/not, .. concat, == ~= < <= > >=, + - * / % ^,
    unary -, #
  - stdlib surface used by the scripts: tonumber, tostring, type, pairs,
    ipairs, string.format/len/sub/lower/upper/rep/byte/char/reverse plus
    find/match/gmatch/gsub with the Lua pattern language (classes, sets,
    quantifiers incl. lazy '-', anchors, captures, backrefs — %b/%f
    unsupported), math.ceil/floor/max/min/abs/huge, table.insert/remove,
    and `require("kube")`

No io/os/debug/load/metatables — the sandbox exposes ONLY the above, and
execution is step-bounded so a runaway script cannot hang the interpreter
(the reference relies on gopher-lua's context cancellation for the same).

Data mapping (lua.go ConvertLuaResultInto equivalents): Python dicts become
map-tables, lists become 1-based array-tables; on the way back a table whose
keys are exactly 1..n returns a list, an empty table returns {} (callers
normalize where the distinction matters, as the reference does by decoding
into typed structs).
"""
from __future__ import annotations

import math
import re
from typing import Any, Callable, Optional

from .interpreter import _parse_quantity


class LuaError(Exception):
    """Compile or runtime error in a Lua customization script."""


# ---------------------------------------------------------------------------
# lexer
# ---------------------------------------------------------------------------

_KEYWORDS = {
    "and", "break", "do", "else", "elseif", "end", "false", "for",
    "function", "if", "in", "local", "nil", "not", "or", "repeat",
    "return", "then", "true", "until", "while",
}

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>--\[(?P<_cl>=*)\[.*?\](?P=_cl)\]|--[^\n]*)
  | (?P<number>0[xX][0-9a-fA-F]+|\d+\.?\d*(?:[eE][+-]?\d+)?|\.\d+)
  | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<longstring>\[(?P<_ll>=*)\[(?P<_lsbody>.*?)\](?P=_ll)\])
  | (?P<string>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
  | (?P<op>\.\.\.|\.\.|==|~=|<=|>=|[-+*/%^#<>=(){}\[\];:,.])
    """,
    re.VERBOSE | re.DOTALL,
)

_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", "a": "\a", "b": "\b",
            "f": "\f", "v": "\v", "\\": "\\", '"': '"', "'": "'", "\n": "\n"}


def _unescape(s: str) -> str:
    out, i = [], 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            if nxt.isdigit():
                j = i + 1
                while j < len(s) and j < i + 4 and s[j].isdigit():
                    j += 1
                out.append(chr(int(s[i + 1:j])))
                i = j
                continue
            out.append(_ESCAPES.get(nxt, nxt))
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def tokenize(src: str) -> list[tuple[str, Any, int]]:
    """→ [(kind, value, line)]; kinds: name/keyword/number/string/op/eof."""
    toks: list[tuple[str, Any, int]] = []
    pos, line = 0, 1
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise LuaError(f"unexpected character {src[pos]!r} at line {line}")
        text = m.group(0)
        # the level-capture backrefs (_cl/_ll/_lsbody) shadow m.lastgroup,
        # so test the bracketed alternatives by group before dispatching
        if m.group("ws") is not None or m.group("comment") is not None:
            pass
        elif m.group("longstring") is not None:
            # Lua 5.1 long strings: no escapes; a leading end-of-line
            # sequence right after the opening bracket is dropped (the
            # lexer skips \r\n / \n\r / \r / \n alike)
            body = m.group("_lsbody")
            for eol in ("\r\n", "\n\r", "\r", "\n"):
                if body.startswith(eol):
                    body = body[len(eol):]
                    break
            toks.append(("string", body, line))
        elif m.lastgroup == "number":
            if text.lower().startswith("0x"):
                val: Any = int(text, 16)
            else:
                f = float(text)
                val = int(f) if f.is_integer() and "." not in text and "e" not in text.lower() else f
            toks.append(("number", val, line))
        elif m.lastgroup == "name":
            kind = "keyword" if text in _KEYWORDS else "name"
            toks.append((kind, text, line))
        elif m.lastgroup == "string":
            toks.append(("string", _unescape(text[1:-1]), line))
        else:
            toks.append(("op", text, line))
        line += text.count("\n")
        pos = m.end()
    toks.append(("eof", None, line))
    return toks


# ---------------------------------------------------------------------------
# parser → AST (tuples: (node_kind, ...))
# ---------------------------------------------------------------------------


class _Parser:
    def __init__(self, toks: list[tuple[str, Any, int]]):
        self.toks = toks
        self.i = 0

    # -- token helpers --
    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def check(self, kind: str, value=None) -> bool:
        k, v, _ = self.peek()
        return k == kind and (value is None or v == value)

    def accept(self, kind: str, value=None) -> bool:
        if self.check(kind, value):
            self.i += 1
            return True
        return False

    def expect(self, kind: str, value=None):
        k, v, ln = self.peek()
        if k != kind or (value is not None and v != value):
            want = value if value is not None else kind
            raise LuaError(f"line {ln}: expected {want!r}, got {v!r}")
        return self.next()

    # -- grammar --
    def parse_chunk(self):
        body = self.parse_block(("eof",))
        self.expect("eof")
        return ("block", body)

    def parse_block(self, stops: tuple[str, ...]):
        stmts = []
        while True:
            k, v, _ = self.peek()
            if k == "eof" or (k == "keyword" and v in stops):
                break
            if k == "keyword" and v in ("end", "else", "elseif", "until"):
                break
            stmts.append(self.parse_statement())
        return stmts

    def parse_statement(self):
        k, v, ln = self.peek()
        if k == "op" and v == ";":
            self.next()
            return ("nop",)
        if k == "keyword":
            if v == "local":
                return self.parse_local()
            if v == "if":
                return self.parse_if()
            if v == "while":
                return self.parse_while()
            if v == "for":
                return self.parse_for()
            if v == "function":
                return self.parse_function_stmt()
            if v == "return":
                self.next()
                exprs = []
                nk, nv, _ = self.peek()
                if not (nk == "eof" or (nk == "keyword" and nv in (
                        "end", "else", "elseif", "until"))):
                    exprs.append(self.parse_expr())
                    while self.accept("op", ","):
                        exprs.append(self.parse_expr())
                return ("return", exprs)
            if v == "break":
                self.next()
                return ("break",)
            if v == "do":
                self.next()
                body = self.parse_block(())
                self.expect("keyword", "end")
                return ("block_stmt", body)
            if v == "repeat":
                self.next()
                body = self.parse_block(("until",))
                self.expect("keyword", "until")
                cond = self.parse_expr()
                return ("repeat", body, cond)
        # expression statement: assignment or call
        expr = self.parse_prefix_expr()
        if self.check("op", "=") or self.check("op", ","):
            targets = [expr]
            while self.accept("op", ","):
                targets.append(self.parse_prefix_expr())
            self.expect("op", "=")
            values = [self.parse_expr()]
            while self.accept("op", ","):
                values.append(self.parse_expr())
            for t in targets:
                if t[0] not in ("name", "index"):
                    raise LuaError(f"line {ln}: cannot assign to {t[0]}")
            return ("assign", targets, values)
        if expr[0] != "call":
            raise LuaError(f"line {ln}: syntax error (unexpected expression)")
        return ("call_stmt", expr)

    def parse_local(self):
        self.expect("keyword", "local")
        if self.accept("keyword", "function"):
            _, name, _ = self.expect("name")
            func = self.parse_function_body()
            return ("local_function", name, func)
        names = [self.expect("name")[1]]
        while self.accept("op", ","):
            names.append(self.expect("name")[1])
        values = []
        if self.accept("op", "="):
            values.append(self.parse_expr())
            while self.accept("op", ","):
                values.append(self.parse_expr())
        return ("local", names, values)

    def parse_if(self):
        self.expect("keyword", "if")
        clauses = []
        cond = self.parse_expr()
        self.expect("keyword", "then")
        body = self.parse_block(())
        clauses.append((cond, body))
        else_body = []
        while True:
            if self.accept("keyword", "elseif"):
                c = self.parse_expr()
                self.expect("keyword", "then")
                b = self.parse_block(())
                clauses.append((c, b))
                continue
            if self.accept("keyword", "else"):
                else_body = self.parse_block(())
            self.expect("keyword", "end")
            break
        return ("if", clauses, else_body)

    def parse_while(self):
        self.expect("keyword", "while")
        cond = self.parse_expr()
        self.expect("keyword", "do")
        body = self.parse_block(())
        self.expect("keyword", "end")
        return ("while", cond, body)

    def parse_for(self):
        self.expect("keyword", "for")
        _, first, _ = self.expect("name")
        if self.accept("op", "="):  # numeric for
            start = self.parse_expr()
            self.expect("op", ",")
            stop = self.parse_expr()
            step = None
            if self.accept("op", ","):
                step = self.parse_expr()
            self.expect("keyword", "do")
            body = self.parse_block(())
            self.expect("keyword", "end")
            return ("for_num", first, start, stop, step, body)
        names = [first]
        while self.accept("op", ","):
            names.append(self.expect("name")[1])
        self.expect("keyword", "in")
        iters = [self.parse_expr()]
        while self.accept("op", ","):
            iters.append(self.parse_expr())
        self.expect("keyword", "do")
        body = self.parse_block(())
        self.expect("keyword", "end")
        return ("for_in", names, iters, body)

    def parse_function_stmt(self):
        self.expect("keyword", "function")
        _, name, _ = self.expect("name")
        target = ("name", name)
        while self.accept("op", "."):
            _, attr, _ = self.expect("name")
            target = ("index", target, ("const", attr))
        func = self.parse_function_body()
        return ("assign", [target], [func])

    def parse_function_body(self):
        self.expect("op", "(")
        params = []
        if not self.check("op", ")"):
            params.append(self.expect("name")[1])
            while self.accept("op", ","):
                params.append(self.expect("name")[1])
        self.expect("op", ")")
        body = self.parse_block(())
        self.expect("keyword", "end")
        return ("function", params, body)

    # -- expressions (precedence climbing) --

    _BINPREC = {
        "or": 1, "and": 2,
        "<": 3, ">": 3, "<=": 3, ">=": 3, "~=": 3, "==": 3,
        "..": 4,
        "+": 5, "-": 5,
        "*": 6, "/": 6, "%": 6,
        "^": 8,
    }

    def parse_expr(self, min_prec: int = 0):
        left = self.parse_unary()
        while True:
            k, v, _ = self.peek()
            op = v if (k == "op" or (k == "keyword" and v in ("and", "or"))) else None
            prec = self._BINPREC.get(op or "", 0)
            if prec == 0 or prec < min_prec:
                return left
            self.next()
            # right-assoc for .. and ^
            nxt = prec if op in ("..", "^") else prec + 1
            right = self.parse_expr(nxt)
            left = ("binop", op, left, right)

    def parse_unary(self):
        k, v, _ = self.peek()
        if (k == "keyword" and v == "not") or (k == "op" and v in ("-", "#")):
            self.next()
            operand = self.parse_unary()
            return ("unop", v, operand)
        return self.parse_power()

    def parse_power(self):
        base = self.parse_prefix_expr()
        if self.check("op", "^"):
            self.next()
            exp = self.parse_unary()
            return ("binop", "^", base, exp)
        return base

    def parse_prefix_expr(self):
        k, v, ln = self.peek()
        if k == "number" or k == "string":
            self.next()
            expr = ("const", v)
        elif k == "keyword" and v in ("nil", "true", "false"):
            self.next()
            expr = ("const", {"nil": None, "true": True, "false": False}[v])
        elif k == "keyword" and v == "function":
            self.next()
            expr = self.parse_function_body()
        elif k == "op" and v == "(":
            self.next()
            expr = ("paren", self.parse_expr())
            self.expect("op", ")")
        elif k == "op" and v == "{":
            expr = self.parse_table()
        elif k == "name":
            self.next()
            expr = ("name", v)
        else:
            raise LuaError(f"line {ln}: unexpected token {v!r}")
        # suffixes: .name  [expr]  (args)  'str'  {table}  :method(args)
        while True:
            if self.accept("op", "."):
                _, attr, _ = self.expect("name")
                expr = ("index", expr, ("const", attr))
            elif self.accept("op", "["):
                idx = self.parse_expr()
                self.expect("op", "]")
                expr = ("index", expr, idx)
            elif self.check("op", "("):
                self.next()
                args = []
                if not self.check("op", ")"):
                    args.append(self.parse_expr())
                    while self.accept("op", ","):
                        args.append(self.parse_expr())
                self.expect("op", ")")
                expr = ("call", expr, args)
            elif self.check("string"):
                _, s, _ = self.next()
                expr = ("call", expr, [("const", s)])
            elif self.check("op", ":"):
                self.next()
                _, meth, _ = self.expect("name")
                self.expect("op", "(")
                args = []
                if not self.check("op", ")"):
                    args.append(self.parse_expr())
                    while self.accept("op", ","):
                        args.append(self.parse_expr())
                self.expect("op", ")")
                expr = ("method_call", expr, meth, args)
            else:
                return expr

    def parse_table(self):
        self.expect("op", "{")
        array_items, hash_items = [], []
        while not self.check("op", "}"):
            k, v, _ = self.peek()
            if k == "name" and self.toks[self.i + 1][:2] == ("op", "="):
                self.next()
                self.next()
                hash_items.append((("const", v), self.parse_expr()))
            elif k == "op" and v == "[":
                self.next()
                key = self.parse_expr()
                self.expect("op", "]")
                self.expect("op", "=")
                hash_items.append((key, self.parse_expr()))
            else:
                array_items.append(self.parse_expr())
            if not (self.accept("op", ",") or self.accept("op", ";")):
                break
        self.expect("op", "}")
        return ("table", array_items, hash_items)


# ---------------------------------------------------------------------------
# runtime values
# ---------------------------------------------------------------------------


class LuaTable:
    """Array+map duality over one dict; integer keys stay integers."""

    __slots__ = ("data",)

    def __init__(self, data: Optional[dict] = None):
        self.data = data if data is not None else {}

    def get(self, key):
        return self.data.get(_normkey(key))

    def set(self, key, value):
        key = _normkey(key)
        if key is None:
            raise LuaError("table index is nil")
        if value is None:
            self.data.pop(key, None)
        else:
            self.data[key] = value

    def length(self) -> int:
        n = 0
        while (n + 1) in self.data:
            n += 1
        return n

    def __repr__(self):
        return f"LuaTable({self.data!r})"


def _normkey(key):
    if isinstance(key, float) and key.is_integer():
        return int(key)
    return key


def to_lua(value: Any) -> Any:
    """Python JSON-ish value → Lua value (lists become 1-based tables)."""
    if isinstance(value, dict):
        return LuaTable({k: to_lua(v) for k, v in value.items()})
    if isinstance(value, (list, tuple)):
        return LuaTable({i + 1: to_lua(v) for i, v in enumerate(value)})
    return value


def from_lua(value: Any) -> Any:
    """Lua value → Python. A table keyed exactly 1..n → list; else dict
    (empty table → {})."""
    if not isinstance(value, LuaTable):
        return value
    data = value.data
    n = len(data)
    if n and all(isinstance(k, int) for k in data):
        if set(data) == set(range(1, n + 1)):
            return [from_lua(data[i]) for i in range(1, n + 1)]
    return {str(k): from_lua(v) for k, v in data.items()}


class _LuaFunction:
    __slots__ = ("params", "body", "env", "vm")

    def __init__(self, params, body, env, vm):
        self.params = params
        self.body = body
        self.env = env
        self.vm = vm

    def __call__(self, *args):
        return self.vm.call(self, list(args))


class _Env:
    __slots__ = ("vars", "parent")

    def __init__(self, parent=None):
        self.vars: dict[str, Any] = {}
        self.parent = parent

    def lookup(self, name):
        env = self
        while env is not None:
            if name in env.vars:
                return env.vars[name]
            env = env.parent
        return None

    def assign(self, name, value):
        env = self
        while env is not None:
            if name in env.vars:
                env.vars[name] = value
                return
            env = env.parent
        # undeclared → global (outermost)
        env = self
        while env.parent is not None:
            env = env.parent
        env.vars[name] = value

    def declare(self, name, value):
        self.vars[name] = value


class _Break(Exception):
    pass


class _Return(Exception):
    def __init__(self, values):
        self.values = values


# ---------------------------------------------------------------------------
# evaluator
# ---------------------------------------------------------------------------

MAX_STEPS = 2_000_000  # statement+expression budget per top-level call


class LuaVM:
    """One sandboxed script: parse once, call its functions many times."""

    def __init__(self, source: str):
        try:
            self.ast = _Parser(tokenize(source)).parse_chunk()
        except LuaError:
            raise
        except RecursionError:
            raise LuaError("script nesting too deep")
        self.globals = _Env()
        for name, value in _stdlib().items():
            self.globals.declare(name, value)
        self._steps = 0
        # run the chunk body (defines functions, requires libraries)
        self._steps_reset()
        try:
            self.exec_block(self.ast[1], self.globals)
        except RecursionError:
            raise LuaError("script recursion too deep")

    # -- public --

    def function(self, name: str) -> Callable:
        fn = self.globals.lookup(name)
        if not isinstance(fn, _LuaFunction):
            raise LuaError(f"script does not define function {name!r}")

        def invoke(*py_args):
            self._steps_reset()
            try:
                out = self.call(fn, [to_lua(a) for a in py_args])
            except RecursionError:
                # Python's stack limit trips before MAX_STEPS on deep
                # recursion — keep it a script error, not a host crash
                raise LuaError("script recursion too deep")
            return [from_lua(v) for v in out]

        return invoke

    # -- internals --

    def _steps_reset(self):
        self._steps = 0

    def _tick(self):
        self._steps += 1
        if self._steps > MAX_STEPS:
            raise LuaError("script exceeded execution budget")

    def call(self, fn: _LuaFunction, args: list):
        env = _Env(parent=fn.env)
        for i, p in enumerate(fn.params):
            env.declare(p, args[i] if i < len(args) else None)
        try:
            self.exec_block(fn.body, env)
        except _Return as r:
            return r.values
        return []

    def exec_block(self, stmts, env):
        for st in stmts:
            self._tick()
            self.exec_stmt(st, env)

    def exec_stmt(self, st, env):
        kind = st[0]
        if kind == "nop":
            return
        if kind == "local":
            _, names, value_exprs = st
            values = self._eval_list(value_exprs, env, want=len(names))
            for n, v in zip(names, values):
                env.declare(n, v)
            return
        if kind == "local_function":
            _, name, func_ast = st
            env.declare(name, None)
            env.vars[name] = _LuaFunction(func_ast[1], func_ast[2], env, self)
            return
        if kind == "assign":
            _, targets, value_exprs = st
            values = self._eval_list(value_exprs, env, want=len(targets))
            for t, v in zip(targets, values):
                if t[0] == "name":
                    env.assign(t[1], v)
                else:  # index
                    obj = self.eval(t[1], env)
                    if not isinstance(obj, LuaTable):
                        raise LuaError(
                            f"attempt to index a {_typename(obj)} value"
                        )
                    obj.set(self.eval(t[2], env), v)
            return
        if kind == "call_stmt":
            self.eval(st[1], env)
            return
        if kind == "if":
            _, clauses, else_body = st
            for cond, body in clauses:
                if _truthy(self.eval(cond, env)):
                    self.exec_block(body, _Env(env))
                    return
            self.exec_block(else_body, _Env(env))
            return
        if kind == "while":
            _, cond, body = st
            while _truthy(self.eval(cond, env)):
                self._tick()
                try:
                    self.exec_block(body, _Env(env))
                except _Break:
                    break
            return
        if kind == "repeat":
            _, body, cond = st
            while True:
                self._tick()
                scope = _Env(env)
                try:
                    self.exec_block(body, scope)
                except _Break:
                    break
                if _truthy(self.eval(cond, scope)):
                    break
            return
        if kind == "for_num":
            _, var, start_e, stop_e, step_e, body = st
            start = _tonum(self.eval(start_e, env), "for start")
            stop = _tonum(self.eval(stop_e, env), "for stop")
            step = _tonum(self.eval(step_e, env), "for step") if step_e else 1
            if step == 0:
                raise LuaError("for step is zero")
            i = start
            while (step > 0 and i <= stop) or (step < 0 and i >= stop):
                self._tick()
                scope = _Env(env)
                scope.declare(var, i)
                try:
                    self.exec_block(body, scope)
                except _Break:
                    break
                i += step
            return
        if kind == "for_in":
            _, names, iter_exprs, body = st
            iterator = self.eval(iter_exprs[0], env)
            if not hasattr(iterator, "__iter__"):
                raise LuaError("for-in expects an iterator (use pairs/ipairs)")
            for pair in iterator:
                self._tick()
                scope = _Env(env)
                vals = list(pair) if isinstance(pair, tuple) else [pair]
                for j, n in enumerate(names):
                    scope.declare(n, vals[j] if j < len(vals) else None)
                try:
                    self.exec_block(body, scope)
                except _Break:
                    break
            return
        if kind == "return":
            values = self._eval_list(st[1], env, want=None)
            raise _Return(values)
        if kind == "break":
            raise _Break()
        if kind == "block_stmt":
            self.exec_block(st[1], _Env(env))
            return
        raise LuaError(f"unknown statement {kind}")

    def _eval_list(self, exprs, env, want: Optional[int]):
        """Evaluate an expression list with Lua multi-value semantics: the
        LAST expression expands its multiple returns, earlier ones truncate
        to one value."""
        values: list = []
        for i, e in enumerate(exprs):
            v = self.eval(e, env, multi=(i == len(exprs) - 1))
            if isinstance(v, _Multi):
                values.extend(v.values if i == len(exprs) - 1 else v.values[:1])
            else:
                values.append(v)
        if want is not None:
            while len(values) < want:
                values.append(None)
        return values

    def eval(self, expr, env, multi: bool = False):
        self._tick()
        kind = expr[0]
        if kind == "const":
            return expr[1]
        if kind == "name":
            return env.lookup(expr[1])
        if kind == "paren":
            v = self.eval(expr[1], env)
            return v.values[0] if isinstance(v, _Multi) and v.values else (
                None if isinstance(v, _Multi) else v
            )
        if kind == "index":
            obj = self.eval(expr[1], env)
            key = self.eval(expr[2], env)
            if isinstance(obj, LuaTable):
                return obj.get(key)
            if isinstance(obj, dict):  # host library (kube/math/…)
                return obj.get(key)
            if obj is None:
                raise LuaError(
                    f"attempt to index a nil value ({_describe(expr[1])})"
                )
            if isinstance(obj, str):
                raise LuaError("attempt to index a string value")
            raise LuaError(f"attempt to index a {_typename(obj)} value")
        if kind == "call":
            fn = self.eval(expr[1], env)
            args = self._eval_list(expr[2], env, want=None)
            return self._invoke(fn, args, expr[1], multi)
        if kind == "method_call":
            obj = self.eval(expr[1], env)
            if isinstance(obj, str):
                lib = _STRING_METHODS.get(expr[2])
                if lib is None:
                    raise LuaError(f"unknown string method {expr[2]!r}")
                args = [obj] + self._eval_list(expr[3], env, want=None)
                return self._invoke(lib, args, expr, multi)
            raise LuaError("method calls are only supported on strings")
        if kind == "function":
            return _LuaFunction(expr[1], expr[2], env, self)
        if kind == "table":
            _, array_items, hash_items = expr
            t = LuaTable()
            idx = 1
            for i, e in enumerate(array_items):
                v = self.eval(e, env, multi=(i == len(array_items) - 1))
                if isinstance(v, _Multi):
                    for mv in v.values:
                        t.set(idx, mv)
                        idx += 1
                else:
                    t.set(idx, v)
                    idx += 1
            for key_e, val_e in hash_items:
                t.set(self.eval(key_e, env), self.eval(val_e, env))
            return t
        if kind == "binop":
            return self._binop(expr, env)
        if kind == "unop":
            op = expr[1]
            v = self.eval(expr[2], env)
            if op == "not":
                return not _truthy(v)
            if op == "-":
                return -_tonum(v, "unary minus")
            if op == "#":
                if isinstance(v, LuaTable):
                    return v.length()
                if isinstance(v, str):
                    return len(v)
                raise LuaError(f"attempt to get length of a {_typename(v)} value")
        raise LuaError(f"unknown expression {kind}")

    def _invoke(self, fn, args, fn_expr, multi: bool):
        if isinstance(fn, _LuaFunction):
            out = self.call(fn, args)
            if multi:
                return _Multi(out)
            return out[0] if out else None
        if callable(fn):
            out = _host_call(fn, *args)
            if isinstance(out, tuple):
                return _Multi(list(out)) if multi else (
                    out[0] if out else None
                )
            return out
        raise LuaError(f"attempt to call a {_typename(fn)} value "
                       f"({_describe(fn_expr)})")

    def _binop(self, expr, env):
        op = expr[1]
        if op == "and":
            left = self.eval(expr[2], env)
            return self.eval(expr[3], env) if _truthy(left) else left
        if op == "or":
            left = self.eval(expr[2], env)
            return left if _truthy(left) else self.eval(expr[3], env)
        a = self.eval(expr[2], env)
        b = self.eval(expr[3], env)
        if op == "==":
            return _lua_eq(a, b)
        if op == "~=":
            return not _lua_eq(a, b)
        if op == "..":
            return _tostr_concat(a) + _tostr_concat(b)
        if op in ("<", "<=", ">", ">="):
            if isinstance(a, str) and isinstance(b, str):
                pass
            elif isinstance(a, (int, float)) and isinstance(b, (int, float)) \
                    and not isinstance(a, bool) and not isinstance(b, bool):
                pass
            else:
                raise LuaError(
                    f"attempt to compare {_typename(a)} with {_typename(b)}"
                )
            return {"<": a < b, "<=": a <= b, ">": a > b, ">=": a >= b}[op]
        x = _tonum(a, f"arithmetic on {_typename(a)}")
        y = _tonum(b, f"arithmetic on {_typename(b)}")
        if op == "+":
            return x + y
        if op == "-":
            return x - y
        if op == "*":
            return x * y
        if op == "/":
            if y == 0:  # Lua float division: 1/0 == inf, 0/0 == nan
                return math.nan if x == 0 else math.copysign(math.inf, x)
            return x / y
        if op == "%":
            if y == 0:
                return math.nan
            return x - math.floor(x / y) * y
        if op == "^":
            return float(x) ** float(y)
        raise LuaError(f"unknown operator {op}")


class _Multi:
    __slots__ = ("values",)

    def __init__(self, values):
        self.values = values


def _truthy(v) -> bool:
    return v is not None and v is not False


def _host_call(fn, *args):
    """Invoke a host (stdlib/kube) function keeping the sandbox's error
    contract: any Python-level failure surfaces as a catchable LuaError,
    never a raw host exception."""
    try:
        return fn(*args)
    except LuaError:
        raise
    except (ValueError, TypeError, OverflowError, OSError, IndexError,
            KeyError, ZeroDivisionError, ArithmeticError) as e:
        raise LuaError(f"{type(e).__name__}: {e}")


def _typename(v) -> str:
    if v is None:
        return "nil"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, (int, float)):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, LuaTable):
        return "table"
    return "function" if callable(v) else type(v).__name__


def _describe(expr) -> str:
    if expr[0] == "name":
        return expr[1]
    if expr[0] == "index" and expr[2][0] == "const":
        return f"field {expr[2][1]!r}"
    return expr[0]


def _lua_eq(a, b) -> bool:
    if isinstance(a, bool) or isinstance(b, bool):
        return a is b
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return a == b
    if type(a) is not type(b):
        return False
    if isinstance(a, LuaTable):
        return a is b
    return a == b


def _tonum(v, what: str):
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return v
    if isinstance(v, str):
        n = _lua_tonumber(v)
        if n is not None:
            return n
    raise LuaError(f"attempt to perform {what}")


def _tostr_concat(v) -> str:
    if isinstance(v, str):
        return v
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return _numstr(v)
    raise LuaError(f"attempt to concatenate a {_typename(v)} value")


def _numstr(v) -> str:
    if isinstance(v, int):
        return str(v)
    # gopher-lua (Lua 5.1) formats numbers with %.14g: tostring(4/2) is
    # "2", not Python's "2.0" (LUAI_NUMFFORMAT semantics)
    return "%.14g" % float(v)


def _lua_tonumber(v, base=None):
    if base is not None:
        try:
            return int(str(v), int(base))
        except (TypeError, ValueError):
            return None
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return v
    if isinstance(v, str):
        s = v.strip()
        try:
            if s.lower().startswith("0x"):
                return int(s, 16)
            f = float(s)
            return int(f) if f.is_integer() and "." not in s and "e" not in s.lower() else f
        except ValueError:
            return None
    return None


# ---------------------------------------------------------------------------
# stdlib + kube library (kube.go)
# ---------------------------------------------------------------------------


def _lua_tostring(v) -> str:
    if v is None:
        return "nil"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return _numstr(v)
    if isinstance(v, str):
        return v
    return _typename(v)


def _pairs(t):
    if not isinstance(t, LuaTable):
        raise LuaError(f"bad argument to 'pairs' ({_typename(t)})")
    return iter([(k, v) for k, v in t.data.items()])


def _ipairs(t):
    if not isinstance(t, LuaTable):
        raise LuaError(f"bad argument to 'ipairs' ({_typename(t)})")
    out = []
    i = 1
    while i in t.data:
        out.append((i, t.data[i]))
        i += 1
    return iter(out)


def _table_insert(t, *args):
    if not isinstance(t, LuaTable):
        raise LuaError("bad argument to 'table.insert'")
    if len(args) == 1:
        t.set(t.length() + 1, args[0])
    else:
        pos, v = int(args[0]), args[1]
        n = t.length()
        for i in range(n, pos - 1, -1):
            t.set(i + 1, t.get(i))
        t.set(pos, v)


def _table_remove(t, pos=None):
    if not isinstance(t, LuaTable):
        raise LuaError("bad argument to 'table.remove'")
    n = t.length()
    if n == 0:
        return None
    p = int(pos) if pos is not None else n
    v = t.get(p)
    for i in range(p, n):
        t.set(i, t.get(i + 1))
    t.set(n, None)
    return v


def _string_format(fmt, *args):
    # Lua %s/%d/%f/%g/%x + %% are what scripts use; map to printf-style
    try:
        return fmt % args
    except (TypeError, ValueError) as e:
        raise LuaError(f"string.format: {e}")


def _string_sub(s, i, j=-1):
    n = len(s)
    i, j = int(i), int(j)
    if i < 0:
        i = max(n + i + 1, 1)
    elif i == 0:
        i = 1
    if j < 0:
        j = n + j + 1
    elif j > n:
        j = n
    if i > j:
        return ""
    return s[i - 1:j]


# -- Lua patterns (string.find/match/gmatch/gsub) ---------------------------
# A backtracking matcher for the Lua 5.x pattern language subset user
# customizations use: literals, ., %a %d %l %s %u %w %x %p %c (and
# complements), %<punct> escapes, [set] with ranges and ^ negation,
# quantifiers * + - ?, anchors ^ $, captures () and %1-%9 backrefs.
# %b/%f are not supported (LuaError). Indices are 1-based like Lua.


def _cls_match(ch: str, cl: str) -> bool:
    if cl.isalpha():
        base = {
            "a": ch.isalpha(), "c": ord(ch) < 32, "d": ch.isdigit(),
            "l": ch.islower(), "p": (not ch.isalnum()) and ch.isprintable()
            and not ch.isspace(),
            "s": ch.isspace(), "u": ch.isupper(), "w": ch.isalnum(),
            "x": ch in "0123456789abcdefABCDEF",
        }.get(cl.lower())
        if base is None:
            return ch == cl
        return base if cl.islower() else not base
    return ch == cl


class _LuaPattern:
    def __init__(self, pat: str):
        self.pat = pat
        i = 0
        while i < len(pat):  # escape-aware: '%%b' is a literal, '%b' is not
            if pat[i] == "%":
                if i + 1 < len(pat) and pat[i + 1] in "bf":
                    raise LuaError("unsupported pattern item (%b/%f)")
                i += 2
            else:
                i += 1
        self.anchored = pat.startswith("^")
        self.items, self.caps = self._parse(pat[1:] if self.anchored else pat)

    def _parse(self, p: str):
        items = []  # (kind, data, quant) kind: lit/any/cls/set/cap_open/cap_close/backref/end
        caps = 0
        i = 0
        while i < len(p):
            c = p[i]
            if c == "(":
                items.append(("cap_open", None, ""))
                caps += 1
                i += 1
                continue
            if c == ")":
                items.append(("cap_close", None, ""))
                i += 1
                continue
            if c == "$" and i == len(p) - 1:
                items.append(("end", None, ""))
                i += 1
                continue
            if c == "%":
                if i + 1 >= len(p):
                    raise LuaError("malformed pattern (ends with %)")
                nxt = p[i + 1]
                if nxt.isdigit():
                    if nxt == "0":
                        raise LuaError("invalid capture index %0 in pattern")
                    items.append(("backref", int(nxt), ""))
                    i += 2
                    continue
                unit = ("cls", nxt)
                i += 2
            elif c == "[":
                j = i + 1
                neg = j < len(p) and p[j] == "^"
                if neg:
                    j += 1
                entries = []
                first = True
                while j < len(p) and (p[j] != "]" or first):
                    first = False
                    if p[j] == "%" and j + 1 < len(p):
                        entries.append(("cls", p[j + 1]))
                        j += 2
                    elif j + 2 < len(p) and p[j + 1] == "-" and p[j + 2] != "]":
                        entries.append(("range", (p[j], p[j + 2])))
                        j += 3
                    else:
                        entries.append(("lit", p[j]))
                        j += 1
                if j >= len(p):
                    raise LuaError("malformed pattern (missing ']')")
                unit = ("set", (neg, entries))
                i = j + 1
            elif c == ".":
                unit = ("any", None)
                i += 1
            else:
                unit = ("lit", c)
                i += 1
            quant = ""
            if i < len(p) and p[i] in "*+-?":
                quant = p[i]
                i += 1
            items.append((unit[0], unit[1], quant))
        return items, caps

    def _single(self, s: str, pos: int, kind, data) -> bool:
        if pos >= len(s):
            return False
        ch = s[pos]
        if kind == "any":
            return True
        if kind == "lit":
            return ch == data
        if kind == "cls":
            return _cls_match(ch, data)
        if kind == "set":
            neg, entries = data
            hit = False
            for ek, ev in entries:
                if ek == "lit" and ch == ev:
                    hit = True
                elif ek == "cls" and _cls_match(ch, ev):
                    hit = True
                elif ek == "range" and ev[0] <= ch <= ev[1]:
                    hit = True
            return hit != neg
        return False

    MAX_STEPS = 200_000  # backtracking bound: patterns are user input

    def match_at(self, s: str, start: int, budget: Optional[list] = None):
        """Try to match at `start`; returns (end, captures) or None.
        captures: list of (cap_start, cap_end) 0-based half-open."""
        caps: list = []
        if budget is None:
            budget = [self.MAX_STEPS]

        def bt(ii: int, pos: int):
            budget[0] -= 1
            if budget[0] <= 0:
                raise LuaError("pattern too complex (backtracking budget)")
            while ii < len(self.items):
                kind, data, quant = self.items[ii]
                if kind == "cap_open":
                    caps.append([pos, None])
                    out = bt(ii + 1, pos)
                    if out is None:
                        caps.pop()  # clean the branch's capture on backtrack
                    return out
                if kind == "cap_close":
                    for c in reversed(caps):
                        if c[1] is None:
                            c[1] = pos
                            out = bt(ii + 1, pos)
                            if out is None:
                                c[1] = None
                            return out
                    raise LuaError("invalid pattern capture")
                if kind == "end":
                    return pos if pos == len(s) else None
                if kind == "backref":
                    idx = data - 1
                    if idx >= len(caps) or caps[idx][1] is None:
                        raise LuaError(f"invalid capture index %{data}")
                    text = s[caps[idx][0]:caps[idx][1]]
                    if s.startswith(text, pos):
                        pos += len(text)
                        ii += 1
                        continue
                    return None
                if quant == "":
                    if self._single(s, pos, kind, data):
                        pos += 1
                        ii += 1
                        continue
                    return None
                if quant == "?":
                    if self._single(s, pos, kind, data):
                        out = bt(ii + 1, pos + 1)
                        if out is not None:
                            return out
                    ii += 1
                    continue
                if quant in "*+":
                    count = 0
                    while self._single(s, pos + count, kind, data):
                        count += 1
                    lo = 1 if quant == "+" else 0
                    for take in range(count, lo - 1, -1):
                        out = bt(ii + 1, pos + take)
                        if out is not None:
                            return out
                    return None
                if quant == "-":
                    take = 0
                    while True:
                        out = bt(ii + 1, pos + take)
                        if out is not None:
                            return out
                        if not self._single(s, pos + take, kind, data):
                            return None
                        take += 1
                raise LuaError(f"unknown quantifier {quant!r}")
            return pos

        end = bt(0, start)
        if end is None:
            return None
        if any(c[1] is None for c in caps):
            raise LuaError("unfinished capture")
        return end, [(c[0], c[1]) for c in caps]

    def search(self, s: str, init: int = 0):
        """First match at or after init: (start, end, captures) or None."""
        stops = [init] if self.anchored else range(init, len(s) + 1)
        budget = [self.MAX_STEPS]
        for start in stops:
            out = self.match_at(s, start, budget)
            if out is not None:
                return start, out[0], out[1]
        return None


def _capture_values(s: str, start: int, end: int, caps):
    if not caps:
        return [s[start:end]]
    return [s[a:b] for a, b in caps]


def _string_find(s, pat, init=1, plain=None):
    init = int(init)
    if init < 0:
        init = max(len(s) + init, 0)
    elif init > 0:
        init -= 1
    if _truthy(plain):
        idx = s.find(pat, init)
        if idx < 0:
            return None
        return (idx + 1, idx + len(pat))
    m = _LuaPattern(pat).search(s, init)
    if m is None:
        return None
    start, end, caps = m
    if caps:
        return tuple([start + 1, end] + _capture_values(s, start, end, caps))
    return (start + 1, end)


def _string_match(s, pat, init=1):
    init = int(init)
    init = max(len(s) + init, 0) if init < 0 else max(init - 1, 0)
    m = _LuaPattern(pat).search(s, init)
    if m is None:
        return None
    start, end, caps = m
    vals = _capture_values(s, start, end, caps)
    return tuple(vals) if len(vals) > 1 else vals[0]


def _string_gmatch(s, pat):
    # PUC Lua: gmatch does not honor '^' as an anchor (it would defeat the
    # iteration); the caret is matched as a literal character instead
    if pat.startswith("^"):
        pat = "%^" + pat[1:]
    compiled = _LuaPattern(pat)

    def gen():
        pos = 0
        while pos <= len(s):
            m = compiled.search(s, pos)
            if m is None:
                return
            start, end, caps = m
            vals = _capture_values(s, start, end, caps)
            yield tuple(vals) if len(vals) > 1 else vals[0]
            pos = end + 1 if end == start else end

    return iter(gen())


def _string_gsub(s, pat, repl, n=None):
    compiled = _LuaPattern(pat)
    limit = int(n) if n is not None else -1
    out = []
    pos = 0
    count = 0
    while pos <= len(s) and (limit < 0 or count < limit):
        if compiled.anchored and pos > 0:
            break  # a ^-anchored pattern only ever applies at the start
        m = compiled.search(s, pos)
        if m is None:
            break
        start, end, caps = m
        out.append(s[pos:start])
        vals = _capture_values(s, start, end, caps)
        whole = s[start:end]
        if isinstance(repl, str):
            rep = []
            i = 0
            while i < len(repl):
                if repl[i] == "%" and i + 1 < len(repl):
                    d = repl[i + 1]
                    if d == "0":
                        rep.append(whole)
                    elif d.isdigit():
                        k = int(d) - 1
                        if k >= len(vals):
                            raise LuaError(f"invalid capture index %{d}")
                        rep.append(vals[k])
                    else:
                        rep.append(d)
                    i += 2
                else:
                    rep.append(repl[i])
                    i += 1
            out.append("".join(rep))
        elif isinstance(repl, LuaTable):
            v = repl.get(vals[0])
            out.append(_tostr_concat(v) if v is not None and v is not False else whole)
        elif callable(repl):
            v = repl(*vals)
            if isinstance(v, (tuple, list)):  # _LuaFunction returns a list
                v = v[0] if v else None
            if isinstance(v, _Multi):
                v = v.values[0] if v.values else None
            out.append(_tostr_concat(v) if v is not None and v is not False else whole)
        else:
            raise LuaError("bad gsub replacement type")
        count += 1
        if end == start:
            if start < len(s):
                out.append(s[start])
            pos = start + 1
        else:
            pos = end
    out.append(s[pos:])
    return ("".join(out), count)


def _string_byte(s, i=1):
    i = int(i)
    idx = i - 1 if i > 0 else len(s) + i  # Lua: negative counts from the end
    if 0 <= idx < len(s):
        return ord(s[idx])
    return None


_MAX_STRING_LEN = 10_000_000  # rep amplification cap (sandbox memory bound)


def _string_rep(s, n, sep=None):
    n = int(n)
    if n <= 0:
        return ""
    total = len(s) * n + (len(str(sep)) * (n - 1) if sep else 0)
    if total > _MAX_STRING_LEN:
        raise LuaError("resulting string too large")
    return (str(sep) if sep is not None else "").join([s] * n) if sep else s * n


_STRING_METHODS = {
    "format": _string_format,
    "sub": _string_sub,
    "len": lambda s: len(s),
    "lower": lambda s: s.lower(),
    "upper": lambda s: s.upper(),
    "find": _string_find,
    "match": _string_match,
    "gmatch": _string_gmatch,
    "gsub": _string_gsub,
    "rep": _string_rep,
    "byte": _string_byte,
    "char": lambda *a: "".join(chr(int(x)) for x in a),
    "reverse": lambda s: s[::-1],
}


def _kube_accurate_pod_requirements(pod_template):
    """kube.accuratePodRequirements(podTemplateSpec) → the full
    ReplicaRequirements table (kube.go:78-102): resourceRequest summed over
    containers, nodeClaim from nodeSelector/tolerations(/affinity), plus
    namespace/priorityClassName when present."""
    tpl = from_lua(pod_template) or {}
    spec = tpl.get("spec") or {}
    request: dict = {}
    for c in spec.get("containers") or []:
        for k, v in (c.get("resources", {}).get("requests") or {}).items():
            request[k] = request.get(k, 0.0) + _parse_quantity(v)
    out: dict = {"resourceRequest": request}
    node_claim: dict = {}
    if spec.get("nodeSelector"):
        node_claim["nodeSelector"] = spec["nodeSelector"]
    if spec.get("tolerations"):
        node_claim["tolerations"] = spec["tolerations"]
    if spec.get("affinity"):
        node_claim["hardNodeAffinity"] = spec["affinity"]
    if node_claim:
        out["nodeClaim"] = node_claim
    if spec.get("priorityClassName"):
        out["priorityClassName"] = spec["priorityClassName"]
    return to_lua(out)


def _kube_get_pod_dependencies(pod_template, namespace):
    from .thirdparty import _pod_spec_dependencies

    tpl = from_lua(pod_template) or {}
    ns = namespace if isinstance(namespace, str) and namespace else "default"
    deps = _pod_spec_dependencies(tpl.get("spec") or {}, ns)
    return to_lua(deps)


def _kube_get_resource_quantity(q):
    """kube.getResourceQuantity (kube.go:134-155)."""
    if q is None:
        return 0.0
    try:
        return float(_parse_quantity(q))
    except (ValueError, TypeError) as e:
        raise LuaError(f"getResourceQuantity: {e}")


_KUBE_LIB = {
    "accuratePodRequirements": _kube_accurate_pod_requirements,
    "getPodDependencies": _kube_get_pod_dependencies,
    "getResourceQuantity": _kube_get_resource_quantity,
}


def _require(name):
    if name == "kube":
        return dict(_KUBE_LIB)
    raise LuaError(f"module {name!r} is not available in the sandbox")


def _lua_error(msg=None, level=None):
    raise LuaError(_lua_tostring(msg) if msg is not None else "error")


def _lua_assert(v, msg=None):
    if not _truthy(v):
        raise LuaError(_lua_tostring(msg) if msg is not None else
                       "assertion failed!")
    return v


def _lua_pcall(fn, *args):
    try:
        if isinstance(fn, _LuaFunction):
            out = fn(*args)  # list of return values
            return tuple([True] + list(out))
        if callable(fn):
            out = _host_call(fn, *args)
            if isinstance(out, tuple):
                return tuple([True] + list(out))
            return (True,) if out is None else (True, out)
        raise LuaError(f"attempt to call a {_typename(fn)} value")
    except LuaError as e:
        return (False, str(e))


def _table_concat(t, sep="", i=1, j=None):
    if not isinstance(t, LuaTable):
        raise LuaError("bad argument to 'table.concat'")
    j = t.length() if j is None else int(j)
    parts = []
    for k in range(int(i), j + 1):
        v = t.get(k)
        if v is None:
            raise LuaError(f"invalid value (at index {k}) in table for 'concat'")
        parts.append(_tostr_concat(v))
    return (sep or "").join(parts)


def _lua_lt(a, b) -> bool:
    if isinstance(a, str) and isinstance(b, str):
        return a < b
    if (isinstance(a, (int, float)) and isinstance(b, (int, float))
            and not isinstance(a, bool) and not isinstance(b, bool)):
        return a < b
    raise LuaError(f"attempt to compare {_typename(a)} with {_typename(b)}")


def _table_sort(t, comp=None):
    import functools

    if not isinstance(t, LuaTable):
        raise LuaError("bad argument to 'table.sort'")
    n = t.length()
    vals = [t.get(k) for k in range(1, n + 1)]
    if comp is None:
        less = _lua_lt
    else:
        def less(a, b) -> bool:
            out = comp(a, b)
            if isinstance(out, (list, tuple)):
                out = out[0] if out else None
            return _truthy(out)

    vals.sort(key=functools.cmp_to_key(
        lambda a, b: -1 if less(a, b) else (1 if less(b, a) else 0)
    ))
    for k, v in enumerate(vals, start=1):
        t.set(k, v)


def _os_time(spec=None):
    # safe os.time (lifted/lua/oslib_safe.go): epoch seconds, or the epoch
    # of a {year, month, day[, hour, min, sec]} table (noon default hour)
    import time as _t

    if spec is None:
        return int(_t.time())
    if not isinstance(spec, LuaTable):
        raise LuaError("bad argument to 'os.time'")

    def g(key, default):
        v = spec.get(key)
        return int(v) if v is not None else default

    # mktime (LOCAL time) like Lua / the lifted oslib; isdst -1 = unknown
    return int(_t.mktime((
        g("year", 1970), g("month", 1), g("day", 1),
        g("hour", 12), g("min", 0), g("sec", 0), 0, 0, -1,
    )))


def _os_date(fmt="%c", t=None):
    # safe os.date: strftime formats plus the '*t'/'!*t' table form
    import time as _t

    when = int(t) if t is not None else int(_t.time())
    utc = fmt.startswith("!")
    if utc:
        fmt = fmt[1:]
    st = _t.gmtime(when) if utc else _t.localtime(when)
    if fmt == "*t":
        return to_lua({
            "year": st.tm_year, "month": st.tm_mon, "day": st.tm_mday,
            "hour": st.tm_hour, "min": st.tm_min, "sec": st.tm_sec,
            # Lua wday: 1 = Sunday; tm_wday: 0 = Monday
            "wday": (st.tm_wday + 1) % 7 + 1, "yday": st.tm_yday,
            "isdst": bool(st.tm_isdst),
        })
    return _t.strftime(fmt, st)


def _stdlib() -> dict:
    return {
        "tonumber": _lua_tonumber,
        "tostring": _lua_tostring,
        "type": _typename,
        "pairs": _pairs,
        "ipairs": _ipairs,
        "error": _lua_error,
        "assert": _lua_assert,
        "pcall": _lua_pcall,
        "require": _require,
        "math": {
            "ceil": lambda x: int(math.ceil(_tonum(x, "math.ceil"))),
            "floor": lambda x: int(math.floor(_tonum(x, "math.floor"))),
            "max": lambda *a: max(_tonum(x, "math.max") for x in a),
            "min": lambda *a: min(_tonum(x, "math.min") for x in a),
            "abs": lambda x: abs(_tonum(x, "math.abs")),
            "huge": math.inf,
        },
        "string": dict(_STRING_METHODS),
        "table": {"insert": _table_insert, "remove": _table_remove,
                  "concat": _table_concat, "sort": _table_sort},
        # the reference sandbox opens a SAFE os with only time/date
        # (lifted/lua/oslib_safe.go via luavm/lua.go:188)
        "os": {"time": _os_time, "date": _os_date},
    }


# ---------------------------------------------------------------------------
# operation adapters (lua.go:59-129 — one function per operation)
# ---------------------------------------------------------------------------

LUA_OPERATION_FUNCTIONS = {
    "replica_resource": "GetReplicas",
    "replica_revision": "ReviseReplica",
    "retention": "Retain",
    "status_aggregation": "AggregateStatus",
    "status_reflection": "ReflectStatus",
    "health_interpretation": "InterpretHealth",
    "dependency_interpretation": "GetDependencies",
}


def looks_like_lua(source: str) -> bool:
    """Heuristic language sniff for CustomizationRule scripts: the reference
    CRD carries Lua; our dialect carries Python `def`s."""
    if re.search(r"^\s*def\s+\w+\s*\(", source, re.MULTILINE):
        return False
    return bool(
        re.search(r"\bfunction\s+\w+\s*\(", source)
        or re.search(r"\b\w+\s*=\s*function\s*\(", source)  # assignment style
        or re.search(r"\blocal\s+\w+", source)
    )


def compile_lua_script(source: str, operation: str) -> Callable:
    """Compile one Lua customization script → a dict-level callable with the
    same contract as declarative.compile_script (the `_wrap_scripts`
    adapter consumes either)."""
    fn_name = LUA_OPERATION_FUNCTIONS.get(operation)
    if fn_name is None:
        raise LuaError(f"unknown operation {operation!r}")
    vm = LuaVM(source)
    fn = vm.function(fn_name)

    if operation == "replica_resource":
        def replica_resource(obj: dict):
            out = fn(obj)
            replicas = out[0] if out else 0
            requirement = out[1] if len(out) > 1 else None
            return replicas, requirement
        return replica_resource

    if operation == "status_aggregation":
        def status_aggregation(obj: dict, items: list):
            # lua.go passes nil when there are no status items
            out = fn(obj, items if items else None)
            return out[0] if out else obj
        return status_aggregation

    def single(*args):
        out = fn(*args)
        return out[0] if out else None

    return single

"""Sandboxed declarative script engine (I4, reference:
pkg/resourceinterpreter/customized/declarative/luavm/lua.go — a gopher-lua
sandbox with k8s helpers; here a restricted Python-expression dialect, since
the operation contracts — not the scripting language — are the API surface).

A script defines ONE function with the operation's canonical name:
    GetReplicas(obj)                -> (replicas, requirement_dict_or_None)
    ReviseReplica(obj, replica)     -> obj
    Retain(desiredObj, observedObj) -> obj
    AggregateStatus(obj, items)     -> obj   (items: list of {cluster, status})
    ReflectStatus(obj)              -> dict or None
    InterpretHealth(obj)            -> bool
    GetDependencies(obj)            -> list of {apiVersion, kind, namespace, name}

Objects are plain dicts (the Lua side also sees tables). The sandbox rejects
imports, dunder access, and exec/eval/open at compile time, and runs with a
minimal builtin set.
"""
from __future__ import annotations

import ast
import functools
import sys
from typing import Any, Callable

OPERATION_FUNCTIONS = {
    "replica_resource": "GetReplicas",
    "replica_revision": "ReviseReplica",
    "retention": "Retain",
    "status_aggregation": "AggregateStatus",
    "status_reflection": "ReflectStatus",
    "health_interpretation": "InterpretHealth",
    "dependency_interpretation": "GetDependencies",
}

_FORBIDDEN_NAMES = {
    "eval", "exec", "open", "compile", "globals", "locals", "vars",
    "getattr", "setattr", "delattr", "__import__", "input", "breakpoint",
}

# Frame/generator/coroutine/code introspection attributes are NOT dunders, so
# the dunder check alone does not stop e.g.
# gen.gi_frame.f_back.f_globals['__builtins__'] escaping to the caller's
# builtins (round-1 advisor PoC). Deny them by name.
_FORBIDDEN_ATTRS = {
    "gi_frame", "gi_code", "gi_yieldfrom",
    "cr_frame", "cr_code", "cr_await", "cr_origin",
    "ag_frame", "ag_code", "ag_await",
    "f_back", "f_globals", "f_builtins", "f_locals", "f_code", "f_trace",
    "tb_frame", "tb_next",
    "co_consts", "co_names", "co_code", "co_filename",
}

# hard cap on traced line events per script call; interpreter scripts are
# small field transforms — anything past this is a runaway loop
_MAX_TRACE_EVENTS = 200_000

_SAFE_BUILTINS = {
    "len": len, "int": int, "float": float, "str": str, "bool": bool,
    "dict": dict, "list": list, "tuple": tuple, "set": set,
    "min": min, "max": max, "sum": sum, "abs": abs, "round": round,
    "sorted": sorted, "reversed": reversed, "range": range,
    "enumerate": enumerate, "zip": zip, "any": any, "all": all,
    "isinstance": isinstance, "True": True, "False": False, "None": None,
    # standard error types so scripts can use try/except; BaseException is
    # deliberately absent (the execution-limit signal must stay uncatchable)
    "Exception": Exception, "ValueError": ValueError, "KeyError": KeyError,
    "TypeError": TypeError, "IndexError": IndexError,
    "AttributeError": AttributeError, "ZeroDivisionError": ZeroDivisionError,
}


class ScriptError(Exception):
    pass


class _ScriptLimitExceeded(BaseException):
    """Raised by the execution-limit tracer. Deliberately a BaseException so
    a script's `except Exception:` cannot swallow it (raising inside a trace
    function unsets tracing, so a caught limit error would leave the rest of
    the script running unbounded). Bare `except:` and `except BaseException:`
    are denied at compile time for the same reason."""


def _check_ast(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            raise ScriptError("imports are not allowed in interpreter scripts")
        if isinstance(node, ast.Attribute) and (
            node.attr.startswith("__") or node.attr in _FORBIDDEN_ATTRS
        ):
            raise ScriptError(f"attribute {node.attr!r} is not allowed")
        if isinstance(node, ast.Name) and node.id in _FORBIDDEN_NAMES:
            raise ScriptError(f"{node.id!r} is not allowed in interpreter scripts")
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            raise ScriptError("global/nonlocal are not allowed")
        if isinstance(node, (ast.Try, ast.TryStar)) and node.finalbody:
            # a finally block runs AFTER the limit tracer raised (tracing is
            # already unset), so code inside it would be unbounded
            raise ScriptError("try/finally is not allowed in interpreter scripts")
        if isinstance(node, ast.ExceptHandler):
            if node.type is None:
                raise ScriptError("bare except is not allowed (catch Exception)")
            names = [n.id for n in ast.walk(node.type) if isinstance(n, ast.Name)]
            if "BaseException" in names:
                raise ScriptError("catching BaseException is not allowed")


def compile_script(script: str, operation: str) -> Callable[..., Any]:
    """Compile a customization script and return the operation function."""
    fn_name = OPERATION_FUNCTIONS.get(operation)
    if fn_name is None:
        raise ScriptError(f"unknown operation {operation!r}")
    try:
        tree = ast.parse(script)
    except SyntaxError as e:
        raise ScriptError(f"syntax error in {operation} script: {e}") from e
    _check_ast(tree)
    env: dict[str, Any] = {"__builtins__": _SAFE_BUILTINS}
    code = compile(tree, f"<{operation}>", "exec")
    try:
        # module-level statements run under the same execution budget as the
        # operation calls (a top-level loop must not hang the reconciler)
        _run_limited(lambda: exec(code, env), operation)  # noqa: S102 - sandboxed above
    except ScriptError:
        raise
    except Exception as e:  # noqa: BLE001
        raise ScriptError(f"error loading {operation} script: {e}") from e
    fn = env.get(fn_name)
    if not callable(fn):
        raise ScriptError(f"{operation} script must define {fn_name}()")
    return _with_execution_limit(fn, operation)


def compile_rule_script(script: str, operation: str):
    """Compile one CustomizationRule script in whichever language it is
    written in, returning (callable, language).

    The sniff (luavm.looks_like_lua) only picks which compiler runs FIRST;
    a script the sniff misroutes still compiles via the other language
    before any error surfaces, so classification can never turn a valid
    script into a denial — only genuinely-invalid scripts fail, and they
    fail with the sniffed language's error (the one the author meant)."""
    from . import luavm

    sniffed_lua = luavm.looks_like_lua(script)
    first, second = (
        ((luavm.compile_lua_script, "lua"), (compile_script, "native"))
        if sniffed_lua
        else ((compile_script, "native"), (luavm.compile_lua_script, "lua"))
    )
    try:
        return first[0](script, operation), first[1]
    except (ScriptError, luavm.LuaError) as primary_err:
        try:
            return second[0](script, operation), second[1]
        except (ScriptError, luavm.LuaError):
            raise primary_err


def _run_limited(thunk: Callable[[], Any], operation: str) -> Any:
    """Run `thunk` under a trace-event budget: an infinite loop becomes a
    ScriptError instead of a stuck controller."""
    budget = _MAX_TRACE_EVENTS

    def tracer(frame, event, arg):  # noqa: ANN001 - cpython trace protocol
        nonlocal budget
        budget -= 1
        if budget < 0:
            raise _ScriptLimitExceeded
        return tracer

    prev = sys.gettrace()
    sys.settrace(tracer)
    try:
        return thunk()
    except _ScriptLimitExceeded:
        raise ScriptError(
            f"{operation} script exceeded the execution limit"
        ) from None
    finally:
        sys.settrace(prev)


def _with_execution_limit(fn: Callable[..., Any], operation: str) -> Callable[..., Any]:
    @functools.wraps(fn)
    def limited(*args: Any, **kwargs: Any) -> Any:
        return _run_limited(lambda: fn(*args, **kwargs), operation)

    return limited

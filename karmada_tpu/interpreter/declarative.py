"""Sandboxed declarative script engine (I4, reference:
pkg/resourceinterpreter/customized/declarative/luavm/lua.go — a gopher-lua
sandbox with k8s helpers; here a restricted Python-expression dialect, since
the operation contracts — not the scripting language — are the API surface).

A script defines ONE function with the operation's canonical name:
    GetReplicas(obj)                -> (replicas, requirement_dict_or_None)
    ReviseReplica(obj, replica)     -> obj
    Retain(desiredObj, observedObj) -> obj
    AggregateStatus(obj, items)     -> obj   (items: list of {cluster, status})
    ReflectStatus(obj)              -> dict or None
    InterpretHealth(obj)            -> bool
    GetDependencies(obj)            -> list of {apiVersion, kind, namespace, name}

Objects are plain dicts (the Lua side also sees tables). The sandbox rejects
imports, dunder access, and exec/eval/open at compile time, and runs with a
minimal builtin set.
"""
from __future__ import annotations

import ast
from typing import Any, Callable

OPERATION_FUNCTIONS = {
    "replica_resource": "GetReplicas",
    "replica_revision": "ReviseReplica",
    "retention": "Retain",
    "status_aggregation": "AggregateStatus",
    "status_reflection": "ReflectStatus",
    "health_interpretation": "InterpretHealth",
    "dependency_interpretation": "GetDependencies",
}

_FORBIDDEN_NAMES = {
    "eval", "exec", "open", "compile", "globals", "locals", "vars",
    "getattr", "setattr", "delattr", "__import__", "input", "breakpoint",
}

_SAFE_BUILTINS = {
    "len": len, "int": int, "float": float, "str": str, "bool": bool,
    "dict": dict, "list": list, "tuple": tuple, "set": set,
    "min": min, "max": max, "sum": sum, "abs": abs, "round": round,
    "sorted": sorted, "reversed": reversed, "range": range,
    "enumerate": enumerate, "zip": zip, "any": any, "all": all,
    "isinstance": isinstance, "True": True, "False": False, "None": None,
}


class ScriptError(Exception):
    pass


def _check_ast(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            raise ScriptError("imports are not allowed in interpreter scripts")
        if isinstance(node, ast.Attribute) and node.attr.startswith("__"):
            raise ScriptError("dunder attribute access is not allowed")
        if isinstance(node, ast.Name) and node.id in _FORBIDDEN_NAMES:
            raise ScriptError(f"{node.id!r} is not allowed in interpreter scripts")
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            raise ScriptError("global/nonlocal are not allowed")


def compile_script(script: str, operation: str) -> Callable[..., Any]:
    """Compile a customization script and return the operation function."""
    fn_name = OPERATION_FUNCTIONS.get(operation)
    if fn_name is None:
        raise ScriptError(f"unknown operation {operation!r}")
    try:
        tree = ast.parse(script)
    except SyntaxError as e:
        raise ScriptError(f"syntax error in {operation} script: {e}") from e
    _check_ast(tree)
    env: dict[str, Any] = {"__builtins__": _SAFE_BUILTINS}
    try:
        exec(compile(tree, f"<{operation}>", "exec"), env)  # noqa: S102 - sandboxed above
    except Exception as e:  # noqa: BLE001
        raise ScriptError(f"error loading {operation} script: {e}") from e
    fn = env.get(fn_name)
    if not callable(fn):
        raise ScriptError(f"{operation} script must define {fn_name}()")
    return fn

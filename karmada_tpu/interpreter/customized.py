"""Customized interpreter tiers (I4/I5) + thirdparty configs (I3).

- DeclarativeInterpreterManager (I4, reference customized/declarative/):
  watches ResourceInterpreterCustomization objects and (un)registers compiled
  script interpreters on the facade's customized tier.
- HookRegistry + WebhookInterpreterManager (I5, reference customized/webhook/ +
  examples/customresourceinterpreter): ResourceInterpreterWebhookConfiguration
  routes operations to named in-process endpoints (the stand-in for the HTTPS
  hook servers).
- THIRDPARTY_CUSTOMIZATIONS (I3, reference
  default/thirdparty/resourcecustomizations/): the shipped per-CRD
  customization library (native hooks in interpreter/thirdparty.py),
  loaded below the customized tiers.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

from ..api.unstructured import Unstructured
from ..api.work import AggregatedStatusItem, NodeClaim, ReplicaRequirements
from ..runtime.controller import DONE, Controller, Runtime
from ..store.store import Store
from .declarative import (
    OPERATION_FUNCTIONS, ScriptError, compile_rule_script,
)
from .interpreter import (
    HEALTHY,
    KindInterpreter,
    ResourceInterpreter,
    UNHEALTHY,
    UNKNOWN,
    _parse_quantity,
)


def _wrap_scripts(fns: dict[str, Callable]) -> KindInterpreter:
    """Adapt dict-level script functions to the Unstructured-level hooks."""
    ki = KindInterpreter()

    get_rep = fns.get("replica_resource")
    if get_rep is not None:
        def get_replicas(obj: Unstructured):
            replicas, req = get_rep(obj.to_dict())
            requirements = None
            if req and "resourceRequest" in req:
                # structured shape (the Lua contract returns the full
                # ReplicaRequirements table, kube.accuratePodRequirements)
                claim = req.get("nodeClaim") or None
                requirements = ReplicaRequirements(
                    node_claim=None if claim is None else NodeClaim(
                        node_selector=dict(claim.get("nodeSelector") or {}),
                        tolerations=list(claim.get("tolerations") or []),
                        hard_node_affinity=claim.get("hardNodeAffinity"),
                    ),
                    resource_request={
                        k: float(_parse_quantity(v))
                        for k, v in (req.get("resourceRequest") or {}).items()
                    },
                    namespace=req.get("namespace") or obj.namespace,
                    priority_class_name=req.get("priorityClassName") or "",
                )
            elif req:
                requirements = ReplicaRequirements(
                    resource_request={k: float(v) for k, v in req.items()},
                    namespace=obj.namespace,
                )
            return int(replicas or 0), requirements
        ki.get_replicas = get_replicas

    revise = fns.get("replica_revision")
    if revise is not None:
        ki.revise_replica = lambda obj, n: Unstructured(revise(obj.to_dict(), n))

    retain = fns.get("retention")
    if retain is not None:
        ki.retain = lambda desired, observed: Unstructured(
            retain(desired.to_dict(), observed.to_dict())
        )

    agg = fns.get("status_aggregation")
    if agg is not None:
        def aggregate(template: Unstructured, items: list[AggregatedStatusItem]):
            dict_items = [
                {"clusterName": it.cluster_name, "status": it.status or {}}
                for it in items
            ]
            return Unstructured(agg(template.to_dict(), dict_items))
        ki.aggregate_status = aggregate

    reflect = fns.get("status_reflection")
    if reflect is not None:
        ki.reflect_status = lambda obj: reflect(obj.to_dict())

    health = fns.get("health_interpretation")
    if health is not None:
        ki.interpret_health = lambda obj: (
            HEALTHY if health(obj.to_dict()) else UNHEALTHY
        )

    deps = fns.get("dependency_interpretation")
    if deps is not None:
        ki.get_dependencies = lambda obj: list(deps(obj.to_dict()) or [])

    return ki


def compile_customization(spec) -> KindInterpreter:
    """Compile every script in a ResourceInterpreterCustomizationSpec.

    Scripts are language-sniffed per rule: the reference CRD carries Lua
    (executed by interpreter/luavm.py, so existing Karmada customizations
    carry over unmodified); the TPU-native dialect stays available."""
    from . import luavm

    fns: dict[str, Callable] = {}
    for op in OPERATION_FUNCTIONS:
        rule = getattr(spec.customizations, op, None)
        if rule is not None and rule.script:
            try:
                fns[op], _ = compile_rule_script(rule.script, op)
            except luavm.LuaError as e:
                raise ScriptError(str(e))
    if not fns:
        raise ScriptError("customization defines no scripts")
    return _wrap_scripts(fns)


class DeclarativeInterpreterManager:
    """Level-triggered registry sync: customization objects → facade tier."""

    def __init__(self, store: Store, interpreter: ResourceInterpreter, runtime: Runtime):
        self.store = store
        self.interpreter = interpreter
        self.controller = runtime.register(
            Controller(name="interpreter-customizations", reconcile=self._reconcile)
        )
        store.watch("ResourceInterpreterCustomization", self._on_change)

    def _on_change(self, event: str, ric) -> None:
        self.controller.enqueue("sync")

    def _reconcile(self, _key: str) -> str:
        """Rebuild the whole customized tier (multiple customizations may
        target one GVK; name-ascending merge order matches the reference's
        configmanager sort)."""
        by_gvk: dict[str, KindInterpreter] = {}
        for ric in sorted(
            self.store.list("ResourceInterpreterCustomization"),
            key=lambda r: r.metadata.name,
        ):
            gvk = f"{ric.spec.target.api_version}/{ric.spec.target.kind}"
            try:
                ki = compile_customization(ric.spec)
            except ScriptError:
                continue  # admission validates scripts; defensive skip here
            merged = by_gvk.get(gvk)
            if merged is None:
                by_gvk[gvk] = ki
            else:
                for f in (
                    "get_replicas", "revise_replica", "retain", "aggregate_status",
                    "get_dependencies", "reflect_status", "interpret_health",
                ):
                    if getattr(ki, f) is not None:
                        setattr(merged, f, getattr(ki, f))
        self.interpreter.set_declarative_tier(by_gvk)
        return DONE


class HookRegistry:
    """Interpreter hook endpoints: named in-process handlers, plus real
    http(s):// hook servers reached through HttpHookClient (the reference's
    webhook mode, customized/webhook/) — resolved lazily per URL+CA."""

    def __init__(self) -> None:
        self._endpoints: dict[str, Any] = {}
        self._http_clients: dict[tuple, Any] = {}

    def register(self, url: str, handler: Any) -> None:
        """handler: object with optional methods named like the operations
        (get_replicas(obj dict) -> (n, req), interpret_health(obj) -> bool...)."""
        self._endpoints[url] = handler

    def get(self, url: str, ca_bundle: str = "",
            timeout_seconds: float = 10.0) -> Optional[Any]:
        handler = self._endpoints.get(url)
        if handler is not None:
            return handler
        if url.startswith(("http://", "https://")):
            key = (url, ca_bundle, timeout_seconds)
            client = self._http_clients.get(key)
            if client is None:
                from .webhook_http import HttpHookClient

                client = HttpHookClient(
                    url, ca_pem=ca_bundle.encode() if ca_bundle else None,
                    timeout=float(timeout_seconds),
                )
                self._http_clients[key] = client
            return client
        return None


class WebhookInterpreterManager:
    """ResourceInterpreterWebhookConfiguration → facade webhook tier."""

    def __init__(self, store: Store, interpreter: ResourceInterpreter,
                 runtime: Runtime, hooks: HookRegistry):
        self.store = store
        self.interpreter = interpreter
        self.hooks = hooks
        self.controller = runtime.register(
            Controller(name="interpreter-webhooks", reconcile=self._reconcile)
        )
        store.watch("ResourceInterpreterWebhookConfiguration", self._on_change)

    def _on_change(self, event: str, cfg) -> None:
        self.controller.enqueue("sync")

    def _reconcile(self, _key: str) -> str:
        by_gvk: dict[str, KindInterpreter] = {}
        for cfg in sorted(
            self.store.list("ResourceInterpreterWebhookConfiguration"),
            key=lambda c: c.metadata.name,
        ):
            for wh in cfg.webhooks:
                handler = self.hooks.get(
                    wh.url, getattr(wh, "ca_bundle", ""),
                    timeout_seconds=getattr(wh, "timeout_seconds", 10) or 10,
                )
                if handler is None:
                    continue
                for rule in wh.rules:
                    for av in rule.api_versions:
                        for kind in rule.kinds:
                            gvk = f"{av}/{kind}"
                            ki = by_gvk.setdefault(gvk, KindInterpreter())
                            self._bind(ki, handler, rule.operations)
        self.interpreter.set_webhook_tier(by_gvk)
        return DONE

    @staticmethod
    def _bind(ki: KindInterpreter, handler, operations: list[str]) -> None:
        ops = set(operations or ["*"])

        def want(op: str) -> bool:
            return "*" in ops or op in ops

        if want("InterpretReplica") and hasattr(handler, "get_replicas"):
            def get_replicas(obj: Unstructured):
                n, req = handler.get_replicas(obj.to_dict())
                requirements = (
                    ReplicaRequirements(
                        resource_request={
                            k: float(_parse_quantity(v))
                            for k, v in dict(req).items()
                        },
                        namespace=obj.namespace,
                    )
                    if req else None
                )
                return int(n), requirements
            ki.get_replicas = get_replicas
        if want("ReviseReplica") and hasattr(handler, "revise_replica"):
            ki.revise_replica = lambda obj, n: Unstructured(handler.revise_replica(obj.to_dict(), n))
        if want("Retain") and hasattr(handler, "retain"):
            ki.retain = lambda d, o: Unstructured(handler.retain(d.to_dict(), o.to_dict()))
        if want("AggregateStatus") and hasattr(handler, "aggregate_status"):
            ki.aggregate_status = lambda t, items: Unstructured(
                handler.aggregate_status(
                    t.to_dict(),
                    [{"clusterName": i.cluster_name, "status": i.status or {}} for i in items],
                )
            )
        if want("InterpretStatus") and hasattr(handler, "reflect_status"):
            ki.reflect_status = lambda obj: handler.reflect_status(obj.to_dict())
        if want("InterpretHealth") and hasattr(handler, "interpret_health"):
            ki.interpret_health = lambda obj: (
                HEALTHY if handler.interpret_health(obj.to_dict()) else UNHEALTHY
            )
        if want("InterpretDependency") and hasattr(handler, "get_dependencies"):
            ki.get_dependencies = lambda obj: list(handler.get_dependencies(obj.to_dict()) or [])


# -- I3: shipped thirdparty customizations ---------------------------------
# The shipped library lives in interpreter/thirdparty.py as native hooks:
# THIRDPARTY_CUSTOMIZATIONS maps gvk -> zero-arg KindInterpreter builder
# (16 GVKs matching the reference's customization sets kind-for-kind), and
# load_thirdparty_tier() instantiates the tier. Aliased here because this
# module historically hosted the registry.

from .thirdparty import (  # noqa: E402
    THIRDPARTY_CUSTOMIZATIONS,
    load_thirdparty_tier,
)
